"""Goodput ledger (ISSUE 20): exhaustive wall-clock and token
attribution — where did every monotonic second GO?

Every plane so far answers "how fast was X" (SLO quantiles, span trees,
per-HLO roofline residuals); none answers for the *denominator*.  A run
that restarts twice, recompiles after warmup, and rolls back half its
speculative drafts can post healthy step latencies while wasting a
third of its compute.  :class:`TimeLedger` attributes every second of a
run's wall span to exactly ONE leaf bucket:

===========  ============================================================
domain       buckets (productive ones starred)
===========  ============================================================
``train``    ``step``\\*, ``compile``, ``checkpoint_save`` (sync saves;
             of an ``async_=True`` save only its blocking enqueue/wait
             slice), ``restore``, ``restart_backoff``, ``data_wait``,
             ``idle``
``serve``    ``decode``\\*, ``prefill``\\*, ``verify``\\* (spec draft +
             verify, acceptance-weighted), ``spec_rollback_waste``,
             ``preempt_recompute_waste``, ``queue_drain``, ``idle``
``fleet``    ``respawn``, ``restart_backoff`` — counter-only (see below)
===========  ============================================================

Attribution model
-----------------
``section(bucket)`` context managers form a nesting stack; a child's
elapsed time is subtracted from its parent's frame on exit, so leaves
are mutually exclusive BY CONSTRUCTION and ``idle`` is the residual
``wall - sum(explicit)``.  That makes the conservation invariant

    ``sum(buckets) + idle == wall span``  (tolerance 1e-6)

machine-checkable: :meth:`check` recomputes the wall span independently
and raises :class:`LedgerError` on violation (the only way to violate
it is double-counting — two threads opening sections on one ledger
concurrently, which no instrumented seam does: train is
single-threaded, serve sections open only under the engine lock).
:meth:`close` runs the check, publishes, and files a
``goodput_ledger`` flight-recorder event — the dump shape
``tools/goodput_report.py --flight`` renders.

``carve(bucket, seconds)`` credits a bucket for time that elapsed
*inside* the innermost open section (debited from that section's frame
like a virtual child) — the PR-14 ``record_compile`` hook carves XLA
backend-compile seconds out of the surrounding ``step`` into
``compile``, and the spec tick carves the rejected-draft share of its
verify window into ``spec_rollback_waste``.  Conservation is unaffected
(carving moves seconds between leaves, never mints them).

The parallel token ledger counts ``useful`` emitted tokens against
``spec_rolled_back`` / ``preempt_recomputed`` / ``shed`` waste classes.

The ``fleet`` domain (``ReplicaSupervisor`` respawn + backoff windows)
is counter-only via :func:`fleet_attribute`: N replicas back off
concurrently against one supervisor wall clock, so a per-process
conservation invariant cannot hold there — the counters still feed
``goodput_seconds_total`` for fleet aggregation.

Cost discipline: every record path is gated on the same
``metrics._runtime["enabled"]`` dict lookup as spans / flight events —
``bench.py _bench_goodput`` guards the disabled path next to
``obs_overhead``.
"""
from __future__ import annotations

import threading
import time

from . import metrics as _metrics
from . import flight_recorder as _flight

__all__ = [
    "TimeLedger", "LedgerError", "TRAIN_BUCKETS", "SERVE_BUCKETS",
    "FLEET_BUCKETS", "TOKEN_CLASSES", "PRODUCTIVE", "NULL",
    "install", "uninstall", "active", "active_section", "on_compile",
    "fleet_attribute",
]

TRAIN_BUCKETS = ("step", "compile", "checkpoint_save", "restore",
                 "restart_backoff", "data_wait", "idle")
SERVE_BUCKETS = ("decode", "prefill", "verify", "spec_rollback_waste",
                 "preempt_recompute_waste", "queue_drain", "idle")
FLEET_BUCKETS = ("respawn", "restart_backoff")
TOKEN_CLASSES = ("useful", "spec_rolled_back", "preempt_recomputed",
                 "shed")

#: Buckets that count toward the goodput numerator, per domain.
PRODUCTIVE = {
    "train": ("step",),
    "serve": ("decode", "prefill", "verify"),
    "fleet": (),
}

_M_SECONDS = _metrics.counter(
    "goodput_seconds_total",
    "Wall seconds attributed per ledger leaf bucket — mutually "
    "exclusive; per domain, sum(buckets incl. idle) equals the wall "
    "span (fleet buckets are counter-only: overlapping replica windows)",
    labelnames=("domain", "bucket"))
_M_TOKENS = _metrics.counter(
    "goodput_tokens_total",
    "Token ledger: useful emitted tokens vs spec_rolled_back / "
    "preempt_recomputed / shed waste classes",
    labelnames=("domain", "class"))
_M_RATIO = _metrics.gauge(
    "goodput_ratio",
    "Productive seconds (train: step; serve: decode+prefill+verify) "
    "over total wall span, cumulative since ledger start",
    labelnames=("domain",))


class LedgerError(AssertionError):
    """Conservation invariant violated (double-counted wall time)."""


class _NullSection:
    """No-op section: the disabled path and absent-active-ledger path."""
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


NULL = _NullSection()


class _Section:
    __slots__ = ("_led", "bucket", "_t0", "_child")

    def __init__(self, led, bucket):
        self._led = led
        self.bucket = bucket
        self._t0 = None
        self._child = 0.0

    def __enter__(self):
        self._t0 = self._led._clock()
        self._child = 0.0
        with self._led._lock:
            self._led._stack.append(self)
        return self

    def __exit__(self, etype, exc, tb):
        led = self._led
        elapsed = max(0.0, led._clock() - self._t0)
        with led._lock:
            stack = led._stack
            if stack and stack[-1] is self:
                stack.pop()
            elif self in stack:  # defensive: misnested exit
                stack.remove(self)
            led._buckets[self.bucket] = (
                led._buckets.get(self.bucket, 0.0)
                + max(0.0, elapsed - self._child))
            if stack:
                stack[-1]._child += elapsed
        return False


class TimeLedger:
    """One domain's wall-clock + token attribution, conservation-checked.

    The wall span opens at construction (monotonic clock; injectable
    for deterministic tests).  All mutators are gated on the process
    observability flag — with the plane disabled a ledger attributes
    nothing and every second lands in ``idle``."""

    def __init__(self, domain, buckets=None, productive=None,
                 clock=time.perf_counter, token_classes=TOKEN_CLASSES):
        self.domain = str(domain)
        if buckets is None:
            buckets = {"train": TRAIN_BUCKETS, "serve": SERVE_BUCKETS,
                       "fleet": FLEET_BUCKETS}.get(self.domain, ())
        self.productive = tuple(
            PRODUCTIVE.get(self.domain, ()) if productive is None
            else productive)
        self._clock = clock
        self._lock = threading.Lock()
        self._buckets = {b: 0.0 for b in buckets if b != "idle"}
        self._tokens = {c: 0 for c in token_classes}
        self._stack = []
        self._t0 = clock()
        self._pub_seconds = {}  # bucket -> already-published seconds
        self._pub_tokens = {}   # class  -> already-published count
        self._closed = False

    # ------------------------------------------------------------ recording
    def section(self, bucket):
        """Context manager attributing its (exclusive) elapsed time to
        ``bucket``.  Nested sections subtract from their parent."""
        if not _metrics._runtime["enabled"]:
            return NULL
        return _Section(self, str(bucket))

    def carve(self, bucket, seconds):
        """Credit ``bucket`` for ``seconds`` that elapsed inside the
        innermost open section (debited from that section's frame like
        a virtual child; with no section open, the credit comes out of
        the idle residual)."""
        s = float(seconds)
        if not _metrics._runtime["enabled"] or s <= 0.0:
            return
        bucket = str(bucket)
        with self._lock:
            self._buckets[bucket] = self._buckets.get(bucket, 0.0) + s
            if self._stack:
                self._stack[-1]._child += s

    def transfer(self, src, dst, seconds):
        """Post-hoc move of already-credited seconds between buckets
        (clamped to what ``src`` holds)."""
        if not _metrics._runtime["enabled"]:
            return
        with self._lock:
            s = max(0.0, min(float(seconds), self._buckets.get(src, 0.0)))
            if s <= 0.0:
                return
            self._buckets[src] -= s
            self._buckets[dst] = self._buckets.get(dst, 0.0) + s

    def count_tokens(self, cls, n):
        n = int(n)
        if not _metrics._runtime["enabled"] or n <= 0:
            return
        cls = str(cls)
        with self._lock:
            self._tokens[cls] = self._tokens.get(cls, 0) + n

    # ------------------------------------------------------------ reporting
    def wall(self, now=None):
        return max(0.0, (self._clock() if now is None else now) - self._t0)

    def _raw(self, now=None):
        """Unrounded (wall, buckets-with-idle, tokens) triple — the
        conservation check must see full precision, not the 6-decimal
        JSON rounding (7 rounded buckets can drift past 1e-6)."""
        wall = self.wall(now)
        with self._lock:
            buckets = dict(self._buckets)
            tokens = dict(self._tokens)
        buckets["idle"] = wall - sum(buckets.values())
        return wall, buckets, tokens

    def snapshot(self, now=None):
        """JSON shape for ``stats()["goodput"]`` / ``/varz``: every
        bucket (idle materialized as the residual), the token classes,
        and the cumulative goodput ratio."""
        wall, buckets, tokens = self._raw(now)
        productive = sum(buckets.get(b, 0.0) for b in self.productive)
        return {
            "domain": self.domain,
            "wall_s": round(wall, 6),
            "ratio": round(productive / wall, 6) if wall > 0 else 0.0,
            "buckets": {b: round(v, 6) for b, v in buckets.items()},
            "tokens": tokens,
        }

    def check(self, tolerance=1e-6, now=None):
        """Assert the conservation invariant; returns the snapshot.
        Raises :class:`LedgerError` when sum(buckets) diverges from the
        wall span or any leaf went negative (double-counted time)."""
        now = self._clock() if now is None else now
        wall, buckets, tokens = self._raw(now)
        total = sum(buckets.values())
        if abs(total - wall) > tolerance:
            raise LedgerError(
                f"goodput[{self.domain}]: sum(buckets)={total!r} != "
                f"wall={wall!r} (tolerance {tolerance})")
        for b, v in buckets.items():
            if v < -tolerance:
                raise LedgerError(
                    f"goodput[{self.domain}]: bucket {b!r} negative "
                    f"({v!r}) — wall time double-counted")
        for c, n in tokens.items():
            if n < 0:
                raise LedgerError(
                    f"goodput[{self.domain}]: token class {c!r} "
                    f"negative ({n})")
        return self.snapshot(now)

    def publish(self, now=None):
        """Push the delta since the last publish onto the registry
        counters and refresh the ratio gauge; returns the snapshot.
        Registered as a telemetry pre-scrape collect hook (the hbm_*
        idiom), so scrapes always see current attribution."""
        snap = self.snapshot(now)
        if not _metrics._runtime["enabled"]:
            return snap
        with self._lock:
            for b, v in snap["buckets"].items():
                if b == "idle":
                    continue  # residual, not a counter: derivable
                d = v - self._pub_seconds.get(b, 0.0)
                if d > 0:
                    _M_SECONDS.labels(domain=self.domain, bucket=b).inc(d)
                    self._pub_seconds[b] = v
            idle = snap["buckets"].get("idle", 0.0)
            d = idle - self._pub_seconds.get("idle", 0.0)
            if d > 0:
                _M_SECONDS.labels(domain=self.domain, bucket="idle").inc(d)
                self._pub_seconds["idle"] = idle
            for c, n in snap["tokens"].items():
                d = n - self._pub_tokens.get(c, 0)
                if d > 0:
                    _M_TOKENS.labels(domain=self.domain,
                                     **{"class": c}).inc(d)
                    self._pub_tokens[c] = n
        _M_RATIO.labels(domain=self.domain).set(snap["ratio"])
        return snap

    def close(self, reason="close", tolerance=1e-6):
        """End of the measured span: conservation-check, publish, and
        file the ``goodput_ledger`` flight event (the shape
        ``goodput_report --flight`` renders).  Idempotent."""
        if self._closed:
            return self.snapshot()
        now = self._clock()
        snap = self.check(tolerance, now=now)
        self.publish(now=now)
        self._closed = True
        _flight.record_event(
            "goodput_ledger", domain=self.domain, reason=str(reason),
            wall_s=snap["wall_s"], ratio=snap["ratio"],
            buckets=snap["buckets"], tokens=snap["tokens"])
        return snap


# --------------------------------------------------- active-ledger registry
# One ledger per domain may be "installed" process-wide so seams that
# cannot thread a ledger through their signature (CheckpointManager.save,
# the record_compile hook) still attribute to the run that owns them.
_active = {}
_active_lock = threading.Lock()


def install(ledger):
    """Make ``ledger`` the process-wide active ledger for its domain."""
    with _active_lock:
        _active[ledger.domain] = ledger
    return ledger


def uninstall(ledger):
    """Remove ``ledger`` if it is still the active one for its domain."""
    with _active_lock:
        if _active.get(ledger.domain) is ledger:
            del _active[ledger.domain]


def active(domain):
    return _active.get(domain)


def active_section(domain, bucket):
    """``section(bucket)`` on the active ledger for ``domain`` — the
    no-op singleton when none is installed or the plane is disabled."""
    if not _metrics._runtime["enabled"]:
        return NULL
    led = _active.get(domain)
    return NULL if led is None else led.section(bucket)


def on_compile(seconds):
    """PR-14 hook: ``record_compile`` reports XLA backend-compile
    seconds here; carved out of the active train ledger's surrounding
    section (normally ``step``) into ``compile``."""
    led = _active.get("train")
    if led is not None:
        led.carve("compile", seconds)


def fleet_attribute(bucket, seconds):
    """Counter-only attribution for the fleet domain (respawn/backoff
    windows overlap across replicas, so no conservation invariant)."""
    s = float(seconds)
    if not _metrics._runtime["enabled"] or s <= 0.0:
        return
    _M_SECONDS.labels(domain="fleet", bucket=str(bucket)).inc(s)
