"""Profiling plane (ISSUE 14): always-on compile & device-memory
telemetry, plus :class:`ProfilingSession` — a ``jax.profiler.trace()``
window whose per-HLO XPlane summary is filed under the owning PR-8 span
(one instrumentation point, three sinks: span tree, flight recorder,
metrics).

Compile telemetry
-----------------
Two silent killers of a compiled fleet are watched here:

- ``jit_compiles_total{fn}`` counts every compiled-program construction
  the engine / train step report through :func:`record_compile` (labeled
  by program family: prefill, decode, verify, ...), plus every XLA
  backend compile ``jax.monitoring`` observes (``fn="backend"`` — the
  catch-all that sees dtype/shape re-traces that never miss a Python
  jit cache).
- ``jit_recompiles_total{fn}`` counts only compiles AFTER
  :func:`mark_warm` (the engine calls it at the end of ``warmup()``).
  A warm process should never compile; the ``recompile_storm`` default
  alert rule is a delta over this family.

``install_compile_hooks()`` is idempotent and lazy: ``jax.monitoring``
is imported on first use, so this module stays importable in a
stdlib-only context (same contract as ``metrics``/``scrape``).

Device-memory telemetry
-----------------------
:func:`poll_device_memory` reads ``device.memory_stats()`` per device
into ``hbm_in_use_bytes`` / ``hbm_limit_bytes`` /
``hbm_utilization_ratio`` gauges and returns the JSON shape served on
``stats()["device_memory"]`` and ``/varz``.  CPU backends return no
memory stats — the poll yields ``[]`` there, gauges untouched, so every
consumer (fleetwatch, /varz) renders a dash instead of a lie.
"""
from __future__ import annotations

import os
import tempfile
import threading
import time

from . import metrics as _metrics
from . import flight_recorder as _flight
from . import goodput as _goodput
from . import xplane as _xplane

__all__ = [
    "install_compile_hooks", "record_compile", "mark_warm", "is_warm",
    "poll_device_memory", "ProfilingSession", "BACKEND_COMPILE_EVENT",
]

#: The jax.monitoring duration event one XLA backend compile emits.
BACKEND_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"

_M_COMPILES = _metrics.counter(
    "jit_compiles_total",
    "Compiled-program constructions by family (engine jit-cache misses, "
    "the train step's first trace) plus XLA backend compiles observed "
    "via jax.monitoring (fn=\"backend\")",
    labelnames=("fn",))
_M_RECOMPILES = _metrics.counter(
    "jit_recompiles_total",
    "Compiles AFTER mark_warm() (warmup() completed) — a warm process "
    "should never compile, so any delta here is a recompilation storm",
    labelnames=("fn",))
_M_COMPILE_S = _metrics.histogram(
    "jit_compile_seconds",
    "XLA backend compile durations (jax.monitoring "
    "backend_compile_duration events)",
    buckets=(0.01, 0.05, 0.25, 1.0, 5.0, 15.0, 60.0, 300.0))
_M_LAST_COMPILE = _metrics.gauge(
    "jit_last_compile_unix_seconds",
    "Wall-clock stamp of the most recent observed compile — fleetwatch "
    "renders it as a last-compile age column")
_M_HBM_USED = _metrics.gauge(
    "hbm_in_use_bytes", "Device memory currently allocated, per device",
    labelnames=("device",))
_M_HBM_LIMIT = _metrics.gauge(
    "hbm_limit_bytes", "Device memory capacity, per device",
    labelnames=("device",))
_M_HBM_RATIO = _metrics.gauge(
    "hbm_utilization_ratio", "in_use / limit per device (0 when the "
    "backend reports no limit)",
    labelnames=("device",))
_M_PROF_SESSIONS = _metrics.counter(
    "profile_sessions_total", "ProfilingSession windows completed")
_M_PROF_EXTRACT_S = _metrics.gauge(
    "profile_extract_seconds",
    "Wall seconds spent parsing + aggregating the last session's XPlane "
    "dump")
_M_PROF_OPS = _metrics.gauge(
    "profile_ops_count",
    "Distinct HLO ops extracted from the last session's dump")

_state = {"installed": False, "warm": False}
_lock = threading.Lock()


# ------------------------------------------------------- compile telemetry
def record_compile(fn, seconds=None, warm=None):
    """One compiled-program construction of family ``fn`` (an engine
    jit-cache miss, the train step's first trace).  ``warm=None`` reads
    the process warm flag; a warm compile also counts as a recompile."""
    fn = str(fn)
    _M_COMPILES.labels(fn=fn).inc()
    _M_LAST_COMPILE.set(time.time())  # tpulint: disable=impure-trace
    if seconds is not None:
        _M_COMPILE_S.observe(float(seconds))
        # goodput ledger (ISSUE 20): backend-compile seconds are the one
        # timed compile source, carved out of the active train ledger's
        # surrounding `step` section into its `compile` bucket
        _goodput.on_compile(float(seconds))
    if _state["warm"] if warm is None else warm:
        _M_RECOMPILES.labels(fn=fn).inc()


def _on_backend_compile(duration_s):
    record_compile("backend", seconds=duration_s)


def install_compile_hooks():
    """Register the ``jax.monitoring`` backend-compile listener once
    (idempotent; safe to call from every engine/train-step __init__).
    Returns True when the listener is active."""
    with _lock:
        if _state["installed"]:
            return True
        try:
            from jax import monitoring
        except Exception:
            return False

        def listener(event, duration_secs, **_kw):
            if event == BACKEND_COMPILE_EVENT:
                _on_backend_compile(duration_secs)

        monitoring.register_event_duration_secs_listener(listener)
        _state["installed"] = True
        return True


def mark_warm(warm=True):
    """Declare the process warm: every expected program is compiled
    (``LLMEngine.warmup()`` calls this on success).  Compiles observed
    after this point land on ``jit_recompiles_total`` and trip the
    ``recompile_storm`` default alert rule."""
    _state["warm"] = bool(warm)


def is_warm():
    return _state["warm"]


# ------------------------------------------------- device-memory telemetry
def poll_device_memory(devices=None):
    """Read ``memory_stats()`` off every device into the hbm_* gauges;
    return the ``stats()["device_memory"]`` / ``/varz`` JSON shape
    (one dict per device that actually reports; ``[]`` on CPU)."""
    if devices is None:
        try:
            import jax
            devices = jax.devices()
        except Exception:
            return []
    rows = []
    for d in devices:
        try:
            ms = d.memory_stats()
        except Exception:
            ms = None
        if not ms:
            continue
        label = f"{getattr(d, 'platform', 'dev')}:{getattr(d, 'id', 0)}"
        in_use = int(ms.get("bytes_in_use", 0))
        limit = int(ms.get("bytes_limit")
                    or ms.get("bytes_reservable_limit") or 0)
        ratio = in_use / limit if limit else 0.0
        _M_HBM_USED.labels(device=label).set(in_use)
        _M_HBM_LIMIT.labels(device=label).set(limit)
        _M_HBM_RATIO.labels(device=label).set(ratio)
        rows.append({"device": label, "bytes_in_use": in_use,
                     "bytes_limit": limit,
                     "utilization": round(ratio, 6)})
    return rows


# --------------------------------------------------------- ProfilingSession
class ProfilingSession:
    """``jax.profiler.trace()`` around a window of work, with the
    extracted per-HLO summary filed three ways on exit: as child spans
    of an ``xplane_profile`` span on the owning PR-8 trace, as a flight
    recorder event, and on the ``profile_*`` gauges.

    ::

        trace = obs.start_trace("train_window")
        with ProfilingSession(trace=trace) as prof:
            for _ in range(n):
                step(batch)
        table = prof.summary          # name -> {count, total_us, ...}
        path  = prof.dump_path        # feed tools/trace_report.py --xplane

    ``logdir=None`` uses a fresh temp dir (kept — the dump is the
    artifact ``trace_report --xplane`` consumes).  A backend that cannot
    profile (no profiler plugin) degrades to an empty summary with the
    failure recorded on the span, never an exception out of ``__exit__``:
    a profiling window must not kill the workload it observes."""

    def __init__(self, logdir=None, trace=None, top_k=12):
        from . import tracing as _tracing  # local: avoid import cycle
        self.logdir = logdir or tempfile.mkdtemp(prefix="paddle_xprof_")
        self.top_k = int(top_k)
        self.trace = trace if trace is not None else _tracing.NULL_TRACE
        self.summary = None
        self.dump_path = None
        self.error = None
        self._span = None
        self._t0 = None

    def __enter__(self):
        install_compile_hooks()
        import jax
        self._span = self.trace.span("xplane_profile",
                                     logdir=self.logdir).open()
        self._t0 = time.perf_counter()
        try:
            jax.profiler.start_trace(self.logdir)
        except Exception as e:  # profiler already active / unsupported
            self.error = repr(e)
            self._span.set_attr("error", self.error)
        return self

    def __exit__(self, etype, exc, tb):
        import jax
        window_s = time.perf_counter() - self._t0
        if self.error is None:
            try:
                jax.profiler.stop_trace()
            except Exception as e:
                self.error = repr(e)
        t_extract = time.perf_counter()
        self.summary = {}
        if self.error is None:
            try:
                self.dump_path = _xplane.find_dump(self.logdir)
                self.summary = _xplane.per_op_summary(
                    _xplane.load_xspace(self.dump_path))
            except Exception as e:
                self.error = repr(e)
        extract_s = time.perf_counter() - t_extract
        top = sorted(self.summary.items(),
                     key=lambda kv: -kv[1]["total_us"])[:self.top_k]
        for name, row in top:
            self.trace.add_span(
                f"hlo:{name}", duration_s=row["total_us"] / 1e6,
                count=row["count"],
                hlo_module=row.get("hlo_module"))
        self._span.set_attr("ops_extracted", len(self.summary))
        self._span.set_attr("device_us", round(sum(
            r["total_us"] for r in self.summary.values()), 3))
        if self.dump_path:
            self._span.set_attr("dump", self.dump_path)
        if self.error is not None:
            self._span.set_attr("error", self.error)
        self._span.close()
        _M_PROF_SESSIONS.inc()
        _M_PROF_EXTRACT_S.set(extract_s)
        _M_PROF_OPS.set(len(self.summary))
        _flight.record_event(
            "xplane_profile", window_s=round(window_s, 6),
            ops=len(self.summary), dump=self.dump_path,
            error=self.error)
        return False
