"""Request-scoped tracing: per-request span trees with tail sampling.

The aggregate planes (metrics PR 2, telemetry PR 5, alerting PR 7) can say
*that* p99 TTFT is burning; nothing in the stack could say *which* request
blew the budget or *why* (a 7-chunk prefill?  a COW fork?  two
page-preempt-requeue episodes?  queue-wait behind a long prompt?).  This
module is that forensic layer — the per-request TTFT/e2e breakdowns the
Ragged Paged Attention and Gemma-on-TPU serving studies (PAPERS.md) treat
as the primary tuning signal:

- every traced operation gets a ``trace_id`` and a TREE of timed spans with
  structured attributes, carried by an EXPLICIT context object (the
  ``Trace``) — no thread-locals anywhere near jitted paths, the object
  rides on the request/supervisor that owns it;
- ONE instrumentation point lands in three sinks: the span tree here, the
  flight recorder (events gain a ``trace_id`` field), and the metrics
  registry via EXEMPLARS (``Histogram.observe(v, exemplar=trace_id)`` —
  ``render_prometheus()`` emits OpenMetrics-style ``# {trace_id="..."}``
  annotations that ``parse_prometheus()`` round-trips);
- completed traces land in a bounded in-memory :class:`TraceStore` under
  TAIL sampling: every error/shed/expired trace, every trace that was
  page-preempted/requeued, every SLO-violating trace (the `slo.py`
  targets mark violations at observe time), plus a deterministic 1-in-N
  of the healthy rest — the store can answer "show me a bad one" without
  retaining the fleet's entire traffic;
- the ``TelemetryServer`` serves the store on ``/tracez`` (list +
  fetch-by-id, JSON and chrome-trace per-trace export) and every
  flight-recorder black box gets a sibling ``traces_<reason>_*.json``
  dump, so a crash leaves the request timelines next to the event ring.

Disabled fast path (the PR-2 ``disable()`` contract): ``start_trace``
checks the same one module-level dict as every metric and returns the
:data:`NULL_TRACE` singleton — every span/attr/end call on it is a no-op
method, so instrumented hot paths stay benchmark-clean with observability
off (guarded by ``_bench_tracing`` in bench.py).

Timing discipline: span durations come from ``time.perf_counter()``
(monotonic); each trace carries ONE wall-clock stamp for joining with
external logs.

No jax / numpy imports (same contract as ``observability.metrics``).
"""
from __future__ import annotations

import itertools
import json
import os
import threading
import time
import uuid
import weakref
from collections import OrderedDict

from . import metrics as _metrics
from . import flight_recorder as _flight

__all__ = [
    "Span", "Trace", "Tracer", "TraceStore", "TRACES", "TRACER",
    "NULL_TRACE", "start_trace", "stats",
]

_M_STARTED = _metrics.counter(
    "trace_started_total", "Request-scoped traces started")
_M_SAMPLED = _metrics.counter(
    "trace_sampled_total",
    "Completed traces retained by the tail sampler, by keep reason",
    labelnames=("reason",))
_M_DROPPED = _metrics.counter(
    "trace_dropped_total",
    "Completed healthy traces dropped by the tail sampler")
_M_STORE_DEPTH = _metrics.gauge(
    "trace_store_depth", "Traces currently retained in the in-memory store")
_M_EVICTED = _metrics.counter(
    "trace_store_evictions_total",
    "Stored traces evicted by the store's ring bound")


class Span:
    """One timed node of a trace tree.  ``start_s`` is relative to the
    trace start (perf_counter delta); attributes are plain JSON-safe
    values."""

    __slots__ = ("name", "start_s", "duration_s", "attrs", "error",
                 "children")

    def __init__(self, name, start_s, attrs=None):
        self.name = str(name)
        self.start_s = float(start_s)
        self.duration_s = None  # None while open
        self.attrs = dict(attrs) if attrs else {}
        self.error = None
        self.children: list[Span] = []

    def to_dict(self):
        d = {"name": self.name, "start_s": round(self.start_s, 6),
             "duration_s": round(self.duration_s, 6)
             if self.duration_s is not None else None}
        if self.attrs:
            d["attrs"] = dict(self.attrs)
        if self.error is not None:
            d["error"] = self.error
        if self.children:
            d["children"] = [c.to_dict() for c in self.children]
        return d

    def span_count(self):
        return 1 + sum(c.span_count() for c in self.children)


class _SpanCtx:
    """Open-span handle: context manager (``with trace.span(...)``) or
    explicit ``open()``/``close()`` for spans held across engine ticks
    (a chunked-prefill admission stays open while decode ticks run)."""

    __slots__ = ("_trace", "_name", "_attrs", "span")

    def __init__(self, trace, name, attrs):
        self._trace = trace
        self._name = name
        self._attrs = attrs
        self.span = None

    def open(self):
        if self.span is None:
            self.span = self._trace._open(self._name, self._attrs)
        return self

    def close(self, error=None):
        if self.span is not None:
            self._trace._close(self.span, error=error)
            self.span = None
        return self

    def set_attr(self, key, value):
        if self.span is not None:
            self.span.attrs[str(key)] = value
        return self

    def __enter__(self):
        return self.open()

    def __exit__(self, etype, exc, tb):
        self.close(error=repr(exc) if exc is not None else None)
        return False


class Trace:
    """One traced operation: a ``trace_id`` plus a tree of spans rooted at
    the operation itself.

    The trace object IS the context: callers thread it explicitly (a
    ``_Request`` field, a supervisor local) — there is deliberately no
    ambient current-trace global, so jitted paths never consult
    thread-local state.  Span open/close through one trace must come from
    one logical thread at a time (the engine lock already serializes the
    request lifecycle); ``end()`` is idempotent and safe to race from a
    failing pump and a stopping caller.
    """

    __slots__ = ("trace_id", "name", "status", "start_unix", "duration_s",
                 "slo_violations", "sampled_reason", "root", "_t0",
                 "_stack", "_tracer", "_end_lock", "_ended")

    def __init__(self, tracer, name, attrs=None, trace_id=None):
        # an inherited id (router -> replica propagation) keeps both hops
        # of one request under a single /tracez document — the store
        # grafts same-id segments into one tree
        self.trace_id = str(trace_id) if trace_id else tracer._next_id()
        self.name = str(name)
        self.status = None  # set by end()
        # one wall stamp per trace: forensic joins with external logs share
        # NTP, not this process's boot clock (durations stay monotonic)
        self.start_unix = time.time()  # tpulint: disable=impure-trace
        self._t0 = time.perf_counter()
        self.duration_s = None
        self.slo_violations: list[str] = []
        self.sampled_reason = None  # stamped by TraceStore.offer
        self.root = Span(self.name, 0.0, attrs)
        self._stack = [self.root]
        self._tracer = tracer
        self._end_lock = threading.Lock()
        self._ended = False

    def __bool__(self):
        return True

    # ------------------------------------------------------------ spans
    def _now_s(self):
        return time.perf_counter() - self._t0

    def _open(self, name, attrs):
        sp = Span(name, self._now_s(), attrs)
        parent = self._stack[-1] if self._stack else self.root
        parent.children.append(sp)
        self._stack.append(sp)
        return sp

    def _close(self, sp, error=None):
        sp.duration_s = max(0.0, self._now_s() - sp.start_s)
        if error is not None:
            sp.error = str(error)
        # defensive unwind: closing a span closes any child left open
        while self._stack and self._stack[-1] is not sp:
            if len(self._stack) == 1:
                return  # sp was already unwound (double close)
            dangling = self._stack.pop()
            if dangling.duration_s is None:
                dangling.duration_s = max(0.0,
                                          self._now_s() - dangling.start_s)
        if len(self._stack) > 1:
            self._stack.pop()

    def span(self, name, **attrs) -> _SpanCtx:
        """A child span of the innermost open span.  Use as a context
        manager, or hold the handle and ``open()``/``close()`` it across
        engine ticks."""
        return _SpanCtx(self, name, attrs)

    def add_span(self, name, duration_s, start_s=None, **attrs):
        """Attach a pre-measured span (e.g. a coalesced decode-tick
        summary) as a child of the innermost open span."""
        sp = Span(name,
                  self._now_s() - float(duration_s)
                  if start_s is None else float(start_s), attrs)
        sp.duration_s = max(0.0, float(duration_s))
        parent = self._stack[-1] if self._stack else self.root
        parent.children.append(sp)
        return sp

    # ------------------------------------------------------- attributes
    def set_attr(self, key, value):
        self.root.attrs[str(key)] = value

    def inc_attr(self, key, amount=1):
        self.root.attrs[key] = self.root.attrs.get(key, 0) + amount

    def mark_slo(self, series):
        """Record that an observation attributed to this trace violated
        the series' SLO target — the tail sampler keeps such traces."""
        s = str(series)
        if s not in self.slo_violations:
            self.slo_violations.append(s)

    def flight(self, kind, **fields):
        """A flight-recorder event correlated to this trace."""
        _flight.record_event(kind, trace_id=self.trace_id, **fields)

    # ------------------------------------------------------------ ending
    def end(self, status="ok", **attrs):
        """Finalize the trace (idempotent): close dangling spans, stamp
        the duration and hand the trace to the tracer's store for the
        tail-sampling decision."""
        with self._end_lock:
            if self._ended:
                return self
            self._ended = True
        dur = self._now_s()
        while len(self._stack) > 1:
            dangling = self._stack.pop()
            if dangling.duration_s is None:
                dangling.duration_s = max(0.0, dur - dangling.start_s)
        self.status = str(status)
        if attrs:
            self.root.attrs.update(attrs)
        self.duration_s = dur
        self.root.duration_s = dur
        self._tracer._finish(self)
        return self

    @property
    def ended(self):
        return self._ended

    # ---------------------------------------------------------- exports
    def links(self):
        """Cross-trace links: span attributes named ``*_donor`` hold
        another trace's trace_id (the COW-fork ``prefix_donor`` stamp on
        an admission span) — collected here so `/tracez` renders a COW
        storm as a navigable graph instead of a bare attribute."""
        out = []

        def walk(sp):
            for k, v in sp.attrs.items():
                if k.endswith("_donor") and v:
                    out.append({"span": sp.name, "attr": k,
                                "trace_id": str(v)})
            for c in sp.children:
                walk(c)
        walk(self.root)
        return out

    def to_dict(self):
        d = {
            "trace_id": self.trace_id,
            "name": self.name,
            "status": self.status,
            "start_unix": self.start_unix,
            "duration_s": round(self.duration_s, 6)
            if self.duration_s is not None else None,
            "slo_violations": list(self.slo_violations),
            "sampled_reason": self.sampled_reason,
            "attrs": dict(self.root.attrs),
            "spans": [c.to_dict() for c in self.root.children],
        }
        links = self.links()
        if links:
            d["links"] = links
        return d

    def span_tree(self):
        """Nested ``[name, [children...]]`` lists — the exact-tree
        assertion helper (attribute-free, deterministic)."""
        def walk(sp):
            return [sp.name, [walk(c) for c in sp.children]]
        return [walk(c) for c in self.root.children]

    def find_spans(self, name):
        """Depth-first list of spans named ``name`` anywhere in the tree."""
        out = []

        def walk(sp):
            if sp.name == name:
                out.append(sp)
            for c in sp.children:
                walk(c)
        for c in self.root.children:
            walk(c)
        return out

    def to_chrome_trace(self):
        """This trace as a chrome://tracing document (complete 'X' events;
        nesting is conveyed by time containment on one tid)."""
        events = []

        def walk(sp):
            events.append({
                "name": sp.name, "ph": "X", "pid": 0, "tid": 0,
                "ts": sp.start_s * 1e6,
                "dur": (sp.duration_s or 0.0) * 1e6,
                "args": dict(sp.attrs),
            })
            for c in sp.children:
                walk(c)
        walk(self.root)
        return {"traceEvents": events,
                "metadata": {"trace_id": self.trace_id,
                             "status": self.status}}


class _NullSpanCtx:
    __slots__ = ()

    def open(self):
        return self

    def close(self, error=None):
        return self

    def set_attr(self, key, value):
        return self

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpanCtx()


class _NullTrace:
    """The disabled / sampled-off trace: every method is a no-op, and the
    object is falsy so call sites can skip optional work cheaply."""

    __slots__ = ()
    trace_id = ""
    name = ""
    status = None
    duration_s = None
    slo_violations = ()
    ended = True

    def __bool__(self):
        return False

    def span(self, name, **attrs):
        return _NULL_SPAN

    def add_span(self, name, duration_s, start_s=None, **attrs):
        return None

    def set_attr(self, key, value):
        pass

    def inc_attr(self, key, amount=1):
        pass

    def mark_slo(self, series):
        pass

    def flight(self, kind, **fields):
        pass

    def end(self, status="ok", **attrs):
        return self

    def to_dict(self):
        return {}

    def span_tree(self):
        return []

    def find_spans(self, name):
        return []

    def links(self):
        return []


NULL_TRACE = _NullTrace()


class TraceStore:
    """Bounded in-memory store of completed traces under TAIL sampling.

    ``offer(trace)`` keeps:

    - every trace whose terminal status is not ``"ok"`` (errors, sheds,
      deadline expiries, engine stops) — reason ``"error"``;
    - every trace that was preempted/requeued mid-flight
      (``preempt_requeues`` root attribute) — reason ``"preempted"``;
    - every trace with a recorded SLO violation (``Trace.mark_slo``, fed
      by the existing `slo.py` targets) — reason ``"slo"``;
    - a deterministic 1-in-``sample_every`` of the healthy rest — reason
      ``"tail"`` (counter-based: same traffic, same decisions).

    Stored traces evict oldest-first past ``capacity`` — the store can
    never OOM a long-running server.
    """

    def __init__(self, capacity=256, sample_every=16):
        self.capacity = max(1, int(capacity))
        self.sample_every = max(0, int(sample_every))  # 0 = no tail keeps
        self._traces: "OrderedDict[str, Trace]" = OrderedDict()
        self._lock = threading.Lock()
        self._ok_seen = 0
        # local ints so stats() works with metrics disabled (the counters
        # above are the fleet-visible mirrors)
        self.sampled = 0
        self.dropped = 0
        self.evicted = 0
        # every live store contributes to the crash-dump sibling (an
        # engine with an injected tracer must not lose its forensics)
        self._created_seq = next(_STORE_SEQ)
        _ALL_STORES.add(self)

    def __len__(self):
        with self._lock:  # finish()/eviction mutate the store concurrently
            return len(self._traces)

    def keep_reason(self, trace):
        """The tail-sampling verdict for ``trace`` (None = drop).  Does
        not consume the 1-in-N counter."""
        if trace.status is not None and trace.status != "ok":
            return "error"
        if trace.root.attrs.get("preempt_requeues") \
                or trace.root.attrs.get("restart_episodes"):
            return "preempted"  # requeued requests / restarted runs
        if trace.slo_violations:
            return "slo"
        return None

    @staticmethod
    def _graft(primary, other):
        """Merge ``other`` (a same-id segment of the same request — e.g.
        the replica-side trace of a routed call) into ``primary``'s tree
        as one child span named after ``other``.  Offsets come from the
        segments' wall stamps (the only clock two processes share)."""
        sp = Span(other.name,
                  max(0.0, other.start_unix - primary.start_unix),
                  other.root.attrs)
        sp.duration_s = other.duration_s or 0.0
        if other.status is not None and other.status != "ok":
            sp.error = other.status
        sp.children = list(other.root.children)
        primary.root.children.append(sp)
        for s in other.slo_violations:
            if s not in primary.slo_violations:
                primary.slo_violations.append(s)

    def offer(self, trace):
        """Tail-sampling decision for one completed trace.  Returns the
        keep reason, or None when the trace was dropped.

        A trace whose id is ALREADY stored is a second segment of the
        same request (inherited ids, ``Tracer.start_trace(trace_id=)``):
        it is grafted into the stored tree — earliest segment becomes the
        root (the router hop starts before the replica hop) — instead of
        overwriting it, so `/tracez` shows one document for the whole
        routed request."""
        reason = self.keep_reason(trace)
        with self._lock:
            existing = self._traces.get(trace.trace_id)
            if existing is not None and existing is not trace:
                if trace.start_unix <= existing.start_unix:
                    primary, other = trace, existing
                else:
                    primary, other = existing, trace
                self._graft(primary, other)
                primary.sampled_reason = existing.sampled_reason
                self._traces[trace.trace_id] = primary
                return primary.sampled_reason
            if reason is None:
                if self.sample_every:
                    self._ok_seen += 1
                    if self._ok_seen % self.sample_every == 0:
                        reason = "tail"
                if reason is None:
                    self.dropped += 1
                    _M_DROPPED.inc()
                    _M_STORE_DEPTH.set(len(self._traces))
                    return None
            trace.sampled_reason = reason
            self._traces[trace.trace_id] = trace
            self.sampled += 1
            while len(self._traces) > self.capacity:
                self._traces.popitem(last=False)
                self.evicted += 1
                _M_EVICTED.inc()
            depth = len(self._traces)
        _M_SAMPLED.labels(reason=reason).inc()
        _M_STORE_DEPTH.set(depth)
        return reason

    # ------------------------------------------------------------ reading
    def get_trace(self, trace_id):
        with self._lock:
            return self._traces.get(str(trace_id))

    def get(self, trace_id):
        t = self.get_trace(trace_id)
        return t.to_dict() if t is not None else None

    def list(self, limit=100):
        """Newest-first summaries (the `/tracez` index payload)."""
        with self._lock:
            traces = list(self._traces.values())
        out = []
        for t in reversed(traces[-max(0, int(limit)):] if limit else traces):
            out.append({
                "trace_id": t.trace_id, "name": t.name, "status": t.status,
                "duration_s": round(t.duration_s, 6)
                if t.duration_s is not None else None,
                "start_unix": t.start_unix,
                "spans": t.root.span_count() - 1,
                "slo_violations": list(t.slo_violations),
                "sampled_reason": t.sampled_reason,
            })
        return out

    def stats(self):
        with self._lock:
            return {"stored": len(self._traces), "capacity": self.capacity,
                    "sample_every": self.sample_every,
                    "sampled": self.sampled, "dropped": self.dropped,
                    "evicted": self.evicted}

    def clear(self):
        with self._lock:
            self._traces.clear()
        _M_STORE_DEPTH.set(0)

    # ------------------------------------------------------------ dumping
    def trace_dicts(self):
        with self._lock:
            return [t.to_dict() for t in self._traces.values()]

    def dump_json(self, path):
        """Write every stored trace as one JSON document (atomic rename,
        like every other black-box artifact)."""
        doc = {"trace_store": 1, "stats": self.stats(),
               "traces": self.trace_dicts()}
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f, separators=(",", ":"), default=repr)
        os.replace(tmp, path)
        return path


class Tracer:
    """Trace factory + the disabled fast path.

    ``start_trace`` is the single entry point: one module-dict lookup when
    observability is disabled (returns :data:`NULL_TRACE`), otherwise a
    new :class:`Trace` whose ``end()`` offers it to ``store``.
    """

    def __init__(self, store=None, enabled=True):
        self.store = store if store is not None else TraceStore()
        self.enabled = bool(enabled)
        self._run = uuid.uuid4().hex[:8]  # distinguishes process restarts
        self._seq = 0
        self._seq_lock = threading.Lock()

    @property
    def started(self):
        """Traces started (== ids handed out; read under the same lock
        the id counter advances under, so concurrent submits can't skew
        the sampling-health numbers)."""
        with self._seq_lock:
            return self._seq

    def _next_id(self):
        with self._seq_lock:
            self._seq += 1
            return f"{self._run}-{self._seq:06x}"

    def start_trace(self, name, trace_id=None, **attrs):
        """``trace_id=None`` mints a fresh id; passing one adopts it (the
        replica side of a routed request inherits the router's id so the
        store can graft both segments into one tree)."""
        if not _metrics._runtime["enabled"] or not self.enabled:
            return NULL_TRACE
        _M_STARTED.inc()
        return Trace(self, name, attrs, trace_id=trace_id)

    def _finish(self, trace):
        if self.store is not None:
            self.store.offer(trace)

    def stats(self):
        """Sampling-health snapshot (``LLMEngine.stats()["tracing"]`` /
        `/varz`): started / sampled / dropped / store occupancy."""
        return {"started": self.started, **self.store.stats()}


#: Live stores, oldest first — the crash-dump sibling snapshots ALL of
#: them, so an engine running on an injected tracer still leaves its
#: request traces next to the black box.
_ALL_STORES: "weakref.WeakSet[TraceStore]" = weakref.WeakSet()
_STORE_SEQ = itertools.count()

#: Process-global store + tracer (mirrors metrics.REGISTRY /
#: flight_recorder.RECORDER): every built-in instrumentation point traces
#: here unless handed an explicit tracer.
TRACES = TraceStore()
TRACER = Tracer(store=TRACES)


def start_trace(name, **attrs):
    return TRACER.start_trace(name, **attrs)


def stats():
    return TRACER.stats()


def _dump_sibling(directory, reason, dumpno):
    """Flight-recorder sibling hook: every black box gets the retained
    traces of EVERY live store dumped next to it (crash forensics read
    both) — an engine on an injected tracer loses nothing."""
    stores = sorted(_ALL_STORES, key=lambda s: s._created_seq)
    traces, seen = [], set()
    for store in stores:
        for t in store.trace_dicts():
            if t["trace_id"] not in seen:
                seen.add(t["trace_id"])
                traces.append(t)
    if not traces:
        return
    doc = {"trace_store": 1, "stores": len(stores), "traces": traces}
    path = os.path.join(directory, f"traces_{reason}_{dumpno:04d}.json")
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f, separators=(",", ":"), default=repr)
    os.replace(tmp, path)


_flight.register_sibling_dump(_dump_sibling)
