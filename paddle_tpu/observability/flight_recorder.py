"""Black-box flight recorder: a bounded ring of structured events that
survives to disk when the process crashes.

The serving/training stack already *counts* failures (metrics) and *times*
them (spans); what a postmortem needs is the ORDER of the last few thousand
things that happened before the crash — which span was open, which store op
retried, which slot shed — the aviation-flight-recorder role Piper's
distributed-training telemetry and the Gemma serving comparison (PAPERS.md)
assign to their event logs.

Design constraints:

- **lock-cheap**: ``record()`` is on hot paths (every span close, every
  shed).  The disabled fast path is the same one dict lookup as
  ``metrics.disable()``; the enabled path is one ``deque.append`` under a
  lock held for the append only (no I/O, no formatting).
- **bounded**: a ``deque(maxlen=capacity)`` — old events fall off the back,
  the recorder can never OOM a long-running server.
- **dump-on-demand, not log-continuously**: ``dump()`` writes one JSONL
  file (header line + events, oldest first) and, when the native
  chrome-trace buffer has spans, a sibling ``*.trace.json`` — the pair an
  operator loads after a crash.  `run_with_recovery` and `LLMEngine` call
  it on unhandled exceptions, ``Preemption`` and watchdog trips so every
  crash/restart leaves a black box next to the checkpoint dir.

No jax / numpy imports: importable from any layer (same contract as
``observability.metrics``).
"""
from __future__ import annotations

import json
import os
import threading
import time
from collections import deque

from . import metrics as _metrics

__all__ = [
    "FlightRecorder", "RECORDER", "record_event", "dump", "safe_dump",
    "events", "clear", "register_sibling_dump",
]

#: Best-effort sibling writers invoked after every dump() with
#: ``(directory, reason_slug, dumpno)`` — how the trace store lands its
#: ``traces_<reason>_*.json`` next to each black box without this module
#: importing it (tracing imports flight_recorder, not the reverse).
_SIBLING_DUMPERS: list = []


def register_sibling_dump(fn):
    _SIBLING_DUMPERS.append(fn)
    return fn

_M_EVENTS = _metrics.counter(
    "flight_recorder_events_total",
    "Events appended to the flight-recorder ring")
_M_DROPPED = _metrics.counter(
    "flight_recorder_dropped_total",
    "Events that pushed an older one off the bounded ring")
_M_DUMPS = _metrics.counter(
    "flight_recorder_dumps_total",
    "Flight-recorder dumps written to disk", labelnames=("reason",))


class FlightRecorder:
    """Bounded ring buffer of structured events.

    Each event is a plain dict: ``{"seq", "time", "mono", "kind", ...}`` —
    ``time`` is wall-clock (forensic joins with external logs), ``mono`` the
    monotonic stamp (ordering/durations within the process).
    """

    def __init__(self, capacity=4096):
        self.capacity = int(capacity)
        self._events: deque = deque(maxlen=self.capacity)
        self._lock = threading.Lock()
        self._seq = 0
        self._dumps = 0  # advances on every dump(), even with metrics off

    def record(self, kind, **fields):
        """Append one event.  One dict lookup when observability is
        disabled; one locked deque.append when enabled."""
        if not _metrics._runtime["enabled"]:
            return
        evt = {"time": time.time(),  # tpulint: disable=impure-trace
               "mono": time.monotonic(), "kind": str(kind)}
        if fields:
            evt.update(fields)
        with self._lock:
            self._seq += 1
            evt["seq"] = self._seq
            if len(self._events) == self.capacity:
                _M_DROPPED.inc()
            self._events.append(evt)
        _M_EVENTS.inc()

    def events(self):
        """Snapshot of the ring, oldest first."""
        with self._lock:
            return list(self._events)

    def clear(self):
        with self._lock:
            self._events.clear()

    def __len__(self):
        with self._lock:  # the deque resizes under concurrent record()s
            return len(self._events)

    # ------------------------------------------------------------- dumping
    def dump(self, directory, reason="manual", extra=None, trace=True):
        """Write the black box: ``flight_<reason>_<dumpno>_<seq>.jsonl`` in
        ``directory`` (created if missing) — a header line
        ``{"flight_recorder": ..., "reason": ..., "pid": ...}`` followed by
        one event per line, oldest first — plus, when the native trace
        buffer holds spans, a sibling ``.trace.json`` chrome trace.

        Returns the JSONL path.  Raises OSError on an unwritable target
        (crash paths go through :func:`safe_dump` instead).  The per-dump
        counter keeps names unique even when observability is disabled and
        the event seq therefore never advances — a later crash must not
        overwrite an earlier black box.
        """
        os.makedirs(directory, exist_ok=True)
        evts = self.events()
        with self._lock:
            seq = self._seq
            self._dumps += 1
            dumpno = self._dumps
        name = f"flight_{_slug(reason)}_{dumpno:04d}_{seq:08d}.jsonl"
        path = os.path.join(directory, name)
        header = {
            "flight_recorder": 1,
            "reason": str(reason),
            "pid": os.getpid(),
            "time": time.time(),  # tpulint: disable=impure-trace
            "events": len(evts),
            "capacity": self.capacity,
        }
        if extra:
            header["extra"] = dict(extra)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            f.write(json.dumps(header, separators=(",", ":")) + "\n")
            for e in evts:
                f.write(json.dumps(e, separators=(",", ":"),
                                   default=repr) + "\n")
        os.replace(tmp, path)  # a torn dump must not look complete
        if trace:
            doc = _native_trace_json()
            if doc is not None:
                with open(path[:-len(".jsonl")] + ".trace.json", "w") as f:
                    f.write(doc)
        for hook in list(_SIBLING_DUMPERS):
            try:
                hook(directory, _slug(reason), dumpno)
            except Exception:
                pass  # a sibling writer must never break the black box
        _M_DUMPS.labels(reason=_slug(reason)).inc()
        return path

    def to_chrome_trace(self):
        """Span-close events as a chrome://tracing document (complete 'X'
        events) — lets ``tools/trace_report.py`` consume a flight dump as a
        timeline even when the native trace buffer was off."""
        out = []
        for e in self.events():
            if e.get("kind") != "span" or "duration_s" not in e:
                continue
            dur_us = float(e["duration_s"]) * 1e6
            out.append({
                "name": e.get("name", "?"), "ph": "X", "pid": os.getpid(),
                "tid": 0, "ts": float(e["mono"]) * 1e6 - dur_us,
                "dur": dur_us,
            })
        return {"traceEvents": out}


def _slug(s):
    return "".join(c if (c.isalnum() or c == "_") else "_"
                   for c in str(s))[:48] or "event"


def _native_trace_json():
    """Chrome-trace JSON from the native host-trace buffer, or None when the
    buffer is unavailable/empty (no toolchain, profiler never enabled)."""
    try:
        from ..profiler import _tracer
        tr = _tracer()
        if tr is None or not tr.count():
            return None
        return tr.dump_json()
    except Exception:
        return None


#: Process-global recorder: every built-in instrumentation point records
#: here; crash handlers dump it.
RECORDER = FlightRecorder()


def record_event(kind, **fields):
    RECORDER.record(kind, **fields)


def dump(directory, reason="manual", extra=None, trace=True):
    return RECORDER.dump(directory, reason=reason, extra=extra, trace=trace)


def safe_dump(directory, reason="crash", extra=None, recorder=None):
    """Crash-path dump: best-effort, NEVER raises — the crash that
    triggered the dump must stay the propagating exception.  A failed dump
    is recorded as a ``flight_dump_failed`` event (visible to a later
    successful dump) instead.  Returns the path or None.  No-op when
    ``directory`` is falsy."""
    if not directory:
        return None
    rec = recorder if recorder is not None else RECORDER
    try:
        return rec.dump(directory, reason=reason, extra=extra)
    except Exception as dump_err:
        rec.record("flight_dump_failed", error=repr(dump_err))
        return None


def events():
    return RECORDER.events()


def clear():
    RECORDER.clear()
