"""Dependency-free reader for the ``.xplane.pb`` dumps ``jax.profiler``
writes — the device-tracer half of the profiling plane (ISSUE 14).

``jax.profiler.trace(logdir)`` (and ``start_trace``/``stop_trace``)
serializes an XSpace protobuf under
``<logdir>/plugins/profile/<run>/<host>.xplane.pb``: per-device planes of
per-HLO events with picosecond timings — the ground truth the census
cost model (``distributed.census.per_op_census``) wants to be joined
against.  Importing tensorflow (or protobuf) for the schema would drag a
second framework into the image, so this module hand-rolls the protobuf
wire format the same way ``scrape.py`` hand-rolls the Prometheus text
format: stdlib only, one pass per message, strict about what it
understands and silent about what it doesn't (unknown fields are legal
protobuf and are skipped, not errors).

Wire format notes (README §Observability, "Profiling plane"):

- A protobuf message is a flat sequence of ``(tag, payload)`` records;
  ``tag = field_number << 3 | wire_type``.  Wire types used by XSpace:
  0 = varint, 1 = fixed 64-bit (doubles), 2 = length-delimited
  (strings, nested messages, maps).
- Field numbers (``tsl/profiler/protobuf/xplane.proto``):
  XSpace.planes=1; XPlane id=1 name=2 lines=3 event_metadata=4
  stat_metadata=5 stats=6; XLine id=1 name=2 timestamp_ns=3 events=4
  duration_ps=9 display_name=11; XEvent metadata_id=1 offset_ps=2
  duration_ps=3 stats=4 num_occurrences=5; XStat metadata_id=1
  double_value=2 uint64_value=3 int64_value=4 str_value=5 bytes_value=6
  ref_value=7; X{Event,Stat}Metadata id=1 name=2.
- Map fields (``event_metadata``/``stat_metadata``) encode each entry as
  a nested message with key=1, value=2.
- ``ref_value`` is string interning: the stat's value is the NAME of the
  stat_metadata entry it points at (XLA uses it for ``hlo_op`` /
  ``hlo_category`` strings repeated across thousands of events).
- int64 fields are plain varints; negatives arrive as 10-byte two's
  complement, so a decoded value >= 2**63 folds down by 2**64.

Event timings are ``line.timestamp_ns`` + ``event.offset_ps``, lasting
``event.duration_ps``.  On TPU the interesting planes are
``/device:TPU:*``; a CPU run (what tier-1 exercises) has the same ops on
the ``/host:CPU`` plane's XLA-client lines (``tf_XLA...`` /
``TfrtCpuClient``), with the per-op ``hlo_op`` / ``hlo_module`` /
``program_id`` stats resolved through the metadata maps either way.

No jax / numpy imports (same contract as ``observability.metrics``) —
the parser must be loadable in a stdlib-only context.
"""
from __future__ import annotations

import os
import struct
from collections import OrderedDict

__all__ = [
    "XStat", "XEvent", "XLine", "XPlane", "XSpace",
    "parse_xspace", "load_xspace", "find_dump",
    "iter_events", "per_op_summary", "to_timeline",
]

_WIRE_VARINT, _WIRE_FIXED64, _WIRE_LEN, _WIRE_FIXED32 = 0, 1, 2, 5


# ------------------------------------------------------------ wire reading
def _read_varint(buf, pos, end):
    """Little-endian base-128 varint at ``pos`` -> (value, next_pos)."""
    result = 0
    shift = 0
    while True:
        if pos >= end:
            raise ValueError("truncated varint")
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7
        if shift > 63:
            raise ValueError("varint wider than 64 bits")


def _fields(buf, pos, end):
    """Yield ``(field_number, wire_type, value)`` records of one message.

    ``value`` is an int for varints, a float for fixed64 (every fixed64
    in xplane.proto is a double), and a ``(start, end)`` byte span for
    length-delimited payloads — spans keep nested decoding copy-free."""
    while pos < end:
        tag, pos = _read_varint(buf, pos, end)
        field, wire = tag >> 3, tag & 7
        if wire == _WIRE_VARINT:
            value, pos = _read_varint(buf, pos, end)
        elif wire == _WIRE_LEN:
            size, pos = _read_varint(buf, pos, end)
            if pos + size > end:
                raise ValueError(
                    f"length-delimited field {field} overruns the buffer")
            value = (pos, pos + size)
            pos += size
        elif wire == _WIRE_FIXED64:
            if pos + 8 > end:
                raise ValueError(f"truncated fixed64 field {field}")
            value = struct.unpack_from("<d", buf, pos)[0]
            pos += 8
        elif wire == _WIRE_FIXED32:
            if pos + 4 > end:
                raise ValueError(f"truncated fixed32 field {field}")
            value = struct.unpack_from("<f", buf, pos)[0]
            pos += 4
        else:  # groups (3/4) predate proto3; XLA never emits them
            raise ValueError(f"unsupported wire type {wire} "
                             f"(field {field})")
        yield field, wire, value


def _int64(v):
    """Fold a 64-bit varint into a signed int (negatives arrive as
    two's complement)."""
    return v - (1 << 64) if v >= (1 << 63) else v


def _text(buf, span):
    return bytes(buf[span[0]:span[1]]).decode("utf-8", "replace")


# ------------------------------------------------------- decoded structure
class XStat:
    """One resolved stat: metadata name + the oneof value (int, float,
    str or bytes; ``ref_value`` already chased to its interned string)."""

    __slots__ = ("name", "value")

    def __init__(self, name, value):
        self.name = name
        self.value = value

    def __repr__(self):
        return f"XStat({self.name}={self.value!r})"


class XEvent:
    __slots__ = ("name", "offset_ps", "duration_ps", "num_occurrences",
                 "stats")

    def __init__(self):
        self.name = ""
        self.offset_ps = 0
        self.duration_ps = 0
        self.num_occurrences = 0  # aggregated-event form (offset absent)
        self.stats = {}  # stat name -> resolved value

    @property
    def duration_us(self):
        return self.duration_ps / 1e6


class XLine:
    __slots__ = ("id", "name", "display_name", "timestamp_ns",
                 "duration_ps", "events")

    def __init__(self):
        self.id = 0
        self.name = ""
        self.display_name = ""
        self.timestamp_ns = 0
        self.duration_ps = 0
        self.events = []


class XPlane:
    __slots__ = ("id", "name", "lines", "stats")

    def __init__(self):
        self.id = 0
        self.name = ""
        self.lines = []
        self.stats = {}  # plane-level stats, resolved


class XSpace:
    __slots__ = ("planes", "hostnames")

    def __init__(self):
        self.planes = []
        self.hostnames = []


# ------------------------------------------------------------ message walk
def _decode_metadata_map(buf, span):
    """An ``event_metadata``/``stat_metadata`` map entry -> (id, name).

    Entry: key=1 (varint id), value=2 (XEventMetadata/XStatMetadata,
    whose own fields are id=1, name=2)."""
    key, name = 0, ""
    for field, wire, value in _fields(buf, *span):
        if field == 1 and wire == _WIRE_VARINT:
            key = value
        elif field == 2 and wire == _WIRE_LEN:
            for f2, w2, v2 in _fields(buf, *value):
                if f2 == 1 and w2 == _WIRE_VARINT:
                    key = key or v2  # metadata carries its own id too
                elif f2 == 2 and w2 == _WIRE_LEN:
                    name = _text(buf, v2)
    return key, name


def _decode_stat(buf, span, stat_meta):
    """XStat -> resolved ``XStat`` (ref_value chased through the
    stat_metadata name table)."""
    name, value = "", None
    for field, wire, v in _fields(buf, *span):
        if field == 1 and wire == _WIRE_VARINT:  # metadata_id
            name = stat_meta.get(v, f"stat_{v}")
        elif field == 2:                          # double_value
            value = v
        elif field == 3 and wire == _WIRE_VARINT:  # uint64_value
            value = v
        elif field == 4 and wire == _WIRE_VARINT:  # int64_value
            value = _int64(v)
        elif field == 5 and wire == _WIRE_LEN:     # str_value
            value = _text(buf, v)
        elif field == 6 and wire == _WIRE_LEN:     # bytes_value
            value = bytes(buf[v[0]:v[1]])
        elif field == 7 and wire == _WIRE_VARINT:  # ref_value -> interned
            value = stat_meta.get(v, f"ref_{v}")
    return XStat(name, value)


def _decode_event(buf, span, event_meta, stat_meta):
    ev = XEvent()
    for field, wire, v in _fields(buf, *span):
        if field == 1 and wire == _WIRE_VARINT:    # metadata_id
            ev.name = event_meta.get(v, f"event_{v}")
        elif field == 2 and wire == _WIRE_VARINT:  # offset_ps (oneof)
            ev.offset_ps = _int64(v)
        elif field == 3 and wire == _WIRE_VARINT:  # duration_ps
            ev.duration_ps = _int64(v)
        elif field == 4 and wire == _WIRE_LEN:     # stats
            st = _decode_stat(buf, v, stat_meta)
            ev.stats[st.name] = st.value
        elif field == 5 and wire == _WIRE_VARINT:  # num_occurrences (oneof)
            ev.num_occurrences = v
    return ev


def _decode_line(buf, span, event_meta, stat_meta):
    ln = XLine()
    for field, wire, v in _fields(buf, *span):
        if field == 1 and wire == _WIRE_VARINT:
            ln.id = _int64(v)
        elif field == 2 and wire == _WIRE_LEN:
            ln.name = _text(buf, v)
        elif field == 3 and wire == _WIRE_VARINT:
            ln.timestamp_ns = _int64(v)
        elif field == 4 and wire == _WIRE_LEN:
            ln.events.append(_decode_event(buf, v, event_meta, stat_meta))
        elif field == 9 and wire == _WIRE_VARINT:
            ln.duration_ps = _int64(v)
        elif field == 11 and wire == _WIRE_LEN:
            ln.display_name = _text(buf, v)
    return ln


def _decode_plane(buf, span):
    """Two passes: serializers write fields in number order so the
    metadata maps (fields 4/5) trail the lines (field 3) — collect raw
    line spans first, resolve names second."""
    plane = XPlane()
    line_spans, stat_spans = [], []
    event_meta, stat_meta = {}, {}
    for field, wire, v in _fields(buf, *span):
        if field == 1 and wire == _WIRE_VARINT:
            plane.id = v
        elif field == 2 and wire == _WIRE_LEN:
            plane.name = _text(buf, v)
        elif field == 3 and wire == _WIRE_LEN:
            line_spans.append(v)
        elif field == 4 and wire == _WIRE_LEN:
            k, name = _decode_metadata_map(buf, v)
            event_meta[k] = name
        elif field == 5 and wire == _WIRE_LEN:
            k, name = _decode_metadata_map(buf, v)
            stat_meta[k] = name
        elif field == 6 and wire == _WIRE_LEN:
            stat_spans.append(v)
    for s in stat_spans:
        st = _decode_stat(buf, s, stat_meta)
        plane.stats[st.name] = st.value
    for s in line_spans:
        plane.lines.append(_decode_line(buf, s, event_meta, stat_meta))
    return plane


def parse_xspace(data) -> XSpace:
    """Parse serialized XSpace bytes -> :class:`XSpace`.

    Concatenated serializations merge (standard protobuf semantics:
    repeated fields accumulate) — ``parse_xspace(a + b)`` sees both
    dumps' planes."""
    buf = memoryview(bytes(data))
    space = XSpace()
    for field, wire, v in _fields(buf, 0, len(buf)):
        if field == 1 and wire == _WIRE_LEN:
            space.planes.append(_decode_plane(buf, v))
        elif field == 4 and wire == _WIRE_LEN:
            space.hostnames.append(_text(buf, v))
    return space


# --------------------------------------------------------------- file I/O
def find_dump(path):
    """Resolve ``path`` to one ``.xplane.pb`` file.

    A file path is returned as-is; a directory (a profiler ``logdir`` or
    any parent of ``plugins/profile/<run>/``) is searched recursively and
    the newest dump wins (ties broken by name, so the pick is
    deterministic under equal mtimes)."""
    if os.path.isfile(path):
        return path
    best = None
    for root, _dirs, files in os.walk(path):
        for fn in files:
            if fn.endswith(".xplane.pb"):
                full = os.path.join(root, fn)
                key = (os.path.getmtime(full), full)
                if best is None or key > best[0]:
                    best = (key, full)
    if best is None:
        raise FileNotFoundError(
            f"no .xplane.pb under {path!r} — did the profiler session "
            f"actually run (jax.profiler.trace writes "
            f"<logdir>/plugins/profile/<run>/<host>.xplane.pb)?")
    return best[1]


def load_xspace(path) -> XSpace:
    """``find_dump`` + ``parse_xspace``."""
    with open(find_dump(path), "rb") as f:
        return parse_xspace(f.read())


# ----------------------------------------------------------- op extraction
#: Host-plane lines that are Python/runtime bookkeeping, never HLO ops.
_HOST_NOISE_LINES = ("python", "TensorFlow Name Scope", "TensorFlow Ops",
                     "Launch Stats", "Steps", "Framework Name Scope")


def _op_lines(space):
    """The (plane, line) pairs whose events are per-HLO op executions.

    Device planes (``/device:...``) win when present (a real TPU run);
    otherwise the ``/host:CPU`` plane's XLA-client lines (the TFRT
    thread-pool lines a CPU run records) carry the same events."""
    device = [(p, ln) for p in space.planes
              if p.name.startswith("/device:") for ln in p.lines]
    if device:
        return device
    return [(p, ln) for p in space.planes if p.name == "/host:CPU"
            for ln in p.lines if ln.name not in _HOST_NOISE_LINES]


def iter_events(space, lines=None):
    """Yield ``(plane, line, event)`` over the per-HLO op lines (or an
    explicit ``lines`` list of (plane, line) pairs)."""
    for plane, line in (lines if lines is not None else _op_lines(space)):
        for ev in line.events:
            yield plane, line, ev


def per_op_summary(space) -> "OrderedDict[str, dict]":
    """Aggregate the op lines into ``name -> {count, total_us,
    hlo_module, program_id}`` (insertion-ordered by first appearance).

    The keys are XLA HLO instruction names (``dot.3``, ``fusion.12``) —
    exactly the namespace ``census.per_op_census`` emits, so the
    ``trace_report`` join needs no fuzzy matching for same-program runs.
    Events that carry an ``hlo_op`` stat differing from their own name
    (device planes nest kernels under op metadata) aggregate under the
    stat."""
    out: "OrderedDict[str, dict]" = OrderedDict()
    for _plane, _line, ev in iter_events(space):
        name = ev.stats.get("hlo_op") or ev.name
        if not name:
            continue
        row = out.setdefault(str(name), {
            "count": 0, "total_us": 0.0, "hlo_module": None,
            "program_id": None})
        row["count"] += max(1, int(ev.num_occurrences or 1))
        row["total_us"] += ev.duration_ps / 1e6
        if row["hlo_module"] is None and "hlo_module" in ev.stats:
            row["hlo_module"] = str(ev.stats["hlo_module"])
        if row["program_id"] is None and "program_id" in ev.stats:
            row["program_id"] = ev.stats["program_id"]
    return out


def to_timeline(path_or_space) -> "OrderedDict[str, dict]":
    """The ``trace_report.load_timeline`` shape (``name -> {count,
    total_us, ...}``) straight from a dump path / logdir / parsed space —
    the ``--xplane`` entry point."""
    space = path_or_space if isinstance(path_or_space, XSpace) \
        else load_xspace(path_or_space)
    return per_op_summary(space)
