"""Alert-rule engine: declarative rules over scraped (or local) samples,
a deterministic hysteresis state machine, and actuation policies.

The decide half of the alerting plane's sense -> decide -> act loop
(ISSUE 7).  Rules are declarative and data-only (JSON round-trip via
``Rule.to_dict``/``from_dict``); evaluation is a pure function of a
:class:`~paddle_tpu.observability.scrape.SampleSet` and an injected clock,
so the golden transition tests replay exactly.

Rule kinds:

- ``threshold`` — instant comparison of every matching sample against a
  bound (``llm_queue_depth > 64``, ``healthcheck_status_value < 1``);
- ``burn_rate`` — sugar for a threshold over ``slo_burn_rate_ratio`` (the
  PR-5 SLO gauges: violating fraction of the current window per series);
- ``absence`` — fires for label sets that were seen on an earlier
  evaluation and have since disappeared (a replica that stopped reporting),
  plus the rule's own explicit selector when the family matches nothing —
  staleness alerting composes with the scraper's
  ``scrape_staleness_seconds`` threshold rules.  ``window_s`` doubles as
  the absence TTL: after firing-absent that long the label set is taken
  as decommissioned (scale-in) and forgotten, so the alert resolves
  instead of paging forever and the engine stays bounded under label
  churn;
- ``delta`` — increase of a counter over a sliding window (counter resets
  tolerated: only positive inter-sample deltas accumulate), e.g. a rising
  ``recovery_restarts_total``.

Each rule instance (one per distinct matched label set) walks a
deterministic state machine::

    inactive -> pending   condition true, ``for_s`` hysteresis running
    pending  -> firing    condition held for ``for_s`` (``for_s=0`` skips
                          pending entirely)
    pending  -> inactive  condition cleared before ``for_s`` elapsed
    firing   -> resolved  condition cleared
    resolved -> pending   condition true again (re-fire / flap)
    resolved -> inactive  quiet for ``resolved_hold_s``

State is exported as ``alert_state_value{alert}`` (0 inactive, 1 resolved,
2 pending, 3 firing — max over the rule's instances, so firing dominates),
every transition lands in the flight recorder and an optional JSONL log,
and ``TelemetryServer`` serves the full engine state on ``/alertz``.

Actuation: :class:`AlertPolicy` maps alert names to actions (``restart``,
``quarantine``, ``widen_deadline``, or a callable) and emits one
:class:`AlertDecision` per firing EPISODE (a flapping alert re-decides only
after re-firing, never once per poll).  ``run_with_recovery`` and
``ElasticManager`` consume decisions — the restart wiring PRs 2/5 left
open.

No jax / numpy imports (same contract as ``observability.metrics``).
"""
from __future__ import annotations

import json
import threading
import time
from collections import deque

from . import metrics as _metrics
from . import flight_recorder as _flight
from .scrape import SampleSet

__all__ = [
    "Rule", "AlertEngine", "AlertPolicy", "AlertDecision", "default_rules",
    "JsonlNotifier",
    "STATE_INACTIVE", "STATE_RESOLVED", "STATE_PENDING", "STATE_FIRING",
    "STATE_VALUES", "ACTIONS",
]

#: Exported state encoding: higher = worse, so a max over instances keeps
#: firing visible while a sibling instance idles.
STATE_INACTIVE = "inactive"
STATE_RESOLVED = "resolved"
STATE_PENDING = "pending"
STATE_FIRING = "firing"
STATE_VALUES = {STATE_INACTIVE: 0, STATE_RESOLVED: 1,
                STATE_PENDING: 2, STATE_FIRING: 3}

#: Actions an AlertPolicy can map a firing alert to (besides a callable).
ACTIONS = ("restart", "quarantine", "widen_deadline")

_KINDS = ("threshold", "burn_rate", "absence", "delta")
_OPS = {
    ">": lambda a, b: a > b,
    ">=": lambda a, b: a >= b,
    "<": lambda a, b: a < b,
    "<=": lambda a, b: a <= b,
    "==": lambda a, b: a == b,
    "!=": lambda a, b: a != b,
}

_M_STATE = _metrics.gauge(
    "alert_state_value",
    "Worst state across the rule's instances "
    "(0 inactive, 1 resolved, 2 pending, 3 firing)",
    labelnames=("alert",))
_M_TRANSITIONS = _metrics.counter(
    "alert_transitions_total",
    "Alert-instance state transitions, by entered state",
    labelnames=("alert", "state"))
_M_EVAL = _metrics.histogram(
    "alert_evaluation_seconds",
    "Wall time of one AlertEngine.evaluate() tick")
_M_ACTIONS = _metrics.counter(
    "alert_actions_total",
    "Actuation decisions emitted by AlertPolicy, by action",
    labelnames=("alert", "action"))
_M_NOTIFY = _metrics.counter(
    "alert_notifications_total",
    "Alert state transitions shipped through the notify hook")
_M_NOTIFY_FAIL = _metrics.counter(
    "alert_notify_failures_total",
    "notify-hook deliveries that raised (transition kept, not retried)")


class Rule:
    """One declarative alert rule.  Pure data + a condition evaluator;
    all state (hysteresis clocks, delta windows) lives in the engine."""

    def __init__(self, name, metric=None, kind="threshold", labels=None,
                 op=">", threshold=0.0, for_s=0.0, window_s=300.0,
                 resolved_hold_s=300.0, severity="page", description=""):
        if kind not in _KINDS:
            raise ValueError(f"rule kind must be one of {_KINDS}, "
                             f"got {kind!r}")
        if op not in _OPS:
            raise ValueError(f"rule op must be one of {sorted(_OPS)}, "
                             f"got {op!r}")
        if kind == "burn_rate" and metric is None:
            metric = "slo_burn_rate_ratio"
        if metric is None:
            raise ValueError(f"rule {name!r} needs a metric")
        self.name = str(name)
        self.kind = kind
        self.metric = str(metric)
        self.labels = {str(k): str(v) for k, v in (labels or {}).items()}
        self.op = op
        self.threshold = float(threshold)
        self.for_s = float(for_s)
        self.window_s = float(window_s)
        self.resolved_hold_s = float(resolved_hold_s)
        self.severity = str(severity)
        self.description = str(description)

    def to_dict(self):
        return {"name": self.name, "kind": self.kind, "metric": self.metric,
                "labels": dict(self.labels), "op": self.op,
                "threshold": self.threshold, "for_s": self.for_s,
                "window_s": self.window_s,
                "resolved_hold_s": self.resolved_hold_s,
                "severity": self.severity, "description": self.description}

    _FIELDS = ("name", "kind", "metric", "labels", "op", "threshold",
               "for_s", "window_s", "resolved_hold_s", "severity",
               "description")

    @classmethod
    def from_dict(cls, d):
        d = dict(d)
        unknown = set(d) - set(cls._FIELDS)
        if unknown:
            # a typo ("for", "treshold") must not silently yield a rule
            # with zero hysteresis/threshold — this is the operator path
            raise ValueError(
                f"rule {d.get('name', '?')!r} has unknown fields "
                f"{sorted(unknown)}; valid fields: {cls._FIELDS}")
        return cls(**d)

    def __repr__(self):
        return (f"Rule({self.name!r}, {self.kind}: {self.metric}"
                f"{self.labels or ''} {self.op} {self.threshold})")


def default_rules(queue_depth=64, burn_rate=0.5, staleness_s=60.0,
                  restart_window_s=600.0):
    """The stock rule set over the existing README catalogue: SLO burn
    rate, component healthchecks (including the LLM pump heartbeat-age
    check), store deadline pressure, serving backlog, recovery restart
    storms, post-warmup recompilation storms, roofline residual
    regressions, sustained goodput degradation, and the scraper's own
    target liveness/staleness."""
    return [
        Rule("slo_burn_rate_high", kind="burn_rate", threshold=burn_rate,
             for_s=30.0,
             description="an SLO series is burning error budget: the "
                         "violating fraction of its sliding window exceeds "
                         f"{burn_rate}"),
        Rule("healthcheck_failing", metric="healthcheck_status_value",
             op="<", threshold=1.0, for_s=15.0,
             description="a registered component healthcheck (pump "
                         "liveness, pump heartbeat age, last-step age, "
                         "rank liveness) reports failing"),
        Rule("store_deadline_pressure", kind="delta",
             metric="store_deadline_hits_total", op=">", threshold=0.0,
             window_s=120.0, for_s=0.0, severity="ticket",
             description="control-plane store ops started missing their "
                         "per-op deadlines within the last window"),
        Rule("llm_queue_backlog", metric="llm_queue_depth", op=">",
             threshold=float(queue_depth), for_s=30.0,
             description="serving admission queue persistently deeper "
                         f"than {queue_depth} (shedding is next)"),
        Rule("recovery_restart_storm", kind="delta",
             metric="recovery_restarts_total", op=">", threshold=2.0,
             window_s=restart_window_s, for_s=0.0,
             description="run_with_recovery restarted more than twice "
                         "inside the window — the job is crash-looping"),
        Rule("recompile_storm", kind="delta",
             metric="jit_recompiles_total", op=">", threshold=0.0,
             window_s=300.0, for_s=0.0,
             description="an XLA program compiled AFTER the process "
                         "declared itself warm (warmup() finished) — "
                         "shape/dtype churn is eating device time on "
                         "recompiles"),
        Rule("roofline_regression", kind="delta",
             metric="roofline_regressions_total", op=">", threshold=0.0,
             window_s=3600.0, for_s=0.0, severity="ticket",
             description="the roofline sentinel (roofline_report --diff / "
                         "roofline.record_diff) flagged an op whose "
                         "measured-vs-predicted residual regressed past "
                         "threshold within the window"),
        # exported_target="" matches only THIS scraper's own liveness
        # samples, never a target's re-exported view of its own fleet
        # (scrape.SampleSet.match: empty selector value = label absent)
        Rule("scrape_target_down", metric="scrape_target_up",
             labels={"exported_target": ""}, op="<",
             threshold=1.0, for_s=10.0,
             description="a fleet scrape target stopped answering "
                         "/metrics"),
        Rule("scrape_target_stale", metric="scrape_staleness_seconds",
             labels={"exported_target": ""},
             op=">", threshold=float(staleness_s), for_s=0.0,
             severity="ticket",
             description="no successful scrape of the target for "
                         f"{staleness_s}s"),
        Rule("telemetry_absent", kind="absence",
             metric="exporter_scrapes_total", for_s=30.0, severity="ticket",
             description="a previously-reporting telemetry exporter's "
                         "series vanished from the scrape"),
        # absence of the family never fires this (threshold rules skip
        # targets without samples) — only a ledger that IS reporting and
        # IS mostly waste trips it
        Rule("goodput_degraded", metric="goodput_ratio", op="<",
             threshold=0.5, for_s=60.0, severity="ticket",
             description="a goodput ledger reports less than half its "
                         "wall clock in productive buckets (step / "
                         "decode+prefill+verify) for a sustained minute — "
                         "restarts, preemption recompute, or spec "
                         "rollback are eating the fleet"),
    ]


def _labelkey(labels: dict) -> tuple:
    return tuple(sorted(labels.items()))


class _Instance:
    """Mutable per-(rule, label set) state-machine cell."""

    __slots__ = ("labels", "state", "since", "pending_since",
                 "resolved_since", "value", "episodes")

    def __init__(self, labels, now):
        self.labels = dict(labels)
        self.state = STATE_INACTIVE
        self.since = now
        self.pending_since = None
        self.resolved_since = None
        self.value = None
        self.episodes = 0  # completed transitions INTO firing


class AlertEngine:
    """Evaluate rules against successive SampleSets; deterministic under an
    injected clock (every `for`/window/hold comparison uses the ``now``
    passed to :meth:`evaluate`, defaulting to ``clock()``).

    Thread-safety: evaluation and state reads share one lock, so a live
    ``/alertz`` scrape never sees a half-applied transition.
    """

    def __init__(self, rules=None, clock=time.monotonic, log_path=None,
                 recorder=None, registry=None, notify=None):
        """``notify`` — the push-style transition shipper: a callable
        invoked with each transition dict (now carrying any correlated
        exemplar ``trace_ids``), or a path string (sugar for
        :class:`JsonlNotifier`).  Runs OUTSIDE the engine lock after each
        evaluate; a raising notifier is counted
        (``alert_notify_failures_total``) and recorded in the flight
        recorder, never propagated — and since transitions only exist on
        state CHANGES, the stream is flap-safe by the same
        one-transition-per-episode machinery the actuation path uses."""
        self.rules = list(rules if rules is not None else default_rules())
        names = [r.name for r in self.rules]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate rule names: {sorted(names)}")
        self.clock = clock
        self.log_path = log_path
        self.recorder = recorder  # None -> module-global flight recorder
        self._registry = registry
        self.notify = JsonlNotifier(notify) if isinstance(notify, str) \
            else notify
        if self.notify is not None and not callable(self.notify):
            raise ValueError(
                f"notify must be a callable or a JSONL path, got "
                f"{notify!r}")
        self._lock = threading.Lock()
        self._instances: dict[str, dict[tuple, _Instance]] = \
            {r.name: {} for r in self.rules}
        self._seen: dict[str, set] = {r.name: set() for r in self.rules}
        self._windows: dict[tuple, deque] = {}  # (rule, labelkey) -> samples
        self._evals = 0

    # ------------------------------------------------------------ conditions
    def _conditions(self, rule, samples, now):
        """[(labelkey, labels, cond_bool, value)] for this evaluation."""
        out = []
        if rule.kind in ("threshold", "burn_rate"):
            for labels, value in samples.match(rule.metric, rule.labels):
                out.append((_labelkey(labels), labels,
                            _OPS[rule.op](value, rule.threshold), value))
        elif rule.kind == "delta":
            for labels, value in samples.match(rule.metric, rule.labels):
                key = (rule.name, _labelkey(labels))
                st = self._windows.get(key)
                if st is None:
                    st = self._windows[key] = {"win": deque(), "inc": 0.0}
                win = st["win"]
                # the counter-reset-tolerant increase (sum of positive
                # consecutive deltas) is maintained INCREMENTALLY, and
                # same-spacing samples coalesce into the tail entry — the
                # window stays O(256) and O(1) per tick no matter how fast
                # the caller evaluates (per-step polls, /alertz scrapes)
                spacing = rule.window_s / 256.0
                if win and now - win[-1][0] < spacing \
                        and value >= win[-1][1]:
                    st["inc"] += value - win[-1][1]
                    win[-1] = (win[-1][0], value)
                else:
                    if win:
                        st["inc"] += max(0.0, value - win[-1][1])
                    win.append((now, value))
                while win and now - win[0][0] > rule.window_s:
                    _, v0 = win.popleft()
                    if win:
                        st["inc"] -= max(0.0, win[0][1] - v0)
                inc = st["inc"]
                out.append((_labelkey(labels), labels,
                            _OPS[rule.op](inc, rule.threshold), inc))
        elif rule.kind == "absence":
            matched = {_labelkey(l): (l, v)
                       for l, v in samples.match(rule.metric, rule.labels)}
            seen = self._seen[rule.name]
            seen.update(matched)
            insts = self._instances[rule.name]
            for key in sorted(seen):
                if key in matched:
                    labels, value = matched[key]
                    out.append((key, labels, False, value))
                    continue
                # absence TTL: a label set that has been FIRING-absent for
                # window_s is taken as decommissioned (scale-in), not lost
                # — un-see it so the alert resolves, the instance reaps,
                # and the engine cannot grow forever under label churn.  A
                # later reappearance re-seeds it fresh.
                inst = insts.get(key)
                if inst is not None and inst.state == STATE_FIRING \
                        and now - inst.since >= rule.window_s:
                    seen.discard(key)
                    out.append((key, dict(key), False, None))
                else:
                    out.append((key, dict(key), True, None))
            if not seen and rule.labels:
                # explicit selector that has never matched at all
                key = _labelkey(rule.labels)
                out.append((key, dict(rule.labels), True, None))
        return out

    # --------------------------------------------------------- state machine
    def _advance(self, rule, inst, cond, value, now):
        """One instance, one tick.  Returns the entered state or None."""
        inst.value = value
        state = inst.state
        if state == STATE_INACTIVE:
            if cond:
                if rule.for_s <= 0:
                    return STATE_FIRING
                inst.pending_since = now
                return STATE_PENDING
        elif state == STATE_PENDING:
            if not cond:
                return STATE_INACTIVE
            if now - inst.pending_since >= rule.for_s:
                return STATE_FIRING
        elif state == STATE_FIRING:
            if not cond:
                inst.resolved_since = now
                return STATE_RESOLVED
        elif state == STATE_RESOLVED:
            if cond:  # re-fire (flap): back through the hysteresis gate
                if rule.for_s <= 0:
                    return STATE_FIRING
                inst.pending_since = now
                return STATE_PENDING
            if now - inst.resolved_since >= rule.resolved_hold_s:
                return STATE_INACTIVE
        return None

    def evaluate(self, samples=None, now=None):
        """One engine tick.  ``samples`` defaults to the local registry
        (in-process mode); pass a scraped SampleSet for fleet mode.
        Returns the list of transition dicts applied this tick."""
        if samples is None:
            samples = SampleSet.from_registry(self._registry)
        t0 = time.perf_counter()
        now = self.clock() if now is None else float(now)
        transitions = []
        with self._lock:
            self._evals += 1
            for rule in self.rules:
                insts = self._instances[rule.name]
                # last-cond-wins dedupe: a malformed payload repeating a
                # series must not advance one instance twice in one tick
                conds = {key: (labels, cond, value) for key, labels, cond,
                         value in self._conditions(rule, samples, now)}
                live_keys = set()
                for key, (labels, cond, value) in conds.items():
                    live_keys.add(key)
                    inst = insts.get(key)
                    if inst is None:
                        inst = insts[key] = _Instance(labels, now)
                    entered = self._advance(rule, inst, cond, value, now)
                    if entered is not None:
                        transitions.append(self._transition(
                            rule, inst, entered, now, samples))
                # instances no longer matched read as condition-false and
                # wind down instead of firing forever (for absence rules
                # this only reaps an explicit-selector instance orphaned by
                # the real series appearing under different labels)
                for key, inst in list(insts.items()):
                    if key in live_keys:
                        continue
                    entered = self._advance(rule, inst, False, None, now)
                    if entered is not None:
                        transitions.append(self._transition(
                            rule, inst, entered, now))
                # drop fully-quiet cells (and their delta windows) so a
                # churning label space (ephemeral targets) cannot grow the
                # engine without bound
                for key, inst in list(insts.items()):
                    if inst.state == STATE_INACTIVE and key not in live_keys:
                        del insts[key]
                        self._windows.pop((rule.name, key), None)
                self._export_state(rule, insts)
        # JSONL write and notify shipping happen OUTSIDE the engine lock:
        # a slow disk/webhook must stall neither concurrent evaluates nor
        # the /alertz handler
        self._write_log(transitions)
        self._ship(transitions)
        _M_EVAL.observe(time.perf_counter() - t0)
        return transitions

    def _exemplar_trace_ids(self, rule, labels, samples):
        """Trace ids correlated with a firing instance, harvested from the
        SampleSet's histogram exemplars: the rule's own metric family
        first (a threshold on ``llm_ttft_seconds_bucket``), else the
        instance's ``series`` label (a burn-rate rule on
        ``slo_burn_rate_ratio{series="llm_ttft"}`` resolves to the
        ``llm_ttft_seconds`` exemplars)."""
        getter = getattr(samples, "exemplar_trace_ids", None)
        if getter is None:
            return []
        base = rule.metric
        for suf in ("_bucket", "_sum", "_count"):
            if base.endswith(suf):
                base = base[:-len(suf)]
                break
        ids = getter(base)
        if not ids and labels.get("series"):
            ids = getter(labels["series"])
        return ids[-4:]  # the newest few; a page needs a pointer, not all

    def _transition(self, rule, inst, entered, now, samples=None):
        prev = inst.state
        inst.state = entered
        inst.since = now
        if entered == STATE_FIRING:
            inst.episodes += 1
        rec = {"alert": rule.name, "labels": dict(inst.labels),
               "from": prev, "to": entered, "mono": now,
               "value": inst.value, "severity": rule.severity,
               "episode": inst.episodes}
        if entered == STATE_FIRING and samples is not None:
            ids = self._exemplar_trace_ids(rule, inst.labels, samples)
            if ids:
                rec["trace_ids"] = ids
        _M_TRANSITIONS.labels(alert=rule.name, state=entered).inc()
        recorder = self.recorder if self.recorder is not None \
            else _flight.RECORDER
        recorder.record("alert_transition", **rec)
        return rec

    def _ship(self, transitions):
        """Push each transition through the notify hook (outside the
        engine lock).  Failures are counted and black-boxed, never
        propagated — alerting must not die with its webhook."""
        if self.notify is None or not transitions:
            return
        recorder = self.recorder if self.recorder is not None \
            else _flight.RECORDER
        for rec in transitions:
            try:
                self.notify(rec)
                _M_NOTIFY.inc()
            except Exception as e:
                _M_NOTIFY_FAIL.inc()
                recorder.record("alert_notify_failed", alert=rec["alert"],
                                to=rec["to"], error=repr(e))

    def _write_log(self, transitions):
        """Append transition lines to the JSONL alert log (called outside
        the engine lock)."""
        if not self.log_path or not transitions:
            return
        # wall-clock stamp is deliberate: the alert log is joined with
        # operator logs and dashboards across hosts, which share NTP,
        # not a boot clock (the monotonic stamp rides along in "mono")
        stamp = time.time()  # tpulint: disable=impure-trace
        try:
            with open(self.log_path, "a") as f:
                for rec in transitions:
                    f.write(json.dumps({"time": stamp, **rec},
                                       separators=(",", ":")) + "\n")
        except OSError as e:
            recorder = self.recorder if self.recorder is not None \
                else _flight.RECORDER
            recorder.record("alert_log_failed", error=repr(e))

    def _export_state(self, rule, insts):
        worst = max((STATE_VALUES[i.state] for i in insts.values()),
                    default=0)
        _M_STATE.labels(alert=rule.name).set(float(worst))

    # -------------------------------------------------------------- reading
    def state(self):
        """JSON-safe full engine state (the `/alertz` payload)."""
        with self._lock:
            alerts = []
            for rule in self.rules:
                insts = self._instances[rule.name]
                alerts.append({
                    **rule.to_dict(),
                    "state": max(
                        (i.state for i in insts.values()),
                        key=lambda s: STATE_VALUES[s], default=STATE_INACTIVE),
                    "instances": [
                        {"labels": dict(i.labels), "state": i.state,
                         "since": i.since, "value": i.value,
                         "episodes": i.episodes}
                        for i in insts.values()],
                })
            return {"evaluations": self._evals, "alerts": alerts}

    def firing(self, name=None):
        """Currently-firing instances: ``[{"alert", "labels", "value",
        "since", "episode"}]`` (optionally for one rule)."""
        with self._lock:
            out = []
            for rule in self.rules:
                if name is not None and rule.name != name:
                    continue
                for inst in self._instances[rule.name].values():
                    if inst.state == STATE_FIRING:
                        out.append({"alert": rule.name,
                                    "labels": dict(inst.labels),
                                    "value": inst.value,
                                    "since": inst.since,
                                    "episode": inst.episodes})
            return out


class JsonlNotifier:
    """The stock notify hook: append each transition as one JSONL line —
    the log-shipper shape (tail it into a webhook forwarder, or let a
    collector pick the file up).  ``AlertEngine(notify="path.jsonl")`` is
    sugar for this class."""

    def __init__(self, path):
        self.path = str(path)
        self._lock = threading.Lock()

    def __call__(self, rec):
        # wall-clock stamp is deliberate: shipped transitions are joined
        # with operator dashboards across hosts, which share NTP, not a
        # boot clock (the monotonic stamp rides along in "mono")
        stamp = time.time()  # tpulint: disable=impure-trace
        line = json.dumps({"time": stamp, **rec}, separators=(",", ":"))
        with self._lock:
            with open(self.path, "a") as f:
                f.write(line + "\n")

    def __repr__(self):
        return f"JsonlNotifier({self.path!r})"


class AlertDecision:
    """One actuation decision: alert X (labels Y) asks for action Z."""

    __slots__ = ("alert", "action", "labels", "value", "episode", "mono")

    def __init__(self, alert, action, labels, value, episode, mono):
        self.alert = alert
        self.action = action
        self.labels = dict(labels)
        self.value = value
        self.episode = episode
        self.mono = mono

    def to_dict(self):
        return {"alert": self.alert, "action": self.action,
                "labels": dict(self.labels), "value": self.value,
                "episode": self.episode, "mono": self.mono}

    def __repr__(self):
        return f"AlertDecision({self.alert!r} -> {self.action!r})"


class AlertPolicy:
    """Map named firing alerts to actions; emit one decision per firing
    EPISODE.

    ``actions`` maps rule name -> ``"restart"`` | ``"quarantine"`` |
    ``"widen_deadline"`` | callable(decision).  Callables run inside
    :meth:`poll` (exceptions propagate to the caller — actuation failures
    must not be silent); string actions are returned as decisions for the
    host (``run_with_recovery``, ``ElasticManager``) to execute.

    ``scraper=None`` evaluates the LOCAL registry — the in-process mode
    ``run_with_recovery(alert_policy=)`` uses; with a
    :class:`~paddle_tpu.observability.scrape.Scraper` every poll scrapes
    the fleet first (sense), evaluates (decide), then maps to actions
    (act).

    ``min_interval_s`` throttles implicit polls: a ``poll()`` with neither
    ``samples`` nor ``now`` (the hot-path shape — ``run_with_recovery``
    calls it after every step) that lands within the interval is a no-op
    returning ``[]``, so a scraper-backed policy never turns each training
    step into a fleet HTTP scrape.  Default: 15 s with a scraper, 0
    (unthrottled — evaluation is microseconds) for local-registry
    policies.  Explicit ``samples``/``now`` bypass the throttle: the
    caller owns the cadence (deterministic tests, ``poll_alerts(now=)``).
    """

    def __init__(self, actions, rules=None, engine=None, scraper=None,
                 clock=time.monotonic, log_path=None, min_interval_s=None):
        self.actions = dict(actions or {})
        for name, act in self.actions.items():
            if not callable(act) and act not in ACTIONS:
                raise ValueError(
                    f"action for alert {name!r} must be callable or one of "
                    f"{ACTIONS}, got {act!r}")
        self.engine = engine if engine is not None else AlertEngine(
            rules=rules, clock=clock, log_path=log_path)
        known = {r.name for r in self.engine.rules}
        unknown = set(self.actions) - known
        if unknown:
            raise ValueError(
                f"actions name alerts with no rule: {sorted(unknown)} "
                f"(rules: {sorted(known)})")
        self.scraper = scraper
        self.clock = clock
        self.min_interval_s = float(
            (15.0 if scraper is not None else 0.0)
            if min_interval_s is None else min_interval_s)
        self._last_implicit_poll = None  # clock() stamp of the last one
        self._acted: dict[tuple, int] = {}  # instance -> last acted episode
        self._last_results = None  # [ScrapeResult] of the latest poll

    def poll(self, samples=None, now=None):
        """Sense -> decide -> act.  Returns the list of
        :class:`AlertDecision` emitted this poll (string actions only;
        callable actions have already run)."""
        results = None
        if samples is None:
            if now is None and self.min_interval_s > 0:
                t = self.clock()
                if self._last_implicit_poll is not None \
                        and t - self._last_implicit_poll \
                        < self.min_interval_s:
                    return []  # throttled: keep scrapes off the hot path
                self._last_implicit_poll = t
            if self.scraper is not None:
                samples, results = self.scraper.poll()
            else:  # local mode: read the engine's registry (default global)
                samples = SampleSet.from_registry(self.engine._registry)
        self.engine.evaluate(samples, now=now)
        decisions = []
        firing = self.engine.firing()
        # prune acted-episode memory for instances no longer firing: bounds
        # it to the live firing set AND keeps a reaped-then-recreated
        # instance (episode numbering restarts at 1) from colliding with a
        # stale entry and silently swallowing its decision
        firing_keys = {(f["alert"], _labelkey(f["labels"])) for f in firing}
        self._acted = {k: v for k, v in self._acted.items()
                       if k in firing_keys}
        for f in firing:
            action = self.actions.get(f["alert"])
            if action is None:
                continue
            key = (f["alert"], _labelkey(f["labels"]))
            if self._acted.get(key) == f["episode"]:
                continue  # already decided for this firing episode
            name = action if isinstance(action, str) \
                else getattr(action, "__name__", "callable")
            d = AlertDecision(f["alert"], name, f["labels"], f["value"],
                              f["episode"],
                              self.clock() if now is None else now)
            if callable(action):
                # run the callable BEFORE any accounting: a raising
                # notifier propagates, stays retryable next poll (no
                # acted-mark), and counts once per episode, not per retry
                action(d)
            self._acted[key] = f["episode"]
            _M_ACTIONS.labels(alert=d.alert, action=d.action).inc()
            recorder = self.engine.recorder if self.engine.recorder \
                is not None else _flight.RECORDER
            recorder.record("alert_action", alert=d.alert, action=d.action,
                            labels=dict(d.labels), episode=d.episode)
            if not callable(action):
                decisions.append(d)
        self._last_results = results
        return decisions
