"""Dependency-free metrics registry (the substrate every perf/elasticity PR
reports through).

Reference analog: the paper's stack pairs host/device tracers with per-step
cost accounting (SURVEY §profiler); Piper and the Gemma-on-TPU serving
comparison (PAPERS.md) both lean on per-step/per-request series to find
stragglers and queue collapse.  This module is the *numbers* half of that
pairing (the *traces* half is `observability.spans` -> `profiler.RecordEvent`):

- ``Counter`` / ``Gauge`` / ``Histogram`` (fixed log-spaced buckets) with
  labeled children, registered in a process-global default ``REGISTRY``;
- ``snapshot()`` (plain dicts), ``render_prometheus()`` (text exposition
  format, the `/metrics` payload) and ``dump_jsonl()`` (append-only local
  time series for offline joins with chrome traces);
- ``disable()``: the per-call overhead of every instrumentation point drops
  to one dict lookup — hot paths stay benchmark-clean with observability
  off (`PADDLE_TPU_OBSERVABILITY=0` starts disabled).

No jax / numpy / paddle imports: the registry must be importable from any
layer (store, checkpoint, server) without dragging in device runtimes.
"""
from __future__ import annotations

import json
import math
import os
import threading
import time
from bisect import bisect_left

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricRegistry", "REGISTRY",
    "counter", "gauge", "histogram", "enable", "disable", "enabled",
    "snapshot", "render_prometheus", "dump_jsonl", "log_buckets",
    "DEFAULT_TIME_BUCKETS",
]

# The disabled fast path: every record call starts with one dict lookup on
# this module-level dict (no attribute chains, no function indirection).
_runtime = {"enabled": os.environ.get("PADDLE_TPU_OBSERVABILITY", "1")
            .lower() not in ("0", "false", "off")}


def enable():
    """(Re-)enable metric recording process-wide."""
    _runtime["enabled"] = True


def disable():
    """Disable recording: every inc/set/observe returns after one dict
    lookup.  Registration still works (the namespace stays lintable)."""
    _runtime["enabled"] = False


def enabled() -> bool:
    return _runtime["enabled"]


def log_buckets(lo: float, hi: float, per_decade: int = 3):
    """Fixed log-spaced bucket bounds covering [lo, hi]: ``per_decade``
    bounds per factor-of-10, rounded to 4 significant digits so the
    Prometheus ``le`` strings stay short and stable."""
    if lo <= 0 or hi <= lo:
        raise ValueError("log_buckets needs 0 < lo < hi")
    n = int(math.ceil(math.log10(hi / lo) * per_decade)) + 1
    out = []
    for i in range(n):
        b = lo * 10.0 ** (i / per_decade)
        mag = 10.0 ** (math.floor(math.log10(b)) - 3)
        out.append(round(round(b / mag) * mag, 12))
    out[-1] = min(out[-1], hi) if out[-1] > hi else out[-1]
    # dedupe while preserving order (rounding can collide at decade edges)
    seen, bounds = set(), []
    for b in out:
        if b not in seen:
            seen.add(b)
            bounds.append(b)
    return tuple(bounds)


#: 100 µs .. 100 s, 3 buckets per decade — wide enough for a store rpc and a
#: full-model compile in the same histogram family.
DEFAULT_TIME_BUCKETS = log_buckets(1e-4, 100.0, per_decade=3)


def _fmt(v: float) -> str:
    """Prometheus sample formatting: integers render bare, floats via repr."""
    f = float(v)
    if f == math.inf:
        return "+Inf"
    if f == -math.inf:
        return "-Inf"
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _escape_label(v: str) -> str:
    """Label-value escaping (exposition format): backslash FIRST, then the
    quote and line feed — the only three escapes the text parser knows."""
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _escape_help(v: str) -> str:
    """HELP-text escaping: ONLY ``\\`` and ``\\n``.  Escaping ``"`` here
    (as label values must) would render a literal ``\\"`` in every scrape —
    the parser recognizes no quote escape outside label values."""
    return v.replace("\\", "\\\\").replace("\n", "\\n")


def _labelstr(names, values) -> str:
    if not names:
        return ""
    inner = ",".join(f'{n}="{_escape_label(str(v))}"'
                     for n, v in zip(names, values))
    return "{" + inner + "}"


# ------------------------------------------------------------------ children
class _CounterChild:
    __slots__ = ("_value", "_lock")

    def __init__(self):
        self._value = 0.0
        self._lock = threading.Lock()

    def inc(self, amount=1.0):
        # validate BEFORE the disabled fast path: a negative delta must fail
        # in CI (metrics off) exactly as it would in production (metrics on)
        if amount < 0:
            raise ValueError("counters only go up; use a Gauge")
        if not _runtime["enabled"]:
            return
        with self._lock:
            self._value += amount

    @property
    def value(self):
        return self._value


class _GaugeChild:
    __slots__ = ("_value", "_lock")

    def __init__(self):
        self._value = 0.0
        self._lock = threading.Lock()

    def set(self, value):
        if not _runtime["enabled"]:
            return
        with self._lock:  # a lock-free set can erase a concurrent inc
            self._value = float(value)

    def inc(self, amount=1.0):
        if not _runtime["enabled"]:
            return
        with self._lock:
            self._value += amount

    def dec(self, amount=1.0):
        self.inc(-amount)

    @property
    def value(self):
        return self._value


class _HistogramChild:
    __slots__ = ("_bounds", "_counts", "_sum", "_count", "_lock",
                 "_exemplars")

    def __init__(self, bounds):
        self._bounds = bounds
        self._counts = [0] * (len(bounds) + 1)  # last slot = +Inf overflow
        self._sum = 0.0
        self._count = 0
        self._lock = threading.Lock()
        # bucket index -> (value, labels): the OpenMetrics exemplar of the
        # bucket, linking an aggregate latency to one concrete trace
        self._exemplars: dict[int, tuple] = {}

    def observe(self, value, exemplar=None):
        """``exemplar`` (a trace id string, or a label dict) attaches an
        OpenMetrics exemplar to the bucket the observation lands in; each
        bucket retains its WORST exemplar (highest value; ties go to the
        newest) — the one a latency investigation wants first."""
        if not _runtime["enabled"]:
            return
        v = float(value)
        i = bisect_left(self._bounds, v)  # first bound >= v (le semantics)
        with self._lock:
            self._counts[i] += 1
            self._sum += v
            self._count += 1
            if exemplar is not None:
                prev = self._exemplars.get(i)
                if prev is None or v >= prev[0]:
                    labels = ({str(k): str(lv) for k, lv in exemplar.items()}
                              if isinstance(exemplar, dict)
                              else {"trace_id": str(exemplar)})
                    self._exemplars[i] = (v, labels)

    def exemplars(self):
        """``{le_string: {"labels": {...}, "value": v}}`` per bucket that
        holds one (keys match ``bucket_counts()`` / the exposition ``le``
        strings)."""
        with self._lock:
            items = dict(self._exemplars)
        out = {}
        for i, (v, labels) in items.items():
            b = self._bounds[i] if i < len(self._bounds) else math.inf
            out[_fmt(b)] = {"labels": dict(labels), "value": v}
        return out

    @property
    def sum(self):
        return self._sum

    @property
    def count(self):
        return self._count

    def bucket_counts(self):
        """{upper_bound: cumulative count} including +Inf."""
        out, cum = {}, 0
        for b, c in zip(self._bounds, self._counts):
            cum += c
            out[b] = cum
        out[math.inf] = cum + self._counts[-1]
        return out


# ------------------------------------------------------------------- parents
class _Metric:
    kind = "untyped"

    def __init__(self, name, help="", labelnames=()):
        _validate_name(name)
        self.name = name
        self.help = str(help)
        self.labelnames = tuple(labelnames)
        self._lock = threading.Lock()
        self._children: dict[tuple, object] = {}
        if not self.labelnames:
            self._children[()] = self._make_child()

    def _make_child(self):
        raise NotImplementedError

    def labels(self, *values, **kv):
        """Child for one label-value combination (created on first use)."""
        if kv:
            if values:
                raise ValueError("pass label values positionally OR by name")
            try:
                values = tuple(kv[n] for n in self.labelnames)
            except KeyError as e:
                raise ValueError(
                    f"metric {self.name} has labels {self.labelnames}; "
                    f"missing {e.args[0]!r}") from None
            if len(kv) != len(self.labelnames):
                extra = set(kv) - set(self.labelnames)
                raise ValueError(
                    f"metric {self.name} got unknown labels {sorted(extra)}")
        values = tuple(str(v) for v in values)
        if len(values) != len(self.labelnames):
            raise ValueError(
                f"metric {self.name} expects {len(self.labelnames)} label "
                f"values {self.labelnames}, got {len(values)}")
        child = self._children.get(values)
        if child is None:
            with self._lock:
                child = self._children.setdefault(values, self._make_child())
        return child

    def _solo(self):
        if self.labelnames:
            raise ValueError(
                f"metric {self.name} is labeled {self.labelnames}; "
                f"call .labels(...) first")
        return self._children[()]

    def series(self):
        """[(labelvalues_tuple, child)] in creation order.  Copied under the
        lock: a scrape iterating while labels() inserts a first-seen child
        must not see the dict resize mid-iteration."""
        with self._lock:
            return list(self._children.items())


class Counter(_Metric):
    kind = "counter"

    def _make_child(self):
        return _CounterChild()

    def inc(self, amount=1.0):
        # same order as _CounterChild.inc: validate even when disabled, so a
        # negative delta fails in metrics-off CI exactly as in production
        if amount < 0:
            raise ValueError("counters only go up; use a Gauge")
        if not _runtime["enabled"]:
            return
        self._solo().inc(amount)

    @property
    def value(self):
        return self._solo().value


class Gauge(_Metric):
    kind = "gauge"

    def _make_child(self):
        return _GaugeChild()

    def set(self, value):
        if not _runtime["enabled"]:
            return
        self._solo().set(value)

    def inc(self, amount=1.0):
        if not _runtime["enabled"]:
            return
        self._solo().inc(amount)

    def dec(self, amount=1.0):
        if not _runtime["enabled"]:
            return
        self._solo().dec(amount)

    @property
    def value(self):
        return self._solo().value


class Histogram(_Metric):
    kind = "histogram"

    def __init__(self, name, help="", labelnames=(), buckets=None):
        bounds = tuple(sorted(float(b) for b in
                              (buckets if buckets is not None
                               else DEFAULT_TIME_BUCKETS)))
        if not bounds:
            raise ValueError("histogram needs at least one bucket bound")
        self.buckets = bounds
        super().__init__(name, help, labelnames)

    def _make_child(self):
        return _HistogramChild(self.buckets)

    def observe(self, value, exemplar=None):
        if not _runtime["enabled"]:
            return
        self._solo().observe(value, exemplar=exemplar)

    @property
    def sum(self):
        return self._solo().sum

    @property
    def count(self):
        return self._solo().count


def _validate_name(name):
    if not name or not all(c.islower() or c.isdigit() or c == "_"
                           for c in name) or not name[0].isalpha():
        raise ValueError(
            f"metric name {name!r} must be snake_case "
            f"([a-z][a-z0-9_]*); see tools/metrics_lint.py")


# ------------------------------------------------------------------ registry
class MetricRegistry:
    """Name -> metric family.  Registration is idempotent: re-registering the
    same (name, kind, labelnames) returns the existing family (so module
    reloads and multiple import paths share series); a conflicting
    re-registration raises."""

    def __init__(self):
        self._metrics: dict[str, _Metric] = {}
        self._lock = threading.Lock()

    def _register(self, cls, name, help, labelnames, **kw):
        with self._lock:
            existing = self._metrics.get(name)
            if existing is not None:
                if (existing.kind != cls.kind
                        or existing.labelnames != tuple(labelnames)):
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{existing.kind}{existing.labelnames}, conflicting "
                        f"with {cls.kind}{tuple(labelnames)}")
                return existing
            m = cls(name, help, labelnames, **kw)
            self._metrics[name] = m
            return m

    def counter(self, name, help="", labelnames=()) -> Counter:
        return self._register(Counter, name, help, labelnames)

    def gauge(self, name, help="", labelnames=()) -> Gauge:
        return self._register(Gauge, name, help, labelnames)

    def histogram(self, name, help="", labelnames=(),
                  buckets=None) -> Histogram:
        return self._register(Histogram, name, help, labelnames,
                              buckets=buckets)

    def get(self, name):
        with self._lock:  # same race as names(): first-use register() resizes
            return self._metrics.get(name)

    def names(self):
        with self._lock:  # list() during a concurrent register() can resize
            return list(self._metrics)

    def __iter__(self):
        # locked copy: scrapes race with first-use register() calls
        with self._lock:
            return iter(list(self._metrics.values()))

    def unregister(self, name):
        with self._lock:
            self._metrics.pop(name, None)

    def reset(self):
        """Zero every series (keep the registered families).  Test hook."""
        with self._lock:
            for m in self._metrics.values():
                # per-metric lock: labels() may be inserting a first-seen
                # child concurrently — without it the iteration can see the
                # dict resize, or the insert lands in the discarded dict
                with m._lock:
                    fresh = {}
                    for lv in m._children:
                        fresh[lv] = m._make_child()
                    m._children = fresh

    # ---------------------------------------------------------- exposition
    def snapshot(self) -> dict:
        """Plain-dict view of every series (JSON-ready)."""
        out = {}
        for m in self:
            series = []
            for lv, child in m.series():
                labels = dict(zip(m.labelnames, lv))
                if m.kind == "histogram":
                    entry = {"labels": labels, "sum": child.sum,
                             "count": child.count,
                             "buckets": {_fmt(b): c for b, c in
                                         child.bucket_counts().items()}}
                    ex = child.exemplars()
                    if ex:  # present only when set, so parse() round-trips
                        entry["exemplars"] = ex
                    series.append(entry)
                else:
                    series.append({"labels": labels, "value": child.value})
            out[m.name] = {"kind": m.kind, "help": m.help, "series": series}
        return out

    def render_prometheus(self, exemplars=True) -> str:
        """Prometheus/OpenMetrics text exposition — the `/metrics` payload
        (serve it from any HTTP handler; nothing here binds a socket).

        ``exemplars=False`` suppresses the OpenMetrics-style
        ``# {trace_id="..."}`` bucket annotations: the classic
        ``text/plain; version=0.0.4`` format has no exemplar syntax, so
        the exporter only includes them for scrapers that negotiate the
        OpenMetrics content type (the built-in fleet ``Scraper`` does)."""
        lines = []
        for m in self:
            if m.help:
                lines.append(f"# HELP {m.name} {_escape_help(m.help)}")
            # exactly one TYPE line per family — labeled children are
            # samples of the SAME family, never their own TYPE block
            lines.append(f"# TYPE {m.name} {m.kind}")
            for lv, child in m.series():
                if m.kind == "histogram":
                    ex = child.exemplars() if exemplars else {}
                    for b, c in child.bucket_counts().items():
                        le = _fmt(b)
                        ls = _labelstr(m.labelnames + ("le",), lv + (le,))
                        line = f"{m.name}_bucket{ls} {c}"
                        e = ex.get(le)
                        if e:  # OpenMetrics exemplar annotation: the
                            # bucket's worst correlated trace
                            els = _labelstr(tuple(e["labels"]),
                                            tuple(e["labels"].values()))
                            line += f" # {els} {_fmt(e['value'])}"
                        lines.append(line)
                    ls = _labelstr(m.labelnames, lv)
                    lines.append(f"{m.name}_sum{ls} {_fmt(child.sum)}")
                    lines.append(f"{m.name}_count{ls} {child.count}")
                else:
                    ls = _labelstr(m.labelnames, lv)
                    lines.append(f"{m.name}{ls} {_fmt(child.value)}")
        return "\n".join(lines) + "\n"

    def dump_jsonl(self, path, extra=None):
        """Append one timestamped snapshot line to ``path`` (local JSONL time
        series; join offline with chrome-trace exports by wall time)."""
        rec = {"time": time.time(), "metrics": self.snapshot()}
        if extra:
            rec["extra"] = dict(extra)
        line = json.dumps(rec, separators=(",", ":"))
        with open(path, "a") as f:
            f.write(line + "\n")
        return path


#: Process-global default registry: every built-in instrumentation point
#: registers here, and `render_prometheus()` below exposes it.
REGISTRY = MetricRegistry()


def counter(name, help="", labelnames=(), registry=None) -> Counter:
    return (registry or REGISTRY).counter(name, help, labelnames)


def gauge(name, help="", labelnames=(), registry=None) -> Gauge:
    return (registry or REGISTRY).gauge(name, help, labelnames)


def histogram(name, help="", labelnames=(), buckets=None,
              registry=None) -> Histogram:
    return (registry or REGISTRY).histogram(name, help, labelnames, buckets)


def snapshot(registry=None) -> dict:
    return (registry or REGISTRY).snapshot()


def render_prometheus(registry=None, exemplars=True) -> str:
    return (registry or REGISTRY).render_prometheus(exemplars=exemplars)


def dump_jsonl(path, extra=None, registry=None):
    return (registry or REGISTRY).dump_jsonl(path, extra=extra)
