"""Structured spans: ONE instrumentation point lands in both sinks.

``with span("checkpoint_save", histogram=H):`` opens a
``profiler.RecordEvent`` (native host-trace buffer -> chrome://tracing
export, plus a jax TraceAnnotation -> XPlane timeline) and, on exit,
observes the wall-clock duration into ``histogram`` and bumps ``counter``.
Metrics and traces therefore always agree on what a "checkpoint_save" is —
the correlation the README's Observability section documents.

``metrics.disable()`` turns spans into no-ops too (one dict lookup on
enter), so instrumented hot paths stay benchmark-clean.

Every span close also lands one structured event in the process-global
flight recorder (``observability.flight_recorder``): after a crash the
black-box dump shows WHICH span was running and how long it had been —
the per-event cost is one bounded deque append.
"""
from __future__ import annotations

import time

from . import metrics as _metrics
from . import flight_recorder as _flight

__all__ = ["span"]

_record_event_cls = None


def _record_event(name):
    """profiler.RecordEvent, imported lazily (profiler drags in jax; the
    metrics registry itself must stay dependency-free)."""
    global _record_event_cls
    if _record_event_cls is None:
        try:
            from ..profiler import RecordEvent
            _record_event_cls = RecordEvent
        except Exception:
            _record_event_cls = False
    return _record_event_cls(name) if _record_event_cls else None


class span:
    """Context manager: trace span + latency histogram + event counter.

    ``trace`` (a ``tracing.Trace``, or the falsy ``NULL_TRACE``) extends
    the single instrumentation point to the request-scoped sinks: the
    span joins the trace's tree (with ``attrs``), the flight-recorder
    event carries the ``trace_id``, and the histogram observation carries
    it as an OpenMetrics exemplar — metrics, black box and span tree all
    name the same request.
    """

    __slots__ = ("name", "histogram", "counter", "trace", "attrs",
                 "_t0", "_ev", "_tspan", "duration")

    def __init__(self, name, histogram=None, counter=None, trace=None,
                 attrs=None):
        self.name = name
        self.histogram = histogram
        self.counter = counter
        self.trace = trace if trace else None  # NULL_TRACE is falsy
        self.attrs = attrs
        self._t0 = None
        self._ev = None
        self._tspan = None
        self.duration = None

    def __enter__(self):
        if not _metrics._runtime["enabled"]:
            return self
        self._ev = _record_event(self.name)
        if self._ev is not None:
            self._ev.__enter__()
        if self.trace is not None:
            self._tspan = self.trace.span(
                self.name, **(self.attrs or {})).open()
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        if self._t0 is not None:
            self.duration = time.perf_counter() - self._t0
            self._t0 = None
            if self._ev is not None:
                self._ev.__exit__(None, None, None)
                self._ev = None
            err = repr(exc[1]) if exc and exc[0] is not None else None
            if self._tspan is not None:
                self._tspan.close(error=err)
                self._tspan = None
            if self.histogram is not None:
                self.histogram.observe(
                    self.duration,
                    exemplar=self.trace.trace_id
                    if self.trace is not None else None)
            if self.counter is not None:
                self.counter.inc()
            fields = {"name": self.name, "duration_s": self.duration}
            if self.trace is not None:
                fields["trace_id"] = self.trace.trace_id
            if err is not None:
                # a span unwound by an exception is exactly the event a
                # postmortem wants last in the black box
                fields["error"] = err
            _flight.record_event("span", **fields)
        return False
