"""Roofline residual plane (ISSUE 17): per-HLO measured-vs-predicted
attribution plus the perf-regression sentinel.

The profiling plane (``observability.xplane``) can name, per HLO, where
device time *goes*; the cost side (``census.per_op_census`` +
``cost_model.peak_flops_per_device`` / ``peak_hbm_bytes_per_sec``)
predicts where it *should* go.  This module joins them:

- **Prediction** is the min-time roofline: an op that moves ``bytes``
  and computes ``flops`` can never finish faster than
  ``max(flops / peak_flops, bytes / peak_bw)``.  Whichever term wins
  classifies the op ``compute``- or ``memory``-bound (ops with neither
  flops nor bytes — or no peaks to divide by — stay ``unknown``: an
  unpredicted op is a finding, not a zero).
- **Residual** is ``measured_us / predicted_us`` — 1.0 means the op runs
  at the roofline; 4.0 means 4x headroom.  ``wasted_us = measured -
  predicted`` ranks the table: the top row is the single best thing to
  optimize next (ROADMAP open item 5's "optimization shopping list").
- **Rounds** persist as ``ROOFLINE_<round>.json`` — content-addressed
  like the BENCH configs: ``key = sha256(hardware fingerprint + config
  hash + schema_version)``, so two rounds are comparable iff their keys
  match.
- **Sentinel**: :func:`diff_reports` compares two rounds per op under a
  relative residual-growth threshold with an absolute wasted-µs floor
  (noise on a 3 µs op must not page anyone); ``tools/roofline_report.py
  --diff`` exits non-zero iff an op regressed — the cron/CI perf gate.

The same numbers reach the live stack through the registry:
``roofline_residual_ratio{op}`` / ``roofline_bound_fraction{bound}``
gauges (on ``/metrics``, ``/varz``) and ``roofline_regressions_total``,
which the ``roofline_regression`` default delta alert rule watches.

Stdlib-only at module scope (same contract as ``xplane`` / ``metrics``);
jax is imported lazily inside :func:`hardware_fingerprint` only.
"""
from __future__ import annotations

import hashlib
import json
import os

from . import metrics as _metrics

__all__ = [
    "SCHEMA_VERSION", "DEFAULT_THRESHOLD", "DEFAULT_MIN_US",
    "match_name", "census_table", "predict_op", "residual_rows",
    "annotate_rows", "build_report", "merge_reports",
    "hardware_fingerprint", "config_hash", "round_key",
    "save_round", "load_round", "round_path", "newest_round",
    "diff_reports", "export_gauges", "record_diff",
    "render_text", "render_diff_text",
]

#: Version of the ROOFLINE_<round>.json document.  Bump on any row/summary
#: schema change — the sentinel refuses to diff across versions.
SCHEMA_VERSION = 1

#: Default sentinel thresholds: an op regresses when its residual ratio
#: grew by more than THRESHOLD (relative) AND its wasted time grew by
#: more than MIN_US (absolute) — the µs floor keeps sub-noise ops from
#: paging anyone, the relative term keeps a 10 ms op's 5% drift quiet.
DEFAULT_THRESHOLD = 0.25
DEFAULT_MIN_US = 50.0

_M_RESIDUAL = _metrics.gauge(
    "roofline_residual_ratio",
    "measured_us / roofline-predicted_us of the op, from the last "
    "exported residual round (top-K ops by wasted time)",
    labelnames=("op",))
_M_BOUND = _metrics.gauge(
    "roofline_bound_fraction",
    "share of measured device time in compute-bound / memory-bound / "
    "unknown (no cost-model match) ops, from the last exported round",
    labelnames=("bound",))
_M_ROUNDS = _metrics.counter(
    "roofline_rounds_total", "residual reports built (build_report calls)")
_M_REGRESSIONS = _metrics.counter(
    "roofline_regressions_total",
    "ops flagged regressed by sentinel diffs (record_diff) — feeds the "
    "roofline_regression default delta alert rule")


# -------------------------------------------------------------- name match
def match_name(event_name, census):
    """The trace_report join rule, factored here so the CLI and the
    roofline plane can never diverge: exact name first, then the trailing
    path component (trace names prefix ops with the program path —
    ``jit_step/dot.12``), then LONGEST containment either way (census row
    ``dot.12`` beats ``dot`` / ``dot.1`` for event ``.../dot.12``).
    ``census`` is any container of names; returns the matched census name
    or None."""
    if event_name in census:
        return event_name
    tail = event_name.rsplit("/", 1)[-1]
    if tail in census:
        return tail
    best = None
    for cname in census:
        if (cname in event_name or event_name in cname) \
                and (best is None or len(cname) > len(best)):
            best = cname
    return best


def census_table(rows):
    """``census.per_op_census()`` rows -> ``name -> {opcode, flops,
    bytes}`` (bytes = in + out: the roofline's memory term is total HBM
    traffic).  A mapping passes through with the same normalization."""
    out = {}
    if isinstance(rows, dict):
        items = [dict(v, name=k) for k, v in rows.items()]
    else:
        items = rows
    for row in items:
        name = str(row.get("name", "?"))
        prev = out.setdefault(name, {"opcode": str(row.get("opcode", "")),
                                     "flops": 0.0, "bytes": 0.0})
        prev["flops"] += float(row.get("flops", 0) or 0)
        prev["bytes"] += float(row.get("bytes", 0) or 0) \
            + float(row.get("bytes_in", 0) or 0) \
            + float(row.get("bytes_out", 0) or 0)
    return out


# -------------------------------------------------------------- prediction
def predict_op(flops, bytes_, peak_flops, peak_bw):
    """Min-time roofline of one op -> ``(predicted_us, bound)``.

    ``predicted_us = max(flops/peak_flops, bytes/peak_bw) * 1e6``; the
    winning term names the bound.  A term with no numerator OR no peak
    contributes 0 — an op with neither is ``("unknown", 0.0)``, never a
    division by zero (the zero-predicted guard the residual math relies
    on)."""
    t_flops = flops / peak_flops if flops > 0 and peak_flops > 0 else 0.0
    t_bytes = bytes_ / peak_bw if bytes_ > 0 and peak_bw > 0 else 0.0
    if t_flops <= 0 and t_bytes <= 0:
        return 0.0, "unknown"
    if t_flops >= t_bytes:
        return t_flops * 1e6, "compute"
    return t_bytes * 1e6, "memory"


def residual_rows(measured, census, peak_flops, peak_bw):
    """Join measured per-op timings against the census cost table into
    the residual table, sorted by wasted µs desc.

    ``measured`` is the ``xplane.per_op_summary`` /
    ``trace_report.load_timeline`` shape (``name -> {count, total_us}``);
    ``census`` is :func:`census_table` output (or per_op_census rows,
    normalized here).  Rows keep deterministic rounding so a report is
    byte-stable for the golden tests and the content-addressed key."""
    census = census_table(census) if not _is_table(census) else census
    rows = []
    used = set()
    for name, t in measured.items():
        cname = match_name(name, census)
        c = census.get(cname) if cname else None
        if cname:
            used.add(cname)
        rows.append(_one_row(name, int(t.get("count", 0)),
                             float(t.get("total_us", 0.0)), c,
                             peak_flops, peak_bw))
    for cname, c in census.items():
        if cname in used:
            continue
        # a census op that never showed up on the device: predicted time
        # with zero measured — attribution MISSING is a finding.  Flagged
        # matched=False like trace_report.join: "matched" means JOINED,
        # not merely costed.
        row = _one_row(cname, 0, 0.0, c, peak_flops, peak_bw)
        row["matched"] = False
        rows.append(row)
    rows.sort(key=lambda r: (-r["wasted_us"], -r["measured_us"],
                             r["name"]))
    return rows


def _is_table(census):
    return isinstance(census, dict) and all(
        isinstance(v, dict) and "bytes" in v for v in census.values()) \
        and census  # empty dict normalizes through census_table harmlessly


def _one_row(name, count, measured_us, c, peak_flops, peak_bw):
    flops = float((c or {}).get("flops", 0.0))
    bytes_ = float((c or {}).get("bytes", 0.0))
    predicted_us, bound = predict_op(flops, bytes_, peak_flops, peak_bw)
    secs = measured_us / 1e6
    ratio = round(measured_us / predicted_us, 4) if predicted_us > 0 \
        and measured_us > 0 else None
    return {
        "name": name,
        "count": count,
        "measured_us": round(measured_us, 3),
        "predicted_us": round(predicted_us, 3),
        "residual_ratio": ratio,
        "wasted_us": round(max(0.0, measured_us - predicted_us), 3)
        if predicted_us > 0 and measured_us > 0 else 0.0,
        "bound": bound,
        "opcode": (c or {}).get("opcode", ""),
        "flops": flops,
        "bytes": bytes_,
        "achieved_flops_per_sec": round(flops / secs, 1)
        if flops > 0 and secs > 0 else 0.0,
        "achieved_bytes_per_sec": round(bytes_ / secs, 1)
        if bytes_ > 0 and secs > 0 else 0.0,
        "matched": c is not None,
    }


def annotate_rows(rows, peak_flops, peak_bw):
    """Residual-annotate ``trace_report.join()`` rows in place (adds
    predicted_us / residual_ratio / wasted_us / bound from each row's own
    flops/bytes) — the ``trace_report --roofline`` path, where the rows
    already exist and only the prediction is missing."""
    for r in rows:
        predicted_us, bound = predict_op(float(r.get("flops", 0.0)),
                                         float(r.get("bytes", 0.0)),
                                         peak_flops, peak_bw)
        measured_us = float(r.get("total_us", 0.0))
        r["predicted_us"] = round(predicted_us, 3)
        r["bound"] = bound
        r["residual_ratio"] = round(measured_us / predicted_us, 4) \
            if predicted_us > 0 and measured_us > 0 else None
        r["wasted_us"] = round(max(0.0, measured_us - predicted_us), 3) \
            if predicted_us > 0 and measured_us > 0 else 0.0
    return rows


# ----------------------------------------------------------------- reports
def hardware_fingerprint(peak_flops=0.0, peak_bw=0.0):
    """The comparability identity of a round: backend platform, device
    kind and count, plus the peaks the predictions were divided by (two
    rounds predicted against different peaks are NOT comparable, even on
    the same chip).  jax is imported lazily and its absence tolerated —
    the sentinel must run where only stdlib exists."""
    platform, kind, count = "unknown", "unknown", 0
    try:
        import jax
        devs = jax.devices()
        platform = jax.default_backend()
        kind = devs[0].device_kind if devs else "unknown"
        count = len(devs)
    except Exception:
        pass
    return {"platform": str(platform), "device_kind": str(kind),
            "device_count": int(count),
            "peak_flops_per_sec": float(peak_flops),
            "peak_hbm_bytes_per_sec": float(peak_bw)}


def config_hash(config):
    """sha256 of the canonical-JSON config dict, 12 hex chars."""
    blob = json.dumps(config or {}, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(blob.encode()).hexdigest()[:12]


def round_key(hardware, cfg_hash):
    """Content address of a round: hardware fingerprint + config hash +
    schema version, 16 hex chars.  Equal keys = comparable rounds."""
    blob = json.dumps({"hardware": hardware, "config_hash": cfg_hash,
                       "schema_version": SCHEMA_VERSION},
                      sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


def build_report(measured, census, peak_flops, peak_bw, config=None,
                 hardware=None, top_k=None):
    """Residual table + summary + content address, the
    ``ROOFLINE_<round>.json`` document body.

    ``hardware`` overrides the fingerprint (tests pin it for byte-exact
    goldens); ``top_k`` truncates the persisted rows (the summary always
    covers ALL rows, so truncation can't hide total waste)."""
    rows = residual_rows(measured, census, peak_flops, peak_bw)
    total_meas = sum(r["measured_us"] for r in rows)
    total_pred = sum(r["predicted_us"] for r in rows if r["measured_us"] > 0)
    bound_us = {"compute": 0.0, "memory": 0.0, "unknown": 0.0}
    for r in rows:
        bound_us[r["bound"]] += r["measured_us"]
    hw = hardware if hardware is not None \
        else hardware_fingerprint(peak_flops, peak_bw)
    cfg_hash = config_hash(config)
    report = {
        "schema_version": SCHEMA_VERSION,
        "hardware": hw,
        "config": config or {},
        "config_hash": cfg_hash,
        "key": round_key(hw, cfg_hash),
        "summary": {
            "ops": len(rows),
            "matched_ops": sum(1 for r in rows if r["matched"]),
            "timed_matched_ops": sum(1 for r in rows
                                     if r["matched"]
                                     and r["measured_us"] > 0),
            "measured_us": round(total_meas, 3),
            "predicted_us": round(total_pred, 3),
            "wasted_us": round(sum(r["wasted_us"] for r in rows), 3),
            "residual_ratio": round(total_meas / total_pred, 4)
            if total_pred > 0 else None,
            "bound_fraction": {
                b: round(us / total_meas, 4) if total_meas > 0 else 0.0
                for b, us in sorted(bound_us.items())},
        },
        "rows": rows[:int(top_k)] if top_k else rows,
    }
    _M_ROUNDS.inc()
    return report


def merge_reports(reports):
    """Fold per-config reports into ONE round document: rows namespaced
    ``<config>/<op>`` so the sentinel diffs each config's ops separately,
    summaries summed, the merged config hash chaining every member's.
    ``reports`` is an ordered ``{config_name: report}`` mapping; all
    members must share a hardware fingerprint (they ran in one
    process)."""
    if not reports:
        raise ValueError("merge_reports needs at least one report")
    names = sorted(reports)
    first = reports[names[0]]
    hw = first["hardware"]
    rows = []
    bound_us = {"compute": 0.0, "memory": 0.0, "unknown": 0.0}
    total_meas = total_pred = total_waste = 0.0
    config = {}
    for name in names:
        rep = reports[name]
        if rep["hardware"] != hw:
            raise ValueError(
                f"config {name!r} ran on different hardware than "
                f"{names[0]!r} — merged rounds must share a fingerprint")
        config[name] = rep["config"]
        s = rep["summary"]
        total_meas += s["measured_us"]
        total_pred += s["predicted_us"]
        total_waste += s["wasted_us"]
        for b, frac in s["bound_fraction"].items():
            bound_us[b] += frac * s["measured_us"]
        for r in rep["rows"]:
            rows.append(dict(r, name=f"{name}/{r['name']}"))
    rows.sort(key=lambda r: (-r["wasted_us"], -r["measured_us"],
                             r["name"]))
    cfg_hash = config_hash(config)
    return {
        "schema_version": SCHEMA_VERSION,
        "hardware": hw,
        "config": config,
        "config_hash": cfg_hash,
        "key": round_key(hw, cfg_hash),
        "summary": {
            "ops": len(rows),
            "matched_ops": sum(1 for r in rows if r["matched"]),
            "timed_matched_ops": sum(1 for r in rows
                                     if r["matched"]
                                     and r["measured_us"] > 0),
            "measured_us": round(total_meas, 3),
            "predicted_us": round(total_pred, 3),
            "wasted_us": round(total_waste, 3),
            "residual_ratio": round(total_meas / total_pred, 4)
            if total_pred > 0 else None,
            "bound_fraction": {
                b: round(us / total_meas, 4) if total_meas > 0 else 0.0
                for b, us in sorted(bound_us.items())},
        },
        "rows": rows,
    }


# ------------------------------------------------------------- persistence
def round_path(root, round_name):
    return os.path.join(root, f"ROOFLINE_{round_name}.json")


def save_round(report, root, round_name):
    """Persist as ``ROOFLINE_<round>.json`` (sorted keys, stable indent:
    the document is content-addressed, so serialization must be
    deterministic).  Returns the path."""
    path = round_path(root, round_name)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(report, f, indent=1, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)
    return path


def load_round(path):
    """Load + schema-gate one round.  A version mismatch raises — the
    sentinel must never silently compare documents whose row semantics
    differ."""
    with open(path) as f:
        doc = json.load(f)
    ver = doc.get("schema_version")
    if ver != SCHEMA_VERSION:
        raise ValueError(
            f"{path}: schema_version {ver!r} != supported "
            f"{SCHEMA_VERSION} — regenerate the round with this tree's "
            f"tools/roofline_report.py")
    return doc


def newest_round(root, exclude=None):
    """Path of the lexically-newest committed ``ROOFLINE_*.json`` under
    ``root`` (the docs_lint / BENCH 'newest = last glob match' idiom), or
    None.  ``exclude`` drops one path (diffing the newest round against
    the baseline must not pick itself)."""
    import glob
    paths = sorted(glob.glob(os.path.join(root, "ROOFLINE_*.json")))
    if exclude is not None:
        ex = os.path.abspath(exclude)
        paths = [p for p in paths if os.path.abspath(p) != ex]
    return paths[-1] if paths else None


# ---------------------------------------------------------------- sentinel
def diff_reports(old, new, threshold=DEFAULT_THRESHOLD,
                 min_us=DEFAULT_MIN_US):
    """Per-op regression verdict between two rounds.

    An op REGRESSES when, between ``old`` and ``new``:
    ``new_ratio > old_ratio * (1 + threshold)`` AND
    ``new_wasted - old_wasted > min_us`` — both the relative and the
    absolute test must trip (see DEFAULT_* notes).  Ops only one side
    knows are reported informationally (``new_ops`` / ``gone_ops``),
    never as regressions: a renamed HLO must not page anyone, the
    ``comparable`` flag (key equality) is the signal that the join is
    trustworthy.  Pure — counters move in :func:`record_diff`."""
    old_rows = {r["name"]: r for r in old.get("rows", [])}
    new_rows = {r["name"]: r for r in new.get("rows", [])}
    regressions, improvements = [], []
    for name, nr in new_rows.items():
        orow = old_rows.get(name)
        if orow is None:
            continue
        o_ratio, n_ratio = orow.get("residual_ratio"), \
            nr.get("residual_ratio")
        if o_ratio is None or n_ratio is None:
            continue
        delta_wasted = nr["wasted_us"] - orow["wasted_us"]
        entry = {"name": name, "old_ratio": o_ratio, "new_ratio": n_ratio,
                 "old_wasted_us": orow["wasted_us"],
                 "new_wasted_us": nr["wasted_us"],
                 "delta_wasted_us": round(delta_wasted, 3),
                 "bound": nr["bound"]}
        if n_ratio > o_ratio * (1.0 + threshold) and delta_wasted > min_us:
            regressions.append(entry)
        elif o_ratio > n_ratio * (1.0 + threshold) \
                and -delta_wasted > min_us:
            improvements.append(entry)
    regressions.sort(key=lambda e: -e["delta_wasted_us"])
    improvements.sort(key=lambda e: e["delta_wasted_us"])
    return {
        "threshold": float(threshold),
        "min_us": float(min_us),
        "comparable": old.get("key") == new.get("key"),
        "old_key": old.get("key"),
        "new_key": new.get("key"),
        "regressions": regressions,
        "improvements": improvements,
        "new_ops": sorted(set(new_rows) - set(old_rows)),
        "gone_ops": sorted(set(old_rows) - set(new_rows)),
    }


def record_diff(diff):
    """Land a sentinel verdict on the registry:
    ``roofline_regressions_total`` += the regression count (the
    ``roofline_regression`` default delta rule fires on any increase).
    Returns the count so callers can exit on it."""
    n = len(diff.get("regressions", ()))
    if n:
        _M_REGRESSIONS.inc(n)
    return n


def export_gauges(report, top_k=16):
    """Put a report's numbers on the live registry — the same table
    ``/metrics`` and ``/varz`` serve: ``roofline_residual_ratio{op}`` for
    the top-K rows by wasted µs (bounded: op names are an unbounded label
    space) and ``roofline_bound_fraction{bound}``."""
    for b, frac in report["summary"]["bound_fraction"].items():
        _M_BOUND.labels(bound=b).set(frac)
    for r in report["rows"][:int(top_k)]:
        if r["residual_ratio"] is not None:
            _M_RESIDUAL.labels(op=r["name"]).set(r["residual_ratio"])
    return report["summary"]


# --------------------------------------------------------------- rendering
def _eng(n, unit=""):
    for div, suf in ((1e12, "T"), (1e9, "G"), (1e6, "M"), (1e3, "K")):
        if abs(n) >= div:
            return f"{n / div:.2f}{suf}{unit}"
    return f"{n:.0f}{unit}"


def render_text(report_or_rows, top=20):
    """The operator table: top-K by wasted µs, residual + bound + achieved
    rates per row, bound split in the footer."""
    if isinstance(report_or_rows, dict) and "rows" in report_or_rows:
        rows = report_or_rows["rows"]
        summary = report_or_rows.get("summary")
    else:
        rows, summary = list(report_or_rows), None
    head = (f"{'op':36s} {'count':>5s} {'meas_ms':>9s} {'pred_ms':>9s} "
            f"{'resid':>7s} {'bound':>7s} {'GF/s':>8s} {'GB/s':>8s} "
            f"{'waste_ms':>9s}")
    lines = [head, "-" * len(head)]
    for r in rows[:top]:
        resid = f"{r['residual_ratio']:.2f}" \
            if r.get("residual_ratio") is not None else "-"
        mark = "" if r.get("matched", True) else " *"
        # tolerate trace_report join rows, which carry total_us instead
        meas = r.get("measured_us", r.get("total_us", 0.0))
        lines.append(
            f"{(r['name'] + mark)[:36]:36s} {r.get('count', 0):5d} "
            f"{meas / 1e3:9.3f} {r['predicted_us'] / 1e3:9.3f} "
            f"{resid:>7s} {r['bound']:>7s} "
            f"{r.get('achieved_flops_per_sec', 0.0) / 1e9:8.2f} "
            f"{r.get('achieved_bytes_per_sec', 0.0) / 1e9:8.2f} "
            f"{r['wasted_us'] / 1e3:9.3f}")
    shown = min(top, len(rows))
    tail = (f"({shown}/{len(rows)} ops shown, sorted by wasted time; "
            f"* = no census match; resid '-' = nothing predicted)")
    if summary:
        bf = summary["bound_fraction"]
        tail += (f"\nbound split of measured time: "
                 f"compute {bf.get('compute', 0.0):.0%} / "
                 f"memory {bf.get('memory', 0.0):.0%} / "
                 f"unknown {bf.get('unknown', 0.0):.0%}; "
                 f"total residual "
                 f"{summary['residual_ratio'] if summary['residual_ratio'] is not None else '-'}")
    lines.append(tail)
    return "\n".join(lines)


def render_diff_text(diff):
    lines = []
    if not diff["comparable"]:
        lines.append(
            f"WARNING: rounds are not content-comparable (old key "
            f"{diff['old_key']}, new key {diff['new_key']}) — different "
            f"hardware, peaks, or config; verdicts below are advisory")
    for kind, entries in (("REGRESSED", diff["regressions"]),
                          ("improved", diff["improvements"])):
        for e in entries:
            lines.append(
                f"{kind}: {e['name']} residual {e['old_ratio']:.2f} -> "
                f"{e['new_ratio']:.2f} ({e['bound']}-bound, "
                f"{e['delta_wasted_us'] / 1e3:+.3f} ms wasted)")
    if diff["new_ops"]:
        lines.append(f"new ops (no baseline): "
                     f"{', '.join(diff['new_ops'][:8])}"
                     + (" ..." if len(diff["new_ops"]) > 8 else ""))
    if diff["gone_ops"]:
        lines.append(f"gone ops (baseline only): "
                     f"{', '.join(diff['gone_ops'][:8])}"
                     + (" ..." if len(diff["gone_ops"]) > 8 else ""))
    lines.append(
        f"{len(diff['regressions'])} regression(s), "
        f"{len(diff['improvements'])} improvement(s) at threshold "
        f"{diff['threshold']:.0%} / floor {diff['min_us']:.0f}us")
    return "\n".join(lines)
