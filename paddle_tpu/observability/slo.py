"""SLO tracking: deterministic sliding-window percentiles + burn rates.

Histograms answer "what is the long-run distribution"; an operator paging
on an SLO needs "what are p50/p95/p99 *right now* and how fast am I
burning error budget".  This module keeps, per tracked series, a bounded
window of the most recent observations and derives:

- ``slo_latency_seconds{series,quantile}`` — nearest-rank percentiles over
  the window (deterministic: same observations => same value, no
  interpolation, no decay constants);
- ``slo_events_total{series}`` / ``slo_violations_total{series}`` — every
  observation, and those above the series' SLO target;
- ``slo_burn_rate_ratio{series}`` — violating fraction of the current
  window: 0.0 = no budget burn, 1.0 = every request out of SLO (multiply
  by the window span for an alerting burn-rate);
- ``slo_target_seconds{series}`` — the configured target, scrapeable next
  to the latencies it judges.

Wired into the serving path (TTFT / e2e / queue-wait / decode tick, see
``LLMEngine``), the train step (``sharded_train_step``) and the hapi
``StatsCallback``; surfaced in ``LLMEngine.stats()["slo"]`` and on
``/metrics``.  ``metrics.disable()`` turns ``observe`` into one dict
lookup, like every other instrumentation point.

No jax / numpy imports (same contract as ``observability.metrics``).
"""
from __future__ import annotations

import math
import threading
from bisect import insort, bisect_left
from collections import deque

from . import metrics as _metrics

__all__ = [
    "SLOTracker", "SLORegistry", "SLOS", "track", "set_target", "summary",
    "DEFAULT_QUANTILES", "DEFAULT_WINDOW",
]

DEFAULT_QUANTILES = (0.5, 0.95, 0.99)
DEFAULT_WINDOW = 512

_M_LATENCY = _metrics.gauge(
    "slo_latency_seconds",
    "Sliding-window latency percentile per tracked series",
    labelnames=("series", "quantile"))
_M_TARGET = _metrics.gauge(
    "slo_target_seconds",
    "Configured SLO target of each tracked series (0 = untargeted)",
    labelnames=("series",))
_M_EVENTS = _metrics.counter(
    "slo_events_total", "Observations per tracked series",
    labelnames=("series",))
_M_VIOLATIONS = _metrics.counter(
    "slo_violations_total",
    "Observations above the series' SLO target", labelnames=("series",))
_M_BURN = _metrics.gauge(
    "slo_burn_rate_ratio",
    "Violating fraction of the current window per series",
    labelnames=("series",))


def _quantile_label(q):
    # 0.5 -> "p50", 0.99 -> "p99", 0.999 -> "p99_9" (label values stay
    # snake-ish so dashboards can template them)
    pct = q * 100.0
    if abs(pct - round(pct)) < 1e-9:
        return f"p{int(round(pct))}"
    return ("p" + f"{pct:.10g}").replace(".", "_")


class SLOTracker:
    """One series: bounded observation window + sorted mirror.

    The sorted mirror makes every percentile read O(1) after an
    O(log n) insert/remove per observation — scrapes never sort, and the
    hot path never allocates beyond the two bounded containers.
    """

    def __init__(self, series, target=None, window=DEFAULT_WINDOW,
                 quantiles=DEFAULT_QUANTILES):
        self.series = str(series)
        self.window = max(1, int(window))
        self.quantiles = tuple(quantiles)
        self._ring: deque = deque()   # arrival order (for eviction)
        self._sorted: list = []       # value order (for percentiles)
        self._viol_ring: deque = deque()  # parallel to _ring (0/1 flags)
        self._viol_count = 0          # running sum of _viol_ring
        self._lock = threading.Lock()
        self.target = None
        self.set_target(target)

    def set_target(self, target):
        self.target = float(target) if target is not None else None
        _M_TARGET.labels(series=self.series).set(self.target or 0.0)
        return self

    def observe(self, value):
        """Record one observation; returns True when it violated the
        series' target — callers correlate the verdict with the request
        that produced it (``Trace.mark_slo``: the tail sampler keeps
        SLO-violating traces)."""
        if not _metrics._runtime["enabled"]:
            return False
        v = float(value)
        violated = self.target is not None and v > self.target
        with self._lock:
            if len(self._ring) == self.window:
                old = self._ring.popleft()
                del self._sorted[bisect_left(self._sorted, old)]
                self._viol_count -= self._viol_ring.popleft()
            self._ring.append(v)
            insort(self._sorted, v)
            self._viol_ring.append(1 if violated else 0)
            self._viol_count += 1 if violated else 0
            burn = self._viol_count / len(self._ring)
            pcts = [self._percentile_locked(q) for q in self.quantiles]
        _M_EVENTS.labels(series=self.series).inc()
        if violated:
            _M_VIOLATIONS.labels(series=self.series).inc()
        _M_BURN.labels(series=self.series).set(burn)
        for q, p in zip(self.quantiles, pcts):
            _M_LATENCY.labels(series=self.series,
                              quantile=_quantile_label(q)).set(p)
        return violated

    def _percentile_locked(self, q):
        n = len(self._sorted)
        if not n:
            return 0.0
        # nearest-rank (inclusive): the smallest value with cumulative
        # frequency >= q — deterministic and exact on small windows
        idx = max(0, min(n - 1, int(math.ceil(q * n)) - 1))
        return self._sorted[idx]

    def percentile(self, q):
        with self._lock:
            return self._percentile_locked(q)

    def summary(self):
        with self._lock:
            n = len(self._ring)
            pcts = {_quantile_label(q): self._percentile_locked(q)
                    for q in self.quantiles}
            burn = (self._viol_count / n) if n else 0.0
        return {"window": n, "target": self.target, "burn_rate": burn,
                **pcts}


class SLORegistry:
    """series name -> tracker, created on first use."""

    def __init__(self):
        self._trackers = {}
        self._lock = threading.Lock()

    def tracker(self, series, target=None, window=DEFAULT_WINDOW) -> SLOTracker:
        t = self._trackers.get(series)
        if t is None:
            with self._lock:
                t = self._trackers.setdefault(
                    series, SLOTracker(series, target=target, window=window))
        return t

    def track(self, series, value):
        return self.tracker(series).observe(value)

    def set_target(self, series, target):
        self.tracker(series).set_target(target)

    def summary(self, prefix=None):
        with self._lock:
            items = list(self._trackers.items())
        return {name: t.summary() for name, t in items
                if prefix is None or name.startswith(prefix)}


#: Process-global SLO registry (mirrors metrics.REGISTRY).
SLOS = SLORegistry()


def track(series, value):
    return SLOS.track(series, value)


def set_target(series, target):
    SLOS.set_target(series, target)


def summary(prefix=None):
    return SLOS.summary(prefix=prefix)
