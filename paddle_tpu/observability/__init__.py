"""Unified observability layer: metrics registry + structured spans.

The numbers half of the paper stack's host-tracer/device-tracer/cost-model
triple: a dependency-free process-global metrics registry
(`observability.metrics`) and span events that feed both the registry and
the native chrome-trace buffer (`observability.spans` via
`profiler.RecordEvent`).  Every built-in hot path — sharded train step,
checkpoint commit protocol, TCPStore client, recovery supervisor, LLM
server — registers its series here at import time, so
``paddle_tpu.observability.render_prometheus()`` is a complete `/metrics`
payload the moment the process starts, and ``tools/metrics_lint.py`` can
police the namespace without running a workload.

Quick start::

    import paddle_tpu as paddle
    obs = paddle.observability
    ...train / serve...
    print(obs.render_prometheus())         # Prometheus text exposition
    obs.dump_jsonl("metrics.jsonl")        # append-only local time series
    obs.disable()                          # per-call cost -> one dict lookup
"""
from .metrics import (  # noqa: F401
    Counter, Gauge, Histogram, MetricRegistry, REGISTRY,
    counter, gauge, histogram, enable, disable, enabled,
    snapshot, render_prometheus, dump_jsonl, log_buckets,
    DEFAULT_TIME_BUCKETS,
)
from .spans import span  # noqa: F401
from . import metrics  # noqa: F401
from . import spans  # noqa: F401

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricRegistry", "REGISTRY",
    "counter", "gauge", "histogram", "enable", "disable", "enabled",
    "snapshot", "render_prometheus", "dump_jsonl", "log_buckets",
    "DEFAULT_TIME_BUCKETS", "span", "metrics", "spans",
]
