"""Unified observability layer: metrics, spans, and the telemetry plane.

The numbers half of the paper stack's host-tracer/device-tracer/cost-model
triple: a dependency-free process-global metrics registry
(`observability.metrics`) and span events that feed both the registry and
the native chrome-trace buffer (`observability.spans` via
`profiler.RecordEvent`).  Every built-in hot path — sharded train step,
checkpoint commit protocol, TCPStore client, recovery supervisor, LLM
server — registers its series here at import time, so
``paddle_tpu.observability.render_prometheus()`` is a complete `/metrics`
payload the moment the process starts, and ``tools/metrics_lint.py`` can
police the namespace without running a workload.

On top of the registry sits the telemetry plane (ISSUE 5):

- `observability.exporter` — stdlib HTTP endpoints: `/metrics`
  (Prometheus text), `/healthz` (component healthchecks), `/varz` (JSON
  snapshot); opt-in via ``LLMEngine(metrics_port=...)``,
  ``run_with_recovery(telemetry_port=...)`` or the launcher's
  ``--metrics_port``;
- `observability.flight_recorder` — a bounded black-box event ring dumped
  to JSONL (+ chrome trace) on crashes, preemptions and watchdog trips;
- `observability.slo` — deterministic sliding-window p50/p95/p99 and
  burn-rate tracking against configurable SLO targets.

And on top of the telemetry plane, the alerting plane (ISSUE 7) — the
first CONSUMER of the endpoints:

- `observability.scrape` — Prometheus text-format parser (the inverse of
  ``render_prometheus()``) plus a multi-target fleet scraper with
  per-target monotonic deadlines, bounded retry and staleness tracking;
- `observability.alerts` — declarative threshold / burn-rate / absence /
  delta rules with `for`-duration hysteresis, a deterministic
  inactive→pending→firing→resolved state machine, `/alertz` state on
  ``TelemetryServer``, and ``AlertPolicy`` actuation that drives
  ``run_with_recovery`` / ``ElasticManager`` restart decisions off the
  scraped series (``tools/fleetwatch.py`` is the operator CLI).

And orthogonal to the aggregate planes, the forensic plane (ISSUE 8):

- `observability.tracing` — request-scoped tracing: per-request span
  trees carried by an explicit context object, tail-sampled into a
  bounded store served on ``TelemetryServer`` `/tracez`, correlated to
  the aggregate planes via flight-recorder ``trace_id`` fields and
  OpenMetrics histogram EXEMPLARS (``# {trace_id="..."}`` annotations on
  `/metrics` that ``parse_prometheus`` round-trips).

And below the host boundary, the profiling plane (ISSUE 14):

- `observability.xplane` — dependency-free reader for the
  ``.xplane.pb`` dumps ``jax.profiler.trace()`` writes (hand-rolled
  protobuf wire parsing; no tensorflow/protobuf import), decoding
  per-HLO device events for the census<->timeline join
  (``tools/trace_report.py --xplane``);
- `observability.profiling` — ``ProfilingSession`` (a profiler window
  filed under the owning span), compile telemetry
  (``jit_compiles_total`` / ``jit_recompiles_total`` feeding the
  ``recompile_storm`` alert rule) and device-memory telemetry
  (``hbm_*`` gauges from ``device.memory_stats()``).

And joining the profiling plane against the cost model, the roofline
residual plane (ISSUE 17):

- `observability.roofline` — per-HLO measured-vs-predicted attribution
  (min-time roofline ``max(flops/peak_flops, bytes/peak_bw)`` vs XPlane
  per-op µs), compute-/memory-bound classification, content-addressed
  ``ROOFLINE_<round>.json`` rounds and the per-op regression sentinel
  (``tools/roofline_report.py --diff``); exports
  ``roofline_residual_ratio{op}`` / ``roofline_bound_fraction{bound}``
  and ``roofline_regressions_total`` (the ``roofline_regression``
  default delta alert rule's series).

Quick start::

    import paddle_tpu as paddle
    obs = paddle.observability
    srv = obs.start_exporter(port=9100)    # /metrics /healthz /varz
    ...train / serve...
    print(obs.render_prometheus())         # Prometheus text exposition
    print(obs.slo.summary())               # sliding-window percentiles
    obs.dump_jsonl("metrics.jsonl")        # append-only local time series
    obs.flight_recorder.dump("black_box")  # forensic event dump
    srv.stop()
    obs.disable()                          # per-call cost -> one dict lookup
"""
from .metrics import (  # noqa: F401
    Counter, Gauge, Histogram, MetricRegistry, REGISTRY,
    counter, gauge, histogram, enable, disable, enabled,
    snapshot, render_prometheus, dump_jsonl, log_buckets,
    DEFAULT_TIME_BUCKETS,
)
from .spans import span  # noqa: F401
from .flight_recorder import FlightRecorder, record_event  # noqa: F401
from .exporter import TelemetryServer, start_exporter  # noqa: F401
from .slo import SLOTracker, SLORegistry, SLOS  # noqa: F401
from .scrape import (  # noqa: F401
    parse_prometheus, SampleSet, Scraper, ScrapeTarget,
)
from .alerts import (  # noqa: F401
    Rule, AlertEngine, AlertPolicy, AlertDecision, default_rules,
    JsonlNotifier,
)
from .tracing import (  # noqa: F401
    Trace, Tracer, TraceStore, TRACES, TRACER, NULL_TRACE, start_trace,
)
from .xplane import (  # noqa: F401
    parse_xspace, load_xspace, find_dump, per_op_summary,
)
from .profiling import (  # noqa: F401
    ProfilingSession, install_compile_hooks, record_compile, mark_warm,
    poll_device_memory,
)
from .roofline import (  # noqa: F401
    predict_op, residual_rows, build_report, merge_reports, diff_reports,
    record_diff, export_gauges, save_round, load_round, newest_round,
)
from . import metrics  # noqa: F401
from . import spans  # noqa: F401
from . import flight_recorder  # noqa: F401
from . import exporter  # noqa: F401
from . import slo  # noqa: F401
from . import scrape  # noqa: F401
from . import alerts  # noqa: F401
from . import tracing  # noqa: F401
from . import xplane  # noqa: F401
from . import profiling  # noqa: F401
from . import roofline  # noqa: F401

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricRegistry", "REGISTRY",
    "counter", "gauge", "histogram", "enable", "disable", "enabled",
    "snapshot", "render_prometheus", "dump_jsonl", "log_buckets",
    "DEFAULT_TIME_BUCKETS", "span", "metrics", "spans",
    "FlightRecorder", "record_event", "flight_recorder",
    "TelemetryServer", "start_exporter", "exporter",
    "SLOTracker", "SLORegistry", "SLOS", "slo",
    "parse_prometheus", "SampleSet", "Scraper", "ScrapeTarget", "scrape",
    "Rule", "AlertEngine", "AlertPolicy", "AlertDecision", "default_rules",
    "JsonlNotifier", "alerts",
    "Trace", "Tracer", "TraceStore", "TRACES", "TRACER", "NULL_TRACE",
    "start_trace", "tracing",
    "parse_xspace", "load_xspace", "find_dump", "per_op_summary",
    "xplane",
    "ProfilingSession", "install_compile_hooks", "record_compile",
    "mark_warm", "poll_device_memory", "profiling",
    "predict_op", "residual_rows", "build_report", "merge_reports",
    "diff_reports", "record_diff", "export_gauges", "save_round",
    "load_round", "newest_round", "roofline",
]
