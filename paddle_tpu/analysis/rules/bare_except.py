"""bare-except-swallow: exception swallowing in the recovery paths.

The fault-tolerance layer's whole contract is that failures are *diagnosed*:
``run_with_recovery`` needs the real exception to decide retry-vs-raise, the
checkpoint loader needs it to quarantine the right step.  A bare ``except:``
(which also eats ``KeyboardInterrupt``/``SystemExit``) or an
``except Exception: pass`` in these files turns a diagnosable fault into a
silent hang one layer up — the exact failure mode PR 1 was built to kill.

Scope is the recovery surface only (fault_tolerance, llm_server, store,
checkpoint): elsewhere a narrow swallowed exception can be a legitimate
best-effort cleanup.  Stays clean by design: handlers that re-raise, log,
record metrics, or catch a NARROW type (``except OSError: pass`` around an
advisory write is fine — the type itself documents the intent).
"""
from __future__ import annotations

import ast

from ..engine import FileRule, register

#: The recovery surface — files whose except-handlers make retry decisions.
RECOVERY_PATHS = (
    "paddle_tpu/distributed/fault_tolerance.py",
    "paddle_tpu/distributed/store.py",
    "paddle_tpu/distributed/checkpoint.py",
    "paddle_tpu/inference/llm_server.py",
)

_BROAD = ("Exception", "BaseException")


def _swallows(handler) -> bool:
    """Body does nothing with the exception: only pass/continue/constants."""
    return all(
        isinstance(s, (ast.Pass, ast.Continue))
        or (isinstance(s, ast.Expr) and isinstance(s.value, ast.Constant))
        for s in handler.body)


@register
class BareExceptSwallowRule(FileRule):
    name = "bare-except-swallow"
    severity = "error"
    description = (
        "bare except (error) or `except Exception: pass` (warning) in "
        "recovery paths — turns diagnosable faults into silent hangs")
    paths = RECOVERY_PATHS

    def check(self, ctx):
        out = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if node.type is None:
                out.append(ctx.finding(
                    self, node,
                    "bare except in a recovery path — also catches "
                    "KeyboardInterrupt/SystemExit; name the exception types "
                    "the recovery decision actually handles",
                    severity="error"))
                continue
            types = (list(node.type.elts)
                     if isinstance(node.type, ast.Tuple) else [node.type])
            tnames = [t.attr if isinstance(t, ast.Attribute)
                      else getattr(t, "id", None) for t in types]
            tname = next((n for n in tnames if n in _BROAD), None)
            if tname is not None and _swallows(node):
                out.append(ctx.finding(
                    self, node,
                    f"'except {tname}' swallows the fault in a recovery "
                    f"path — re-raise, log, or narrow the type; baseline "
                    f"with a justification if the swallow is load-bearing",
                    severity="warning"))
        return out
