"""Project-level catalogue rules: the non-AST lints unified under tpulint.

- ``metrics-catalogue`` — PR 2's ``tools/metrics_lint.py`` registered as a
  tpulint rule so CI has ONE lint entrypoint.  The old CLI remains as a thin
  shim; the logic (registry walk vs README §Observability catalogue) still
  lives in tools/metrics_lint.py and is loaded from there, so the two
  entrypoints cannot drift.
- ``docs-stale`` — ``tools/docs_lint.py``: PROJECTION.md must cite the
  newest ``BENCH_r*.json`` and ``ROOFLINE_*.json`` rounds; a stale citation
  means the pod projections are anchored to superseded measurements.

Both degrade to a ``note`` (never fails the build) when their inputs are
absent — fixture trees and installed-package environments have no tools/
directory, and the metrics rule needs the live package importable.
"""
from __future__ import annotations

import importlib.util
import os
import sys

from ..engine import Finding, ProjectRule, register


def _load_tool(root: str, filename: str, modname: str):
    """Import a tools/ script by path (tools/ is not a package).  The cache
    key includes the root: one process may lint several trees (fixture tests,
    a daemon over two checkouts) and must not serve rootA's module to
    rootB."""
    path = os.path.join(root, "tools", filename)
    if not os.path.exists(path):
        return None
    modname = f"{modname}_{abs(hash(os.path.abspath(root))):x}"
    if modname in sys.modules:
        return sys.modules[modname]
    spec = importlib.util.spec_from_file_location(modname, path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules[modname] = mod
    spec.loader.exec_module(mod)
    return mod


@register
class MetricsCatalogueRule(ProjectRule):
    name = "metrics-catalogue"
    severity = "error"
    description = (
        "metric namespace lint (tools/metrics_lint.py): snake_case, unit "
        "suffix, README §Observability catalogue membership")

    def check_project(self, project):
        ml = _load_tool(project.root, "metrics_lint.py", "_tpulint_metrics")
        if ml is None:
            return []  # not a repo checkout — nothing to police
        readme = os.path.join(project.root, "README.md")
        try:
            registry = ml.import_instrumented(project.root)
        except Exception as e:  # package unimportable: report, don't crash
            return [Finding(
                rule=self.name, path="tools/metrics_lint.py", line=1, col=0,
                message=f"skipped: cannot import the instrumented package "
                        f"({type(e).__name__}: {e})", severity="note")]
        # `import paddle_tpu` is cached process-wide: if an EARLIER lint (or
        # the host app) imported it from a different checkout, this registry
        # does not describe project.root — say so instead of mis-linting
        pkg = sys.modules.get("paddle_tpu")
        pkg_file = getattr(pkg, "__file__", None)
        if pkg_file and os.path.realpath(os.path.dirname(os.path.dirname(
                pkg_file))) != os.path.realpath(project.root):
            return [Finding(
                rule=self.name, path="tools/metrics_lint.py", line=1, col=0,
                message=f"skipped: paddle_tpu already imported from "
                        f"{os.path.dirname(pkg_file)}, not this root — "
                        f"run `python tools/tpulint.py --select "
                        f"metrics-catalogue` in a fresh process",
                severity="note")]
        # content = message: project findings have no source line, and the
        # baseline must be able to address ONE finding, not the whole rule
        return [Finding(rule=self.name, path="README.md", line=1, col=0,
                        message=err, severity=self.severity, content=err)
                for err in ml.lint(registry, readme)]


@register
class DocsStaleRule(ProjectRule):
    name = "docs-stale"
    severity = "warning"
    description = (
        "PROJECTION.md must cite the newest BENCH_r*.json and "
        "ROOFLINE_*.json rounds (tools/docs_lint.py)")

    def check_project(self, project):
        dl = _load_tool(project.root, "docs_lint.py", "_tpulint_docs")
        if dl is None:
            return []
        return [Finding(rule=self.name, path=path, line=line, col=0,
                        message=msg, severity=self.severity, content=msg)
                for path, line, msg in dl.check(project.root)]
