"""blocking-under-lock: no blocking work inside a ``with <lock>`` body.

A lock in the serving tick path is a shared-latency budget: every
millisecond spent holding it is added to every other thread's p99.  The
review logs of PRs 7/8/15 caught the same bug by hand three times (a JSONL
log write under the engine lock, a notifier callback under the engine lock,
a device upload under the adapter-registry lock) — this rule fails lint
instead.  Findings are ``error`` under ``paddle_tpu/inference/`` and
``paddle_tpu/observability/`` (the tick/scrape hot paths, where a stall is a
direct TTFT/SLO cost) and ``warning`` elsewhere.

Blocking categories (one finding per ``with``-block per category):

- ``sleep`` — ``time.sleep(...)``
- ``thread-join`` — ``t.join()`` / ``t.join(5)`` / ``t.join(timeout=...)``
  (string/path joins have non-numeric arguments and are ignored)
- ``future-result`` — ``fut.result(...)``
- ``wait`` — ``event.wait(...)``; ``cond.wait()`` on the *held* condition is
  NOT flagged (it releases the lock while waiting — that is its contract)
- ``subprocess`` — ``subprocess.*``, ``os.system``/``popen``/``waitpid``
- ``net-io`` — ``socket.*``/``urllib.*``/``requests.*``/``http.*`` roots,
  ``urlopen``/``create_connection``/``getaddrinfo``, and local ``_http*``
  helpers (the router's ``_http_json`` is a network round-trip)
- ``file-io`` — builtin ``open()``, ``os.replace``/``rename``/``makedirs``/
  ``fsync``/``remove``/``unlink``, ``shutil.*``, ``json.dump``
- ``jit-dispatch`` — ``jnp.asarray``/``jnp.array``/``jax.device_put``,
  names bound from ``jit(...)``/``pjit(...)``, ``*_jit`` callables, and the
  double-call idiom ``self._get_foo(k)(...)`` (fetch-then-invoke of a
  cached jitted callable — first call compiles)
- ``device-transfer`` — ``jax.device_get``, bare ``np.asarray`` (a device
  array operand forces a BLOCKING device->host copy; dispatch is async but
  the fetch is not), and ``.block_until_ready()``.  The hierarchical-kv
  demotion worker is the canonical tenant: gather dispatch under the lock
  is fine, the host-side fetch must happen outside it

True positives this rule exists for::

    with self._lock:
        self._trace.append(ev)
        json.dump(self._trace, open(path, "w"))   # file-io under the lock

    with self._lock:
        w = jnp.asarray(host_w)                   # device transfer under lock

Documented false-positive patterns (and their dispositions):

- ``cond.wait()`` inside ``with cond:`` — skipped automatically (the wait
  releases the lock).
- A warmup/startup path that deliberately compiles under the engine lock
  while no traffic exists — real finding by the rule's lights; baseline it
  with a justification (``llm_server.warmup`` is the canonical entry).
- A lock whose entire purpose is serializing the blocking call itself
  (single-writer JSONL append) — baseline with a justification naming the
  invariant.

Code inside nested ``def``/``lambda`` bodies is never flagged: it is
deferred, not executed while the lock is held.
"""
from __future__ import annotations

import ast

from ..engine import FileRule, register
from ._locks import (attr_chain, file_lock_names, iter_lexical,
                     jit_bound_names, lock_items)
from ._traced import callee_name

#: Paths where a lock stall is a direct serving-latency cost -> error.
HOT_PREFIXES = ("paddle_tpu/inference/", "paddle_tpu/observability/")

_NET_ROOTS = ("socket.", "urllib.", "requests.", "http.")
_NET_NAMES = frozenset({"urlopen", "create_connection", "getaddrinfo"})
_OS_BLOCKING = frozenset({
    "os.system", "os.popen", "os.waitpid", "os.replace", "os.rename",
    "os.makedirs", "os.fsync", "os.remove", "os.unlink"})
_FILE_OS = frozenset({"os.replace", "os.rename", "os.makedirs", "os.fsync",
                      "os.remove", "os.unlink"})
_JNP_DISPATCH = frozenset({"asarray", "array", "device_put", "copy"})
_DEVICE_TRANSFER = frozenset({"jax.device_get", "np.asarray",
                              "numpy.asarray"})


def _classify(call, jit_names, held_lock_dumps):
    """(category, label) for a blocking call, or None."""
    func = call.func
    name = callee_name(func)
    chain = attr_chain(func)
    root = chain.split(".", 1)[0] + "." if "." in chain else ""

    if name == "sleep" and (chain in ("sleep", "time.sleep")
                            or chain.endswith(".sleep")):
        return ("sleep", chain or name)
    if name == "join" and isinstance(func, ast.Attribute):
        if isinstance(func.value, ast.Constant):
            return None  # ", ".join(...)
        blocking_sig = (
            (not call.args and not call.keywords)
            or any(kw.arg == "timeout" for kw in call.keywords)
            or (len(call.args) == 1
                and isinstance(call.args[0], ast.Constant)
                and isinstance(call.args[0].value, (int, float))))
        if blocking_sig and not chain.startswith("os.path"):
            return ("thread-join", chain or ".join()")
        return None
    if name == "result" and isinstance(func, ast.Attribute):
        return ("future-result", chain or ".result()")
    if name == "wait" and isinstance(func, ast.Attribute):
        # cond.wait() on the HELD lock releases it — that is the point
        if ast.dump(func.value) in held_lock_dumps:
            return None
        return ("wait", chain or ".wait()")
    if root == "subprocess." or chain in _OS_BLOCKING - _FILE_OS:
        return ("subprocess", chain)
    if (root in _NET_ROOTS or name in _NET_NAMES
            or name.lstrip("_").startswith("http")
            or name.startswith("_http")):
        return ("net-io", chain or name)
    if ((name == "open" and isinstance(func, ast.Name))
            or chain in _FILE_OS or root == "shutil."
            or (name == "dump" and root == "json.")):
        return ("file-io", chain or name)
    if (chain in _DEVICE_TRANSFER or name == "device_get"
            or name == "block_until_ready"):
        # device->host transfers BLOCK on the copy (unlike async dispatch):
        # checked before jit-dispatch so jax.device_get lands here
        return ("device-transfer", chain or name)
    if ((root in ("jnp.", "jax.") and name in _JNP_DISPATCH)
            or name in jit_names or name.endswith("_jit")):
        return ("jit-dispatch", chain or name)
    if isinstance(func, ast.Call):
        inner = callee_name(func.func)
        if inner.startswith("_get_") or inner in jit_names \
                or inner.endswith("_jit"):
            return ("jit-dispatch", f"{inner}(...)(...)")
    return None


@register
class BlockingUnderLockRule(FileRule):
    name = "blocking-under-lock"
    severity = "warning"
    description = ("blocking calls (I/O, sleep, join, subprocess, jit "
                   "dispatch) lexically inside a `with <lock>` body; error "
                   "in inference/ + observability/ hot paths")

    def check(self, ctx):
        lock_attrs, lock_names = file_lock_names(ctx.tree)
        jit_names = jit_bound_names(ctx.tree)
        hot = ctx.relpath.startswith(HOT_PREFIXES)
        findings = []
        for wnode in ast.walk(ctx.tree):
            if not isinstance(wnode, ast.With):
                continue
            locks = lock_items(wnode, lock_attrs, lock_names)
            if not locks:
                continue
            held = {ast.dump(e) for e in locks}
            lock_src = attr_chain(locks[0]) or "lock"

            # A nested lock-`with` gets its own scan as the walk reaches it;
            # pruning here keeps each call attributed to its innermost lock.
            def _nested_lock_with(n):
                return (n is not wnode and isinstance(n, ast.With)
                        and lock_items(n, lock_attrs, lock_names))

            hits = {}  # category -> [(node, label)]
            # items too: `with self._lock, open(p) as f:` opens under the lock
            extra = [it.context_expr for it in wnode.items
                     if it.context_expr not in locks]
            for n in iter_lexical(list(wnode.body) + extra,
                                  skip=_nested_lock_with):
                if not isinstance(n, ast.Call):
                    continue
                got = _classify(n, jit_names, held)
                if got:
                    hits.setdefault(got[0], []).append((n, got[1]))
            for category, sites in sorted(hits.items()):
                sites.sort(key=lambda s: (s[0].lineno, s[0].col_offset))
                node, label = sites[0]
                more = (f" (+{len(sites) - 1} more in this block)"
                        if len(sites) > 1 else "")
                findings.append(ctx.finding(
                    self, node,
                    f"`{label}` blocks while holding `{lock_src}` "
                    f"({category}){more} — move it outside the critical "
                    f"section (snapshot under the lock, act outside)",
                    severity="error" if hot else "warning"))
        return findings
