"""Shared helpers: which expressions are LOCKS, and what runs under them?

The concurrency rule family (``lock-guard-inference``, ``blocking-under-lock``,
``refcount-balance``) needs one shared answer to three questions:

- *Is this expression a lock?*  Two signals, both purely lexical: the name
  (``self._lock``, ``_checks_lock``, ``cond`` — matched by underscore-separated
  segment so ``clock``/``blocker`` do NOT match) and the constructor
  (anything assigned ``threading.Lock()`` / ``RLock()`` / ``Condition()`` /
  ``Semaphore()`` counts regardless of its name).
- *What are the aliases?*  ``lk = self._lock; with lk:`` guards the same
  attribute set as ``with self._lock:`` — :func:`file_lock_names` folds
  single-assignment aliases of known lock attributes into the lock-name set.
- *What is lexically inside a block?*  :func:`iter_lexical` walks a statement
  list without descending into nested ``def``/``lambda``/``class`` bodies —
  code in a nested function is *deferred*, not executed while the lock is
  held, so rules must neither flag nor learn from it.
"""
from __future__ import annotations

import ast

from ._traced import callee_name

#: threading constructors whose result is a lock for our purposes.  Condition
#: and Semaphore are included: ``with self._cond:`` holds the underlying lock.
THREADING_LOCK_CTORS = frozenset({
    "Lock", "RLock", "Condition", "Semaphore", "BoundedSemaphore"})

#: Name segments that mark a variable/attribute as a lock.  Matched on whole
#: ``_``-separated segments so ``self._clock`` and ``blocker`` stay clean
#: while ``self._checks_lock``, ``_seq_lock``, ``mu`` and ``cond`` match.
_LOCK_SEGMENTS = frozenset({
    "lock", "locks", "mutex", "mu", "cond", "condition",
    "sem", "semaphore", "cv"})


def is_lockish_name(name: str) -> bool:
    """Does ``name`` look like a lock, judged by its ``_``-split segments?"""
    return any(seg in _LOCK_SEGMENTS
               for seg in name.lower().strip("_").split("_"))


def attr_chain(node) -> str:
    """Dotted source form of a Name/Attribute chain (``self._lock``,
    ``jax.lax.psum``), or ``""`` when the chain has a non-name root."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def is_lock_ctor(node) -> bool:
    """``threading.Lock()`` / ``RLock()`` / ... call?"""
    return (isinstance(node, ast.Call)
            and callee_name(node.func) in THREADING_LOCK_CTORS)


def file_lock_names(tree):
    """(lock_attrs, lock_names) assigned a threading ctor anywhere in the
    file, plus local aliases of those attrs (``lk = self._lock``).

    ``lock_attrs`` are attribute names (``_lock`` from ``self._lock = ...``);
    ``lock_names`` are bare variable names (module-level ``_lock``, closure
    locals, and aliases).  Name-based detection (:func:`is_lockish_name`)
    is applied separately by :func:`is_lock_expr` — these sets only carry
    the constructor/alias facts a name cannot.
    """
    attrs, names = set(), set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and is_lock_ctor(node.value):
            for t in node.targets:
                if isinstance(t, ast.Attribute):
                    attrs.add(t.attr)
                elif isinstance(t, ast.Name):
                    names.add(t.id)
    # alias pass (after ctor pass so `lk = self._lock` sees `_lock`)
    for node in ast.walk(tree):
        if (isinstance(node, ast.Assign)
                and isinstance(node.value, ast.Attribute)
                and (node.value.attr in attrs
                     or is_lockish_name(node.value.attr))):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    names.add(t.id)
    return attrs, names


def is_lock_expr(expr, lock_attrs=frozenset(), lock_names=frozenset()) -> bool:
    """Is ``expr`` (typically a ``with``-item) a lock?  Only bare names and
    attribute chains qualify — a call like ``lock_path.open()`` never does."""
    if isinstance(expr, ast.Attribute):
        return expr.attr in lock_attrs or is_lockish_name(expr.attr)
    if isinstance(expr, ast.Name):
        return expr.id in lock_names or is_lockish_name(expr.id)
    return False


def lock_items(with_node, lock_attrs=frozenset(), lock_names=frozenset()):
    """The lock expressions among a With statement's context managers."""
    return [it.context_expr for it in with_node.items
            if is_lock_expr(it.context_expr, lock_attrs, lock_names)]


def iter_lexical(nodes, skip=None):
    """Yield every AST node lexically within ``nodes`` (a node or list),
    without descending into nested function/lambda/class bodies — those run
    later, not here.  ``skip(node) -> True`` prunes a subtree after yielding
    its root (used to hand nested lock-``with`` blocks to their own scan)."""
    stack = list(nodes) if isinstance(nodes, list) else [nodes]
    while stack:
        n = stack.pop()
        yield n
        if skip is not None and skip(n):
            continue
        for child in ast.iter_child_nodes(n):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda, ast.ClassDef)):
                continue
            stack.append(child)


def jit_bound_names(tree):
    """Names/attrs assigned from a ``jit``/``pjit`` call anywhere in the file
    — calling one of these IS device dispatch (blocks on compile the first
    time), wherever the call site is."""
    out = set()
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Assign)
                and isinstance(node.value, ast.Call)
                and callee_name(node.value.func) in ("jit", "pjit")):
            continue
        for t in node.targets:
            if isinstance(t, ast.Name):
                out.add(t.id)
            elif isinstance(t, ast.Attribute):
                out.add(t.attr)
    return out
