"""donation-misuse: a donated buffer read after the jitted call.

``donate_argnums`` hands the argument's buffer to XLA for reuse; the Python
reference left behind is poison — reading it after the call returns garbage
or raises ``BufferDonationError`` only on some platforms/layouts, i.e. it
works on CPU tests and corrupts on TPU pods.

Statically tractable slice handled here: the jitted callable is bound to a
simple name or ``self.attr`` with a LITERAL ``donate_argnums``, and a call
site passes a plain name / ``self.attr`` in a donated position.  The rule
fires when that expression is loaded again later in the same function body
without an intervening rebind.  Rebinding the result over the donated input
(``state = step(state)``) is the sanctioned idiom and stays clean; variable
``donate_argnums`` values are skipped (not resolvable without execution).
"""
from __future__ import annotations

import ast

from ..engine import FileRule, register
from ._traced import callee_name


def _expr_key(node):
    """Stable key for a donated-arg expression: Name or self.attr chains."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = _expr_key(node.value)
        return f"{base}.{node.attr}" if base else None
    return None


def _donated_indices(call: ast.Call):
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            try:
                val = ast.literal_eval(kw.value)
            except ValueError:
                return None  # non-literal: skip, can't resolve statically
            if isinstance(val, int):
                return (val,)
            if isinstance(val, (tuple, list)):
                return tuple(v for v in val if isinstance(v, int))
    return None


@register
class DonationMisuseRule(FileRule):
    name = "donation-misuse"
    severity = "error"
    description = (
        "argument in a donate_argnums position read after the jitted call "
        "in the same scope — donated buffers are invalidated by XLA")

    def check(self, ctx):
        # jitted-callable binding (name or self.attr) -> donated index tuple
        donators = {}
        for node in ast.walk(ctx.tree):
            if (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.value, ast.Call)
                    and callee_name(node.value.func) in ("jit", "pjit")):
                idxs = _donated_indices(node.value)
                key = _expr_key(node.targets[0])
                if idxs and key:
                    donators[key] = idxs
        if not donators:
            return []
        out = []
        for fn in ast.walk(ctx.tree):
            if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                out.extend(self._check_scope(ctx, fn, donators))
        # nested defs are walked by both their own and the enclosing scope
        return list(dict.fromkeys(out))

    def _check_scope(self, ctx, fn, donators):
        """Linear scan of one function body for donated-then-read args."""
        calls = []  # (call node, donated arg key)
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            key = _expr_key(node.func)
            idxs = donators.get(key)
            if not idxs:
                continue
            for i in idxs:
                if i < len(node.args):
                    akey = _expr_key(node.args[i])
                    if akey:
                        calls.append((node, akey, i))
        if not calls:
            return []
        out = []
        for call, akey, idx in calls:
            rebind_line = None
            for node in ast.walk(fn):
                if isinstance(node, (ast.Assign, ast.AugAssign)):
                    targets = (node.targets
                               if isinstance(node, ast.Assign)
                               else [node.target])
                    if any(_expr_key(t) == akey for t in targets) \
                            and node.lineno >= call.lineno:
                        if rebind_line is None or node.lineno < rebind_line:
                            rebind_line = node.lineno
            for node in ast.walk(fn):
                if (isinstance(node, (ast.Name, ast.Attribute))
                        and isinstance(getattr(node, "ctx", None), ast.Load)
                        and _expr_key(node) == akey
                        and node.lineno > call.lineno
                        and (rebind_line is None
                             or node.lineno < rebind_line)):
                    out.append(ctx.finding(
                        self, node,
                        f"'{akey}' was donated (donate_argnums index {idx}) "
                        f"to the jitted call at line {call.lineno} and is "
                        f"read here — the buffer may already be reused by "
                        f"XLA; rebind the call's result instead"))
                    break  # one finding per donated call is enough
        return out
