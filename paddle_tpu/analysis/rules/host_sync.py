"""host-sync-in-jit: device→host synchronization in traced or hot-path code.

Inside a traced function, ``.item()`` / ``float()`` / ``np.asarray()`` on a
traced value either breaks tracing outright or — worse — silently bakes a
host round-trip into every step.  In the serving/training hot-path modules the
same calls are legal but each one stalls the dispatch pipeline, so they are
reported as warnings and the deliberate ones live in the baseline with a
justification (e.g. llm_server's host-side admission-token sampling).

Documented false positives that stay clean:

- ``int(x.shape[0])`` / ``float(q.shape[-1])`` — static shape math, resolved
  at trace time, no sync (anything mentioning ``.shape``/``.ndim``/``.size``/
  ``len()`` is exempt);
- ``jnp.asarray(...)`` — device-side, only ``np.asarray``/``np.array`` sync;
- ``.item()`` in ordinary eager helpers outside traced spans and hot paths;
- ``int(accum_steps)`` / ``float(req.temperature)`` in hot-path modules —
  ``int()``/``float()``/``bool()`` on host config values is not a sync, so
  the builtin-cast check applies only INSIDE traced spans (where the
  argument is a tracer and the cast forces concretization).
"""
from __future__ import annotations

import ast

from ..engine import FileRule, register
from ._traced import callee_name, in_traced, traced_spans

#: Method calls that force a device→host transfer.
SYNC_METHODS = frozenset({"item", "numpy", "tolist", "block_until_ready"})

#: Serving/training hot paths where even host-legal syncs are budget items.
HOT_PATHS = (
    "paddle_tpu/distributed/sharded_train_step.py",
    "paddle_tpu/inference/llm_server.py",
    "paddle_tpu/models/generation.py",
)

_SHAPE_WORDS = frozenset({"shape", "ndim", "size", "dtype"})


def _is_shape_math(node) -> bool:
    """True when the expression is static-shape arithmetic (no sync)."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Attribute) and sub.attr in _SHAPE_WORDS:
            return True
        if isinstance(sub, ast.Name) and sub.id in _SHAPE_WORDS:
            return True
        if isinstance(sub, ast.Call) and callee_name(sub.func) == "len":
            return True
    return False


@register
class HostSyncRule(FileRule):
    name = "host-sync-in-jit"
    severity = "error"
    description = (
        ".item()/float()/int()/np.asarray()/.block_until_ready() on traced "
        "values inside jit/pjit/shard_map (error), or in serving/training "
        "hot-path modules (warning)")

    def check(self, ctx):
        spans = traced_spans(ctx.tree)
        hot = any(ctx.relpath == p or ctx.relpath.startswith(p)
                  for p in HOT_PATHS)
        aliases = ctx.import_aliases()
        np_names = {a for a, mod in aliases.items() if mod == "numpy"}
        out = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            traced = in_traced(node, spans)
            what = self._sync_kind(node, np_names, include_casts=traced)
            if what is None:
                continue
            if traced:
                out.append(ctx.finding(
                    self, node,
                    f"{what} inside a traced function — forces a host sync "
                    f"at trace time or breaks tracing; hoist it out of the "
                    f"jitted step", severity="error"))
            elif hot:
                out.append(ctx.finding(
                    self, node,
                    f"{what} in a hot-path module — each call stalls device "
                    f"dispatch; baseline with a justification if the sync "
                    f"is deliberate", severity="warning"))
        return out

    @staticmethod
    def _sync_kind(node: ast.Call, np_names, include_casts: bool):
        func = node.func
        if isinstance(func, ast.Attribute):
            if func.attr in SYNC_METHODS:
                return f".{func.attr}()"
            if (isinstance(func.value, ast.Name) and func.value.id in np_names
                    and func.attr in ("asarray", "array")):
                return f"{func.value.id}.{func.attr}()"
            if (func.attr == "device_get"
                    and isinstance(func.value, ast.Name)
                    and func.value.id == "jax"):
                return "jax.device_get()"
        elif include_casts and isinstance(func, ast.Name) \
                and func.id in ("float", "int", "bool"):
            if len(node.args) == 1 and not isinstance(node.args[0],
                                                      ast.Constant):
                if not _is_shape_math(node.args[0]):
                    return f"{func.id}()"
        return None
