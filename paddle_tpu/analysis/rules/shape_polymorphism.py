"""shape-polymorphism: concrete-shape branching baked into traced code.

``if x.shape[0] > 1:`` inside a jitted function is resolved at TRACE time —
jax happily specializes the program on the concrete shape and the branch
disappears from the compiled artifact.  That is sometimes exactly what you
want (layout dispatch on a static config), but it silently multiplies the
compile zoo (every distinct shape re-traces through a different branch) and
breaks shape-polymorphic lowering/export, where ``x.shape[0]`` is a symbolic
dimension that cannot be compared concretely.  The serving engine's
bounded-bucket discipline only works when shape branches are deliberate and
audited — so each one is reported as a warning and the intentional ones live
in the baseline with a justification.

Flagged inside traced spans (``_traced.traced_spans``):

- ``if`` / ``elif`` / ``while`` / conditional expressions whose test reads
  ``.shape`` or ``.ndim``, calls ``len(...)``, or probes via
  ``getattr(x, "shape"/"ndim", ...)``.

Documented false positives that stay clean:

- shape math OUTSIDE a test position (``jnp.arange(x.shape[1])`` — static
  and branch-free);
- branching on shapes in eager helpers outside traced spans (host-side
  dispatch into compiled programs is the sanctioned pattern);
- ``if training:`` / value-based ``jnp.where`` inside traces — no shape
  words in the test.
"""
from __future__ import annotations

import ast

from ..engine import FileRule, register
from ._traced import callee_name, in_traced, traced_spans

_SHAPE_ATTRS = frozenset({"shape", "ndim"})


def _shape_probe(test) -> str | None:
    """Name the first concrete-shape read in a branch test, else None."""
    for sub in ast.walk(test):
        if isinstance(sub, ast.Attribute) and sub.attr in _SHAPE_ATTRS:
            return f".{sub.attr}"
        if isinstance(sub, ast.Call):
            name = callee_name(sub.func)
            if name == "len":
                return "len()"
            if (name == "getattr" and len(sub.args) >= 2
                    and isinstance(sub.args[1], ast.Constant)
                    and sub.args[1].value in _SHAPE_ATTRS):
                return f'getattr(…, "{sub.args[1].value}")'
    return None


@register
class ShapePolymorphismRule(FileRule):
    name = "shape-polymorphism"
    severity = "warning"
    description = (
        "if/while/conditional tests reading .shape/.ndim/len() inside "
        "jit/pjit/shard_map — the branch is specialized away at trace time "
        "and breaks shape-polymorphic lowering; baseline deliberate "
        "layout dispatch")

    def check(self, ctx):
        spans = traced_spans(ctx.tree)
        if not spans:
            return []
        out = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, (ast.If, ast.IfExp, ast.While)):
                continue
            if not in_traced(node, spans):
                continue
            probe = _shape_probe(node.test)
            if probe is None:
                continue
            kind = {"If": "if", "IfExp": "conditional expression",
                    "While": "while"}[type(node).__name__]
            out.append(ctx.finding(
                self, node,
                f"{kind} test reads {probe} inside a traced function — the "
                f"branch specializes on the concrete shape at trace time "
                f"(re-traces per shape, breaks shape-polymorphic export); "
                f"hoist the dispatch to the host caller or baseline it as "
                f"deliberate"))
        return out
