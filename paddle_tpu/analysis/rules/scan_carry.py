"""scan-carry-dtype: loop carries must leave the body at the dtype they
entered.

``lax.scan`` / ``fori_loop`` / ``while_loop`` require the carry pytree to
have identical dtypes on entry and exit, but jax only errors when the
mismatch is *structural*.  A body that casts its carry to a concrete dtype
(``acc.astype(jnp.float32)``) silently pins the loop to that dtype: call the
step with bf16 state and either (a) XLA re-compiles a second program per
dtype (compile-zoo growth) or (b) the whole carry is upcast — double the HBM
for the loop state and double the carry bandwidth per step.  The ROADMAP has
carried this as a standing-floor candidate since the dtype-drift rule
landed; it is the loop-carry completion of that rule.

Flagged: a concrete-dtype cast in the *returned carry position* of a loop
body —

- ``scan`` body: first element of the returned ``(carry, y)`` pair;
- ``fori_loop`` body (``f(i, carry)``) / ``while_loop`` body: the whole
  return value;
- through one level of local assignment (``acc = x.astype(jnp.float32);
  return (acc, y)`` is resolved).

Concrete = ``jnp.float32``-style attribute, bare dtype name, or a string
constant (``"bfloat16"``).  Casts *derived from the carry itself* are the
sanctioned idiom and never flagged::

    def body(c, x):
        upd = jnp.dot(a, b).astype(c.dtype)     # OK: follows the carry
        return c + upd, None

Nor is a cast whose dtype the loop's *init* visibly shares — entry == exit
is the stable case (the flash-attention f32 accumulator pattern)::

    acc0 = jnp.zeros((B, D), jnp.float32)
    def body(i, acc):
        return acc + p.astype(jnp.float32)      # OK: init is f32 too
    out = lax.fori_loop(0, n, body, acc0)

True positive::

    def body(c, x):
        c = (c * decay + x).astype(jnp.float32)  # entry dtype unknown ->
        return c, c                              # silent f32 pin: flagged

Documented false-positive pattern: a body that *intentionally* widens the
carry (and whose caller passes an f32 init defined in another file) — the
init dtype is not lexically visible, so the rule cannot prove stability.
Baseline it with a justification naming where the init is pinned.
"""
from __future__ import annotations

import ast

from ..engine import FileRule, register
from ._locks import attr_chain
from ._traced import callee_name, _unwrap_partial

#: (callee, body-arg position, carry-param position, init-arg position)
_LOOPS = {
    "scan": (0, 0, 1),        # scan(f, init, xs): f(carry, x)
    "fori_loop": (2, 1, 3),   # fori_loop(lo, hi, body, init): body(i, c)
    "while_loop": (1, 0, 2),  # while_loop(cond, body, init): body(c)
}

_CONCRETE_DTYPES = frozenset({
    "float64", "float32", "float16", "bfloat16",
    "float8_e4m3fn", "float8_e5m2",
    "int64", "int32", "int16", "int8", "uint64", "uint32", "uint16", "uint8",
    "bool_", "complex64", "complex128",
    "f32", "f16", "bf16", "i32", "i8", "u8",
})


def _concrete_dtype(node):
    """Dtype name when ``node`` is a concrete dtype expression, else None.
    ``c.dtype`` / ``jnp.result_type(...)`` / a plain variable are symbolic
    (carry-derived or unknown) and return None."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value if node.value in _CONCRETE_DTYPES else None
    if isinstance(node, ast.Attribute) and node.attr in _CONCRETE_DTYPES:
        return node.attr
    if isinstance(node, ast.Name) and node.id in _CONCRETE_DTYPES:
        return node.id
    return None


def _casts_in(expr):
    """[(node, dtype-name)] concrete-dtype casts anywhere in ``expr``:
    ``x.astype(D)``, ``fn(..., dtype=D)``, ``jnp.float32(x)``."""
    out = []
    for n in ast.walk(expr):
        if not isinstance(n, ast.Call):
            continue
        name = callee_name(n.func)
        if name == "astype" and n.args:
            d = _concrete_dtype(n.args[0])
            if d:
                out.append((n, d))
        elif name in _CONCRETE_DTYPES and isinstance(n.func, ast.Attribute):
            out.append((n, name))
        for kw in n.keywords:
            if kw.arg == "dtype":
                d = _concrete_dtype(kw.value)
                if d:
                    out.append((n, d))
    return out


def _dtypes_mentioned(expr, assigns, depth=2):
    """Concrete dtype names lexically visible in ``expr``, resolving plain
    names through local assignments ``depth`` levels."""
    out = set()
    for n in ast.walk(expr):
        d = _concrete_dtype(n)
        if d:
            out.add(d)
        elif (isinstance(n, ast.Name) and depth > 0
              and n.id in assigns and n is not expr):
            for rhs in assigns[n.id]:
                out |= _dtypes_mentioned(rhs, assigns, depth - 1)
    if isinstance(expr, ast.Name) and expr.id in assigns and depth > 0:
        for rhs in assigns[expr.id]:
            out |= _dtypes_mentioned(rhs, assigns, depth - 1)
    return out


def _local_assigns(scope):
    """name -> [RHS exprs] for plain-name assignments in ``scope``."""
    out = {}
    for n in ast.walk(scope):
        if isinstance(n, ast.Assign):
            for t in n.targets:
                if isinstance(t, ast.Name):
                    out.setdefault(t.id, []).append(n.value)
        elif isinstance(n, ast.AugAssign) and isinstance(n.target, ast.Name):
            out.setdefault(n.target.id, []).append(n.value)
    return out


def _body_returns(fn):
    """Return statements lexically in ``fn`` (not nested defs)."""
    stack = list(fn.body) if not isinstance(fn, ast.Lambda) else [fn.body]
    rets = []
    while stack:
        n = stack.pop()
        if isinstance(n, ast.Return) and n.value is not None:
            rets.append(n.value)
            continue
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(n))
    if isinstance(fn, ast.Lambda):
        rets.append(fn.body)
    return rets


@register
class ScanCarryDtypeRule(FileRule):
    name = "scan-carry-dtype"
    severity = "warning"
    description = ("lax.scan/fori_loop/while_loop bodies whose carry is cast "
                   "to a concrete dtype the init does not visibly share "
                   "(silent upcast: HBM + recompile hazard)")

    def check(self, ctx):
        tree = ctx.tree
        defs = {}  # name -> [(lineno, def/lambda node)]
        for n in ast.walk(tree):
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                defs.setdefault(n.name, []).append((n.lineno, n))
            elif isinstance(n, ast.Assign) and isinstance(n.value, ast.Lambda):
                for t in n.targets:
                    if isinstance(t, ast.Name):
                        defs.setdefault(t.id, []).append((n.lineno, n.value))

        def resolve(name, at_line):
            """Nearest def of ``name`` preceding the call site — the usual
            `def body(...)` + `scan(body, ...)` adjacency; a same-named
            method elsewhere in the file must not shadow it."""
            cands = sorted(defs.get(name, ()))
            before = [d for ln, d in cands if ln <= at_line]
            if before:
                return before[-1]
            return cands[0][1] if cands else None
        findings = []
        for call in ast.walk(tree):
            if not (isinstance(call, ast.Call)
                    and callee_name(call.func) in _LOOPS):
                continue
            kind = callee_name(call.func)
            body_pos, carry_pos, init_pos = _LOOPS[kind]
            if len(call.args) <= max(body_pos, init_pos):
                continue
            body = _unwrap_partial(call.args[body_pos])
            if isinstance(body, ast.Name):
                body = resolve(body.id, call.lineno)
            if not isinstance(body, (ast.FunctionDef, ast.AsyncFunctionDef,
                                     ast.Lambda)):
                continue
            params = body.args.args
            if len(params) <= carry_pos:
                continue

            assigns = _local_assigns(body)
            # dtypes the init visibly pins (resolved through enclosing-scope
            # assignments): entry == exit for these -> stable, not flagged
            init_dtypes = _dtypes_mentioned(call.args[init_pos],
                                            _local_assigns(tree), depth=2)

            seen = set()
            for ret in _body_returns(body):
                if kind == "scan":
                    if not (isinstance(ret, ast.Tuple) and ret.elts):
                        continue
                    carry_expr = ret.elts[0]
                else:
                    carry_expr = ret
                # resolve returned names one assignment level deep
                exprs = [carry_expr]
                for n in ast.walk(carry_expr):
                    if isinstance(n, ast.Name) and n.id in assigns:
                        exprs.extend(assigns[n.id])
                for e in exprs:
                    for node, dtype in _casts_in(e):
                        if dtype in init_dtypes or id(node) in seen:
                            continue
                        seen.add(id(node))
                        findings.append(ctx.finding(
                            self, node,
                            f"`{kind}` carry leaves the body as {dtype} "
                            f"regardless of its entry dtype — cast with "
                            f"`.astype(carry.dtype)` (or pin the init to "
                            f"{dtype} in the same scope) to avoid a silent "
                            f"upcast/recompile"))
        return findings
