"""refcount-balance: every acquire must release on every exit edge.

The serving stack has three refcounted pools — KV pages
(``_incref``/``_decref`` + the raw ``self._page_ref[p] += 1`` counter),
adapter pages (``AdapterRegistry.acquire``/``release``), and plain
``threading`` locks taken imperatively (``.acquire()``/``.release()``).
A leak on an ``except`` or early-``return`` edge is invisible until the pool
runs dry under load; the conservation tests in ``test_prefix_cache.py`` /
``test_multi_tenant.py`` catch it at runtime — this rule catches it at lint.

Recognizers live in :data:`POOLS` — one line per pool; a new pool opts in by
adding its ``(label, acquire-names, release-names)`` row.  Raw counters
(``self.<x>_ref[k] += 1`` / ``-= 1``) are matched by the ``_ref`` attribute
suffix.

A function that calls an acquire-recognizer is accepted when one of:

- the acquire is a ``with`` item (``with pool.acquire(k) as page:``);
- a ``try/finally`` whose ``finally`` releases covers the acquire (either
  encloses it, or starts within 3 lines after it);
- the acquire sits in a ``try`` whose every ``except`` handler releases AND
  a release follows on the normal path;
- ownership escapes: the acquired resource is returned, yielded, stored
  into ``self``/a container, or passed to another call — the caller or the
  store owns the release now (this is how ``_alloc_pages`` hands pages to
  the request table);
- a matching release appears between the acquire and EVERY later ``return``
  (and on the fall-off-the-end path).

Otherwise it is flagged: no release at all, a ``return`` that skips the
release, or — when risky calls sit between acquire and release with no
``try/finally`` — an exception edge that would leak.

True positive::

    def claim(self, k):
        self._incref(k)
        if self._budget[k] > self.cap:
            return None          # leaked: the incref is never undone
        return self._decode(k)   # escape of the DECODE, not the refcount

False positives this rule deliberately does NOT emit:

- ``try/finally`` release (the sanctioned shape) — covered above;
- functions *implementing* an acquire/release API (their own name is a
  recognizer) — skipped, the pairing is cross-method by design;
- ``__enter__``/``__exit__`` pairs — skipped for the same reason;
- acquire whose result is returned/stored — ownership moved, the release
  lives with the new owner (pair it with a conservation test).

Documented residual false-positive pattern: a release performed by a helper
the rule cannot see (``self._teardown()`` calling ``release`` internally).
Baseline it naming the helper that releases.
"""
from __future__ import annotations

import ast

from ..engine import FileRule, register
from ._locks import attr_chain, iter_lexical
from ._traced import callee_name

#: One row per refcounted pool: (label, acquire callee names, release callee
#: names).  New pools opt in with one line here.
POOLS = (
    ("lock/adapter-pool", frozenset({"acquire"}),
     frozenset({"release", "release_page"})),
    ("kv-page", frozenset({"incref", "_incref"}),
     frozenset({"decref", "_decref"})),
)

#: ``self.<attr>[k] += 1`` with this attr suffix is a raw refcount bump
#: (llm_server's ``_page_ref``), paired with the matching ``-= 1``.
REF_ATTR_SUFFIX = "_ref"

_ACQUIRE_NAMES = frozenset().union(*(p[1] for p in POOLS))
_RELEASE_NAMES = frozenset().union(*(p[2] for p in POOLS))

#: Callees that cannot plausibly raise in a way that leaks the refcount —
#: used for the exception-window check between acquire and release.
_SAFE_CALLEES = frozenset({
    "append", "add", "discard", "remove", "pop", "popleft", "get", "items",
    "keys", "values", "setdefault", "update", "extend", "clear", "insert",
    "len", "int", "float", "str", "bool", "min", "max", "abs", "sum", "id",
    "isinstance", "sorted", "list", "dict", "set", "tuple", "frozenset",
    "enumerate", "zip", "range", "monotonic", "perf_counter", "time",
    "debug", "info", "warning", "error", "inc", "dec", "observe", "labels",
    "set_value", "notify", "notify_all", "startswith", "endswith", "join",
    "split", "format", "copy", "count", "index",
}) | _ACQUIRE_NAMES | _RELEASE_NAMES


def _release_names_for(acq_name: str):
    out = set()
    for _, acq, rel in POOLS:
        if acq_name in acq:
            out |= rel
    return out


def _stmt_parents(fn):
    """node -> parent map, lexical to ``fn`` (nested defs excluded)."""
    parents = {}
    for n in iter_lexical(list(fn.body)):
        for c in ast.iter_child_nodes(n):
            parents[c] = n
    for c in fn.body:
        parents.setdefault(c, fn)
    return parents


def _is_release(node, rel_names):
    """A release for this pool: a matching call, or ``<x>_ref[k] -= 1``."""
    if (isinstance(node, ast.Call)
            and callee_name(node.func) in rel_names):
        return True
    if (isinstance(node, ast.AugAssign) and isinstance(node.op, ast.Sub)
            and isinstance(node.target, ast.Subscript)
            and isinstance(node.target.value, ast.Attribute)
            and node.target.value.attr.endswith(REF_ATTR_SUFFIX)):
        return True
    return False


@register
class RefcountBalanceRule(FileRule):
    name = "refcount-balance"
    severity = "warning"
    description = ("acquire-style calls (POOLS table: acquire/incref/"
                   "_page_ref bumps) must release on every exit edge "
                   "(except/early-return) or sit under try/finally")

    def check(self, ctx):
        findings = []
        for fn in ast.walk(ctx.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if (fn.name in _ACQUIRE_NAMES or fn.name in _RELEASE_NAMES
                    or fn.name in ("__enter__", "__exit__", "__del__",
                                   "close", "shutdown")):
                continue  # implements the API / cross-method pairing
            findings.extend(self._check_fn(ctx, fn))
        return findings

    # ------------------------------------------------------------- internals
    def _check_fn(self, ctx, fn):
        nodes = list(iter_lexical(list(fn.body)))
        with_items = {id(it.context_expr) for n in nodes
                      if isinstance(n, ast.With) for it in n.items}
        acquires = []  # (node, lineno, rel_names, resource_repr, result_name)
        for n in nodes:
            if (isinstance(n, ast.Call)
                    and callee_name(n.func) in _ACQUIRE_NAMES
                    and id(n) not in with_items):
                acquires.append(n)
            elif (isinstance(n, ast.AugAssign)
                  and isinstance(n.op, ast.Add)
                  and isinstance(n.target, ast.Subscript)
                  and isinstance(n.target.value, ast.Attribute)
                  and n.target.value.attr.endswith(REF_ATTR_SUFFIX)):
                acquires.append(n)
        if not acquires:
            return []

        parents = _stmt_parents(fn)
        out = []
        for acq in acquires:
            f = self._check_acquire(ctx, fn, acq, nodes, parents)
            if f is not None:
                out.append(f)
        return out

    def _check_acquire(self, ctx, fn, acq, nodes, parents):
        if isinstance(acq, ast.Call):
            acq_name = callee_name(acq.func)
            rel_names = _release_names_for(acq_name)
            resource = attr_chain(acq.func) or acq_name
        else:  # AugAssign += 1 on *_ref
            rel_names = frozenset()
            resource = attr_chain(acq.target.value) + "[...] += 1"

        # -------------------------------------------------- ownership escape
        result_name = None
        if isinstance(acq, ast.Call):
            parent = parents.get(acq)
            if isinstance(parent, ast.Assign) and parent.value is acq:
                tgts = parent.targets
                if len(tgts) == 1 and isinstance(tgts[0], ast.Name):
                    result_name = tgts[0].id
                else:
                    return None  # stored into self./container: owner moved
            elif not isinstance(parent, (ast.Expr, type(None))):
                # `return pool.acquire(k)` / `xs.append(self._incref(p))` /
                # part of a larger expression: the value escapes
                return None
            elif acq.args and isinstance(acq.args[0], ast.Name):
                # no-result acquire (`self._incref(p)`): if the refcounted
                # KEY itself escapes (stored in the request table, returned),
                # the release lives with the new owner (`_free_pages`)
                result_name = acq.args[0].id
        if result_name is not None and self._escapes(
                fn, acq, result_name, rel_names):
            return None

        # -------------------------------------------------- try/finally etc.
        rel_pred = lambda n: _is_release(n, rel_names)  # noqa: E731
        line = acq.lineno
        for t in (n for n in nodes if isinstance(n, ast.Try)):
            if not any(rel_pred(x) for b in [t.finalbody]
                       for s in b for x in ast.walk(s)):
                continue
            if (t.lineno <= line <= (t.end_lineno or t.lineno)
                    or line < t.lineno <= line + 3):
                return None  # finally-covered
        for t in (n for n in nodes if isinstance(n, ast.Try)):
            if not (t.lineno <= line <= (t.body[-1].end_lineno
                                         or t.lineno)):
                continue
            if t.handlers and all(
                    any(rel_pred(x) for s in h.body for x in ast.walk(s))
                    for h in t.handlers):
                return None  # every except edge releases

        # ---------------------------------------------------- release matching
        releases = [n for n in nodes if rel_pred(n)
                    and n.lineno > line]
        if not releases:
            return ctx.finding(
                self, acq,
                f"`{resource}` acquired but never released in "
                f"{fn.name}() — release on every exit edge, use "
                f"try/finally, or hand ownership off explicitly")
        first_rel = min(n.lineno for n in releases)
        for ret in (n for n in nodes if isinstance(n, ast.Return)):
            if ret.lineno <= line:
                continue
            if not any(line < r.lineno <= ret.lineno for r in releases):
                return ctx.finding(
                    self, acq,
                    f"`{resource}` acquired but the return at line "
                    f"{ret.lineno} exits {fn.name}() without a "
                    f"release — release before returning or use "
                    f"try/finally")
        # ------------------------------------------------- exception window
        risky = [n for n in nodes
                 if isinstance(n, ast.Call)
                 and line < n.lineno < first_rel
                 and callee_name(n.func) not in _SAFE_CALLEES]
        if risky:
            return ctx.finding(
                self, acq,
                f"`{resource}` acquired at line {line} but "
                f"`{callee_name(risky[0].func)}()` (line "
                f"{risky[0].lineno}) can raise before the release at "
                f"line {first_rel} — wrap the span in try/finally")
        return None

    @staticmethod
    def _escapes(fn, acq, name, rel_names):
        """Does ``name`` (the acquire result) leave this function's
        ownership — returned, yielded, stored, or passed along?"""
        for n in iter_lexical(list(fn.body)):
            if isinstance(n, (ast.Return, ast.Yield, ast.YieldFrom)):
                v = n.value
                if v is not None and any(
                        isinstance(x, ast.Name) and x.id == name
                        for x in ast.walk(v)):
                    return True
            elif isinstance(n, ast.Call) and n is not acq:
                if callee_name(n.func) in rel_names:
                    continue
                if any(isinstance(x, ast.Name) and x.id == name
                       for a in list(n.args) + [k.value for k in n.keywords]
                       for x in ast.walk(a)):
                    return True
            elif isinstance(n, (ast.Assign, ast.AugAssign)):
                tgts = (n.targets if isinstance(n, ast.Assign)
                        else [n.target])
                for t in tgts:
                    if not isinstance(t, (ast.Subscript, ast.Attribute)):
                        continue
                    # stored as a VALUE (`self.x = page`) or as a KEY
                    # (`self._page_cached[page] = True`) — either way the
                    # table now owns the release
                    if any(isinstance(x, ast.Name) and x.id == name
                           for src in (n.value, t)
                           for x in ast.walk(src)):
                        return True
        return False
