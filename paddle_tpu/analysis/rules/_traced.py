"""Shared helper: which functions in a module get TRACED by jax?

A function body runs under tracing when it is

- decorated with a jit-family decorator (``@jax.jit``, ``@partial(jax.jit,
  static_argnums=...)``, ``@paddle.jit.to_static``), or
- passed by name (or as a lambda / ``partial(fn, ...)``) into a tracing
  entry point — ``jax.jit(fn)``, ``shard_map(fn)``, ``pl.pallas_call(kernel)``,
  ``lax.scan(body, ...)``, ``jax.grad(f)`` — anywhere in the module, or
- *defined inside* such a function: closures like the decode ``tick`` in
  llm_server execute during the enclosing trace.

This is a deliberate over-approximation by lexical span: everything between a
traced function's first and last line is treated as traced.  Rules that only
make sense on traced values (host-sync, impurity) use :func:`in_traced`.
"""
from __future__ import annotations

import ast

#: Call/decorator names whose function-valued arguments are traced.  The
#: trailing attribute is matched (``jax.jit``, ``jax.experimental.pjit.pjit``
#: and a bare ``jit`` all end in the same segment).
TRACE_ENTRY_NAMES = frozenset({
    "jit", "pjit", "shard_map", "pallas_call", "to_static",
    "grad", "value_and_grad", "vjp", "jvp", "linearize",
    "vmap", "pmap", "scan", "while_loop", "fori_loop", "cond", "switch",
    "remat", "checkpoint", "custom_vjp", "custom_jvp",
})


def callee_name(func) -> str:
    """Trailing segment of a call target: ``jax.lax.psum`` -> ``psum``."""
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return ""


def _unwrap_partial(node):
    """``partial(fn, ...)`` -> ``fn``; anything else unchanged."""
    if (isinstance(node, ast.Call) and callee_name(node.func) == "partial"
            and node.args):
        return node.args[0]
    return node


def traced_spans(tree):
    """Return the list of function/lambda nodes whose bodies are traced."""
    defs = {}
    spans = []
    seen = set()

    def add(node):
        if id(node) not in seen:
            seen.add(id(node))
            spans.append(node)

    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs.setdefault(node.name, []).append(node)
            for dec in node.decorator_list:
                names = {callee_name(dec)}
                if isinstance(dec, ast.Call):
                    names.add(callee_name(dec.func))
                    inner = _unwrap_partial(dec)
                    if inner is not dec:
                        names.add(callee_name(inner))
                        names.add(callee_name(getattr(inner, "func", inner)))
                if names & TRACE_ENTRY_NAMES:
                    add(node)

    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and callee_name(node.func) in TRACE_ENTRY_NAMES):
            continue
        for arg in node.args:
            arg = _unwrap_partial(arg)
            if isinstance(arg, ast.Lambda):
                add(arg)
            elif isinstance(arg, ast.Name):
                for d in defs.get(arg.id, ()):
                    add(d)
    return spans


def in_traced(node, spans) -> bool:
    """Is ``node`` lexically inside any traced function's span?"""
    line = getattr(node, "lineno", None)
    if line is None:
        return False
    for s in spans:
        if s.lineno <= line <= (getattr(s, "end_lineno", None) or s.lineno):
            return True
    return False
