"""lock-guard-inference: a lightweight AST-level race detector.

Nobody writes down which lock guards which attribute — the code does.  Per
class, this rule *infers* the guarded-attribute set: ``self._foo`` counts as
guarded by ``self._lock`` when it is accessed at least
:data:`MIN_GUARDED_ACCESSES` times inside ``with self._lock:`` bodies AND at
least one of those accesses is a write (read-only-under-lock attributes are
usually just convenience, not an invariant).  Any *other* method that then
reads or writes a guarded attribute while holding no lock is a candidate
race and gets flagged.

What counts as "under the lock" (all alias-aware — ``lk = self._lock;
with lk:`` guards the same set):

- lexically inside a ``with self._lock`` body in the same method;
- anywhere in a ``_``-private method that is ONLY ever called with the lock
  held — the intra-class call graph is closed over transitively, so the
  ``step() -> _step_locked() -> _admit()`` tower in llm_server needs no
  annotations (public methods are never exempted this way: external callers
  are invisible to the AST);
- anywhere in a method whose name ends in ``_locked`` — the repo's explicit
  "caller holds the lock" convention.

Never flagged: ``__init__``/``__new__``/``__del__``/``__post_init__``
(construction and teardown are single-threaded by contract), and accesses
inside nested ``def``/``lambda`` bodies (deferred execution — the lock state
at run time is unknowable lexically).

True positive (the shape this rule exists for)::

    class Router:
        def add(self, r):
            with self._lock:
                self._replicas[r.name] = r     # infers: _replicas guarded
        def drop(self, name):
            with self._lock:
                del self._replicas[name]
        def peek(self, name):
            return self._replicas[name]        # flagged: no lock held

Documented false-positive patterns (and their dispositions):

- A deliberately lock-free reader (a ``stats()``/metrics snapshot that
  tolerates torn reads for latency) — baseline it with a justification
  naming the tolerance, or suppress inline; the point is that lock-free
  access is now a *decision on record*, not an accident.
- A public method that is in fact only called under the lock — rename it
  ``*_locked`` or make it private to encode the contract.
"""
from __future__ import annotations

import ast

from ..engine import Finding, ProjectRule, register
from ._locks import file_lock_names, iter_lexical, lock_items

#: An attribute joins the guarded set at this many under-lock accesses
#: (with >=1 write among them).  Below it, the evidence is too thin to
#: out-rank coincidence.
MIN_GUARDED_ACCESSES = 3

#: Method names that mutate their receiver in place — `self.xs.append(v)`
#: is a write to `self.xs` even though the Attribute reads as a Load.
_MUTATORS = frozenset({
    "append", "appendleft", "add", "pop", "popleft", "clear", "update",
    "insert", "extend", "remove", "discard", "setdefault", "sort"})

#: Methods whose unlocked accesses are never flagged.
_EXEMPT_METHODS = frozenset({
    "__init__", "__new__", "__del__", "__post_init__",
    "__getstate__", "__setstate__", "__repr__"})


class _MethodFacts:
    """Per-method lexical facts: lock spans, attr accesses, self-calls."""

    def __init__(self, cls_locks, lock_names, method):
        self.node = method
        self.name = method.name
        aliases = set()
        for n in iter_lexical(list(method.body)):
            if (isinstance(n, ast.Assign)
                    and isinstance(n.value, ast.Attribute)
                    and isinstance(n.value.value, ast.Name)
                    and n.value.value.id == "self"
                    and n.value.attr in cls_locks):
                aliases |= {t.id for t in n.targets
                            if isinstance(t, ast.Name)}
        self.spans = []  # (start, end) of `with <lock>` bodies
        for n in iter_lexical(list(method.body)):
            if isinstance(n, ast.With) and lock_items(
                    n, cls_locks, lock_names | aliases):
                self.spans.append((n.lineno, n.end_lineno or n.lineno))
        # write-position self-attrs: `self.x = v` is a Store, but the
        # dominant mutations — `self.d[k] = v`, `del self.d[k]`,
        # `self.xs.append(v)` — leave the Attribute in Load context;
        # collect their node ids first so they count as writes
        def _self_attr(x):
            return (isinstance(x, ast.Attribute)
                    and isinstance(x.value, ast.Name) and x.value.id == "self")
        write_ids = set()
        for n in iter_lexical(list(method.body)):
            if (isinstance(n, ast.Subscript)
                    and isinstance(n.ctx, (ast.Store, ast.Del))
                    and _self_attr(n.value)):
                write_ids.add(id(n.value))
            elif (isinstance(n, ast.Call)
                  and isinstance(n.func, ast.Attribute)
                  and n.func.attr in _MUTATORS
                  and _self_attr(n.func.value)):
                write_ids.add(id(n.func.value))
        # (attr, node, is_store, under_lock) for self.<attr> accesses
        self.accesses = []
        # (callee method name, under_lock) for self.<m>() calls
        self.self_calls = []
        for n in iter_lexical(list(method.body)):
            if (isinstance(n, ast.Attribute)
                    and isinstance(n.value, ast.Name)
                    and n.value.id == "self"
                    and n.attr not in cls_locks):
                store = (isinstance(n.ctx, (ast.Store, ast.Del))
                         or id(n) in write_ids)
                self.accesses.append(
                    (n.attr, n, store, self.under_lock(n.lineno)))
            if (isinstance(n, ast.Call)
                    and isinstance(n.func, ast.Attribute)
                    and isinstance(n.func.value, ast.Name)
                    and n.func.value.id == "self"):
                self.self_calls.append(
                    (n.func.attr, self.under_lock(n.lineno)))

    def under_lock(self, lineno) -> bool:
        return any(s <= lineno <= e for s, e in self.spans)


@register
class LockGuardInferenceRule(ProjectRule):
    name = "lock-guard-inference"
    severity = "warning"
    description = ("per class, infer which attributes a lock guards (>=%d "
                   "locked accesses incl. a write) and flag lock-free "
                   "reads/writes of them" % MIN_GUARDED_ACCESSES)

    def check_project(self, project):
        findings = []
        for relpath, tree, lines in project.parsed_files():
            _, lock_names = file_lock_names(tree)
            for cls in (n for n in ast.walk(tree)
                        if isinstance(n, ast.ClassDef)):
                findings.extend(self._check_class(
                    relpath, lines, cls, lock_names))
        return findings

    # ------------------------------------------------------------- internals
    def _check_class(self, relpath, lines, cls, lock_names):
        methods = [n for n in cls.body
                   if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
        cls_locks = self._class_locks(methods)
        if not cls_locks:
            return []
        facts = [_MethodFacts(cls_locks, lock_names, m) for m in methods]

        # ---- exempt closure: private methods only ever called under lock
        exempt = {f.name for f in facts if f.name.endswith("_locked")}
        callsites = {}  # method name -> [(caller facts, under_lock)]
        for f in facts:
            for callee, locked in f.self_calls:
                callsites.setdefault(callee, []).append((f, locked))
        changed = True
        while changed:
            changed = False
            for f in facts:
                if f.name in exempt or not f.name.startswith("_") \
                        or f.name.startswith("__"):
                    continue
                sites = callsites.get(f.name)
                if sites and all(
                        locked or caller.name in exempt
                        for caller, locked in sites):
                    exempt.add(f.name)
                    changed = True

        def effective_locked(f, locked):
            return locked or f.name in exempt

        # ---- inference: guarded attr -> (locked count, write count)
        counts = {}
        for f in facts:
            for attr, node, store, locked in f.accesses:
                if effective_locked(f, locked):
                    c = counts.setdefault(attr, [0, 0])
                    c[0] += 1
                    c[1] += int(store)
        guarded = {a for a, (n, w) in counts.items()
                   if n >= MIN_GUARDED_ACCESSES and w >= 1}
        if not guarded:
            return []
        lock_name = sorted(cls_locks)[0]

        # ---- flag lock-free accesses, one finding per (method, attr)
        findings = []
        flagged = set()
        for f in facts:
            if f.name in exempt or f.name in _EXEMPT_METHODS:
                continue
            for attr, node, store, locked in f.accesses:
                if attr not in guarded or effective_locked(f, locked):
                    continue
                key = (f.name, attr)
                if key in flagged:
                    continue
                flagged.add(key)
                n, w = counts[attr]
                line = node.lineno
                findings.append(Finding(
                    rule=self.name, path=relpath,
                    line=line, col=node.col_offset,
                    message=(
                        f"self.{attr} is guarded by self.{lock_name} in "
                        f"{cls.name} ({n} locked accesses, {w} writes) but "
                        f"{'written' if store else 'read'} without it in "
                        f"{f.name}() — take the lock, or record the "
                        f"lock-free access as deliberate"),
                    severity=self.severity,
                    content=(lines[line - 1].strip()
                             if 0 < line <= len(lines) else "")))
        return findings

    @staticmethod
    def _class_locks(methods):
        """Lock attributes of the class: assigned a threading ctor in any
        method, or used as a lock-ish `with self.X:` item."""
        from ._locks import is_lock_ctor, is_lockish_name
        locks = set()
        for m in methods:
            for n in iter_lexical(list(m.body)):
                if isinstance(n, ast.Assign) and is_lock_ctor(n.value):
                    locks |= {t.attr for t in n.targets
                              if isinstance(t, ast.Attribute)
                              and isinstance(t.value, ast.Name)
                              and t.value.id == "self"}
                elif isinstance(n, ast.With):
                    for it in n.items:
                        e = it.context_expr
                        if (isinstance(e, ast.Attribute)
                                and isinstance(e.value, ast.Name)
                                and e.value.id == "self"
                                and is_lockish_name(e.attr)):
                            locks.add(e.attr)
        return locks
