"""tpulint built-in rule suite.  Importing this package registers every rule
with the engine registry (``paddle_tpu.analysis.engine.RULES``).

Catalogue (see README §Static analysis for the operator-facing version):

====================  ========  =================================================
rule                  severity  polices
====================  ========  =================================================
host-sync-in-jit      error     .item()/float()/np.asarray() on traced values
impure-trace          error     time/random/global state baked into a trace;
                                wall-clock time.time() anywhere (warning)
collective-axis       error     literal mesh-axis names vs topology.AXIS_ORDER
donation-misuse       error     donated buffers read after the jitted call
dtype-drift           warning   f32 upcasts materialized in bf16 hot paths
silent-noop           warning   exported functions whose body does nothing
bare-except-swallow   error     swallowed faults in the recovery paths
metrics-catalogue     error     metric namespace vs README catalogue (PR 2)
docs-stale            warning   PROJECTION.md cites the newest BENCH and
                                ROOFLINE rounds
shape-polymorphism    warning   concrete .shape/.ndim/len() branching in
                                traced functions (compile-zoo growth)
lock-guard-inference  warning   per-class inferred guarded-attribute sets;
                                lock-free reads/writes of guarded state
blocking-under-lock   warning   blocking I/O / sleep / join / jit dispatch
                                inside `with <lock>` (error in inference/
                                + observability/ hot paths)
refcount-balance      warning   acquire/incref without a release on every
                                exit edge (except / early return)
scan-carry-dtype      warning   loop carries cast to a concrete dtype the
                                init does not share (upcast/recompile)
====================  ========  =================================================
"""
from . import bare_except      # noqa: F401
from . import blocking_lock    # noqa: F401
from . import catalogues       # noqa: F401
from . import collective_axis  # noqa: F401
from . import donation         # noqa: F401
from . import dtype_drift      # noqa: F401
from . import host_sync        # noqa: F401
from . import impure_trace     # noqa: F401
from . import lock_guard       # noqa: F401
from . import refcount_balance  # noqa: F401
from . import scan_carry       # noqa: F401
from . import shape_polymorphism  # noqa: F401
from . import silent_noop      # noqa: F401
