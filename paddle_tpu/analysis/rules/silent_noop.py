"""silent-noop: exported functions whose body does nothing.

An API that accepts user intent and silently discards it is the worst failure
mode a framework has (round-1 verdict #10; ``tests/test_no_silent_noops.py``
pins the semantic cases).  This rule is the static sweep: any function whose
body is only ``pass`` / ``...`` / bare ``return`` AND whose name is part of
an ``__init__`` surface (imported by the sibling ``__init__.py``, listed in
``__all__``, or defined publicly in an ``__init__.py`` itself) is flagged.

Deliberate no-ops are real on TPU (``get_cudnn_version`` — there is no cuDNN)
and belong in the baseline with the reason.  Stays clean by design: private
helpers, decorated defs (abstract methods, overloads, registrations), class
methods (callback hooks like ``on_epoch_begin`` are no-op by contract), and
functions not reachable from any ``__init__`` surface.
"""
from __future__ import annotations

import ast

from ..engine import FileRule, register


def _trivial_body(fn) -> bool:
    body = fn.body
    if (body and isinstance(body[0], ast.Expr)
            and isinstance(body[0].value, ast.Constant)
            and isinstance(body[0].value.value, str)):
        body = body[1:]  # docstring
    if not body:
        return True
    if len(body) != 1:
        return False
    stmt = body[0]
    if isinstance(stmt, ast.Pass):
        return True
    if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Constant):
        return True  # bare `...`
    if isinstance(stmt, ast.Return):
        return stmt.value is None or (
            isinstance(stmt.value, ast.Constant) and stmt.value.value is None)
    return False


@register
class SilentNoopRule(FileRule):
    name = "silent-noop"
    severity = "warning"
    description = (
        "exported function whose body is pass/.../bare return — silently "
        "discards user intent; raise, implement, or baseline with the "
        "documented no-op reason")

    def check(self, ctx):
        exported = None  # computed lazily: most files have no trivial defs
        out = []
        for node in ctx.tree.body:  # module level only: the exported surface
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if node.decorator_list or node.name.startswith("_"):
                continue
            if not _trivial_body(node):
                continue
            if exported is None:
                exported = ctx.project.exported_names(ctx.relpath)
            if node.name in exported:
                out.append(ctx.finding(
                    self, node,
                    f"'{node.name}' is exported but its body is a no-op — "
                    f"implement it, raise NotImplementedError, or baseline "
                    f"with the documented no-op reason"))
        return out
