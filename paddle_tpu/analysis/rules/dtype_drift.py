"""dtype-drift: f32 upcasts materialized inside bf16 hot paths.

The bf16 training/serving paths (``ops/``, the sharded train step) budget
HBM bandwidth and MXU throughput for 2-byte activations.  An ``astype(
jnp.float32)`` on a traced tensor silently doubles the tensor's footprint and
drags every consumer up to f32 — XLA will compile it happily and the step
just gets slower (the paper's MFU floor erodes with no error anywhere).

Flagged: ``.astype(float32)`` (attribute or "float32" string form) and
``asarray/array(x, float32)`` on non-constant ``x`` inside the configured
hot paths.  Severity is warning: legitimate precision choices exist (bwd-pass
softmax statistics, loss accumulation) and live in the baseline with a
one-line justification each.

Sanctioned idioms that stay CLEAN by design (the documented false-positive
surface):

- ``preferred_element_type=jnp.float32`` — MXU accumulation dtype without
  materializing f32 tensors: the right way to get f32 accuracy in bf16 paths;
- f32 *creation* of scratch accumulators: ``jnp.zeros(shape, jnp.float32)``,
  ``jnp.full(...)`` — online-softmax/loss state is supposed to be f32;
- casts *down* (``.astype(jnp.bfloat16)``, ``.astype(x.dtype)``).
"""
from __future__ import annotations

import ast

from ..engine import FileRule, register

#: bf16-annotated hot paths (root-relative prefixes).
BF16_PATHS = (
    "paddle_tpu/ops/",
    "paddle_tpu/distributed/sharded_train_step.py",
)


def _is_f32(node) -> bool:
    if isinstance(node, ast.Constant):
        return node.value == "float32"
    if isinstance(node, ast.Attribute) and node.attr == "float32":
        base = node.value
        return isinstance(base, ast.Name) and base.id in ("jnp", "np",
                                                          "numpy", "jax")
    return False


@register
class DtypeDriftRule(FileRule):
    name = "dtype-drift"
    severity = "warning"
    description = (
        "astype(float32)/asarray(x, float32) inside bf16 hot paths "
        "(ops/, sharded_train_step) — materialized f32 doubles HBM traffic; "
        "use preferred_element_type or baseline deliberate precision "
        "choices")
    paths = BF16_PATHS

    def check(self, ctx):
        out = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            if (isinstance(func, ast.Attribute) and func.attr == "astype"
                    and len(node.args) == 1 and _is_f32(node.args[0])):
                out.append(ctx.finding(
                    self, node,
                    "f32 upcast materialized in a bf16 hot path — prefer "
                    "preferred_element_type for accumulation, downcast on "
                    "store, or baseline with the precision rationale"))
                continue
            if isinstance(func, ast.Attribute) and func.attr in ("asarray",
                                                                 "array"):
                dtype_arg = None
                if len(node.args) >= 2:
                    dtype_arg = node.args[1]
                for kw in node.keywords:
                    if kw.arg == "dtype":
                        dtype_arg = kw.value
                if (dtype_arg is not None and _is_f32(dtype_arg)
                        and node.args
                        and not isinstance(node.args[0], ast.Constant)):
                    out.append(ctx.finding(
                        self, node,
                        f"{func.attr}(..., float32) materializes f32 in a "
                        f"bf16 hot path — baseline with the precision "
                        f"rationale if deliberate"))
        return out
