"""impure-trace: host state read inside a traced function, and wall-clock
discipline everywhere.

Tracing runs the Python body ONCE: ``time.time()``, stdlib ``random``,
``np.random.*``, ``os.environ`` reads and ``global`` mutation are evaluated at
trace time and the *result* is burned into the compiled program — every later
step replays the same "random" number and the same timestamp.  The sanctioned
randomness path is the framework PRNG (``framework/random.py`` keys threaded
through the step, ``ops/_prng.py`` inside Pallas kernels); ``jax.random.*`` on
an explicit key is pure and never flagged.

Module-wide sub-check (warning): ``time.time()`` anywhere in the package.
Wall clock is not monotonic — NTP slew makes deadlines and durations lie.
Durations and deadlines must use ``time.monotonic()``/``perf_counter()``;
genuinely wall-clock timestamps (operator logs, cross-host heartbeats,
checkpoint metadata) are baselined with a justification.

Documented false positive that stays clean: ``jax.random.normal(key, ...)``
inside a traced function, and ``from ..framework import random as _random``
usage — the alias map distinguishes it from stdlib ``random``.
"""
from __future__ import annotations

import ast

from ..engine import FileRule, register
from ._traced import in_traced, traced_spans

#: module-path -> attribute names that are impure under trace (empty set =
#: any attribute of the module).
_IMPURE_MODULE_CALLS = {
    "time": frozenset(),          # any time.* read is a trace-time constant
    "random": frozenset(),        # stdlib PRNG: hidden global host state
    "numpy.random": frozenset(),
    "datetime": frozenset({"now", "utcnow", "today"}),
    "datetime.datetime": frozenset({"now", "utcnow", "today"}),
    "datetime.date": frozenset({"today"}),
    "os": frozenset({"getenv"}),  # os.environ handled as an attribute read
    "uuid": frozenset({"uuid1", "uuid4"}),
    "secrets": frozenset(),
}


@register
class ImpureTraceRule(FileRule):
    name = "impure-trace"
    severity = "error"
    description = (
        "time.*/random.*/np.random.*/os.environ/global mutation inside "
        "traced functions (error); wall-clock time.time() anywhere "
        "(warning — use monotonic clocks for durations/deadlines)")

    def check(self, ctx):
        spans = traced_spans(ctx.tree)
        aliases = ctx.import_aliases()
        out = []
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Global) and in_traced(node, spans):
                out.append(ctx.finding(
                    self, node,
                    f"'global {', '.join(node.names)}' inside a traced "
                    f"function — mutation happens at trace time, not per "
                    f"step", severity="error"))
                continue
            if self._is_environ_read(node, aliases):
                if in_traced(node, spans):
                    out.append(ctx.finding(
                        self, node,
                        "os.environ read inside a traced function is "
                        "evaluated ONCE at trace time and baked into the "
                        "program; read it on the host and pass the value in",
                        severity="error"))
                continue
            if not isinstance(node, ast.Call):
                continue
            dotted = self._resolve(node.func, aliases)
            if dotted is None:
                continue
            mod, attr = dotted
            impure = self._impure(mod, attr)
            if impure is None:
                continue
            if in_traced(node, spans):
                out.append(ctx.finding(
                    self, node,
                    f"{impure} inside a traced function is evaluated ONCE at "
                    f"trace time and baked into the program; thread a "
                    f"framework PRNG key / pass host values as arguments",
                    severity="error"))
            elif mod == "time" and attr == "time":
                out.append(ctx.finding(
                    self, node,
                    "wall-clock time.time() — use time.monotonic()/"
                    "perf_counter() for durations and deadlines; baseline "
                    "with a justification if a wall-clock timestamp is "
                    "intended", severity="warning"))
        return out

    @staticmethod
    def _is_environ_read(node, aliases) -> bool:
        """Any access spelled through os.environ: subscripts, .get(), plain
        attribute reads — none of them are Call(os.environ), so the call
        table can never catch them."""
        if (isinstance(node, ast.Attribute) and node.attr == "environ"
                and isinstance(node.value, ast.Name)
                and aliases.get(node.value.id) == "os"):
            return True
        return (isinstance(node, ast.Name)
                and aliases.get(node.id) == "os.environ")

    @staticmethod
    def _resolve(func, aliases):
        """Map a call target to (canonical module path, attr) via the file's
        import table; None when it cannot be an impure-module call."""
        if isinstance(func, ast.Attribute):
            parts = [func.attr]
            cur = func.value
            while isinstance(cur, ast.Attribute):
                parts.append(cur.attr)
                cur = cur.value
            if not isinstance(cur, ast.Name):
                return None
            base = aliases.get(cur.id)
            if base is None:
                return None
            parts.append(base)
            dotted = ".".join(reversed(parts))
            mod, _, attr = dotted.rpartition(".")
            return mod, attr
        if isinstance(func, ast.Name):
            dotted = aliases.get(func.id)
            if dotted is None:
                return None
            mod, _, attr = dotted.rpartition(".")
            return mod, attr
        return None

    @staticmethod
    def _impure(mod: str, attr: str):
        """Human-readable description when (mod, attr) is impure, else
        None.  Relative imports (leading dots) never match: the framework's
        own ``random``/``time`` siblings are sanctioned."""
        if mod.startswith("."):
            return None
        for impure_mod, attrs in _IMPURE_MODULE_CALLS.items():
            if mod == impure_mod and (not attrs or attr in attrs):
                return f"{mod}.{attr}()"
        return None
