"""collective-axis: literal mesh-axis names must exist in the declared mesh.

The hybrid-parallel mesh axes are declared ONCE — ``AXIS_ORDER`` in
``distributed/topology.py`` — and every ``psum``/``all_gather``/``ppermute``
references them by string.  XLA does not validate the *intent*: a collective
over a renamed or misspelled axis raises at best a late shape error and at
worst silently reduces over the wrong group (EQuARX's observation: collective
layout mistakes cost silently).  This rule makes the rename fail lint, not a
pod run.

Checked: string-literal axis arguments (positional or ``axis``/
``axis_name=``) of collective calls, and string defaults of parameters named
``axis``/``axis_name``/``*_axis``.  Variables are not resolved — a
non-literal axis is the caller's contract, not this file's.
"""
from __future__ import annotations

import ast

from ..engine import FileRule, register
from ._traced import callee_name

#: Collectives (and the process-group constructor) whose axis argument names
#: a mesh axis.  ``axis_index`` included: it burns the axis name into the
#: program the same way.
COLLECTIVE_CALLS = frozenset({
    "psum", "pmean", "pmax", "pmin", "psum_scatter",
    "all_gather", "all_gather_invariant", "ppermute", "pshuffle",
    "all_to_all", "axis_index", "new_group",
})

#: Parameter-name suffixes whose string defaults are mesh axes.
_AXIS_PARAM = ("axis_name", "axis")


def _axis_param_name(name: str) -> bool:
    return name in _AXIS_PARAM or name.endswith("_axis")


def _literal_axes(node):
    """Axis names in a literal str / tuple / list node, else None."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for el in node.elts:
            if isinstance(el, ast.Constant) and isinstance(el.value, str):
                out.append(el.value)
        return out or None
    return None


@register
class CollectiveAxisRule(FileRule):
    name = "collective-axis"
    severity = "error"
    description = (
        "psum/pmean/all_gather/ppermute axis names (and *_axis parameter "
        "defaults) must match the mesh axes declared in "
        "distributed/topology.py AXIS_ORDER")

    def check(self, ctx):
        axes = ctx.project.mesh_axes()
        if not axes:
            return []  # no declared mesh in this tree — nothing to validate
        out = []
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call):
                out.extend(self._check_call(ctx, node, axes))
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                out.extend(self._check_defaults(ctx, node, axes))
        return out

    def _check_call(self, ctx, node, axes):
        if callee_name(node.func) not in COLLECTIVE_CALLS:
            return []
        callee = callee_name(node.func)
        # keyword candidates that actually carry literal axis names;
        # all_gather/all_to_all's `axis=` keyword is an INT array dimension,
        # so a non-literal keyword must not shadow the positional mesh axis
        candidates = [kw.value for kw in node.keywords
                      if kw.arg in ("axis_name", "axis")
                      and _literal_axes(kw.value)]
        if not candidates:
            if callee == "axis_index" and node.args:
                candidates.append(node.args[0])  # axis_index(axis_name)
            elif len(node.args) >= 2 and callee != "new_group":
                candidates.append(node.args[1])  # lax convention: (x, axis)
        return self._validate(ctx, node, candidates, axes,
                              f"collective {callee_name(node.func)}()")

    def _check_defaults(self, ctx, node, axes):
        args = node.args
        pos = list(args.posonlyargs) + list(args.args)
        named = pos + list(args.kwonlyargs)
        defaults = ([None] * (len(pos) - len(args.defaults))
                    + list(args.defaults) + list(args.kw_defaults))
        out = []
        for a, d in zip(named, defaults):
            if d is not None and _axis_param_name(a.arg):
                out.extend(self._validate(
                    ctx, d, [d], axes, f"default of parameter '{a.arg}'"))
        return out

    def _validate(self, ctx, node, candidates, axes, where):
        out = []
        for cand in candidates:
            names = _literal_axes(cand)
            if not names:
                continue
            for name in names:
                if name not in axes:
                    out.append(ctx.finding(
                        self, node,
                        f"unknown mesh axis '{name}' in {where} — declared "
                        f"axes are {sorted(axes)} "
                        f"(distributed/topology.py AXIS_ORDER)"))
        return out
