"""tpulint engine: rule registry, file walker, suppressions, baseline, reporters.

The paper's ≥70%-MFU target dies by a thousand silent cuts — a ``.item()``
host-sync baked into a jitted step, a wall-clock read traced into a constant,
a collective issued under a renamed mesh axis.  XLA compiles all of these into
slow-but-plausible programs, so they must be caught at the *program* level:
this module is the AST lint engine that every rule plugs into.

Design constraints:

- **Dependency-free.** Only the stdlib (``ast``/``json``/``re``) — the engine
  must run even when jax or the package itself cannot import (a linter that
  needs the patient healthy is not a diagnostic tool).  Rules that *do* need
  the live package (metrics-catalogue) import it lazily and degrade to a
  ``note`` finding.
- **Two rule kinds.** :class:`FileRule` runs per file on a shared parsed AST;
  :class:`ProjectRule` runs once per lint with repo-level context
  (:class:`ProjectContext`: declared mesh axes, the exported-name map).
- **Suppression and baseline are explicit.** An inline
  ``# tpulint: disable=RULE[,RULE]`` comment silences that line; a checked-in
  baseline file grandfathers pre-existing findings, and every entry MUST carry
  a one-line justification — the loader rejects empty or ``TODO`` entries.

Severities: ``error`` > ``warning`` > ``note``.  The driver fails on error and
warning by default; notes are informational (e.g. a rule that skipped itself
because its inputs are unavailable).
"""
from __future__ import annotations

import ast
import dataclasses
import json
import os
import re

SEVERITIES = ("error", "warning", "note")

#: Inline suppression: ``# tpulint: disable=rule-a,rule-b`` or ``disable=all``.
_SUPPRESS_RE = re.compile(r"#\s*tpulint:\s*disable=([A-Za-z0-9_,\- ]+)")


class BaselineError(Exception):
    """The baseline file is malformed or an entry lacks a justification."""


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str
    path: str          # root-relative, posix separators
    line: int
    col: int
    message: str
    severity: str = "error"
    #: Stripped source line — the baseline key.  Content-addressed so the
    #: baseline survives unrelated line-number drift.
    content: str = ""

    def render(self) -> str:
        return (f"{self.path}:{self.line}:{self.col}: {self.rule} "
                f"[{self.severity}] {self.message}")

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


class Rule:
    """Base: a named, described check with a default severity and an optional
    path scope (root-relative prefixes; ``None`` = every file)."""

    name: str = ""
    severity: str = "error"
    description: str = ""
    paths: tuple | None = None

    def applies_to(self, relpath: str) -> bool:
        if self.paths is None:
            return True
        return any(relpath == p or relpath.startswith(p) for p in self.paths)


class FileRule(Rule):
    def check(self, ctx: "FileContext"):
        raise NotImplementedError


class ProjectRule(Rule):
    def check_project(self, project: "ProjectContext"):
        raise NotImplementedError


#: name -> rule instance.  Populated by :func:`register` at import of
#: ``paddle_tpu.analysis.rules``.
RULES: dict = {}


def register(rule_cls):
    """Class decorator: instantiate and register a rule by its ``name``."""
    rule = rule_cls()
    if not rule.name:
        raise ValueError(f"{rule_cls.__name__} has no name")
    RULES[rule.name] = rule
    return rule_cls


# --------------------------------------------------------------------- context
class FileContext:
    """One parsed file: source, lines, AST, and per-line suppressions."""

    def __init__(self, project: "ProjectContext", abspath: str, relpath: str):
        self.project = project
        self.path = abspath
        self.relpath = relpath
        with open(abspath, encoding="utf-8") as f:
            self.source = f.read()
        self.lines = self.source.splitlines()
        self.tree = ast.parse(self.source)  # SyntaxError handled by the runner
        self._suppressions = None

    def line_content(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""

    def suppressions(self) -> dict:
        """lineno -> set of rule names (or {'all'}) suppressed on that line."""
        if self._suppressions is None:
            out = {}
            for i, line in enumerate(self.lines, 1):
                m = _SUPPRESS_RE.search(line)
                if m:
                    out[i] = {r.strip() for r in m.group(1).split(",")
                              if r.strip()}
            self._suppressions = out
        return self._suppressions

    def finding(self, rule: Rule, node, message: str,
                severity: str | None = None) -> Finding:
        """Build a Finding anchored at an AST node (or explicit line int)."""
        line = node if isinstance(node, int) else getattr(node, "lineno", 1)
        col = 0 if isinstance(node, int) else getattr(node, "col_offset", 0)
        return Finding(rule=rule.name, path=self.relpath, line=line, col=col,
                       message=message, severity=severity or rule.severity,
                       content=self.line_content(line))

    # ------------------------------------------------------------ import map
    def import_aliases(self) -> dict:
        """Top-of-file import table: local alias -> dotted module path.

        ``import numpy as np`` -> {'np': 'numpy'};
        ``from ..framework import random as _random`` -> {'_random':
        '..framework.random'} — lets rules tell stdlib ``random`` apart from
        the framework's sanctioned PRNG of the same trailing name.
        """
        aliases = {}
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    aliases[a.asname or a.name.split(".")[0]] = a.name
            elif isinstance(node, ast.ImportFrom):
                mod = ("." * node.level) + (node.module or "")
                for a in node.names:
                    if a.name == "*":
                        continue
                    aliases[a.asname or a.name] = f"{mod}.{a.name}"
        return aliases


class ProjectContext:
    """Repo-level facts shared by rules: the declared mesh axes and the
    exported-name surfaces.  Everything is parsed from source with ``ast`` —
    nothing is imported."""

    #: Where the mesh axes are declared.  A rename here must fail lint, not a
    #: pod run — so the collective-axis rule reads THIS file, not a copy.
    TOPOLOGY_RELPATH = "paddle_tpu/distributed/topology.py"

    def __init__(self, root: str):
        self.root = os.path.abspath(root)
        self._mesh_axes = -1  # unset sentinel
        self._export_cache = {}
        #: absolute lint targets, set by :func:`run_project` — project rules
        #: that walk source (lock-guard-inference) analyze exactly the
        #: linted tree, not whatever else lives under root
        self.lint_targets = None
        self._parsed = None

    # ---------------------------------------------------------- parsed files
    def parsed_files(self):
        """[(relpath, tree, lines)] for every parseable ``.py`` under the
        lint targets (fallback: ``<root>/paddle_tpu``, else root).  Cached —
        cross-function project rules share one parse of the tree.  Files
        that fail to parse are skipped here; the per-file pass already
        emitted their ``parse-error`` finding."""
        if self._parsed is not None:
            return self._parsed
        targets = self.lint_targets
        if not targets:
            pkg = os.path.join(self.root, "paddle_tpu")
            targets = [pkg if os.path.isdir(pkg) else self.root]
        out, seen = [], set()
        for target in targets:
            for abspath in _iter_py_files(target):
                if abspath in seen:
                    continue
                seen.add(abspath)
                relpath = os.path.relpath(
                    abspath, self.root).replace(os.sep, "/")
                try:
                    with open(abspath, encoding="utf-8") as f:
                        source = f.read()
                    tree = ast.parse(source)
                except (OSError, SyntaxError, ValueError):
                    continue
                out.append((relpath, tree, source.splitlines()))
        self._parsed = out
        return out

    def suppressions_for(self, relpath: str) -> dict:
        """lineno -> suppressed-rule set for a parsed file ({} when the path
        was not linted — e.g. a project finding anchored at README.md)."""
        for rp, _tree, lines in self.parsed_files():
            if rp == relpath:
                out = {}
                for i, line in enumerate(lines, 1):
                    m = _SUPPRESS_RE.search(line)
                    if m:
                        out[i] = {r.strip() for r in m.group(1).split(",")
                                  if r.strip()}
                return out
        return {}

    # ------------------------------------------------------------- mesh axes
    def mesh_axes(self):
        """frozenset of axis names from topology.py's ``AXIS_ORDER``, or
        ``None`` when the file/assignment is absent (validation skipped)."""
        if self._mesh_axes != -1:
            return self._mesh_axes
        self._mesh_axes = None
        path = os.path.join(self.root, *self.TOPOLOGY_RELPATH.split("/"))
        try:
            with open(path, encoding="utf-8") as f:
                tree = ast.parse(f.read())
        except (OSError, SyntaxError):
            return None
        for node in tree.body:
            if isinstance(node, ast.Assign):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name) and tgt.id == "AXIS_ORDER":
                        try:
                            val = ast.literal_eval(node.value)
                        except ValueError:
                            continue
                        if isinstance(val, (tuple, list)) and all(
                                isinstance(v, str) for v in val):
                            self._mesh_axes = frozenset(val)
        return self._mesh_axes

    # -------------------------------------------------------- export surface
    def exported_names(self, relpath: str):
        """Names of module ``relpath`` that are part of an ``__init__``
        surface: imported by the sibling ``__init__.py``, listed in the
        module's own ``__all__``, or (for an ``__init__.py`` itself) defined
        publicly at top level."""
        if relpath in self._export_cache:
            return self._export_cache[relpath]
        exported = set()
        abspath = os.path.join(self.root, *relpath.split("/"))
        modname = os.path.splitext(os.path.basename(relpath))[0]
        try:
            with open(abspath, encoding="utf-8") as f:
                tree = ast.parse(f.read())
        except (OSError, SyntaxError):
            tree = None
        if tree is not None:
            exported |= self._own_all(tree)
            if modname == "__init__":
                exported |= {n.name for n in tree.body
                             if isinstance(n, (ast.FunctionDef,
                                               ast.AsyncFunctionDef,
                                               ast.ClassDef))
                             and not n.name.startswith("_")}
        init = os.path.join(os.path.dirname(abspath), "__init__.py")
        if modname != "__init__" and os.path.exists(init):
            exported |= self._init_imports(init, modname, tree)
        out = frozenset(exported)
        self._export_cache[relpath] = out
        return out

    @staticmethod
    def _own_all(tree) -> set:
        for node in tree.body:
            if isinstance(node, ast.Assign):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name) and tgt.id == "__all__":
                        try:
                            val = ast.literal_eval(node.value)
                        except ValueError:
                            return set()
                        return {v for v in val if isinstance(v, str)}
        return set()

    @staticmethod
    def _init_imports(init_path: str, modname: str, modtree) -> set:
        """Names the package __init__ pulls from sibling module ``modname``."""
        try:
            with open(init_path, encoding="utf-8") as f:
                tree = ast.parse(f.read())
        except (OSError, SyntaxError):
            return set()
        out = set()
        for node in ast.walk(tree):
            if not isinstance(node, ast.ImportFrom) or node.level == 0:
                continue
            if (node.module or "").split(".")[0] != modname:
                continue
            for a in node.names:
                if a.name == "*":
                    if modtree is not None:
                        out |= {n.name for n in modtree.body
                                if isinstance(n, (ast.FunctionDef,
                                                  ast.AsyncFunctionDef,
                                                  ast.ClassDef))
                                and not n.name.startswith("_")}
                else:
                    out.add(a.name)
        return out


# -------------------------------------------------------------------- baseline
def load_baseline(path: str):
    """Parse + validate the baseline file.  Each entry: ``rule``, ``path``,
    one of ``content`` (exact stripped line) or ``match`` (regex over the
    line), and a non-empty ``justification`` that is not a TODO stub."""
    try:
        with open(path, encoding="utf-8") as f:
            entries = json.load(f)
    except OSError as e:
        raise BaselineError(f"cannot read baseline {path}: {e}")
    except ValueError as e:
        raise BaselineError(f"baseline {path} is not valid JSON: {e}")
    if not isinstance(entries, list):
        raise BaselineError(f"baseline {path}: expected a JSON list")
    for i, e in enumerate(entries):
        if not isinstance(e, dict) or "rule" not in e or "path" not in e:
            raise BaselineError(
                f"baseline {path} entry {i}: needs 'rule' and 'path'")
        if ("content" in e) == ("match" in e):
            raise BaselineError(
                f"baseline {path} entry {i} ({e.get('rule')}): needs exactly "
                f"one of 'content' or 'match'")
        # an empty content would match EVERY finding of that rule+path —
        # current and future — silently defeating the gate
        if not (e.get("content", "x") or "").strip():
            raise BaselineError(
                f"baseline {path} entry {i} ({e.get('rule')} @ "
                f"{e.get('path')}): 'content' must be the non-empty "
                f"stripped source line (or the finding's message for "
                f"project rules)")
        just = (e.get("justification") or "").strip()
        if not just or just.upper().startswith("TODO"):
            raise BaselineError(
                f"baseline {path} entry {i} ({e.get('rule')} @ "
                f"{e.get('path')}): every baseline entry must carry a "
                f"one-line justification (found: {just!r})")
        if "match" in e:
            # an empty regex matches every line — same gate-defeating
            # blanket as empty content
            if not (e["match"] or "").strip():
                raise BaselineError(
                    f"baseline {path} entry {i} ({e.get('rule')} @ "
                    f"{e.get('path')}): 'match' must be a non-empty regex")
            try:
                re.compile(e["match"])
            except re.error as err:
                raise BaselineError(
                    f"baseline {path} entry {i}: bad regex: {err}")
    return entries


def apply_baseline(findings, entries):
    """Split findings into (kept, baselined); also return entries that
    matched nothing (stale — candidates for deletion)."""
    used = [False] * len(entries)

    def matches(entry, f: Finding) -> bool:
        if entry["rule"] != f.rule or entry["path"] != f.path:
            return False
        if "content" in entry:
            return entry["content"] == f.content
        return re.search(entry["match"], f.content) is not None

    kept, baselined = [], []
    for f in findings:
        hit = False
        for i, e in enumerate(entries):
            if matches(e, f):
                used[i] = hit = True
                break
        (baselined if hit else kept).append(f)
    unused = [e for i, e in enumerate(entries) if not used[i]]
    return kept, baselined, unused


# ---------------------------------------------------------------------- runner
def _iter_py_files(target: str):
    if os.path.isfile(target):
        yield target
        return
    for dirpath, dirnames, filenames in os.walk(target):
        dirnames[:] = sorted(d for d in dirnames
                             if d != "__pycache__" and not d.startswith("."))
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                yield os.path.join(dirpath, fn)


def _selected(rule: Rule, select, ignore) -> bool:
    if select is not None and rule.name not in select:
        return False
    if ignore is not None and rule.name in ignore:
        return False
    return True


def list_target_files(root: str, paths=None):
    """Deduplicated ``(abspath, relpath)`` pairs for the lint targets, in
    walk order — the one file enumeration shared by the serial runner and
    the ``--jobs`` parallel driver (identical lists => identical findings)."""
    root = os.path.abspath(root)
    targets = [os.path.join(root, p) if not os.path.isabs(p) else p
               for p in (paths or [root])]
    out, seen = [], set()
    for target in targets:
        for abspath in _iter_py_files(target):
            if abspath in seen:
                continue
            seen.add(abspath)
            out.append((abspath,
                        os.path.relpath(abspath, root).replace(os.sep, "/")))
    return out


def lint_file(project, abspath: str, relpath: str, file_rules):
    """File-rule pass for ONE file -> post-suppression findings."""
    try:
        ctx = FileContext(project, abspath, relpath)
    except (SyntaxError, ValueError, OSError) as e:
        # OSError: broken symlink / perms / deleted mid-walk — one
        # unreadable file must not abort the whole run
        return [Finding(rule="parse-error", path=relpath,
                        line=getattr(e, "lineno", 1) or 1, col=0,
                        message=f"cannot read/parse: {e}", severity="error")]
    file_findings = []
    for rule in file_rules:
        if rule.applies_to(relpath):
            file_findings.extend(rule.check(ctx))
    sup = ctx.suppressions()
    return [f for f in file_findings
            if f.rule not in sup.get(f.line, ())
            and "all" not in sup.get(f.line, ())]


def run_files(root: str, pairs, select=None, ignore=None):
    """Worker entry for process-parallel lints: run the FILE rules over
    ``pairs`` (list of ``(abspath, relpath)``) and return findings as dicts
    — pickle-stable across the Pool boundary.  Project rules stay in the
    parent process."""
    project = ProjectContext(os.path.abspath(root))
    file_rules = [r for r in RULES.values()
                  if isinstance(r, FileRule) and _selected(r, select, ignore)]
    out = []
    for abspath, relpath in pairs:
        out.extend(f.to_dict()
                   for f in lint_file(project, abspath, relpath, file_rules))
    return out


def project_rule_findings(project, select=None, ignore=None):
    """Run the project rules and apply each file's inline suppressions to
    their findings (a ``# tpulint: disable=lock-guard-inference`` must work
    for project rules exactly like it does for file rules)."""
    findings = []
    for rule in RULES.values():
        if isinstance(rule, ProjectRule) and _selected(rule, select, ignore):
            for f in rule.check_project(project):
                sup = project.suppressions_for(f.path).get(f.line, ())
                if f.rule in sup or "all" in sup:
                    continue
                findings.append(f)
    return findings


def finding_sort_key(f: Finding):
    """The one ordering applied to every findings list — the serial runner
    and the ``--jobs`` merge must sort identically to stay byte-identical."""
    return (f.path, f.line, f.col, f.rule)


def run_project(root: str, paths=None, select=None, ignore=None,
                project_rules: bool = True):
    """Lint ``paths`` (files/dirs, default: the whole root) and return the
    sorted post-suppression findings.  Baseline application is the driver's
    job — this returns everything a human could be asked about."""
    root = os.path.abspath(root)
    project = ProjectContext(root)
    targets = [os.path.join(root, p) if not os.path.isabs(p) else p
               for p in (paths or [root])]
    project.lint_targets = targets
    file_rules = [r for r in RULES.values()
                  if isinstance(r, FileRule) and _selected(r, select, ignore)]
    findings = []
    for abspath, relpath in list_target_files(root, paths):
        findings.extend(lint_file(project, abspath, relpath, file_rules))
    if project_rules:
        findings.extend(project_rule_findings(project, select, ignore))
    findings.sort(key=finding_sort_key)
    return findings


# ------------------------------------------------------------------- reporters
def render_text(findings, baselined_count: int = 0, unused_baseline=None):
    lines = [f.render() for f in findings]
    fail = [f for f in findings if f.severity in ("error", "warning")]
    tail = (f"tpulint: {len(fail)} finding(s)"
            if fail else "tpulint: clean")
    if baselined_count:
        tail += f" ({baselined_count} baselined)"
    for e in (unused_baseline or []):
        lines.append(f"note: stale baseline entry matched nothing: "
                     f"{e['rule']} @ {e['path']}")
    lines.append(tail)
    return "\n".join(lines)


def render_json(findings, baselined_count: int = 0, unused_baseline=None):
    counts = {s: 0 for s in SEVERITIES}
    for f in findings:
        counts[f.severity] = counts.get(f.severity, 0) + 1
    return json.dumps({
        "version": 1,
        "findings": [f.to_dict() for f in findings],
        "counts": counts,
        "baselined": baselined_count,
        "stale_baseline_entries": list(unused_baseline or []),
    }, indent=2, sort_keys=True)
