"""tpulint — TPU-native static analysis for the whole package.

The paper's ≥70%-MFU north star is killed by bug classes XLA compiles
without complaint: host syncs inside jitted steps, wall-clock reads baked
into traces, collectives over renamed mesh axes, donated buffers read after
the call, f32 drift in bf16 paths.  This package is the machine-checked
floor: an AST-based, dependency-free lint engine plus a framework-aware rule
suite, run in tier-1 via ``tools/tpulint.py --check paddle_tpu``.

Public surface::

    from paddle_tpu.analysis import run_project, RULES
    findings = run_project(repo_root, paths=["paddle_tpu"])

Suppress one line:   ``# tpulint: disable=rule-name``
Grandfather history: ``tools/tpulint_baseline.json`` (every entry justified).
"""
from .engine import (  # noqa: F401
    RULES,
    BaselineError,
    FileContext,
    FileRule,
    Finding,
    ProjectContext,
    ProjectRule,
    Rule,
    apply_baseline,
    finding_sort_key,
    lint_file,
    list_target_files,
    load_baseline,
    project_rule_findings,
    register,
    render_json,
    render_text,
    run_files,
    run_project,
)
from . import rules  # noqa: F401  (registers the built-in suite)
