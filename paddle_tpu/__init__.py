"""paddle_tpu: a TPU-native deep learning framework with PaddlePaddle's API surface.

Built on JAX/XLA/Pallas: eager ops are jax.numpy compositions with taped autograd
(jax.vjp per op); `to_static`/jit compiles whole training steps with XLA; distribution
is jax.sharding Meshes + XLA collectives over ICI/DCN.  See SURVEY.md for the blueprint
and per-module docstrings for reference file:line parity pointers.
"""
from __future__ import annotations

# -- core dtype / device / rng surface
from .core.dtypes import (  # noqa: F401
    bool_ as bool8,
    uint8,
    int8,
    int16,
    int32,
    int64,
    float16,
    bfloat16,
    float32,
    float64,
    complex64,
    complex128,
    set_default_dtype,
    get_default_dtype,
)
from .core import dtypes as dtypes  # noqa: F401
from .core.device import (  # noqa: F401
    CPUPlace,
    CUDAPlace,
    TPUPlace,
    CustomPlace,
    set_device,
    get_device,
    is_compiled_with_cuda,
    is_compiled_with_tpu,
)
from .framework.random import seed, Generator  # noqa: F401

# -- autograd
from .autograd.tape import no_grad, enable_grad, is_grad_enabled, set_grad_enabled, grad  # noqa: F401
from . import autograd  # noqa: F401

# -- tensor & ops: re-export every public op into the paddle namespace
from .tensor import Tensor, Parameter  # noqa: F401
from .tensor.creation import *  # noqa: F401,F403
from .tensor.math import *  # noqa: F401,F403
from .tensor.manipulation import *  # noqa: F401,F403
from .tensor.logic import *  # noqa: F401,F403
from .tensor.search import *  # noqa: F401,F403
from .tensor import linalg  # noqa: F401
from .tensor.linalg import norm, dist, cholesky, dot, t, einsum  # noqa: F401
from .tensor.math import max, min, sum, abs, pow, round  # noqa: F401  (shadow builtins as paddle does)
from .tensor.logic import all, any  # noqa: F401
from .tensor import creation as _creation
from .tensor import math as _math

# -- subpackages (import order matters: nn depends on tensor)
from . import nn  # noqa: F401
from . import optimizer  # noqa: F401
from . import io  # noqa: F401
from .io import DataLoader  # noqa: F401
from . import metric  # noqa: F401
from . import amp  # noqa: F401
from . import jit  # noqa: F401
from . import static  # noqa: F401
from . import vision  # noqa: F401
from . import distributed  # noqa: F401
from . import distribution  # noqa: F401
from . import incubate  # noqa: F401
from . import inference  # noqa: F401
from . import fft  # noqa: F401
from . import signal  # noqa: F401
from . import text  # noqa: F401
from . import profiler  # noqa: F401
from . import observability  # noqa: F401
from . import framework  # noqa: F401
from . import device  # noqa: F401
from . import hapi  # noqa: F401
from .hapi import Model  # noqa: F401
from . import models  # noqa: F401
from . import sysconfig  # noqa: F401
from . import utils  # noqa: F401
from . import regularizer  # noqa: F401
from . import callbacks  # noqa: F401
from . import version  # noqa: F401
from . import hub  # noqa: F401
from . import reader  # noqa: F401
from . import dataset  # noqa: F401
from . import compat  # noqa: F401
from . import cost_model  # noqa: F401
from . import onnx  # noqa: F401
from . import quantization  # noqa: F401
from .batch import batch  # noqa: F401
from .framework.io import save, load  # noqa: F401
from .framework.param_attr import ParamAttr  # noqa: F401
from .framework.random import get_cuda_rng_state, set_cuda_rng_state  # noqa: F401
from .framework.flags import set_flags, get_flags  # noqa: F401
from .jit import to_static  # noqa: F401
from .nn.layer.container import Sequential  # noqa: F401
from .amp.grad_scaler import GradScaler  # noqa: F401
from .hapi import summary, flops  # noqa: F401

# DataParallel at top level (ref: paddle.DataParallel)
from .distributed.parallel import DataParallel  # noqa: F401

def disable_static(place=None):
    """Leave static-graph capture and return to eager dygraph (the default).
    Must actually deactivate the capture hooks — a no-op here would leave
    every subsequent op silently recording onto the default main program."""
    static.disable_static()


enable_static = static.enable_static

__version__ = "0.1.0"


def is_grad_enabled_():
    return is_grad_enabled()


def get_cudnn_version():
    return None


def device_count():
    import jax

    return len(jax.devices())


def in_dynamic_mode():
    return not static.in_static_mode()


def set_printoptions(**kwargs):
    import numpy as np

    np.set_printoptions(**{k: v for k, v in kwargs.items() if k in ("precision", "threshold", "edgeitems", "linewidth")})


# ---- small top-level parity shims (ref python/paddle/__init__.py __all__)
from .core.dtypes import bool_ as bool  # noqa: F401,A001  (paddle.bool dtype)
dtype = __import__('numpy').dtype  # paddle.dtype callable parity


def is_complex(x):
    from .core import dtypes as _dt

    return _dt.is_complex(x.dtype)


def is_floating_point(x):
    from .core import dtypes as _dt

    return _dt.is_floating(x.dtype)


def is_integer(x):
    from .core import dtypes as _dt

    return _dt.is_integer(x.dtype)


def complex(real, imag, name=None):
    import jax.lax as _lax

    from .tensor.tensor import apply_op as _ap

    return _ap(_lax.complex, (real, imag), name="complex")


def check_shape(*a, **k):  # static-graph debug helper: shapes are static here
    pass


def disable_signal_handler():
    pass


class CUDAPinnedPlace:  # GPU-era place shims (accepted, meaningless on TPU)
    pass


class NPUPlace:
    def __init__(self, idx=0):
        self.idx = idx
