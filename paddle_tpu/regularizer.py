"""paddle.regularizer — L1/L2 weight decay regularizers.

Ref: python/paddle/regularizer.py (L1Decay/L2Decay over fluid.regularizer).
Semantics: a regularizer set on a ``ParamAttr`` takes priority over one set on
the optimizer's ``weight_decay``; the optimizer folds the penalty gradient into
each parameter's gradient before the update rule
(see Optimizer._param_decay_coeff / _apply_update).
"""
from __future__ import annotations

__all__ = ["L1Decay", "L2Decay"]


class WeightDecayRegularizer:
    _mode = "l2"

    def __init__(self, coeff=0.0):
        self._coeff = float(coeff)

    @property
    def coeff(self):
        return self._coeff

    def __repr__(self):
        return f"{type(self).__name__}(coeff={self._coeff})"


class L2Decay(WeightDecayRegularizer):
    """loss += coeff * 0.5 * sum(x^2)  =>  grad += coeff * x."""

    _mode = "l2"


class L1Decay(WeightDecayRegularizer):
    """loss += coeff * sum(|x|)  =>  grad += coeff * sign(x)."""

    _mode = "l1"
