"""Compiled autoregressive generation.

Ref surface: PaddleNLP's `model.generate(...)` (greedy / sampling); the
reference repo itself stops at fused attention ops, so the decode loop is
designed TPU-first: ONE `jax.jit` containing the prefill plus a
`lax.scan` over decode steps on a STATIC kv-cache (fixed-size buffers +
`dynamic_update_slice`), so nothing recompiles per token and the whole
generation is a single device program.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from contextlib import nullcontext

from ..autograd import tape
from ..framework import random as _random
from ..ops import lora as _oplora
from ..ops.sampling import sample_rows, spec_accept
from ..tensor.tensor import Tensor

__all__ = ["generate"]


def _resolve_lora(model, adapter_id, adapters):
    """-> (pool, page, release_cb|None) for the solo-parity adapter path.

    ``adapters`` is either a shared ``models.lora.AdapterRegistry`` (the
    engine's pool — page contents then match the engine bit for bit; the
    adapter is pinned for the duration of the call) or a plain
    ``{id: LoraAdapter}`` mapping (a throwaway 2-page pool is built)."""
    if adapter_id is None:
        return None, 0, None
    from .lora import AdapterRegistry, build_solo_pool

    if adapters is None:
        raise ValueError(
            "adapter_id= needs adapters= (an AdapterRegistry or an "
            "{id: LoraAdapter} mapping)")
    if isinstance(adapters, AdapterRegistry):
        page = adapters.acquire(adapter_id)
        if page is None:
            raise RuntimeError(
                "adapter pool exhausted: every page is pinned by live "
                "requests")
        return adapters.pool, page, (lambda: adapters.release(adapter_id))
    return build_solo_pool(model, adapters[adapter_id]), 1, None


def _resolve_constraint(token_mask_fn):
    """``token_mask_fn`` is a compiled ``inference.constrain``
    TokenConstraint, or a zero-arg callable returning one (the "fn"
    spelling for lazy compilation)."""
    if token_mask_fn is None:
        return None
    c = token_mask_fn() if callable(token_mask_fn) else token_mask_fn
    if not hasattr(c, "device_tables"):
        raise TypeError(
            "token_mask_fn must be an inference.constrain.TokenConstraint "
            "(or a zero-arg callable returning one), got "
            f"{type(c).__name__}")
    return c


def _lora_trace_ctx(pool, lora_tree, lora_rows):
    """Context manager activating the LoRA epilogues during tracing; a
    no-op when the call carries no adapter.  ``lora_tree`` is the traced
    pool tree (a jit argument — swapping adapter weights never
    recompiles); ``pool`` only supplies the static site layout."""
    if pool is None:
        return nullcontext()
    return _oplora.activate(pool.site_pools(lora_tree), lora_rows)


def _gen_extra_args(pool, page, B, constraint):
    """The per-call device-array tail (lora_tree, lora_rows, c_masks,
    c_trans) — dummies keep the jit signature stable when a knob is
    off."""
    if pool is not None:
        tree, rows = pool.tree(), jnp.full((B,), page, jnp.int32)
    else:
        tree, rows = (), jnp.zeros((0,), jnp.int32)
    if constraint is not None:
        cm, ct = constraint.device_tables()
    else:
        cm, ct = jnp.zeros((1, 1), bool), jnp.zeros((1, 1), jnp.int32)
    return tree, rows, cm, ct


def _select(logits, key, do_sample, temperature, top_k, top_p,
            token_mask=None):
    """logits [B, V] -> token ids [B, 1].  Scalar-knob wrapper over the
    fused per-row sampler (ops/sampling.sample_rows) — ONE masking +
    categorical implementation serves the solo loop, the serving engine
    and the speculative verify programs.  ``token_mask`` (bool [B, V]) is
    the constrained-decoding path: greedy rows argmax over the masked
    logits, sampled rows inherit it through mask_logits."""
    if not do_sample:
        src = logits if token_mask is None else jnp.where(
            token_mask, logits, -jnp.inf)
        return jnp.argmax(src, axis=-1).astype(jnp.int32)[:, None]
    B = logits.shape[0]
    return sample_rows(
        logits, key, jnp.ones((B,), bool),
        jnp.full((B,), temperature, jnp.float32),
        jnp.full((B,), int(top_k), jnp.int32),
        jnp.full((B,), top_p, jnp.float32),
        token_mask=token_mask)[:, None]


def _to_static_caches(caches, ids, total, cache_dtype, kv_layout, page_size,
                      share_prefix):
    """Convert a prefill's concat-caches into HEAD-MAJOR static buffers
    [B, H, L, D] (traced; runs inside the compiled prefill).  L is padded
    up to a multiple of 128 so the Pallas decode kernel's key blocks tile
    cleanly (the padded tail is never valid, the kernel masks by
    position).  kv_layout="paged" additionally pads to whole pages and
    reshapes each row's buffer into page-pool rows behind an identity page
    table (page 0 stays the reserved trash page)."""
    B, S0 = ids.shape
    unit = 128
    if kv_layout == "paged":
        import math

        unit = page_size * 128 // math.gcd(page_size, 128)
    L_pad = ((total + unit - 1) // unit) * unit
    n_pages = L_pad // page_size if kv_layout == "paged" else 0

    def to_pool(x):  # [B, H, L_pad, D] -> [1 + B*M, H, ps, D]
        Bb, H, L, D = x.shape
        pg = x.reshape(Bb, H, n_pages, page_size, D)
        pg = jnp.transpose(pg, (0, 2, 1, 3, 4))
        pg = pg.reshape(Bb * n_pages, H, page_size, D)
        return jnp.concatenate(
            [jnp.zeros((1,) + pg.shape[1:], pg.dtype), pg], axis=0)

    def to_spool(s):  # [B, H, L_pad] -> [1 + B*M, H, ps]
        Bb, H, L = s.shape
        pg = s.reshape(Bb, H, n_pages, page_size)
        pg = jnp.transpose(pg, (0, 2, 1, 3))
        pg = pg.reshape(Bb * n_pages, H, page_size)
        return jnp.concatenate(
            [jnp.full((1,) + pg.shape[1:], 1e-8, pg.dtype), pg],
            axis=0)

    page_tbl = None
    if kv_layout == "paged":
        page_tbl = (1 + jnp.arange(B * n_pages, dtype=jnp.int32)
                    ).reshape(B, n_pages)
        if share_prefix and B > 1:
            # alias every row's page-aligned common prompt
            # prefix onto row 0's PHYSICAL pages.  Aliased
            # pages are never written: decode scatters at
            # positions >= S0 >= cpl, whose page index is >=
            # k_shared, and only pages < k_shared are shared.
            same = jnp.all(ids == ids[:1], axis=0)
            cpl = jnp.where(same.all(), S0, jnp.argmin(same))
            k_shared = (cpl // page_size).astype(jnp.int32)
            page_tbl = jnp.where(
                jnp.arange(n_pages, dtype=jnp.int32)[None, :]
                < k_shared, page_tbl[:1], page_tbl)
    static = []
    for (k, v) in caches:
        pad = [(0, 0), (0, 0), (0, L_pad - S0), (0, 0)]
        kp = jnp.pad(jnp.transpose(k._value, (0, 2, 1, 3)), pad)
        vp = jnp.pad(jnp.transpose(v._value, (0, 2, 1, 3)), pad)
        pos = jnp.asarray(S0, jnp.int32)
        if cache_dtype == "int8":
            from .kv_cache import _quantize_kv

            kq, ks = _quantize_kv(kp)
            vq, vs = _quantize_kv(vp)
            if kv_layout == "paged":
                static.append((to_pool(kq), to_pool(vq), pos,
                               page_tbl, to_spool(ks),
                               to_spool(vs)))
            else:
                static.append((kq, vq, pos, ks, vs))
        elif kv_layout == "paged":
            static.append((to_pool(kp), to_pool(vp), pos,
                           page_tbl))
        else:
            static.append((kp, vp, pos))
    return static


def generate(model, input_ids, max_new_tokens=32, do_sample=False,
             temperature=1.0, top_k=0, top_p=1.0, eos_token_id=None,
             pad_token_id=0, cache_dtype=None, kv_layout=None,
             page_size=128, share_prefix=False, spec_k=0, spec_drafter=None,
             adapter_id=None, adapters=None, token_mask_fn=None):
    """Generate `max_new_tokens` continuations of `input_ids` [B, S0].

    Returns int32 ids [B, max_new_tokens]; once a row emits `eos_token_id`
    the rest of that row is `pad_token_id`.  The model must expose
    `generate_step(ids, caches)` (prefill/decode) — LlamaForCausalLM does.

    cache_dtype="int8" stores the kv-cache quantized (per-head-token
    absmax scales), HALVING the cache's HBM footprint AND the kv bytes the
    decode step streams: the Pallas decode-attention kernel
    (ops/decode_attention.py) reads the int8 buffers directly and
    dequantizes in VMEM — capacity and speed lever in one.

    kv_layout="paged" decodes through the PAGED cache (global page pool +
    per-row identity page tables, `page_size` tokens per page) — the
    serving engine's layout, exposed here so the ragged paged kernel can be
    parity-tested and benchmarked against the dense static path with no
    server in the loop.  Greedy outputs are identical to the static
    layout's: same math, different residency.

    share_prefix=True (paged only) additionally aliases every row's
    page-aligned common prompt prefix onto row 0's physical pages — the
    serving engine's shared-prefix read path (inference/prefix_cache.py),
    run solo so it can be parity-tested with no server in the loop.  The
    aliased pages are read-only by construction: decode writes land at
    positions >= the prompt length, i.e. in each row's private pages, so
    no copy-on-write is ever needed here and outputs stay bitwise
    identical to private tables.

    adapter_id=/adapters= runs the whole generation through a LoRA
    adapter: every hooked projection adds the paged-pool epilogue
    ``(x @ A[page]) @ B[page]`` (ops/lora.py).  ``adapters`` is the
    serving engine's ``AdapterRegistry`` (page contents and math then
    match the engine bit for bit — the solo-parity surface the
    multi-tenant tests pin down) or a plain ``{id: LoraAdapter}``
    mapping.  ``adapter_id=None`` rows never touch the epilogue, so the
    output is bitwise identical to a build without LoRA.

    token_mask_fn= (a compiled ``inference.constrain.TokenConstraint``,
    or a zero-arg callable returning one) turns on CONSTRAINED decoding:
    the automaton's dense ``[n_states, V]`` mask/transition tables ride
    into the compiled program as device arrays, the scan carries one
    int32 automaton state per row, and every step's logits are masked
    before selection (explicit mask -> temperature -> top-k -> top-p).
    The same table bits drive the serving engine's per-tick mask upload,
    so engine and solo constrained outputs are bitwise identical.  Not
    composable with spec_k (masks are per-position).

    spec_k > 0 switches to SPECULATIVE decoding: a host-side drafter
    (``spec_drafter``: "ngram" prompt-lookup by default, or a small draft
    model — models/spec_decode.py) proposes K tokens per step and one
    compiled verify pass scores all K+1 positions, accepting the longest
    valid prefix (ops/sampling.spec_accept).  Greedy outputs are BITWISE
    identical to spec_k=0 on every cache layout; sampled outputs are
    distribution-preserving via rejection sampling.  Each verify emits
    between 1 and K+1 tokens, so good drafts cut the number of serial
    model passes by up to (K+1)x.
    """
    ids = input_ids._value if isinstance(input_ids, Tensor) else jnp.asarray(input_ids)
    ids = ids.astype(jnp.int32)
    B, S0 = ids.shape
    total = S0 + int(max_new_tokens)
    params, buffers = model.functional_state()
    eos = -1 if eos_token_id is None else int(eos_token_id)

    # one compiled program per generation signature, cached on the model —
    # a fresh jax.jit per call would recompile the whole prefill+scan.
    # params AND buffers are explicit jit arguments, so weight/buffer updates
    # (set_state_dict, dtype casts) flow into cached programs; a dtype change
    # simply retraces under the same jit object.
    if cache_dtype not in (None, "int8"):
        raise ValueError(f"cache_dtype must be None or 'int8', got {cache_dtype!r}")
    if cache_dtype == "int8" and not getattr(model, "_supports_quant_cache", False):
        raise ValueError(
            f"{type(model).__name__} does not support the int8 kv-cache "
            "layout (its attention only understands the (k, v, pos) tuple); "
            "use the default cache_dtype")
    if kv_layout not in (None, "paged"):
        raise ValueError(f"kv_layout must be None or 'paged', got {kv_layout!r}")
    if kv_layout == "paged" and not getattr(model, "_supports_paged_cache",
                                            False):
        raise ValueError(
            f"{type(model).__name__} does not support the paged kv-cache "
            "layout; use the default kv_layout")
    if share_prefix and kv_layout != "paged":
        raise ValueError(
            "share_prefix requires kv_layout='paged' (sharing rides on the "
            "page tables)")
    page_size = int(page_size)
    if int(spec_k) < 0:
        raise ValueError(f"spec_k must be >= 0, got {spec_k}")
    constraint = _resolve_constraint(token_mask_fn)
    if constraint is not None:
        if spec_k:
            raise ValueError(
                "token_mask_fn does not compose with spec_k (constraint "
                "masks are per-position; drafts cannot be pre-masked)")
        vocab = getattr(getattr(model, "config", None), "vocab_size", None)
        if vocab is not None and int(vocab) != constraint.V:
            raise ValueError(
                f"constraint vocab size {constraint.V} != model vocab "
                f"size {int(vocab)}")
        if eos_token_id is None:
            eos = int(constraint.eos_token_id)
        elif eos != int(constraint.eos_token_id):
            raise ValueError(
                f"eos_token_id {eos} != the constraint's eos "
                f"{int(constraint.eos_token_id)}")
    pool, page, release = _resolve_lora(model, adapter_id, adapters)
    try:
        if spec_k:
            return _generate_spec(
                model, ids, int(max_new_tokens), bool(do_sample),
                float(temperature), int(top_k), float(top_p), eos,
                int(pad_token_id), cache_dtype, kv_layout, page_size,
                bool(share_prefix), int(spec_k), spec_drafter, pool, page)
        # the lora/constraint signatures capture only SHAPE-relevant facts
        # (pool geometry, automaton size), so swapping adapter weights or
        # constraint specs of the same shape reuses the compiled program
        lora_sig = (None if pool is None else
                    ("lora", pool.num_pages, pool.rank, str(pool.dtype)))
        c_sig = (None if constraint is None else
                 ("constraint", constraint.n_states, constraint.V))
        cache_key = (B, S0, int(max_new_tokens), bool(do_sample),
                     float(temperature), int(top_k), float(top_p), eos,
                     int(pad_token_id), bool(model.training), cache_dtype,
                     kv_layout, page_size, bool(share_prefix), lora_sig,
                     c_sig)
        gen_cache = model.__dict__.setdefault("_generate_cache", {})
        extra = _gen_extra_args(pool, page, B, constraint)
        if cache_key in gen_cache:
            key = _random.get_rng_key()
            out = gen_cache[cache_key](params, buffers, ids, key, *extra)
            t = Tensor(out)
            t.stop_gradient = True
            return t
        use_c = constraint is not None

        def run(params, buffers, ids, key, lora_tree, lora_rows, c_masks,
                c_trans):
            restore = model.bind_functional_state(params, buffers)
            try:
                with tape.no_grad(), _lora_trace_ctx(pool, lora_tree,
                                                     lora_rows):
                    logits, caches = model.generate_step(Tensor(ids))
                    static = _to_static_caches(
                        caches, ids, total, cache_dtype, kv_layout,
                        page_size, share_prefix)
                    key, sub = jax.random.split(key)
                    cstate = jnp.zeros((B,), jnp.int32) if use_c else None
                    mask = c_masks[cstate] if use_c else None
                    tok = _select(logits._value[:, -1], sub, do_sample,
                                  temperature, top_k, top_p, mask)
                    if use_c:
                        cstate = c_trans[cstate, tok[:, 0]]
                    done = (tok[:, 0] == eos)

                    def body(carry, key_t):
                        if use_c:
                            caches, tok, done, cstate = carry
                        else:
                            caches, tok, done = carry
                            cstate = None
                        t_caches = [tuple(Tensor(x)
                                          if getattr(x, "ndim", 0) > 0
                                          else x for x in c) for c in caches]
                        logits, new_caches = model.generate_step(
                            Tensor(tok), caches=t_caches)
                        mask = c_masks[cstate] if use_c else None
                        nxt = _select(logits._value[:, -1], key_t, do_sample,
                                      temperature, top_k, top_p, mask)
                        nxt = jnp.where(done[:, None],
                                        jnp.asarray(pad_token_id, jnp.int32),
                                        nxt)
                        new_done = done | (nxt[:, 0] == eos)
                        raw = [tuple(x._value if isinstance(x, Tensor) else x
                                     for x in c) for c in new_caches]
                        if use_c:
                            # finished rows emit pad; park them in state 0
                            # (any valid state works — masks are unused
                            # once done) so the gather stays in-bounds
                            ncs = jnp.where(
                                new_done, 0, c_trans[cstate, nxt[:, 0]])
                            return (raw, nxt, new_done, ncs), tok[:, 0]
                        return (raw, nxt, new_done), tok[:, 0]

                    if max_new_tokens > 1:
                        keys = jax.random.split(key, max_new_tokens - 1)
                        init = ((static, tok, done, cstate) if use_c
                                else (static, tok, done))
                        carry, toks = jax.lax.scan(body, init, keys)
                        out = jnp.concatenate([toks.T, carry[1]], axis=1)
                    else:
                        out = tok
            finally:
                restore()
            return out

        jitted = jax.jit(run)
        gen_cache[cache_key] = jitted
        key = _random.get_rng_key()
        out = jitted(params, buffers, ids, key, *extra)
        t = Tensor(out)
        t.stop_gradient = True
        return t
    finally:
        if release is not None:
            release()


def _generate_spec(model, ids, max_new_tokens, do_sample, temperature,
                   top_k, top_p, eos, pad_token_id, cache_dtype, kv_layout,
                   page_size, share_prefix, spec_k, spec_drafter,
                   pool=None, page=0):
    """Speculative decoding: K host-drafted tokens verified per compiled
    step (S = K+1 through the same static/paged cache paths the plain
    loop uses), host loop over draft -> verify -> accept.

    Greedy output is BITWISE identical to the non-speculative loop: the
    verify ladder's argmaxes are exactly the tokens single-step decoding
    would have produced (ops/sampling.spec_accept), and every accepted
    prefix extends them.  Rollback is free on the static layouts — the
    per-row position vector simply does not advance past the accept
    point, and rejected rows' kv is overwritten by the next verify pass
    before any read can reach it.  ``do_sample`` rows run one-hot-q
    rejection sampling (distribution-preserving, not bitwise).

    Drafting is host-side (models/spec_decode; prompt-lookup n-gram by
    default), so the compiled programs never depend on the draft source.
    """
    from .spec_decode import get_drafter

    drafter = get_drafter(spec_drafter)
    B, S0 = ids.shape
    K = int(spec_k)
    # verify scatters rows pos .. pos+K; pad the cache so a row one token
    # short of max_new_tokens still scatters in-bounds
    total = S0 + int(max_new_tokens) + K
    params, buffers = model.functional_state()
    lora_sig = (None if pool is None else
                ("lora", pool.num_pages, pool.rank, str(pool.dtype)))
    cache_key = ("spec", B, S0, int(max_new_tokens), bool(do_sample),
                 float(temperature), int(top_k), float(top_p), eos,
                 int(pad_token_id), bool(model.training), cache_dtype,
                 kv_layout, page_size, bool(share_prefix), K, lora_sig)
    gen_cache = model.__dict__.setdefault("_generate_cache", {})
    l_tree, l_rows = _gen_extra_args(pool, page, B, None)[:2]
    if cache_key not in gen_cache:
        def prefill(params, buffers, ids, key, lora_tree, lora_rows):
            restore = model.bind_functional_state(params, buffers)
            try:
                with tape.no_grad(), _lora_trace_ctx(pool, lora_tree,
                                                     lora_rows):
                    logits, caches = model.generate_step(Tensor(ids))
                    static = _to_static_caches(
                        caches, ids, total, cache_dtype, kv_layout,
                        page_size, share_prefix)
                    # strip the scalar pos at [2]: the host loop owns the
                    # per-row positions (rows advance by different amounts)
                    stripped = [c[:2] + c[3:] for c in static]
                    tok = _select(logits._value[:, -1], key, do_sample,
                                  temperature, top_k, top_p)
            finally:
                restore()
            return tok, stripped

        def verify(params, buffers, caches, tok, drafts, pos, key,
                   lora_tree, lora_rows):
            restore = model.bind_functional_state(params, buffers)
            try:
                with tape.no_grad(), _lora_trace_ctx(pool, lora_tree,
                                                     lora_rows):
                    t_caches = [
                        tuple(Tensor(x) for x in c[:2]) + (pos,)
                        + tuple(Tensor(x) for x in c[2:]) for c in caches]
                    ids_in = jnp.concatenate([tok, drafts], axis=1)
                    logits, new_caches = model.verify_step(
                        Tensor(ids_in), caches=t_caches)
                    raw = []
                    for c in new_caches:
                        vals = tuple(x._value if isinstance(x, Tensor) else x
                                     for x in c)
                        raw.append(vals[:2] + vals[3:])
                    out, n_acc = spec_accept(
                        logits._value, drafts, key,
                        jnp.full((B,), do_sample, bool),
                        jnp.full((B,), temperature, jnp.float32),
                        jnp.full((B,), top_k, jnp.int32),
                        jnp.full((B,), top_p, jnp.float32))
            finally:
                restore()
            return out, n_acc, raw

        gen_cache[cache_key] = (jax.jit(prefill),
                                jax.jit(verify, donate_argnums=(2,)))
    prefill_jit, verify_jit = gen_cache[cache_key]
    key = _random.get_rng_key()
    key, sub = jax.random.split(key)
    first, caches = prefill_jit(params, buffers, ids, sub, l_tree, l_rows)
    first = np.asarray(first).reshape(B)
    out = np.full((B, int(max_new_tokens)), int(pad_token_id), np.int32)
    counts = np.zeros(B, np.int64)
    done = np.zeros(B, bool)
    # pos[b] is the position the NEXT verify writes last[b]'s kv at —
    # i.e. the count of already-written rows: S0 + emitted - 1 (the
    # newest emitted token's kv is always written by the verify that
    # consumes it, never by the one that produced it)
    pos = np.full(B, S0, np.int32)
    last = first.astype(np.int32)
    ctx = [list(map(int, ids[b])) for b in range(B)]
    for b in range(B):
        out[b, 0] = last[b]
        counts[b] = 1
        ctx[b].append(int(last[b]))
        if last[b] == eos or max_new_tokens <= 1:
            done[b] = True
    while not done.all():
        drafts = np.stack([drafter.propose(np.asarray(ctx[b], np.int32), K)
                           for b in range(B)])
        key, sub = jax.random.split(key)
        o_dev, n_dev, caches = verify_jit(
            params, buffers, caches, jnp.asarray(last[:, None]),
            jnp.asarray(drafts), jnp.asarray(pos), sub, l_tree, l_rows)
        o = np.asarray(o_dev)
        n = np.asarray(n_dev)
        for b in range(B):
            if done[b]:
                continue
            for j in range(int(n[b]) + 1):
                tok = int(o[b, j])
                out[b, counts[b]] = tok
                counts[b] += 1
                ctx[b].append(tok)
                pos[b] += 1
                last[b] = tok
                if tok == eos or counts[b] >= max_new_tokens:
                    done[b] = True
                    break
    t = Tensor(jnp.asarray(out))
    t.stop_gradient = True
    return t
