"""GPT family (learned-position causal decoder; complements LLaMA for the zoo)."""
from __future__ import annotations

from dataclasses import dataclass

from .. import nn
from ..nn import functional as F
from ..ops import lora as _lora
from ..tensor import creation
from ..distributed.meta_parallel.mp_layers import (
    ColumnParallelLinear, RowParallelLinear, VocabParallelEmbedding,
)


@dataclass
class GPTConfig:
    vocab_size: int = 50304
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    max_position_embeddings: int = 1024
    hidden_dropout_prob: float = 0.1
    attention_probs_dropout_prob: float = 0.1
    layer_norm_eps: float = 1e-5
    tensor_parallel: bool = False

    @staticmethod
    def tiny(**kw):
        base = dict(vocab_size=1024, hidden_size=128, num_hidden_layers=2,
                    num_attention_heads=4, intermediate_size=512, max_position_embeddings=128)
        base.update(kw)
        return GPTConfig(**base)


class GPTBlock(nn.Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        h = config.hidden_size
        tp = config.tensor_parallel
        self.ln_1 = nn.LayerNorm(h, epsilon=config.layer_norm_eps)
        self.num_heads = config.num_attention_heads
        self.head_dim = h // config.num_attention_heads
        if tp:
            self.qkv = ColumnParallelLinear(h, 3 * h, gather_output=False)
            self.proj = RowParallelLinear(h, h, input_is_parallel=True)
            self.fc_in = ColumnParallelLinear(h, config.intermediate_size, gather_output=False)
            self.fc_out = RowParallelLinear(config.intermediate_size, h, input_is_parallel=True)
        else:
            self.qkv = nn.Linear(h, 3 * h)
            self.proj = nn.Linear(h, h)
            self.fc_in = nn.Linear(h, config.intermediate_size)
            self.fc_out = nn.Linear(config.intermediate_size, h)
        self.ln_2 = nn.LayerNorm(h, epsilon=config.layer_norm_eps)
        self.drop = nn.Dropout(config.hidden_dropout_prob)
        self.attn_drop = config.attention_probs_dropout_prob

    def _proj_out(self, x, attn_flat):
        y = self.proj(attn_flat)
        d = _lora.apply_site("proj", attn_flat)
        return x + self.drop(y if d is None else y + d)

    def _mlp(self, x):
        h = self.ln_2(x)
        u = self.fc_in(h)
        d_in = _lora.apply_site("fc_in", h)
        if d_in is not None:  # multi-tenant LoRA epilogues (see forward)
            u = u + d_in
        g = F.gelu(u)
        y = self.fc_out(g)
        d_out = _lora.apply_site("fc_out", g)
        return x + self.drop(y if d_out is None else y + d_out)

    def forward(self, x, cache=None, use_cache=False):
        B, S = x.shape[0], x.shape[1]
        h = self.ln_1(x)
        qkv = self.qkv(h)
        dqkv = _lora.apply_site("qkv", h)
        if dqkv is not None:
            # multi-tenant LoRA epilogue: per-row adapter-page gathers add
            # the low-rank delta; zero-adapter rows gather page 0 (exact +0)
            qkv = qkv + dqkv
        qkv = qkv.reshape([B, S, 3, self.num_heads, self.head_dim])
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        attn_mask = None
        if cache is not None and len(cache) in (4, 6):
            # PAGED layout (kv_cache.py paged contract): scatter into the
            # global page pool, attend through the slot's page table —
            # ONE ragged paged Pallas kernel for any S on tile-aligned
            # shapes (decode, prefill chunks, spec-verify); gathered dense
            # math only for CPU-odd shapes
            from .kv_cache import paged_attention_update

            offset = cache[2]
            new_cache, attn = paged_attention_update(cache, q, k, v, offset)
            x = self._proj_out(x, attn.reshape([B, S, -1]))
            x = self._mlp(x)
            return x, new_cache
        elif cache is not None and len(cache) in (3, 5):
            # static head-major (k_buf, v_buf, pos) layout for the compiled
            # generate loop; the 5-tuple adds (k_scale, v_scale) for the int8
            # cache (kv_cache._quantize_kv) — the decode-attention kernel
            # dequantizes in VMEM and masks by the carried valid length
            from ..tensor.tensor import apply_op

            from ..ops.decode_attention import decode_attention
            from .kv_cache import update_plain_cache, update_quant_cache

            offset = cache[2]
            if len(cache) == 5:
                new_cache, k_q, v_q, k_sc, v_sc = update_quant_cache(
                    cache, k, v, offset, x.dtype)
                attn = apply_op(
                    lambda qq, kk, vv, ks, vs: decode_attention(
                        qq, kk, vv, offset, ks, vs),
                    (q, k_q, v_q, k_sc, v_sc), name="decode_attention")
            else:
                new_cache, k_b, v_b = update_plain_cache(cache, k, v, offset)
                attn = apply_op(
                    lambda qq, kk, vv: decode_attention(qq, kk, vv, offset),
                    (q, k_b, v_b), name="decode_attention")
            x = self._proj_out(x, attn.reshape([B, S, -1]))
            x = self._mlp(x)
            return x, new_cache
        elif cache is not None:
            from ..tensor import manipulation as M

            k = M.concat([cache[0], k], axis=1)
            v = M.concat([cache[1], v], axis=1)
            new_cache = (k, v)
            # queries are the last S positions of the concatenated sequence
            import jax.numpy as jnp

            from ..tensor.tensor import Tensor

            L = k.shape[1]
            jpos = jnp.arange(L)[None, :]
            qpos = jnp.arange(S)[:, None] + (L - S)
            attn_mask = Tensor(jnp.where(jpos <= qpos, 0.0, -1e9)[None, None])
        else:
            new_cache = (k, v) if use_cache else None
        attn = F.scaled_dot_product_attention(
            q, k, v, is_causal=attn_mask is None, attn_mask=attn_mask,
            dropout_p=self.attn_drop if self.training else 0.0,
        )
        x = self._proj_out(x, attn.reshape([B, S, -1]))
        x = self._mlp(x)
        if use_cache or cache is not None:
            return x, new_cache
        return x


class GPTModel(nn.Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.config = config
        Emb = VocabParallelEmbedding if config.tensor_parallel else nn.Embedding
        self.wte = Emb(config.vocab_size, config.hidden_size)
        self.wpe = nn.Embedding(config.max_position_embeddings, config.hidden_size)
        self.drop = nn.Dropout(config.hidden_dropout_prob)
        self.h = nn.LayerList([GPTBlock(config) for _ in range(config.num_hidden_layers)])
        self.ln_f = nn.LayerNorm(config.hidden_size, epsilon=config.layer_norm_eps)

    def forward(self, input_ids, caches=None, use_cache=False):
        S = input_ids.shape[1]
        use_cache = use_cache or caches is not None
        if use_cache and caches is None:
            caches = [None] * len(self.h)
        if caches is not None and caches[0] is not None \
                and len(caches[0]) in (3, 4, 5, 6):
            # static or paged cache: the live offset is at [2] in every
            # fixed-capacity layout; the legacy growing (k, v) pair falls to
            # the elif, where the past length IS the k buffer's axis-1 extent
            import jax.numpy as jnp

            from ..tensor.tensor import Tensor

            off = caches[0][2]
            if getattr(off, "ndim", 0) >= 1:
                off = off[:, None]  # per-slot offsets (continuous batching)
            pos = Tensor(jnp.arange(S, dtype=jnp.int32)[None, :] + off)
        elif caches is not None and caches[0] is not None:
            off = caches[0][0].shape[1]
            pos = creation.arange(off, off + S, dtype="int32").unsqueeze(0)
        else:
            pos = creation.arange(S, dtype="int32").unsqueeze(0)
        x = self.drop(self.wte(input_ids) + self.wpe(pos))
        new_caches = [] if use_cache else None
        for i, block in enumerate(self.h):
            if use_cache:
                x, c = block(x, cache=caches[i], use_cache=True)
                new_caches.append(c)
            else:
                x = block(x)
        x = self.ln_f(x)
        if use_cache:
            return x, new_caches
        return x


class GPTForCausalLM(nn.Layer):
    _supports_quant_cache = True  # GPTBlock understands the 5-tuple
    _supports_paged_cache = True  # ... and the paged 4/6-tuples

    def __init__(self, config: GPTConfig):
        super().__init__()
        self.config = config
        self.gpt = GPTModel(config)
        if config.tensor_parallel:
            self.lm_head = ColumnParallelLinear(config.hidden_size, config.vocab_size,
                                                has_bias=False, gather_output=True)
        else:
            self.lm_head = nn.Linear(config.hidden_size, config.vocab_size, bias_attr=False)

    def forward(self, input_ids, labels=None):
        logits = self.lm_head(self.gpt(input_ids))
        if labels is not None:
            loss = F.cross_entropy(
                logits.reshape([-1, self.config.vocab_size]),
                labels.reshape([-1]), ignore_index=-100,
            )
            return loss, logits
        return logits

    def generate_step(self, input_ids, caches=None):
        """Prefill (caches=None) or single-token decode step."""
        hidden, caches = self.gpt(input_ids, caches=caches, use_cache=True)
        return self.lm_head(hidden[:, -1:]), caches

    def verify_step(self, input_ids, caches):
        """Speculative-decoding verify: full-ladder logits [B, S, V] for
        S = K+1 tokens scored in one pass (see llama.py)."""
        hidden, caches = self.gpt(input_ids, caches=caches, use_cache=True)
        return self.lm_head(hidden), caches

    def prefill_step(self, input_ids, last_index):
        """Bucket-padded prefill for the serving engine (see llama.py)."""
        import jax

        from ..tensor.tensor import apply_op

        hidden, caches = self.gpt(input_ids, caches=None, use_cache=True)
        last = apply_op(
            lambda h: jax.lax.dynamic_slice_in_dim(h, last_index, 1, 1),
            (hidden,), name="prefill_last")
        return self.lm_head(last), caches

    def prefill_chunk_step(self, input_ids, caches, last_index):
        """One chunk of an incremental paged prefill (see llama.py)."""
        import jax

        from ..tensor.tensor import apply_op

        hidden, caches = self.gpt(input_ids, caches=caches, use_cache=True)
        last = apply_op(
            lambda h: jax.lax.dynamic_slice_in_dim(h, last_index, 1, 1),
            (hidden,), name="prefill_chunk_last")
        return self.lm_head(last), caches

    def generate(self, input_ids, max_new_tokens=32, do_sample=False,
                 temperature=1.0, top_k=0, top_p=1.0, eos_token_id=None,
                 pad_token_id=0, cache_dtype=None, kv_layout=None,
                 page_size=128, share_prefix=False, spec_k=0,
                 spec_drafter=None, adapter_id=None, adapters=None,
                 token_mask_fn=None):
        """Compiled decode loop on a static kv-cache (models/generation.py)."""
        from .generation import generate as _gen

        return _gen(self, input_ids, max_new_tokens, do_sample, temperature,
                    top_k, top_p, eos_token_id, pad_token_id,
                    cache_dtype=cache_dtype, kv_layout=kv_layout,
                    page_size=page_size, share_prefix=share_prefix,
                    spec_k=spec_k, spec_drafter=spec_drafter,
                    adapter_id=adapter_id, adapters=adapters,
                    token_mask_fn=token_mask_fn)
