"""Paged LoRA adapter pool: refcounted A/B weight pages + registry.

The PR-6 kv page-pool playbook applied to WEIGHTS instead of KV: adapter
low-rank factors live in per-projection device pools shaped
``[num_adapter_pages, D_in, r]`` (A) and ``[num_adapter_pages, r, D_out]``
(B), bf16, and every batch row addresses its adapter through an int32
page id — so a mixed-tenant batch is one gather away from its weights and
the compiled program never changes shape when the adapter mix does.

Page 0 is the reserved ZERO adapter (all-zero A/B, never written, never
evicted): ``adapter_id=None`` rows gather it and receive an exact ``+0``
delta, so base-model traffic co-batches with adapter traffic without a
masking branch.

:class:`AdapterRegistry` owns the host-side accounting the kv allocator
owns for pages: refcounts (a live request pins its adapter for its whole
lifetime — admission charges the pool, finish/expiry/preemption release
it), a free list, and LRU eviction of LOADED-BUT-UNREFERENCED adapters
when a cold adapter needs a page.  ``acquire`` returning ``None`` means
"every page is pinned right now" — the engine requeues the request
head-of-line exactly like kv-pool exhaustion.

Metric families (lazily registered here, linted via
``tools/metrics_lint.py import_instrumented``):
``llm_adapter_loads_total``, ``llm_adapter_evictions_total``,
``llm_adapter_pool_pages_in_use_count``,
``llm_adapter_pool_utilization_ratio``.
"""
from __future__ import annotations

import threading

import jax
import jax.numpy as jnp
import numpy as np

from ..observability import metrics as _obs
from ..observability import profiling as _profiling

__all__ = ["lora_sites", "LoraAdapter", "LoraPool", "AdapterRegistry",
           "build_solo_pool"]

_M_LOADS = _obs.counter(
    "llm_adapter_loads_total",
    "Adapter weight uploads into LoRA pool pages (cold loads + reloads)")
_M_EVICTIONS = _obs.counter(
    "llm_adapter_evictions_total",
    "Unreferenced LoRA adapters LRU-evicted to make room for a cold load")
_M_PAGES_IN_USE = _obs.gauge(
    "llm_adapter_pool_pages_in_use_count",
    "LoRA adapter pages pinned by live requests (refcount > 0)")
_M_POOL_UTIL = _obs.gauge(
    "llm_adapter_pool_utilization_ratio",
    "Pinned adapter pages / usable pool pages (page 0 excluded)")


def lora_sites(model):
    """Projection-site name -> ``(d_in, d_out)`` for a supported model.

    Site names match the ``ops.lora.apply_site`` hooks in the model
    forwards; the A pool for a site is ``[P, d_in, r]`` and the B pool is
    ``[P, r, d_out]``.
    """
    cfg = getattr(model, "config", None) or model  # model or bare config
    kind = type(model).__name__
    h = cfg.hidden_size
    inter = cfg.intermediate_size
    if "Llama" in kind:
        hd = h // cfg.num_attention_heads
        nq = cfg.num_attention_heads * hd
        nkv = cfg.num_key_value_heads * hd
        return {"q": (h, nq), "k": (h, nkv), "v": (h, nkv), "o": (nq, h),
                "gate": (h, inter), "up": (h, inter), "down": (inter, h)}
    if "GPT" in kind:
        return {"qkv": (h, 3 * h), "proj": (h, h),
                "fc_in": (h, inter), "fc_out": (inter, h)}
    raise ValueError(f"no LoRA site map for model type {kind!r}")


class LoraAdapter:
    """Host-side adapter weights: ``{site: (A [d_in, r], B [r, d_out])}``.

    ``scale`` (alpha / r in the usual parameterisation) is folded into B
    at construction so the serving path is a bare two-matmul epilogue.
    """

    def __init__(self, weights, rank=None, scale=1.0):
        self.weights = {}
        self.rank = 0
        for site, (a, b) in weights.items():
            a = np.asarray(a, np.float32)
            b = np.asarray(b, np.float32) * float(scale)
            if a.ndim != 2 or b.ndim != 2 or a.shape[1] != b.shape[0]:
                raise ValueError(
                    f"adapter site {site!r}: A {a.shape} / B {b.shape} are "
                    f"not a rank-factorised pair")
            self.weights[site] = (a, b)
            self.rank = max(self.rank, a.shape[1])
        if rank is not None and int(rank) != self.rank:
            raise ValueError(f"declared rank {rank} != factor rank {self.rank}")

    @staticmethod
    def random(sites, rank, seed, scale=0.05):
        """A deterministic random adapter for tests/benches.  Unlike the
        training init (B=0 so the delta starts as a no-op), BOTH factors
        are non-zero so the adapter visibly changes logits."""
        rng = np.random.default_rng(seed)
        w = {}
        for site, (din, dout) in sites.items():
            w[site] = (rng.standard_normal((din, rank)).astype(np.float32),
                       rng.standard_normal((rank, dout)).astype(np.float32))
        return LoraAdapter(w, rank=rank, scale=scale)

    def validate_against(self, sites, rank):
        if set(self.weights) != set(sites):
            raise ValueError(
                f"adapter sites {sorted(self.weights)} != pool sites "
                f"{sorted(sites)}")
        for site, (din, dout) in sites.items():
            a, b = self.weights[site]
            if a.shape[0] != din or b.shape[1] != dout:
                raise ValueError(
                    f"adapter site {site!r}: ({a.shape[0]}, {b.shape[1]}) "
                    f"does not match model ({din}, {dout})")
        if self.rank > rank:
            raise ValueError(f"adapter rank {self.rank} > pool rank {rank}")


class LoraPool:
    """Device-side paged A/B pools, one (A, B) pair per projection site.

    The pools are ordinary jax arrays passed as ARGUMENTS into the
    engine's compiled programs (the per-slot device-array knob mechanism),
    so loading/evicting adapters changes values, never program shapes.
    Page writes go through ONE jitted donating updater, pre-compiled by
    :meth:`warm` so post-warmup loads cannot show up as recompiles.
    """

    def __init__(self, sites, num_pages, rank, dtype=jnp.bfloat16):
        if num_pages < 2:
            raise ValueError("LoRA pool needs >= 2 pages (page 0 is the "
                             "reserved zero adapter)")
        self.sites = dict(sites)
        self.site_names = sorted(self.sites)
        self.num_pages = int(num_pages)
        self.rank = int(rank)
        self.dtype = dtype
        self._tree = tuple(
            (jnp.zeros((self.num_pages, self.sites[s][0], self.rank), dtype),
             jnp.zeros((self.num_pages, self.rank, self.sites[s][1]), dtype))
            for s in self.site_names)
        self._write_jit = jax.jit(self._write_impl, donate_argnums=(0,))
        self._write_compiled = False

    @staticmethod
    def _write_impl(tree, idx, vals):
        return tuple((a.at[idx].set(av), b.at[idx].set(bv))
                     for (a, b), (av, bv) in zip(tree, vals))

    def tree(self):
        """The pools as a jit-friendly pytree (site-name sorted)."""
        return self._tree

    def site_pools(self, tree=None):
        """``{site: (a_pool, b_pool)}`` for ``ops.lora.activate`` — from
        ``tree`` when called inside a traced function (tracers), else from
        the live pool arrays."""
        t = self._tree if tree is None else tree
        return dict(zip(self.site_names, t))

    def _padded(self, adapter):
        vals = []
        for s in self.site_names:
            a, b = adapter.weights[s]
            r = a.shape[1]
            if r < self.rank:  # zero-padded ranks contribute exact zeros
                a = np.pad(a, ((0, 0), (0, self.rank - r)))
                b = np.pad(b, ((0, self.rank - r), (0, 0)))
            vals.append((jnp.asarray(a, self.dtype),
                         jnp.asarray(b, self.dtype)))
        return tuple(vals)

    def write(self, page, adapter):
        if not 0 < page < self.num_pages:
            raise IndexError(f"adapter page {page} outside usable pool")
        if not self._write_compiled:
            _profiling.record_compile("lora_write")
            self._write_compiled = True
        self._tree = self._write_jit(self._tree, page, self._padded(adapter))

    def warm(self):
        """Compile the page writer by rewriting page 0 with zeros (a
        value-level no-op that preserves the zero-adapter invariant), so a
        post-warmup adapter load is a cache hit, not a recompile."""
        if not self._write_compiled:
            _profiling.record_compile("lora_write")
            self._write_compiled = True
        # build the zero values exactly the way write() builds real ones —
        # host float32 numpy through jnp.asarray(., dtype) — so the tiny
        # per-shape convert programs XLA compiles for the host->device
        # dtype cast are also warmed (they'd otherwise land on
        # jit_recompiles_total at the first post-warmup adapter load)
        zeros = tuple(
            (jnp.asarray(np.zeros((self.sites[s][0], self.rank),
                                  np.float32), self.dtype),
             jnp.asarray(np.zeros((self.rank, self.sites[s][1]),
                                  np.float32), self.dtype))
            for s in self.site_names)
        self._tree = self._write_jit(self._tree, 0, zeros)


class AdapterRegistry:
    """Loads/pins adapters by id over a :class:`LoraPool`.

    Refcount contract (mirrors the engine's kv page allocator):
    ``acquire(id)`` at admission pins the adapter's page (loading it
    first if cold, LRU-evicting an unreferenced adapter if the free list
    is dry); ``release(id)`` at finish/expiry/preemption unpins it.  A
    released adapter STAYS loaded — warm for the next request — until a
    cold load needs its page.  ``acquire`` returns ``None`` when every
    page is pinned, and raises on unknown ids and on decref-below-zero
    (loud, like ``kv page decref below zero``).
    """

    def __init__(self, model, max_adapters=8, rank=8, dtype=jnp.bfloat16):
        self.sites = lora_sites(model)
        self.pool = LoraPool(self.sites, int(max_adapters) + 1, rank, dtype)
        self._adapters = {}   # id -> LoraAdapter (host weights)
        self._page_of = {}    # id -> loaded page
        self._ref = {}        # id -> live-request refcount (loaded ids only)
        self._free = list(range(1, self.pool.num_pages))
        self._stamp = 0       # LRU clock for unreferenced loaded adapters
        self._mru = {}        # id -> last acquire/release stamp
        self._lock = threading.RLock()
        self.loads = 0
        self.evictions = 0

    @staticmethod
    def from_adapters(model, adapters, rank=None, dtype=jnp.bfloat16):
        """Registry sized to hold every adapter in ``adapters`` resident."""
        r = rank or max((a.rank for a in adapters.values()), default=8)
        reg = AdapterRegistry(model, max_adapters=max(1, len(adapters)),
                              rank=r, dtype=dtype)
        for aid, ad in adapters.items():
            reg.register(aid, ad)
        return reg

    def register(self, adapter_id, adapter):
        if adapter_id is None:
            raise ValueError("adapter_id None is the reserved zero adapter")
        adapter.validate_against(self.sites, self.pool.rank)
        with self._lock:
            self._adapters[adapter_id] = adapter

    def ids(self):
        with self._lock:
            return sorted(self._adapters)

    # ------------------------------------------------------------ paging
    def acquire(self, adapter_id):
        """Pin ``adapter_id`` and return its page (0 for ``None``), or
        ``None`` when the pool is exhausted by pinned adapters."""
        if adapter_id is None:
            return 0
        with self._lock:
            if adapter_id not in self._adapters:
                raise KeyError(f"unknown adapter id {adapter_id!r}")
            page = self._page_of.get(adapter_id)
            if page is None:
                page = self._take_page()
                if page is None:
                    return None
                self.pool.write(page, self._adapters[adapter_id])
                self._page_of[adapter_id] = page
                self._ref[adapter_id] = 0
                self.loads += 1
                _M_LOADS.inc()
            self._ref[adapter_id] += 1
            self._stamp += 1
            self._mru[adapter_id] = self._stamp
            self._update_gauges()
            return page

    def release(self, adapter_id):
        if adapter_id is None:
            return
        with self._lock:
            ref = self._ref.get(adapter_id)
            assert ref is not None and ref > 0, \
                f"adapter {adapter_id!r} release below zero"
            self._ref[adapter_id] = ref - 1
            self._stamp += 1
            self._mru[adapter_id] = self._stamp
            self._update_gauges()

    def page_for(self, adapter_id):
        """Loaded page for ``adapter_id`` (no pin), ``None`` when cold."""
        if adapter_id is None:
            return 0
        with self._lock:
            return self._page_of.get(adapter_id)

    def _take_page(self):
        if self._free:
            return self._free.pop()
        victim = None
        for aid, ref in self._ref.items():
            if ref == 0 and (victim is None
                             or self._mru.get(aid, 0) < self._mru.get(victim, 0)):
                victim = aid
        if victim is None:
            return None  # every loaded adapter is pinned
        page = self._page_of.pop(victim)
        del self._ref[victim]
        self._mru.pop(victim, None)
        self.evictions += 1
        _M_EVICTIONS.inc()
        return page

    # ------------------------------------------------------- introspection
    def pinned_pages(self):
        with self._lock:
            return sum(1 for r in self._ref.values() if r > 0)

    def _update_gauges(self):
        usable = self.pool.num_pages - 1
        pinned = sum(1 for r in self._ref.values() if r > 0)
        _M_PAGES_IN_USE.set(pinned)
        _M_POOL_UTIL.set(pinned / usable if usable else 0.0)

    def stats(self):
        with self._lock:
            return {
                "pages_total": self.pool.num_pages - 1,
                "pages_loaded": len(self._page_of),
                "pages_pinned": sum(1 for r in self._ref.values() if r > 0),
                "registered": len(self._adapters),
                "loads": self.loads,
                "evictions": self.evictions,
                "rank": self.pool.rank,
            }

    def warm(self):
        self.pool.warm()


def build_solo_pool(model, adapter, dtype=jnp.bfloat16):
    """A minimal 2-page pool (zero page + ``adapter`` on page 1) for the
    solo ``generate(adapter_id=...)`` parity path when the caller passes
    bare adapter weights instead of a shared registry.  Uses the
    adapter's own rank; the extra zero-padded rank columns a larger
    registry pool would carry contribute exact zeros, so tokens match."""
    sites = lora_sites(model)
    adapter.validate_against(sites, adapter.rank)
    pool = LoraPool(sites, 2, adapter.rank, dtype)
    pool.write(1, adapter)
    return pool
