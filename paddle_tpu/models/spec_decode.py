"""Draft-token sources for speculative decoding.

Two drafters behind one interface — ``propose(context, k) -> np.int32[k]``:

- :class:`NGramDrafter` — prompt-lookup / n-gram drafting: find the longest
  suffix n-gram of the context that occurred EARLIER in the context and
  propose the tokens that followed it.  Host-side, zero extra weights,
  deterministic — the CPU-testable default.  Great on repetitive /
  extractive workloads (code, summarization, retrieval), harmless
  elsewhere: the verify pass emits at least one true token per call no
  matter how bad the drafts are.
- :class:`DraftModelDrafter` — a small causal LM sharing the target's
  tokenizer, rolled out greedily over a bucketed context window (fixed
  window lengths bound the compile count; the window truncation shifts
  absolute positions, which is fine — drafts are PROPOSALS, the verify
  pass against the full context is what guarantees correctness).

Drafting is a host-side concern by design: the draft source feeds token
ids into the compiled verify program but never participates in it, so
swapping drafters never recompiles the serving step.
"""
from __future__ import annotations

import numpy as np

__all__ = ["NGramDrafter", "DraftModelDrafter", "get_drafter"]


class NGramDrafter:
    """Prompt-lookup drafting (the "assisted generation" n-gram trick).

    For each n from ``max_ngram`` down to ``min_ngram``, the context's
    last n tokens are searched for their most RECENT earlier occurrence;
    on a hit, the k tokens that followed that occurrence become the
    drafts.  No match (or a short continuation) pads by repeating the
    final draft/context token — deterministic filler the verifier simply
    rejects when wrong.
    """

    name = "ngram"

    def __init__(self, max_ngram=3, min_ngram=1):
        if not (1 <= int(min_ngram) <= int(max_ngram)):
            raise ValueError(
                f"need 1 <= min_ngram <= max_ngram, got "
                f"({min_ngram}, {max_ngram})")
        self.max_ngram = int(max_ngram)
        self.min_ngram = int(min_ngram)

    def propose(self, context, k):
        ctx = np.asarray(context, np.int32).reshape(-1)
        k = int(k)
        n_ctx = ctx.size
        drafts = None
        for n in range(min(self.max_ngram, n_ctx - 1), self.min_ngram - 1, -1):
            suffix = ctx[n_ctx - n:]
            # most recent earlier occurrence of the suffix n-gram
            windows = np.lib.stride_tricks.sliding_window_view(
                ctx[:n_ctx - 1], n)
            hits = np.flatnonzero((windows == suffix).all(axis=1))
            if hits.size:
                start = int(hits[-1]) + n
                drafts = ctx[start:start + k]
                break
        if drafts is None:
            drafts = ctx[n_ctx - 1:]  # repeat-last-token filler
        out = np.empty(k, np.int32)
        m = min(k, drafts.size)
        out[:m] = drafts[:m]
        if m < k:
            out[m:] = out[m - 1] if m else int(ctx[-1])
        return out


class DraftModelDrafter:
    """Small-model drafting: greedy rollout of a draft LM sharing the
    target's tokenizer.  The context is truncated to the largest bucket
    length that fits (one compiled rollout per bucket — the same
    bounded-compile-zoo discipline as the serving prefill buckets)."""

    name = "draft_model"

    def __init__(self, model, buckets=(8, 16, 32, 64)):
        self.model = model
        self.buckets = tuple(sorted(int(b) for b in buckets))
        if not self.buckets or self.buckets[0] < 1:
            raise ValueError(f"buckets must be positive, got {buckets!r}")

    def propose(self, context, k):
        ctx = np.asarray(context, np.int32).reshape(-1)
        k = int(k)
        win = self.buckets[0]
        for b in self.buckets:
            if b <= ctx.size:
                win = b
        ctx = ctx[-win:]
        if ctx.size < win:  # prompt shorter than the smallest bucket:
            ctx = np.pad(ctx, (win - ctx.size, 0), mode="edge")  # left-fill
        out = self.model.generate(ctx[None, :], max_new_tokens=k,
                                  do_sample=False)
        return np.asarray(out._value if hasattr(out, "_value") else out,
                          np.int32).reshape(-1)[:k]


def get_drafter(spec):
    """Resolve a drafter spec: ``"ngram"`` (default config), a drafter
    instance (anything with ``propose``), or a model object (wrapped in
    :class:`DraftModelDrafter`)."""
    if spec is None or spec == "ngram":
        return NGramDrafter()
    if hasattr(spec, "propose"):
        return spec
    if hasattr(spec, "generate"):
        return DraftModelDrafter(spec)
    raise ValueError(
        f"spec_draft must be 'ngram', a drafter with .propose, or a model "
        f"with .generate; got {spec!r}")
