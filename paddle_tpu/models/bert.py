"""BERT / ERNIE encoder family (BASELINE config #4: ERNIE-3.0 / BERT-base pretrain).

Reference gap: PaddleNLP models live outside the snapshot; structure follows the
standard BERT encoder with paddle-style MLM+NSP pretraining heads.  ERNIE shares the
architecture (its contribution is the masking strategy, a data-pipeline concern) —
ErnieModel aliases the encoder with task-type embeddings added.
"""
from __future__ import annotations

from dataclasses import dataclass

import jax.numpy as jnp

from .. import nn
from ..nn import functional as F
from ..tensor.tensor import Tensor
from ..tensor import manipulation as M
from ..tensor import creation
from ..distributed.meta_parallel.mp_layers import (
    ColumnParallelLinear, RowParallelLinear, VocabParallelEmbedding,
)


@dataclass
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    hidden_act: str = "gelu"
    hidden_dropout_prob: float = 0.1
    attention_probs_dropout_prob: float = 0.1
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    layer_norm_eps: float = 1e-12
    tensor_parallel: bool = False
    use_task_id: bool = False  # ERNIE task-type embedding

    @staticmethod
    def base(**kw):
        return BertConfig(**kw)

    @staticmethod
    def tiny(**kw):
        base = dict(vocab_size=1024, hidden_size=128, num_hidden_layers=2,
                    num_attention_heads=4, intermediate_size=512,
                    max_position_embeddings=128)
        base.update(kw)
        return BertConfig(**base)


ErnieConfig = BertConfig


class BertEmbeddings(nn.Layer):
    def __init__(self, config: BertConfig):
        super().__init__()
        Emb = VocabParallelEmbedding if config.tensor_parallel else nn.Embedding
        self.word_embeddings = Emb(config.vocab_size, config.hidden_size)
        self.position_embeddings = nn.Embedding(config.max_position_embeddings, config.hidden_size)
        self.token_type_embeddings = nn.Embedding(config.type_vocab_size, config.hidden_size)
        if config.use_task_id:
            self.task_type_embeddings = nn.Embedding(16, config.hidden_size)
        self.layer_norm = nn.LayerNorm(config.hidden_size, epsilon=config.layer_norm_eps)
        self.dropout = nn.Dropout(config.hidden_dropout_prob)
        self._use_task_id = config.use_task_id

    def forward(self, input_ids, token_type_ids=None, position_ids=None, task_type_ids=None):
        S = input_ids.shape[1]
        if position_ids is None:
            position_ids = creation.arange(S, dtype="int32").unsqueeze(0)
        if token_type_ids is None:
            token_type_ids = creation.zeros(list(input_ids.shape), "int32")
        emb = (self.word_embeddings(input_ids)
               + self.position_embeddings(position_ids)
               + self.token_type_embeddings(token_type_ids))
        if self._use_task_id and task_type_ids is not None:
            emb = emb + self.task_type_embeddings(task_type_ids)
        return self.dropout(self.layer_norm(emb))


class BertSelfAttention(nn.Layer):
    def __init__(self, config: BertConfig):
        super().__init__()
        self.num_heads = config.num_attention_heads
        self.head_dim = config.hidden_size // config.num_attention_heads
        h = config.hidden_size
        tp = config.tensor_parallel
        if tp:
            self.qkv = ColumnParallelLinear(h, 3 * h, gather_output=False)
            self.out = RowParallelLinear(h, h, input_is_parallel=True)
        else:
            self.qkv = nn.Linear(h, 3 * h)
            self.out = nn.Linear(h, h)
        self.attn_drop = config.attention_probs_dropout_prob

    def forward(self, x, mask=None):
        B, S = x.shape[0], x.shape[1]
        qkv = self.qkv(x).reshape([B, S, 3, self.num_heads, self.head_dim])
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        out = F.scaled_dot_product_attention(q, k, v, attn_mask=mask,
                                             dropout_p=self.attn_drop if self.training else 0.0)
        return self.out(out.reshape([B, S, self.num_heads * self.head_dim]))


class BertLayer(nn.Layer):
    def __init__(self, config: BertConfig):
        super().__init__()
        h = config.hidden_size
        tp = config.tensor_parallel
        self.attention = BertSelfAttention(config)
        self.attn_norm = nn.LayerNorm(h, epsilon=config.layer_norm_eps)
        if tp:
            self.ffn_in = ColumnParallelLinear(h, config.intermediate_size, gather_output=False)
            self.ffn_out = RowParallelLinear(config.intermediate_size, h, input_is_parallel=True)
        else:
            self.ffn_in = nn.Linear(h, config.intermediate_size)
            self.ffn_out = nn.Linear(config.intermediate_size, h)
        self.ffn_norm = nn.LayerNorm(h, epsilon=config.layer_norm_eps)
        self.dropout = nn.Dropout(config.hidden_dropout_prob)
        self.act = getattr(F, config.hidden_act)

    def forward(self, x, mask=None):
        # dropout + residual + LN fused into one kernel on TPU (ref
        # fused_dropout_helper.h epilogue; F.fused_dropout_add_layer_norm).
        # honours the Dropout sublayer's OWN flags (a user may call
        # layer.dropout.eval() or configure downscale mode); the fused path
        # assumes upscale_in_train, so other modes take the composed ops
        drop = self.dropout
        if drop.mode != "upscale_in_train":
            x = self.attn_norm(x + drop(self.attention(x, mask)))
            x = self.ffn_norm(x + drop(self.ffn_out(self.act(self.ffn_in(x)))))
            return x
        x = F.fused_dropout_add_layer_norm(
            self.attention(x, mask), x, self.attn_norm.weight,
            self.attn_norm.bias, drop.p, self.attn_norm._epsilon, drop.training)
        x = F.fused_dropout_add_layer_norm(
            self.ffn_out(self.act(self.ffn_in(x))), x, self.ffn_norm.weight,
            self.ffn_norm.bias, drop.p, self.ffn_norm._epsilon, drop.training)
        return x


class BertModel(nn.Layer):
    def __init__(self, config: BertConfig):
        super().__init__()
        self.config = config
        self.embeddings = BertEmbeddings(config)
        self.encoder = nn.LayerList([BertLayer(config) for _ in range(config.num_hidden_layers)])
        self.pooler = nn.Linear(config.hidden_size, config.hidden_size)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None, task_type_ids=None):
        if attention_mask is not None and attention_mask.ndim == 2:
            # [B,S] 1/0 mask -> additive [B,1,1,S]
            m = (1.0 - attention_mask.astype("float32")) * -1e9
            attention_mask = m.unsqueeze(1).unsqueeze(1)
        x = self.embeddings(input_ids, token_type_ids, task_type_ids=task_type_ids)
        for layer in self.encoder:
            x = layer(x, attention_mask)
        pooled = F.tanh(self.pooler(x[:, 0]))
        return x, pooled


ErnieModel = BertModel


class BertPretrainingHeads(nn.Layer):
    """MLM transform + decoder and NSP head.  When `embedding_weights` (the
    [vocab, hidden] word-embedding Parameter) is given, the MLM decoder is TIED to
    it — logits = x @ W_emb^T + b — matching the reference pretraining setup."""

    def __init__(self, config: BertConfig, embedding_weights=None):
        super().__init__()
        self.transform = nn.Linear(config.hidden_size, config.hidden_size)
        self.act = getattr(F, config.hidden_act)
        self.norm = nn.LayerNorm(config.hidden_size, epsilon=config.layer_norm_eps)
        if embedding_weights is not None:
            # bypass Layer.__setattr__: the Parameter must stay registered ONLY under
            # the embedding's name or the functional path would train two copies
            object.__setattr__(self, "_tied_weight", embedding_weights)
            self.decoder_bias = self.create_parameter(
                [config.vocab_size], is_bias=True,
                default_initializer=nn.initializer.Constant(0.0))
            self.decoder = None
        else:
            object.__setattr__(self, "_tied_weight", None)
            self.decoder = nn.Linear(config.hidden_size, config.vocab_size)
        self.seq_relationship = nn.Linear(config.hidden_size, 2)

    def forward(self, sequence_output, pooled_output, masked_positions=None):
        if masked_positions is not None:
            # reference pretrain recipe (create_pretraining_data's
            # masked_lm_positions, max_predictions_per_seq ~ 0.15*seq): gather
            # the masked rows BEFORE the transform/decoder so the [*, vocab]
            # logits matmul runs over B*P rows, not B*S — at 15% masking this
            # drops the MLM-head FLOPs and logits traffic ~6.7x.
            # masked_positions: [B, P] PER-SEQUENCE indices (offsets added
            # here), or flat [B*P] indices that must ALREADY be globally
            # offset into the flattened [B*S] rows (the reference pipeline's
            # pre-offset masked_lm_positions form).
            B, S = sequence_output.shape[0], sequence_output.shape[1]
            h = sequence_output.shape[-1]
            flat = sequence_output.reshape([B * S, h])
            pos = masked_positions
            if pos.ndim == 2:
                offs = creation.arange(B, dtype="int64").unsqueeze(1) * S
                pos = (pos.astype("int64") + offs).reshape([-1])
            sequence_output = M.gather(flat, pos)
        x = self.norm(self.act(self.transform(sequence_output)))
        if self._tied_weight is not None:
            from ..tensor import linalg as L

            mlm = L.matmul(x, self._tied_weight, transpose_y=True) + self.decoder_bias
        else:
            mlm = self.decoder(x)
        return mlm, self.seq_relationship(pooled_output)


class BertForPretraining(nn.Layer):
    """MLM + NSP pretraining (the config #4 objective)."""

    def __init__(self, config: BertConfig):
        super().__init__()
        self.config = config
        self.bert = BertModel(config)
        self.cls = BertPretrainingHeads(
            config, embedding_weights=self.bert.embeddings.word_embeddings.weight)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None,
                masked_lm_labels=None, next_sentence_label=None,
                masked_positions=None):
        """With `masked_positions` [B, P], `masked_lm_labels` must be the
        gathered [B, P] (or flat) labels for those positions (-100 padding
        ignored) — the reference's masked_lm_positions/masked_lm_ids pair."""
        seq, pooled = self.bert(input_ids, token_type_ids, attention_mask)
        mlm_logits, nsp_logits = self.cls(seq, pooled, masked_positions)
        if masked_lm_labels is not None:
            mlm_loss = F.cross_entropy(
                mlm_logits.reshape([-1, self.config.vocab_size]),
                masked_lm_labels.reshape([-1]),
                ignore_index=-100,
            )
            loss = mlm_loss
            if next_sentence_label is not None:
                loss = loss + F.cross_entropy(nsp_logits, next_sentence_label.reshape([-1]))
            return loss, mlm_logits
        return mlm_logits, nsp_logits


class ErnieForPretraining(BertForPretraining):
    def __init__(self, config: BertConfig):
        import dataclasses

        super().__init__(dataclasses.replace(config, use_task_id=True))
