"""NLP model zoo (PaddleNLP-equivalent families needed by BASELINE configs #4/#5)."""
from .llama import LlamaConfig, LlamaForCausalLM, LlamaModel  # noqa: F401
from .bert import BertConfig, BertModel, BertForPretraining, ErnieConfig, ErnieModel, ErnieForPretraining  # noqa: F401
from .gpt import GPTConfig, GPTModel, GPTForCausalLM  # noqa: F401
from .generation import generate  # noqa: F401
from .lora import AdapterRegistry, LoraAdapter, lora_sites  # noqa: F401
