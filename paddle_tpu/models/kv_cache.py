"""Shared static + paged kv-cache layouts for the compiled decode loops.

Four layouts, distinguished by tuple length (see generation.generate and
inference/llm_server.py):
  (k_buf, v_buf, pos)                      — plain static, cache dtype = kv dtype
  (k_pages, v_pages, pos, page_tbl)        — PAGED plain: global page pool
                                             [P, H, page_size, D] + per-slot
                                             page tables [B, max_pages]
  (k_q, v_q, pos, k_scale, v_scale)        — int8 static + per-(head, token)
                                             absmax scales: HALF the HBM
                                             footprint AND half the decode
                                             stream when the Pallas decode
                                             kernel runs
                                             (ops/decode_attention.py
                                             dequantizes in VMEM)
  (k_pages, v_pages, pos, page_tbl,
   k_scale_pages, v_scale_pages)           — PAGED int8: scale pools are
                                             [P, H, page_size] f32

Paged layout contract (the vLLM/Ragged-Paged-Attention design, TPU-native):
  - page 0 is the TRASH page: never allocated to a slot; unused page-table
    entries point at it, so masked/padded scatters land there instead of in
    another slot's memory, and reads never see it (valid-length masking).
  - a token at absolute position t of slot b lives in page
    page_tbl[b, t // page_size] at row t % page_size; distinct live slots
    never share a page, so the vectorized scatter has no write collisions
    outside the trash page.
  - capacity is bounded by ACTUAL sequence lengths rounded up to a page,
    not by max_seq_len — the whole point: admission is by free pages.
  - SHARING (prefix cache, inference/prefix_cache.py): a page may appear in
    several slots' tables at once — requests with a common prompt prefix
    map the same physical pages and the host allocator refcounts them.
    Shared FULL pages are read-only by construction (every write lands at
    a position past the prompt); a shared partially-filled TAIL page is
    forked copy-on-write (``cow_copy_pages``) the moment a slot must write
    its continuation rows into it, so readers keep the frozen original.
    None of this reaches the kernel: it still just walks page tables.

Buffers are HEAD-MAJOR [B, H, L, D] (scales [B, H, L]): each (batch, head)
streams contiguous [L, D] keys/values — the layout the decode kernel and the
flash prefill kernel both want, with no per-step relayout.  New k/v arrive
from the projections as [B, S, H, D] and are transposed (cheap: S is 1 in
the decode loop) before the scatter at axis 2.

Both LlamaAttention and GPTBlock call the helpers here so the layout and
quantization contracts live in one place.

RECOMMENDATION (measured on v5e, 738M model, b8/p1024, r4): with the Pallas
decode kernel the int8 cache is now FASTER than bf16 at small batch (3.51
vs 3.96 ms/token — it streams half the kv bytes and dequantizes in VMEM)
and doubles the max decode batch/context at fixed HBM
(kv_int8_max_batch_gain ~1.9 in BENCH_r04: 114 -> 214 max batch at 1152
context).  Default to cache_dtype="int8" for serving whenever the model
tolerates the ~absmax/254 per-element roundtrip error (logit drift <5% on
the parity test); keep bf16 for exact-parity evaluation runs.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..tensor.tensor import apply_op


def _quantize_kv(kv):
    """Per-(head, token) absmax int8 quantization of a HEAD-MAJOR
    [B, H, S, D] slice: returns (int8 values, f32 scale [B, H, S])."""
    f = kv.astype(jnp.float32)
    scale = jnp.max(jnp.abs(f), axis=-1) / 127.0
    scale = jnp.maximum(scale, 1e-8)
    q = jnp.clip(jnp.round(f / scale[..., None]), -127, 127).astype(jnp.int8)
    return q, scale.astype(jnp.float32)


def _to_head_major(kv):
    """[B, S, H, D] (projection layout) -> [B, H, S, D] (cache layout)."""
    return jnp.transpose(kv, (0, 2, 1, 3))


def _scatter(buf, kv, offset):
    """Write head-major new kv into the buffer at `offset` — a scalar (all
    slots aligned: the generate() loop) or a per-slot [B] vector
    (continuous batching decode S == 1; speculative verify S == K+1, where
    token t of slot b lands at row offset[b] + t and rows past the
    buffer's extent are dropped by the scatter's out-of-bounds rule)."""
    hm = kv
    if getattr(offset, "ndim", 0) >= 1:
        B, H = buf.shape[0], buf.shape[1]
        S = hm.shape[2]
        bi = jnp.arange(B)[:, None, None]
        hi = jnp.arange(H)[None, :, None]
        ti = offset[:, None, None] + jnp.arange(S, dtype=jnp.int32)[None, None, :]
        return buf.at[bi, hi, ti].set(hm)
    return jax.lax.dynamic_update_slice_in_dim(buf, hm, offset, 2)


def update_plain_cache(cache, k, v, offset):
    """Scatter new k/v [B, S, H, D] into the head-major (k_buf, v_buf, pos)
    layout.  Returns (new_cache, k_full, v_full) with the full buffers in
    head-major [B, H, L, D]."""
    S = k.shape[1]
    upd = lambda buf, kv: _scatter(  # noqa: E731
        buf, _to_head_major(kv.astype(buf.dtype)), offset)
    k_buf = apply_op(upd, (cache[0], k), name="kv_scatter")
    v_buf = apply_op(upd, (cache[1], v), name="kv_scatter")
    return (k_buf, v_buf, offset + S), k_buf, v_buf


def update_quant_cache(cache, k, v, offset, out_dtype):
    """Quantize + scatter new k/v [B, S, H, D] into the head-major 5-tuple
    int8 layout.  Returns (new_cache, k_q, v_q, k_scale, v_scale) — the
    int8 buffers and scales go STRAIGHT to the decode kernel, which
    dequantizes in VMEM (no bf16 cache materialization in HBM)."""
    S = k.shape[1]

    def upd_q(buf, sbuf, kv):
        kv_q, scale = _quantize_kv(_to_head_major(kv))
        if getattr(offset, "ndim", 0) >= 1:
            B, H = buf.shape[0], buf.shape[1]
            Sq = kv_q.shape[2]
            bi = jnp.arange(B)[:, None, None]
            hi = jnp.arange(H)[None, :, None]
            ti = offset[:, None, None] \
                + jnp.arange(Sq, dtype=jnp.int32)[None, None, :]
            return (buf.at[bi, hi, ti].set(kv_q),
                    sbuf.at[bi, hi, ti].set(scale))
        return (jax.lax.dynamic_update_slice_in_dim(buf, kv_q, offset, 2),
                jax.lax.dynamic_update_slice_in_dim(sbuf, scale, offset, 2))

    k_buf, k_sc = apply_op(upd_q, (cache[0], cache[3], k), name="kv_scatter_q")
    v_buf, v_sc = apply_op(upd_q, (cache[1], cache[4], v), name="kv_scatter_q")
    return (k_buf, v_buf, offset + S, k_sc, v_sc), k_buf, v_buf, k_sc, v_sc


# ------------------------------------------------------------------- paged

TRASH_PAGE = 0  # reserved pool slot: padding/garbage writes land here


def pages_for(n_tokens, page_size):
    """Pages needed to hold n_tokens (host-side allocator arithmetic)."""
    return -(-int(n_tokens) // int(page_size))


def cow_copy_pages(caches, src, dst):
    """Copy page ``src``'s rows into page ``dst`` across every layer's
    pools — the device side of a COPY-ON-WRITE fork.  ``caches`` is the
    engine's per-layer list of pool tuples (k/v pools, plus scale pools in
    the int8 layout — every element is ``[P, ...]`` page-major, so one
    generic row copy covers both layouts).  The caller then repoints the
    writing slot's page-table entry at ``dst``; readers of ``src`` are
    untouched."""
    return [tuple(x.at[dst].set(x[src]) for x in c) for c in caches]


def gather_pages_to_host(caches, pages):
    """Gather the rows of page ids ``pages`` ([N] int32) across every
    layer's pools in ONE batched program — the device half of a DEMOTION
    (hierarchical kv: HBM -> host RAM).  ``caches`` is the engine's
    per-layer list of pool tuples (k/v pools plus scale pools in the int8
    layout; every element is ``[P, ...]`` page-major, the same contract as
    :func:`cow_copy_pages`), so one generic row gather covers both
    layouts.  Returns per-layer tuples of ``[N, ...]`` blocks; the caller
    fetches them host-side (``np.asarray``) OUTSIDE any engine lock —
    dispatch is async, the transfer is the blocking part."""
    return [tuple(x[pages] for x in c) for c in caches]


def upload_host_pages(caches, pages, blocks):
    """Scatter host-staged page blocks back into the pools in ONE batched
    program — the device half of a PROMOTION (host RAM -> HBM), the dual
    of :func:`gather_pages_to_host`.  ``blocks`` mirrors the gather's
    output: per-layer tuples of ``[N, ...]`` rows, scattered to page ids
    ``pages`` ([N] int32).  Padding entries may target ``TRASH_PAGE``
    (garbage rows land in the reserved page, never in live memory).  The
    caller typically donates ``caches`` — after the upload the promoted
    pages are indistinguishable from never-evicted ones (the ragged paged
    kernel just walks page tables)."""
    return [tuple(x.at[pages].set(b) for x, b in zip(c, blk))
            for c, blk in zip(caches, blocks)]


def _token_pages_rows(pos, page_tbl, S, page_size, max_pages):
    """Per-token (page id, row) for S new tokens starting at `pos` (scalar
    or [B]).  Positions past the table's coverage (a padded prefill tail
    overflowing max_pages * page_size) route to TRASH_PAGE explicitly — a
    clip to the last entry would alias a fully-populated table's REAL last
    page and clobber live rows.  Within coverage, unallocated entries
    already point at TRASH_PAGE by the engine's convention."""
    B = page_tbl.shape[0]
    pos = jnp.broadcast_to(jnp.asarray(pos, jnp.int32), (B,))
    tpos = pos[:, None] + jnp.arange(S, dtype=jnp.int32)[None, :]  # [B, S]
    in_table = tpos < max_pages * page_size
    pidx = jnp.clip(tpos // page_size, 0, max_pages - 1)
    page = jnp.take_along_axis(page_tbl, pidx, axis=1)             # [B, S]
    page = jnp.where(in_table, page, TRASH_PAGE)
    return page, tpos % page_size


def _paged_scatter(pool, hm, pos, page_tbl):
    """Write head-major new kv [B, H, S, D] into the page pool
    [P, H, page_size, D] at absolute positions pos..pos+S-1 of each slot,
    routed through that slot's page-table row."""
    H, ps = pool.shape[1], pool.shape[2]
    S = hm.shape[2]
    page, row = _token_pages_rows(pos, page_tbl, S, ps, page_tbl.shape[1])
    hi = jnp.arange(H)[None, None, :]
    vals = jnp.transpose(hm, (0, 2, 1, 3))  # [B, S, H, D]
    return pool.at[page[..., None], hi, row[..., None]].set(vals)


def _paged_scatter_scale(spool, scale, pos, page_tbl):
    """Same routing for the f32 scale pool [P, H, page_size]; scale arrives
    head-major [B, H, S]."""
    H, ps = spool.shape[1], spool.shape[2]
    S = scale.shape[2]
    page, row = _token_pages_rows(pos, page_tbl, S, ps, page_tbl.shape[1])
    hi = jnp.arange(H)[None, None, :]
    vals = jnp.transpose(scale, (0, 2, 1))  # [B, S, H]
    return spool.at[page[..., None], hi, row[..., None]].set(vals)


def update_paged_cache(cache, k, v, offset):
    """Scatter new k/v [B, S, H, D] into the paged 4-tuple layout.  Returns
    (new_cache, k_pages, v_pages) — the pools plus the (unchanged) page
    table go straight to paged_decode_attention."""
    S = k.shape[1]
    upd = lambda pool, kv, tbl: _paged_scatter(  # noqa: E731
        pool, _to_head_major(kv.astype(pool.dtype)), offset, tbl)
    k_pool = apply_op(upd, (cache[0], k, cache[3]), name="kv_paged_scatter")
    v_pool = apply_op(upd, (cache[1], v, cache[3]), name="kv_paged_scatter")
    return (k_pool, v_pool, offset + S, cache[3]), k_pool, v_pool


def update_paged_quant_cache(cache, k, v, offset):
    """Quantize + scatter new k/v [B, S, H, D] into the paged int8 6-tuple.
    Returns (new_cache, k_pages, v_pages, k_scale_pages, v_scale_pages)."""
    S = k.shape[1]

    def upd_q(pool, spool, kv, tbl):
        kv_q, scale = _quantize_kv(_to_head_major(kv))
        return (_paged_scatter(pool, kv_q, offset, tbl),
                _paged_scatter_scale(spool, scale, offset, tbl))

    k_pool, k_sc = apply_op(upd_q, (cache[0], cache[4], k, cache[3]),
                            name="kv_paged_scatter_q")
    v_pool, v_sc = apply_op(upd_q, (cache[1], cache[5], v, cache[3]),
                            name="kv_paged_scatter_q")
    return ((k_pool, v_pool, offset + S, cache[3], k_sc, v_sc),
            k_pool, v_pool, k_sc, v_sc)


def paged_attention_update(cache, q, k, v, offset):
    """Scatter new k/v [B, S, H, D] into the paged cache, then attend q
    through the page table (the ragged paged Pallas kernel for ANY S >= 1
    on tile-aligned shapes — decode, prefill chunks, the K+1 spec-verify
    ladder; gathered dense math only for CPU-odd shapes) — the ONE paged
    decode / chunked-prefill / verify hot path shared by every attention
    family that understands the paged 4/6-tuples.  Returns
    (new_cache, out [B, S, Hq, D])."""
    from ..ops.decode_attention import paged_decode_attention

    if len(cache) == 6:
        new_cache, k_q, v_q, k_sc, v_sc = update_paged_quant_cache(
            cache, k, v, offset)
        out = apply_op(
            lambda qq, kk, vv, pt, ks, vs: paged_decode_attention(
                qq, kk, vv, offset, pt, ks, vs),
            (q, k_q, v_q, cache[3], k_sc, v_sc),
            name="paged_decode_attention")
    else:
        new_cache, k_p, v_p = update_paged_cache(cache, k, v, offset)
        out = apply_op(
            lambda qq, kk, vv, pt: paged_decode_attention(
                qq, kk, vv, offset, pt),
            (q, k_p, v_p, cache[3]), name="paged_decode_attention")
    return new_cache, out
