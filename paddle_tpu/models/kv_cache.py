"""Shared static kv-cache layouts for the compiled generate() loop.

Two layouts, distinguished by tuple length (see generation.generate):
  (k_buf, v_buf, pos)                      — plain, cache dtype = kv dtype
  (k_q, v_q, pos, k_scale, v_scale)        — int8 + per-(head, token) absmax
                                             scales: HALF the HBM footprint
                                             AND half the decode stream when
                                             the Pallas decode kernel runs
                                             (ops/decode_attention.py
                                             dequantizes in VMEM)

Buffers are HEAD-MAJOR [B, H, L, D] (scales [B, H, L]): each (batch, head)
streams contiguous [L, D] keys/values — the layout the decode kernel and the
flash prefill kernel both want, with no per-step relayout.  New k/v arrive
from the projections as [B, S, H, D] and are transposed (cheap: S is 1 in
the decode loop) before the scatter at axis 2.

Both LlamaAttention and GPTBlock call the helpers here so the layout and
quantization contracts live in one place.

RECOMMENDATION (measured on v5e, 738M model, b8/p1024, r4): with the Pallas
decode kernel the int8 cache is now FASTER than bf16 at small batch (3.51
vs 3.96 ms/token — it streams half the kv bytes and dequantizes in VMEM)
and doubles the max decode batch/context at fixed HBM
(kv_int8_max_batch_gain ~1.9 in BENCH_r04: 114 -> 214 max batch at 1152
context).  Default to cache_dtype="int8" for serving whenever the model
tolerates the ~absmax/254 per-element roundtrip error (logit drift <5% on
the parity test); keep bf16 for exact-parity evaluation runs.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..tensor.tensor import apply_op


def _quantize_kv(kv):
    """Per-(head, token) absmax int8 quantization of a HEAD-MAJOR
    [B, H, S, D] slice: returns (int8 values, f32 scale [B, H, S])."""
    f = kv.astype(jnp.float32)
    scale = jnp.max(jnp.abs(f), axis=-1) / 127.0
    scale = jnp.maximum(scale, 1e-8)
    q = jnp.clip(jnp.round(f / scale[..., None]), -127, 127).astype(jnp.int8)
    return q, scale.astype(jnp.float32)


def _to_head_major(kv):
    """[B, S, H, D] (projection layout) -> [B, H, S, D] (cache layout)."""
    return jnp.transpose(kv, (0, 2, 1, 3))


def _scatter(buf, kv, offset):
    """Write head-major new kv into the buffer at `offset` — a scalar (all
    slots aligned: the generate() loop) or a per-slot [B] vector
    (continuous batching; decode S == 1)."""
    hm = kv
    if getattr(offset, "ndim", 0) >= 1:
        B, H = buf.shape[0], buf.shape[1]
        bi = jnp.arange(B)[:, None]
        hi = jnp.arange(H)[None, :]
        return buf.at[bi, hi, offset[:, None]].set(hm[:, :, 0])
    return jax.lax.dynamic_update_slice_in_dim(buf, hm, offset, 2)


def update_plain_cache(cache, k, v, offset):
    """Scatter new k/v [B, S, H, D] into the head-major (k_buf, v_buf, pos)
    layout.  Returns (new_cache, k_full, v_full) with the full buffers in
    head-major [B, H, L, D]."""
    S = k.shape[1]
    upd = lambda buf, kv: _scatter(  # noqa: E731
        buf, _to_head_major(kv.astype(buf.dtype)), offset)
    k_buf = apply_op(upd, (cache[0], k), name="kv_scatter")
    v_buf = apply_op(upd, (cache[1], v), name="kv_scatter")
    return (k_buf, v_buf, offset + S), k_buf, v_buf


def update_quant_cache(cache, k, v, offset, out_dtype):
    """Quantize + scatter new k/v [B, S, H, D] into the head-major 5-tuple
    int8 layout.  Returns (new_cache, k_q, v_q, k_scale, v_scale) — the
    int8 buffers and scales go STRAIGHT to the decode kernel, which
    dequantizes in VMEM (no bf16 cache materialization in HBM)."""
    S = k.shape[1]

    def upd_q(buf, sbuf, kv):
        kv_q, scale = _quantize_kv(_to_head_major(kv))
        if getattr(offset, "ndim", 0) >= 1:
            B, H = buf.shape[0], buf.shape[1]
            bi = jnp.arange(B)[:, None]
            hi = jnp.arange(H)[None, :]
            return (buf.at[bi, hi, offset[:, None]].set(kv_q[:, :, 0]),
                    sbuf.at[bi, hi, offset[:, None]].set(scale[:, :, 0]))
        return (jax.lax.dynamic_update_slice_in_dim(buf, kv_q, offset, 2),
                jax.lax.dynamic_update_slice_in_dim(sbuf, scale, offset, 2))

    k_buf, k_sc = apply_op(upd_q, (cache[0], cache[3], k), name="kv_scatter_q")
    v_buf, v_sc = apply_op(upd_q, (cache[1], cache[4], v), name="kv_scatter_q")
    return (k_buf, v_buf, offset + S, k_sc, v_sc), k_buf, v_buf, k_sc, v_sc
