"""Shared static kv-cache layouts for the compiled generate() loop.

Two layouts, distinguished by tuple length (see generation.generate):
  (k_buf, v_buf, pos)                      — plain, cache dtype = kv dtype
  (k_q, v_q, pos, k_scale, v_scale)        — int8 + per-(token, head) absmax
                                             scales: HALF the HBM footprint
Both LlamaAttention and GPTBlock call the helpers here so the quantization
contract lives in one place.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..tensor.tensor import apply_op


def _quantize_kv(kv):
    """Per-(token, head) absmax int8 quantization of a [B, S, H, D] slice:
    returns (int8 values, f32 scale [B, S, H, 1])."""
    f = kv.astype(jnp.float32)
    scale = jnp.max(jnp.abs(f), axis=-1, keepdims=True) / 127.0
    scale = jnp.maximum(scale, 1e-8)
    q = jnp.clip(jnp.round(f / scale), -127, 127).astype(jnp.int8)
    return q, scale.astype(jnp.float32)


def update_plain_cache(cache, k, v, offset):
    """Scatter new k/v into the (k_buf, v_buf, pos) layout.
    Returns (new_cache, k_full, v_full)."""
    S = k.shape[1]
    upd = lambda buf, kv: jax.lax.dynamic_update_slice_in_dim(  # noqa: E731
        buf, kv.astype(buf.dtype), offset, 1)
    k_buf = apply_op(upd, (cache[0], k), name="kv_scatter")
    v_buf = apply_op(upd, (cache[1], v), name="kv_scatter")
    return (k_buf, v_buf, offset + S), k_buf, v_buf


def update_quant_cache(cache, k, v, offset, out_dtype):
    """Quantize + scatter new k/v into the 5-tuple int8 layout and
    dequantize the full buffers for this step's attention.  Measured on
    v5e: XLA materializes the dequant (capacity lever, costs ms/token —
    see generation.generate).  Returns (new_cache, k_deq, v_deq)."""
    S = k.shape[1]

    def upd_q(buf, sbuf, kv):
        kv_q, scale = _quantize_kv(kv)
        return (jax.lax.dynamic_update_slice_in_dim(buf, kv_q, offset, 1),
                jax.lax.dynamic_update_slice_in_dim(sbuf, scale, offset, 1))

    k_buf, k_sc = apply_op(upd_q, (cache[0], cache[3], k), name="kv_scatter_q")
    v_buf, v_sc = apply_op(upd_q, (cache[1], cache[4], v), name="kv_scatter_q")
    deq = lambda b, s: b.astype(out_dtype) * s.astype(out_dtype)  # noqa: E731
    k_deq = apply_op(deq, (k_buf, k_sc), name="kv_dequant")
    v_deq = apply_op(deq, (v_buf, v_sc), name="kv_dequant")
    return (k_buf, v_buf, offset + S, k_sc, v_sc), k_deq, v_deq
