"""Shared static kv-cache layouts for the compiled generate() loop.

Two layouts, distinguished by tuple length (see generation.generate):
  (k_buf, v_buf, pos)                      — plain, cache dtype = kv dtype
  (k_q, v_q, pos, k_scale, v_scale)        — int8 + per-(head, token) absmax
                                             scales: HALF the HBM footprint
                                             AND half the decode stream when
                                             the Pallas decode kernel runs
                                             (ops/decode_attention.py
                                             dequantizes in VMEM)

Buffers are HEAD-MAJOR [B, H, L, D] (scales [B, H, L]): each (batch, head)
streams contiguous [L, D] keys/values — the layout the decode kernel and the
flash prefill kernel both want, with no per-step relayout.  New k/v arrive
from the projections as [B, S, H, D] and are transposed (cheap: S is 1 in
the decode loop) before the scatter at axis 2.

Both LlamaAttention and GPTBlock call the helpers here so the layout and
quantization contracts live in one place.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..tensor.tensor import apply_op


def _quantize_kv(kv):
    """Per-(head, token) absmax int8 quantization of a HEAD-MAJOR
    [B, H, S, D] slice: returns (int8 values, f32 scale [B, H, S])."""
    f = kv.astype(jnp.float32)
    scale = jnp.max(jnp.abs(f), axis=-1) / 127.0
    scale = jnp.maximum(scale, 1e-8)
    q = jnp.clip(jnp.round(f / scale[..., None]), -127, 127).astype(jnp.int8)
    return q, scale.astype(jnp.float32)


def _to_head_major(kv):
    """[B, S, H, D] (projection layout) -> [B, H, S, D] (cache layout)."""
    return jnp.transpose(kv, (0, 2, 1, 3))


def update_plain_cache(cache, k, v, offset):
    """Scatter new k/v [B, S, H, D] into the head-major (k_buf, v_buf, pos)
    layout.  Returns (new_cache, k_full, v_full) with the full buffers in
    head-major [B, H, L, D]."""
    S = k.shape[1]
    upd = lambda buf, kv: jax.lax.dynamic_update_slice_in_dim(  # noqa: E731
        buf, _to_head_major(kv.astype(buf.dtype)), offset, 2)
    k_buf = apply_op(upd, (cache[0], k), name="kv_scatter")
    v_buf = apply_op(upd, (cache[1], v), name="kv_scatter")
    return (k_buf, v_buf, offset + S), k_buf, v_buf


def update_quant_cache(cache, k, v, offset, out_dtype):
    """Quantize + scatter new k/v [B, S, H, D] into the head-major 5-tuple
    int8 layout.  Returns (new_cache, k_q, v_q, k_scale, v_scale) — the
    int8 buffers and scales go STRAIGHT to the decode kernel, which
    dequantizes in VMEM (no bf16 cache materialization in HBM)."""
    S = k.shape[1]

    def upd_q(buf, sbuf, kv):
        kv_q, scale = _quantize_kv(_to_head_major(kv))
        return (jax.lax.dynamic_update_slice_in_dim(buf, kv_q, offset, 2),
                jax.lax.dynamic_update_slice_in_dim(sbuf, scale, offset, 2))

    k_buf, k_sc = apply_op(upd_q, (cache[0], cache[3], k), name="kv_scatter_q")
    v_buf, v_sc = apply_op(upd_q, (cache[1], cache[4], v), name="kv_scatter_q")
    return (k_buf, v_buf, offset + S, k_sc, v_sc), k_buf, v_buf, k_sc, v_sc
