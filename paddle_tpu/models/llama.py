"""LLaMA-2 family (BASELINE config #5: LLaMA-2-7B hybrid tp+pp+sharding-stage-2).

Reference gap: the Paddle snapshot has no LLaMA (PaddleNLP's lives outside the repo);
this is the TPU-native flagship decoder: RMSNorm + RoPE + GQA + SwiGLU, with
Megatron-style TP expressed as sharding annotations (mp_layers) so the SAME module
runs dense on one chip or tp/dp/pp/sharded on a mesh via ShardedTrainStep /
PipelineTrainStep.  Attention routes through F.scaled_dot_product_attention, which
selects the Pallas flash kernel on TPU for long sequences.
"""
from __future__ import annotations

import math
from dataclasses import dataclass, field

import jax.numpy as jnp
import numpy as np

from .. import nn
from ..nn import functional as F
from ..ops import lora as _lora
from ..tensor.tensor import Tensor, apply_op
from ..tensor import manipulation as M
from ..distributed.meta_parallel.mp_layers import (
    ColumnParallelLinear, RowParallelLinear, VocabParallelEmbedding,
)
from ..distributed.sharding_ctx import annotate, constraint


@dataclass
class LlamaConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 11008
    num_hidden_layers: int = 32
    num_attention_heads: int = 32
    num_key_value_heads: int = 32
    max_position_embeddings: int = 4096
    rms_norm_eps: float = 1e-5
    rope_theta: float = 10000.0
    tie_word_embeddings: bool = False
    use_flash_attention: bool = True
    dtype: str = "float32"
    # parallel plan (consumed via sharding annotations)
    tensor_parallel: bool = True
    sequence_parallel: bool = False

    @staticmethod
    def llama2_7b(**kw):
        return LlamaConfig(**kw)

    @staticmethod
    def tiny(**kw):
        base = dict(vocab_size=1024, hidden_size=256, intermediate_size=688,
                    num_hidden_layers=2, num_attention_heads=8, num_key_value_heads=4,
                    max_position_embeddings=512)
        base.update(kw)
        return LlamaConfig(**base)


def _rope_cache(head_dim, max_pos, theta):
    inv = 1.0 / (theta ** (np.arange(0, head_dim, 2, dtype=np.float32) / head_dim))
    t = np.arange(max_pos, dtype=np.float32)
    freqs = np.outer(t, inv)  # [T, D/2]
    return jnp.asarray(np.cos(freqs)), jnp.asarray(np.sin(freqs))


from .kv_cache import (  # noqa: E402  (shared cache layouts; re-exported
    _quantize_kv,         # for backward compat — tests import from here)
    paged_attention_update,
    update_plain_cache,
    update_quant_cache,
)


def _static_decode_mask(offset, S, L):
    """Additive causal+padding mask for a static-cache step: queries at
    pos offset+i see keys j <= offset+i; the padded tail is masked."""
    jpos = jnp.arange(L)[None, :]
    qpos = jnp.arange(S)[:, None] + offset
    return jnp.where(jpos <= qpos, 0.0, -1e9)[None, None]


def apply_rope(x, cos, sin, position_offset=0):
    """x: [B, S, H, D] raw array; rotate-half RoPE — pairs (x_i, x_{i+D/2}).
    Contiguous half-splits instead of stride-2 interleaving: on TPU the
    lane-dim strided gather + stack materializes [., D/2, 2] copies in the
    decode scan body (each one a serial kernel dispatch); the half-split
    form fuses clean.  Attention scores are identical under either pairing
    since q and k share the permutation.
    position_offset may be a traced scalar (static-cache decode) or a
    PER-BATCH [B] vector (continuous-batching slots at different depths)."""
    S, D = x.shape[1], x.shape[-1]
    if isinstance(position_offset, (int, np.integer)):
        c = cos[position_offset:position_offset + S]
        s = sin[position_offset:position_offset + S]
    elif getattr(position_offset, "ndim", 0) >= 1:
        # per-slot offsets: gather [B, S, D/2] position rows
        pos = position_offset[:, None] + jnp.arange(S)[None, :]
        c = cos[pos][:, :, None, :]  # [B,S,1,D/2]
        s = sin[pos][:, :, None, :]
        x1, x2 = x[..., :D // 2], x[..., D // 2:]
        return jnp.concatenate([x1 * c - x2 * s, x2 * c + x1 * s], axis=-1)
    else:
        import jax

        c = jax.lax.dynamic_slice_in_dim(cos, position_offset, S, 0)
        s = jax.lax.dynamic_slice_in_dim(sin, position_offset, S, 0)
    c = c[None, :, None, :]  # [1,S,1,D/2]
    s = s[None, :, None, :]
    x1, x2 = x[..., :D // 2], x[..., D // 2:]
    return jnp.concatenate([x1 * c - x2 * s, x2 * c + x1 * s], axis=-1)


class LlamaAttention(nn.Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.config = config
        self.hidden_size = config.hidden_size
        self.num_heads = config.num_attention_heads
        self.num_kv_heads = config.num_key_value_heads
        self.head_dim = config.hidden_size // config.num_attention_heads
        tp = config.tensor_parallel
        Lin = ColumnParallelLinear if tp else nn.Linear
        mk = (lambda i, o: ColumnParallelLinear(i, o, has_bias=False, gather_output=False)) if tp \
            else (lambda i, o: nn.Linear(i, o, bias_attr=False))
        self.q_proj = mk(self.hidden_size, self.num_heads * self.head_dim)
        self.k_proj = mk(self.hidden_size, self.num_kv_heads * self.head_dim)
        self.v_proj = mk(self.hidden_size, self.num_kv_heads * self.head_dim)
        if tp:
            self.o_proj = RowParallelLinear(self.num_heads * self.head_dim, self.hidden_size,
                                            has_bias=False, input_is_parallel=True)
        else:
            self.o_proj = nn.Linear(self.num_heads * self.head_dim, self.hidden_size, bias_attr=False)

    def _o(self, out):
        y = self.o_proj(out)
        d = _lora.apply_site("o", out)
        return y if d is None else y + d

    def forward(self, hidden_states, rope, attn_mask=None, cache=None, use_cache=False):
        """rope: (cos, sin) Tensors shared at LlamaModel level (one copy, not 32).
        cache=None with use_cache=True is the prefill step: the returned cache is
        this call's own k/v."""
        rope_cos, rope_sin = rope
        B, S = hidden_states.shape[0], hidden_states.shape[1]
        fusable = (type(self.q_proj) is nn.Linear and type(self.k_proj) is nn.Linear
                   and type(self.v_proj) is nn.Linear  # not wrapped (quant etc.)
                   and all(getattr(p, "bias", None) is None
                           for p in (self.q_proj, self.k_proj, self.v_proj)))
        nq = self.num_heads * self.head_dim
        nkv = self.num_kv_heads * self.head_dim
        if S == 1 and fusable:
            # decode step: ONE fused qkv gemv instead of three — at batch<<128
            # each projection is weight-streaming-bound and per-op latency
            # dominates; the concat of the (loop-invariant) weights is hoisted
            # out of the decode scan by XLA LICM, so the fusion costs nothing
            def _fused_qkv(h, wq, wk, wv):
                w = jnp.concatenate([wq, wk, wv], axis=1)
                return h @ w.astype(h.dtype)

            qkv = apply_op(_fused_qkv,
                           (hidden_states, self.q_proj.weight,
                            self.k_proj.weight, self.v_proj.weight),
                           name="fused_qkv")
            q = qkv[:, :, :nq]
            k = qkv[:, :, nq:nq + nkv]
            v = qkv[:, :, nq + nkv:]
        else:
            q = self.q_proj(hidden_states)
            k = self.k_proj(hidden_states)
            v = self.v_proj(hidden_states)
        dq = _lora.apply_site("q", hidden_states)
        if dq is not None:
            # multi-tenant LoRA epilogue: per-row adapter-page gathers add
            # the low-rank delta; zero-adapter rows gather page 0 (exact +0)
            q = q + dq
            k = k + _lora.apply_site("k", hidden_states)
            v = v + _lora.apply_site("v", hidden_states)
        q = q.reshape([B, S, self.num_heads, self.head_dim])
        k = k.reshape([B, S, self.num_kv_heads, self.head_dim])
        v = v.reshape([B, S, self.num_kv_heads, self.head_dim])

        # a 3-tuple cache (k_buf, v_buf, pos) is the STATIC layout used by the
        # compiled generate() loop: fixed-size HEAD-MAJOR [B, H, L, D] buffers
        # + in-place scatter, so every decode step has identical shapes and
        # compiles once.  A 5-tuple (k_q, v_q, pos, k_scale, v_scale) is the
        # int8-quantized variant: per-(head, token) absmax scales — HALF the
        # cache HBM footprint AND half the decode stream (the Pallas decode
        # kernel dequantizes in VMEM; ops/decode_attention.py).  The 4/6-tuple
        # PAGED variants route through a global page pool + per-slot page
        # tables (kv_cache.py paged contract): same math, but capacity scales
        # with actual sequence lengths — the serving engine's layout.
        static_cache = cache is not None and len(cache) in (3, 5)
        quant_cache = cache is not None and len(cache) == 5
        paged_cache = cache is not None and len(cache) in (4, 6)
        if static_cache or paged_cache:
            offset = cache[2]
        else:
            offset = cache[0].shape[1] if cache is not None else 0
        q = apply_op(lambda a, c, s: apply_rope(a, c, s, offset), (q, rope_cos, rope_sin), name="rope")
        k = apply_op(lambda a, c, s: apply_rope(a, c, s, offset), (k, rope_cos, rope_sin), name="rope")

        if paged_cache and attn_mask is None:
            # paged decode / chunked-prefill / spec-verify path: scatter
            # into the page pool, then attend through the page table — ONE
            # ragged paged Pallas kernel for any S on tile-aligned shapes
            # (S=1 decode, prefill chunks, the K+1 verify ladder); gathered
            # dense math only for CPU-odd shapes
            # (llm_attn_kernel_total{path,reason} counts the dispatch)
            new_cache, out = paged_attention_update(cache, q, k, v, offset)
            out = out.reshape([B, S, self.num_heads * self.head_dim])
            out = self._o(out)
            if use_cache:
                return out, new_cache
            return out

        if static_cache and attn_mask is None:
            # decode hot path: single-query attention straight off the
            # head-major static cache (Pallas on TPU, dense math elsewhere)
            from ..ops.decode_attention import decode_attention

            if quant_cache:
                new_cache, k_q, v_q, k_sc, v_sc = update_quant_cache(
                    cache, k, v, offset, hidden_states.dtype)
                out = apply_op(
                    lambda qq, kk, vv, ks, vs: decode_attention(
                        qq, kk, vv, offset, ks, vs),
                    (q, k_q, v_q, k_sc, v_sc), name="decode_attention")
            else:
                new_cache, k_b, v_b = update_plain_cache(cache, k, v, offset)
                out = apply_op(
                    lambda qq, kk, vv: decode_attention(qq, kk, vv, offset),
                    (q, k_b, v_b), name="decode_attention")
            out = out.reshape([B, S, self.num_heads * self.head_dim])
            out = self._o(out)
            if use_cache:
                return out, new_cache
            return out

        if static_cache:
            # external mask with a static cache: dense path over the
            # head-major buffers brought back to [B, L, H, D]
            if quant_cache:
                new_cache, k_q, v_q, k_sc, v_sc = update_quant_cache(
                    cache, k, v, offset, hidden_states.dtype)
                deq = lambda b, s, dt=hidden_states.dtype: jnp.transpose(  # noqa: E731
                    b.astype(dt) * s.astype(dt)[..., None], (0, 2, 1, 3))
                k = apply_op(deq, (k_q, k_sc), name="kv_dequant")
                v = apply_op(deq, (v_q, v_sc), name="kv_dequant")
            else:
                new_cache, k_b, v_b = update_plain_cache(cache, k, v, offset)
                tohm = lambda b: jnp.transpose(b, (0, 2, 1, 3))  # noqa: E731
                k = apply_op(tohm, (k_b,), name="kv_unpack")
                v = apply_op(tohm, (v_b,), name="kv_unpack")
        else:
            if cache is not None:
                k = M.concat([cache[0], k], axis=1)
                v = M.concat([cache[1], v], axis=1)
            new_cache = (k, v) if use_cache else None

        # GQA: repeat kv heads to match q heads
        if self.num_kv_heads != self.num_heads:
            rep = self.num_heads // self.num_kv_heads
            k = apply_op(lambda a: jnp.repeat(a, rep, axis=2), (k,), name="gqa_repeat")
            v = apply_op(lambda a: jnp.repeat(a, rep, axis=2), (v,), name="gqa_repeat")

        if self.config.sequence_parallel and attn_mask is None and cache is None:
            # context parallelism (§5.7): ring attention across the 'sep' mesh
            # axis — the sequence stays sharded through the whole layer stack
            from ..ops.sequence_parallel import ring_attention_global

            out = apply_op(
                lambda a, b, c: ring_attention_global(
                    a, b, c, causal=True,
                    use_flash=self.config.use_flash_attention),
                (q, k, v), name="ring_attention")
        else:
            backend = "auto" if self.config.use_flash_attention else "math"
            out = F.scaled_dot_product_attention(
                q, k, v, attn_mask=attn_mask, is_causal=attn_mask is None, backend=backend,
            )
        out = out.reshape([B, S, self.num_heads * self.head_dim])
        out = self._o(out)
        if use_cache:
            return out, new_cache
        return out


class LlamaMLP(nn.Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        tp = config.tensor_parallel
        h, inter = config.hidden_size, config.intermediate_size
        if tp:
            self.gate_proj = ColumnParallelLinear(h, inter, has_bias=False, gather_output=False)
            self.up_proj = ColumnParallelLinear(h, inter, has_bias=False, gather_output=False)
            self.down_proj = RowParallelLinear(inter, h, has_bias=False, input_is_parallel=True)
        else:
            self.gate_proj = nn.Linear(h, inter, bias_attr=False)
            self.up_proj = nn.Linear(h, inter, bias_attr=False)
            self.down_proj = nn.Linear(inter, h, bias_attr=False)

    def forward(self, x):
        if x.shape[1] == 1 and type(self.gate_proj) is nn.Linear \
                and type(self.up_proj) is nn.Linear \
                and getattr(self.gate_proj, "bias", None) is None \
                and getattr(self.up_proj, "bias", None) is None:
            # decode step: fuse gate+up into one gemv (see fused_qkv note)
            def _fused_gu(h, wg, wu):
                w = jnp.concatenate([wg, wu], axis=1)
                return h @ w.astype(h.dtype)

            gu = apply_op(_fused_gu, (x, self.gate_proj.weight, self.up_proj.weight),
                          name="fused_gate_up")
            inter = self.gate_proj.weight.shape[1]
            g, u = gu[:, :, :inter], gu[:, :, inter:]
        else:
            g, u = self.gate_proj(x), self.up_proj(x)
        dg = _lora.apply_site("gate", x)
        if dg is not None:  # multi-tenant LoRA epilogues (see LlamaAttention)
            g = g + dg
            u = u + _lora.apply_site("up", x)
        h = F.silu(g) * u
        y = self.down_proj(h)
        dd = _lora.apply_site("down", h)
        return y if dd is None else y + dd


class LlamaDecoderLayer(nn.Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.self_attn = LlamaAttention(config)
        self.mlp = LlamaMLP(config)
        self.input_layernorm = nn.RMSNorm(config.hidden_size, config.rms_norm_eps)
        self.post_attention_layernorm = nn.RMSNorm(config.hidden_size, config.rms_norm_eps)

    def forward(self, x, rope, attn_mask=None, cache=None, use_cache=False):
        h = self.input_layernorm(x)
        if use_cache:
            attn_out, new_cache = self.self_attn(h, rope, attn_mask, cache, use_cache=True)
        else:
            attn_out = self.self_attn(h, rope, attn_mask)
        x = x + attn_out
        x = x + self.mlp(self.post_attention_layernorm(x))
        if use_cache:
            return x, new_cache
        return x


class LlamaModel(nn.Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.config = config
        if config.tensor_parallel:
            self.embed_tokens = VocabParallelEmbedding(config.vocab_size, config.hidden_size)
        else:
            self.embed_tokens = nn.Embedding(config.vocab_size, config.hidden_size)
        self.layers = nn.LayerList([LlamaDecoderLayer(config) for _ in range(config.num_hidden_layers)])
        self.norm = nn.RMSNorm(config.hidden_size, config.rms_norm_eps)
        cos, sin = _rope_cache(config.hidden_size // config.num_attention_heads,
                               config.max_position_embeddings, config.rope_theta)
        self.register_buffer("rope_cos", Tensor(cos), persistable=False)
        self.register_buffer("rope_sin", Tensor(sin), persistable=False)

    def forward(self, input_ids, attn_mask=None, caches=None, use_cache=False):
        """caches=[None]*num_layers (or caches=None with use_cache=True) is the
        prefill bootstrap; each entry is then a (k, v) pair for the decode steps."""
        use_cache = use_cache or caches is not None
        if use_cache and caches is None:
            caches = [None] * len(self.layers)
        x = self.embed_tokens(input_ids)
        rope = (self.rope_cos, self.rope_sin)
        # static-cache decode needs NO mask tensor: the decode-attention
        # kernel masks by the carried valid length (ops/decode_attention.py)
        new_caches = [] if use_cache else None
        for i, layer in enumerate(self.layers):
            if use_cache:
                x, c = layer(x, rope, attn_mask, caches[i], use_cache=True)
                new_caches.append(c)
            else:
                x = layer(x, rope, attn_mask)
        x = self.norm(x)
        if use_cache:
            return x, new_caches
        return x


class LlamaForCausalLM(nn.Layer):
    _supports_quant_cache = True  # LlamaAttention understands the 5-tuple
    _supports_paged_cache = True  # ... and the paged 4/6-tuples

    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.config = config
        self.llama = LlamaModel(config)
        if config.tensor_parallel:
            self.lm_head = ColumnParallelLinear(config.hidden_size, config.vocab_size,
                                                has_bias=False, gather_output=True)
        else:
            self.lm_head = nn.Linear(config.hidden_size, config.vocab_size, bias_attr=False)

    def forward(self, input_ids, labels=None):
        hidden = self.llama(input_ids)
        logits = self.lm_head(hidden)
        if labels is not None:
            loss = F.cross_entropy(
                logits.reshape([-1, self.config.vocab_size]),
                labels.reshape([-1]),
                ignore_index=-100,
            )
            return loss, logits
        return logits

    @property
    def num_params(self):
        import numpy as np

        return sum(int(np.prod(p.shape)) for p in self.parameters())

    def generate_step(self, input_ids, caches=None):
        """Prefill (caches=None) or single-token decode step (inference path)."""
        hidden, caches = self.llama(input_ids, caches=caches, use_cache=True)
        return self.lm_head(hidden[:, -1:]), caches

    def verify_step(self, input_ids, caches):
        """Speculative-decoding verify: score S = K+1 tokens in ONE pass
        through the decode cache path (on the paged layout this is the
        ragged Pallas kernel — the verify ladder is just another ragged
        query block), returning the logits at EVERY position [B, S, V] —
        generate_step keeps only the last, but the accept/rollback
        decision needs the whole ladder (ops/sampling spec_accept)."""
        hidden, caches = self.llama(input_ids, caches=caches, use_cache=True)
        return self.lm_head(hidden), caches

    def prefill_step(self, input_ids, last_index):
        """Bucket-padded prefill (serving admission): the prompt is padded
        PAST `last_index`, so the next-token logits live there, not at -1
        (causal attention keeps positions <= last_index exact under the
        padding).  Returns (logits [B, 1, V], caches)."""
        import jax

        hidden, caches = self.llama(input_ids, caches=None, use_cache=True)
        last = apply_op(
            lambda h: jax.lax.dynamic_slice_in_dim(h, last_index, 1, 1),
            (hidden,), name="prefill_last")
        return self.lm_head(last), caches

    def prefill_chunk_step(self, input_ids, caches, last_index):
        """One CHUNK of an incremental (paged) prefill: input_ids [B, C] are
        the next C prompt tokens of each row (pad-padded past `last_index`
        on the final chunk), caches carry the paged pools + page tables with
        pos = tokens already prefilled.  Returns (logits [B, 1, V] at
        `last_index`, caches) — the logits only matter on the final chunk;
        earlier chunks pay one [B, 1, V] head gemv for shape stability
        (llm_server.py compiles exactly ONE chunk program, killing the
        per-bucket prefill zoo).  On tile-aligned shapes the chunk's
        attention is the ragged paged Pallas kernel — the per-slot chunk
        offset rides the kernel's prefetched lengths vector."""
        import jax

        hidden, caches = self.llama(input_ids, caches=caches, use_cache=True)
        last = apply_op(
            lambda h: jax.lax.dynamic_slice_in_dim(h, last_index, 1, 1),
            (hidden,), name="prefill_chunk_last")
        return self.lm_head(last), caches

    def generate(self, input_ids, max_new_tokens=32, do_sample=False,
                 temperature=1.0, top_k=0, top_p=1.0, eos_token_id=None,
                 pad_token_id=0, cache_dtype=None, kv_layout=None,
                 page_size=128, share_prefix=False, spec_k=0,
                 spec_drafter=None, adapter_id=None, adapters=None,
                 token_mask_fn=None):
        """Compiled autoregressive decoding on a static kv-cache — one XLA
        program for prefill + the whole token scan (models/generation.py).
        cache_dtype='int8' halves the kv-cache HBM footprint;
        kv_layout='paged' decodes through the paged pool + page-table
        layout (the serving engine's cache) for parity/benchmarking;
        share_prefix=True additionally aliases the batch's common prompt
        prefix onto shared physical pages (the prefix-cache read path);
        spec_k=K enables speculative decoding (K drafts verified per
        compiled step; greedy output is bitwise identical to spec_k=0);
        adapter_id=/adapters= routes the call through a paged LoRA
        adapter pool (models/lora.py); token_mask_fn= applies a compiled
        token automaton (inference/constrain.py) for constrained
        decoding."""
        from .generation import generate as _gen

        return _gen(self, input_ids, max_new_tokens, do_sample, temperature,
                    top_k, top_p, eos_token_id, pad_token_id,
                    cache_dtype=cache_dtype, kv_layout=kv_layout,
                    page_size=page_size, share_prefix=share_prefix,
                    spec_k=spec_k, spec_drafter=spec_drafter,
                    adapter_id=adapter_id, adapters=adapters,
                    token_mask_fn=token_mask_fn)
