"""paddle.static.nn layer builders.

Ref: python/paddle/static/nn/__init__.py (fc, conv2d, batch_norm, the
sequence_* ops, StaticRNN...).  The reference appends ops + persistable
parameters to a ProgramDesc; the legacy graph stack is a non-goal here
(SURVEY §7.4), so these builders follow the TPU-native translation:

- parameters are created once and cached by `name` (pass a unique name per
  call site — an automatic shape key is used otherwise), so repeated calls
  train one set of weights, whether eager or inside a @to_static trace;
- the reference's LoD (ragged) sequence ops operate on the PADDED dense
  layout [B, T, ...] with an optional `seq_len` — the standard TPU-ification
  of variable-length sequences (static shapes for XLA, masks for semantics).
"""
from __future__ import annotations

import warnings

import jax
import jax.numpy as jnp

from ..tensor.tensor import Tensor, apply_op, _unwrap

__all__ = [
    "fc", "batch_norm", "embedding", "bilinear_tensor_product", "conv2d",
    "conv2d_transpose", "conv3d", "conv3d_transpose", "crf_decoding",
    "data_norm", "deform_conv2d", "group_norm", "instance_norm", "layer_norm",
    "multi_box_head", "nce", "prelu", "row_conv", "spectral_norm",
    "sparse_embedding",
    "sequence_conv", "sequence_softmax", "sequence_pool", "sequence_concat",
    "sequence_first_step", "sequence_last_step", "sequence_slice",
    "sequence_expand", "sequence_expand_as", "sequence_pad", "sequence_unpad",
    "sequence_reshape", "sequence_scatter", "sequence_enumerate",
    "sequence_reverse", "StaticRNN",
]

_layer_registry = {}


def _cached(name, default_key, factory):
    key = name
    if key is None:
        key = default_key
        warnings.warn(
            f"static.nn builder called without `name`: parameters cached by "
            f"the automatic key {key!r}, which collides for two identical "
            f"call signatures — pass a unique name per call site", stacklevel=3)
    layer = _layer_registry.get(key)
    if layer is None:
        layer = factory()
        _layer_registry[key] = layer
    return layer


# ------------------------------------------------------------------ builders

def fc(x, size, num_flatten_dims=1, weight_attr=None, bias_attr=None,
       activation=None, name=None):
    """Ref static/nn/common.py fc: flatten trailing dims, project, activate."""
    from .. import nn
    from ..nn import functional as F

    in_dim = 1
    for d in x.shape[num_flatten_dims:]:
        in_dim *= int(d)
    lin = _cached(name, f"fc:{in_dim}:{size}",
                  lambda: nn.Linear(in_dim, size, weight_attr=weight_attr,
                                    bias_attr=bias_attr))
    # -1 in the batch position keeps the recorded reshape polymorphic over
    # the fed batch size (static.data placeholders carry batch=1)
    lead = [-1] + [int(d) for d in x.shape[1:num_flatten_dims]]
    out = lin(x.reshape(lead + [in_dim]))
    if activation:
        out = getattr(F, activation)(out)
    return out


def embedding(input, size, is_sparse=False, is_distributed=False,
              padding_idx=None, param_attr=None, dtype="float32", name=None):
    from .. import nn

    emb = _cached(name, f"emb:{tuple(size)}",
                  lambda: nn.Embedding(size[0], size[1], padding_idx=padding_idx,
                                       weight_attr=param_attr))
    return emb(input)


def sparse_embedding(input, size, padding_idx=None, is_test=False,
                     entry=None, table_class="MemorySparseTable",
                     param_attr=None, dtype="float32", slot=None):
    """Ref static/nn/common.py sparse_embedding — a parameter-server sparse
    table.  On TPU embeddings are dense HBM arrays sharded over the mesh
    (VocabParallelEmbedding for big vocabularies); this maps to the dense
    embedding so scripts run, which is the whole difference."""
    return embedding(input, size, padding_idx=padding_idx,
                     param_attr=param_attr, dtype=dtype,
                     name=getattr(param_attr, "name", None))


def _conv_nd(x, num_filters, filter_size, stride, padding, dilation, groups,
             param_attr, bias_attr, name, nd, transpose=False, output_size=None,
             data_format="NCHW"):
    from .. import nn

    cls = {(2, False): nn.Conv2D, (2, True): nn.Conv2DTranspose,
           (3, False): nn.Conv3D, (3, True): nn.Conv3DTranspose}[(nd, transpose)]
    ch_axis = 1 if data_format.startswith("NC") else -1
    in_ch = int(x.shape[ch_axis])
    if transpose and filter_size is None:
        if output_size is None:
            raise ValueError("conv transpose needs filter_size or output_size")
        # k = out - (in-1)*stride + 2*pad (ref conv2d_transpose filter-size
        # derivation; symmetric padding, dilation 1)
        sp_axis = 2 if ch_axis == 1 else 1
        out0 = output_size[0] if isinstance(output_size, (list, tuple)) else output_size
        st0 = stride[0] if isinstance(stride, (list, tuple)) else stride
        pd0 = padding[0] if isinstance(padding, (list, tuple)) else padding
        filter_size = int(out0) - (int(x.shape[sp_axis]) - 1) * st0 + 2 * pd0
        if filter_size < 1:
            raise ValueError(
                f"derived filter_size {filter_size} < 1 from output_size "
                f"{output_size}; check stride/padding")
    conv = _cached(name,
                   f"conv{nd}{'t' if transpose else ''}:{in_ch}:{num_filters}:"
                   f"{filter_size}:{stride}:{padding}:{dilation}:{groups}:"
                   f"{data_format}",
                   lambda: cls(in_ch, num_filters, filter_size, stride=stride,
                               padding=padding, dilation=dilation,
                               groups=groups or 1, weight_attr=param_attr,
                               bias_attr=bias_attr, data_format=data_format))
    return conv(x)


def conv2d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=None, param_attr=None, bias_attr=None, use_cudnn=True,
           act=None, name=None, data_format="NCHW"):
    from ..nn import functional as F

    out = _conv_nd(input, num_filters, filter_size, stride, padding, dilation,
                   groups, param_attr, bias_attr, name, 2,
                   data_format=data_format)
    return getattr(F, act)(out) if act else out


def conv2d_transpose(input, num_filters, output_size=None, filter_size=None,
                     stride=1, padding=0, dilation=1, groups=None,
                     param_attr=None, bias_attr=None, use_cudnn=True, act=None,
                     name=None, data_format="NCHW"):
    from ..nn import functional as F

    out = _conv_nd(input, num_filters, filter_size, stride, padding,
                   dilation, groups, param_attr, bias_attr, name, 2,
                   transpose=True, output_size=output_size,
                   data_format=data_format)
    return getattr(F, act)(out) if act else out


def conv3d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=None, param_attr=None, bias_attr=None, use_cudnn=True,
           act=None, name=None, data_format="NCDHW"):
    from ..nn import functional as F

    out = _conv_nd(input, num_filters, filter_size, stride, padding, dilation,
                   groups, param_attr, bias_attr, name, 3,
                   data_format="NCHW" if data_format == "NCDHW" else data_format)
    return getattr(F, act)(out) if act else out


def conv3d_transpose(input, num_filters, output_size=None, filter_size=None,
                     stride=1, padding=0, dilation=1, groups=None,
                     param_attr=None, bias_attr=None, use_cudnn=True, act=None,
                     name=None, data_format="NCDHW"):
    from ..nn import functional as F

    out = _conv_nd(input, num_filters, filter_size, stride, padding,
                   dilation, groups, param_attr, bias_attr, name, 3,
                   transpose=True, output_size=output_size,
                   data_format="NCHW" if data_format == "NCDHW" else data_format)
    return getattr(F, act)(out) if act else out


def batch_norm(input, act=None, is_test=False, momentum=0.9, epsilon=1e-5,
               param_attr=None, bias_attr=None, data_layout="NCHW",
               in_place=False, name=None, moving_mean_name=None,
               moving_variance_name=None, do_model_average_for_mean_and_var=True,
               use_global_stats=False):
    from .. import nn
    from ..nn import functional as F

    ch = int(input.shape[1 if data_layout.startswith("NC") else -1])
    bn = _cached(name or moving_mean_name, f"bn:{ch}",
                 lambda: nn.BatchNorm(ch, momentum=momentum, epsilon=epsilon,
                                      param_attr=param_attr, bias_attr=bias_attr,
                                      data_layout=data_layout,
                                      use_global_stats=use_global_stats))
    bn.training = not is_test
    out = bn(input)
    return getattr(F, act)(out) if act else out


def layer_norm(input, scale=True, shift=True, begin_norm_axis=1, epsilon=1e-5,
               param_attr=None, bias_attr=None, act=None, name=None):
    from .. import nn
    from ..nn import functional as F

    shape = [int(d) for d in input.shape[begin_norm_axis:]]
    ln = _cached(name, f"ln:{shape}",
                 lambda: nn.LayerNorm(shape, epsilon=epsilon,
                                      weight_attr=param_attr if scale else False,
                                      bias_attr=bias_attr if shift else False))
    out = ln(input)
    return getattr(F, act)(out) if act else out


def group_norm(input, groups, epsilon=1e-5, param_attr=None, bias_attr=None,
               act=None, data_layout="NCHW", name=None):
    from .. import nn
    from ..nn import functional as F

    ch = int(input.shape[1 if data_layout.startswith("NC") else -1])
    gn = _cached(name, f"gn:{groups}:{ch}:{data_layout}",
                 lambda: nn.GroupNorm(groups, ch, epsilon=epsilon,
                                      weight_attr=param_attr, bias_attr=bias_attr,
                                      data_format=data_layout))
    out = gn(input)
    return getattr(F, act)(out) if act else out


def instance_norm(input, epsilon=1e-5, param_attr=None, bias_attr=None,
                  name=None):
    from .. import nn

    ch = int(input.shape[1])
    inorm = _cached(name, f"in:{ch}",
                    lambda: nn.InstanceNorm2D(ch, epsilon=epsilon,
                                              weight_attr=param_attr,
                                              bias_attr=bias_attr))
    return inorm(input)


def data_norm(input, act=None, epsilon=1e-5, param_attr=None, enable_scale_and_shift=False,
              name=None, moving_mean_name=None, moving_variance_name=None,
              do_model_average_for_mean_and_var=True, slot_dim=-1,
              sync_stats=False, summary_decay_rate=0.9999999, in_place=False):
    """Ref static/nn/common.py data_norm — normalization by accumulated
    batch statistics (no learned gamma/beta unless enabled); implemented as
    BatchNorm in global-stats mode over the feature axis."""
    from .. import nn
    from ..nn import functional as F

    ch = int(input.shape[-1])
    bn = _cached(name, f"dn:{ch}",
                 lambda: nn.BatchNorm1D(ch, momentum=summary_decay_rate,
                                        epsilon=epsilon,
                                        weight_attr=(param_attr if enable_scale_and_shift else False),
                                        bias_attr=(None if enable_scale_and_shift else False)))
    out = bn(input)
    return getattr(F, act)(out) if act else out


def prelu(x, mode="all", param_attr=None, data_format="NCHW", name=None):
    from .. import nn

    if mode == "element":
        # per-element slope: weight shaped like one sample (ref prelu op
        # element mode); F.prelu only broadcasts per-channel, so compute here
        from ..nn.layer.layers import Layer
        from ..nn.initializer import Constant

        shape = [int(d) for d in x.shape[1:]]

        def make():
            holder = Layer()
            return holder.create_parameter(shape, attr=param_attr,
                                           default_initializer=Constant(0.25))

        w = _cached(name, f"prelu:element:{shape}", make)
        return apply_op(lambda v, wv: jnp.where(v > 0, v, wv[None] * v),
                        (x, w), name="prelu_element")
    ch_axis = 1 if data_format.startswith("NC") else -1
    n = 1 if mode == "all" else int(x.shape[ch_axis])
    pr = _cached(name, f"prelu:{mode}:{n}",
                 lambda: nn.PReLU(num_parameters=n, weight_attr=param_attr,
                                  data_format=data_format))
    return pr(x)


def bilinear_tensor_product(x, y, size, act=None, name=None, param_attr=None,
                            bias_attr=None):
    from .. import nn
    from ..nn import functional as F

    bl = _cached(name, f"btp:{int(x.shape[-1])}:{int(y.shape[-1])}:{size}",
                 lambda: nn.Bilinear(int(x.shape[-1]), int(y.shape[-1]), size,
                                     weight_attr=param_attr, bias_attr=bias_attr))
    out = bl(x, y)
    return getattr(F, act)(out) if act else out


def spectral_norm(weight, dim=0, power_iters=1, eps=1e-12, name=None):
    """Functional spectral normalization of a weight tensor (ref
    static/nn/common.py spectral_norm — power iteration, fresh u/v)."""
    def _f(w):
        mat = jnp.moveaxis(w.astype(jnp.float32), dim, 0).reshape(w.shape[dim], -1)
        u = jnp.ones((mat.shape[0],), jnp.float32) / jnp.sqrt(mat.shape[0] * 1.0)
        for _ in range(max(1, power_iters)):
            v = mat.T @ u
            v = v / (jnp.linalg.norm(v) + eps)
            u = mat @ v
            u = u / (jnp.linalg.norm(u) + eps)
        sigma = u @ (mat @ v)
        return (w.astype(jnp.float32) / sigma).astype(w.dtype)

    return apply_op(_f, (weight,), name="spectral_norm")


def deform_conv2d(input, offset, mask, num_filters, filter_size, stride=1,
                  padding=0, dilation=1, groups=None, deformable_groups=1,
                  im2col_step=1, param_attr=None, bias_attr=None,
                  modulated=True, name=None):
    from ..vision.ops import DeformConv2D

    in_ch = int(input.shape[1])
    dc = _cached(name, f"dconv:{in_ch}:{num_filters}:{filter_size}",
                 lambda: DeformConv2D(in_ch, num_filters, filter_size,
                                      stride=stride, padding=padding,
                                      dilation=dilation,
                                      deformable_groups=deformable_groups,
                                      groups=groups or 1,
                                      weight_attr=param_attr,
                                      bias_attr=bias_attr))
    return dc(input, offset, mask if modulated else None)


def crf_decoding(input, param_attr, length=None, label=None, name=None):
    """Viterbi decode with a learned transition matrix (ref crf_decoding op):
    the transitions are a cached parameter addressed by param_attr/name."""
    from ..text import viterbi_decode
    from ..nn.layer.layers import Layer
    from ..nn.initializer import Normal

    T = int(input.shape[-1])
    key = getattr(param_attr, "name", None) or name

    def make():
        holder = Layer()
        return holder.create_parameter([T + 2, T + 2], attr=param_attr,
                                       default_initializer=Normal(0.0, 0.1))

    trans = _cached(key, f"crfw:{T}", make)
    if length is None:
        B, L = int(input.shape[0]), int(input.shape[1])
        length = Tensor(jnp.full((B,), L, jnp.int64))
    # pad emissions to T+2 tags (bos/eos rows of the transition matrix)
    pot = apply_op(lambda v: jnp.pad(v, [(0, 0), (0, 0), (0, 2)],
                                     constant_values=-1e4), (input,),
                   name="crf_pad")
    scores, path = viterbi_decode(pot, trans, length, include_bos_eos_tag=True)
    return path


def nce(input, label, num_total_classes, sample_weight=None, param_attr=None,
        bias_attr=None, num_neg_samples=5, name=None, sampler="uniform",
        custom_dist=None, seed=0, is_sparse=False):
    """Noise-contrastive estimation loss (ref static/nn/loss.py nce):
    logistic loss on the true class + `num_neg_samples` sampled noise
    classes.  Negatives are drawn with jax PRNG inside the op."""
    from ..nn.layer.layers import Layer
    from ..nn.initializer import Normal, Constant

    D = int(input.shape[-1])

    def make():
        holder = Layer()
        w = holder.create_parameter([num_total_classes, D], attr=param_attr,
                                    default_initializer=Normal(0.0, 0.05))
        b = holder.create_parameter([num_total_classes], attr=bias_attr,
                                    is_bias=True,
                                    default_initializer=Constant(0.0))
        return (w, b)

    w, b = _cached(name, f"nce:{num_total_classes}:{D}", make)

    from ..framework import random as _random

    key = _random.get_rng_key()

    def _f(x, lbl, wv, bv):
        B = x.shape[0]
        lbl = lbl.reshape(-1).astype(jnp.int32)
        neg = jax.random.randint(key, (B, num_neg_samples), 0, num_total_classes)
        pos_logit = jnp.sum(x * wv[lbl], -1) + bv[lbl]
        neg_logit = jnp.einsum("bd,bkd->bk", x, wv[neg]) + bv[neg]
        softplus = lambda z: jnp.maximum(z, 0) + jnp.log1p(jnp.exp(-jnp.abs(z)))  # noqa: E731
        loss = softplus(-pos_logit) + softplus(neg_logit).sum(-1)
        return loss[:, None]

    return apply_op(_f, (input, label, w, b), name="nce")


def row_conv(input, future_context_size, param_attr=None, act=None, name=None):
    """Lookahead row convolution (ref static/nn/common.py row_conv):
    out[t] = sum_{k=0..K} w[k] * in[t+k], per feature channel."""
    from ..nn.layer.layers import Layer
    from ..nn.initializer import Normal
    from ..nn import functional as F

    D = int(input.shape[-1])
    K = future_context_size + 1

    def make():
        holder = Layer()
        return holder.create_parameter([K, D], attr=param_attr,
                                       default_initializer=Normal(0.0, 0.1))

    w = _cached(name, f"rowconv:{K}:{D}", make)

    def _f(v, wv):
        pad = jnp.pad(v, [(0, 0), (0, K - 1), (0, 0)])
        out = jnp.zeros_like(v)
        for k in range(K):  # K is small and static: unrolled adds fuse
            out = out + pad[:, k:k + v.shape[1]] * wv[k][None, None, :]
        return out

    out = apply_op(_f, (input, w), name="row_conv")
    return getattr(F, act)(out) if act else out


def multi_box_head(inputs, image, base_size, num_classes, aspect_ratios,
                   min_ratio=None, max_ratio=None, min_sizes=None,
                   max_sizes=None, steps=None, step_w=None, step_h=None,
                   offset=0.5, variance=(0.1, 0.1, 0.2, 0.2), flip=True,
                   clip=False, kernel_size=1, pad=0, stride=1, name=None,
                   min_max_aspect_ratios_order=False):
    """SSD detection head (ref static/nn/common.py multi_box_head): per
    feature map, conv predictors for box offsets + class scores and the
    matching prior boxes."""
    import numpy as np

    from ..nn import functional as F

    n_in = len(inputs)
    if min_sizes is None:
        # the reference's ratio schedule
        min_ratio, max_ratio = min_ratio or 20, max_ratio or 90
        step = int((max_ratio - min_ratio) / max(n_in - 2, 1))
        min_sizes, max_sizes = [base_size * 0.1], [base_size * 0.2]
        for r in range(min_ratio, max_ratio + 1, step):
            min_sizes.append(base_size * r / 100.0)
            max_sizes.append(base_size * (r + step) / 100.0)
        min_sizes = min_sizes[:n_in]
        max_sizes = max_sizes[:n_in]

    img_h, img_w = int(image.shape[2]), int(image.shape[3])
    locs, confs, priors, pvars = [], [], [], []
    for i, feat in enumerate(inputs):
        ars = aspect_ratios[i] if isinstance(aspect_ratios[i], (list, tuple)) \
            else [aspect_ratios[i]]
        n_prior = 1 + len(ars) * (2 if flip else 1) + (1 if max_sizes else 0)
        loc = conv2d(feat, n_prior * 4, kernel_size, stride=stride, padding=pad,
                     name=f"{name or 'mbh'}_loc{i}")
        conf = conv2d(feat, n_prior * num_classes, kernel_size, stride=stride,
                      padding=pad, name=f"{name or 'mbh'}_conf{i}")
        H, W = int(feat.shape[2]), int(feat.shape[3])
        locs.append(loc.transpose([0, 2, 3, 1]).reshape([int(feat.shape[0]), -1, 4]))
        confs.append(conf.transpose([0, 2, 3, 1]).reshape(
            [int(feat.shape[0]), -1, num_classes]))
        # prior boxes (host precompute — static per shape, like the reference op)
        sw = (steps[i] if steps else (step_w[i] if step_w else img_w / W))
        sh = (steps[i] if steps else (step_h[i] if step_h else img_h / H))
        sizes = [float(min_sizes[i])]
        if max_sizes:
            sizes.append(float(np.sqrt(min_sizes[i] * max_sizes[i])))
        # vectorized prior grid: centers [H, W] x per-cell (w, h) variants
        wh = [(s, s) for s in sizes]
        for ar in ars:
            for a in ([ar, 1.0 / ar] if flip else [ar]):
                wh.append((min_sizes[i] * np.sqrt(a), min_sizes[i] / np.sqrt(a)))
        wh = np.asarray(wh, np.float32)                      # [P, 2]
        cx = (np.arange(W, dtype=np.float32) + offset) * sw
        cy = (np.arange(H, dtype=np.float32) + offset) * sh
        cxy = np.stack(np.meshgrid(cx, cy), -1).reshape(-1, 1, 2)  # [H*W, 1, 2]
        half = wh[None] / 2                                   # [1, P, 2]
        pb = np.concatenate([cxy - half, cxy + half], -1).reshape(-1, 4)
        pb = pb / [img_w, img_h, img_w, img_h]
        if clip:
            pb = np.clip(pb, 0.0, 1.0)
        priors.append(Tensor(jnp.asarray(pb)))
        pvars.append(Tensor(jnp.broadcast_to(
            jnp.asarray(np.asarray(variance, np.float32)), pb.shape)))

    from ..tensor import manipulation as M

    return (M.concat(locs, 1), M.concat(confs, 1),
            M.concat(priors, 0), M.concat(pvars, 0))


# -------------------------------------------------------- sequence ops (LoD
# -> padded-dense translation: [B, T, ...] plus seq_len, SURVEY §7.3.4)

def _mask(x, seq_len):
    if seq_len is None:
        return None
    lens = _unwrap(seq_len)
    T = x.shape[1]
    return (jnp.arange(T)[None, :] < lens.reshape(-1, 1)).astype(jnp.float32)


def sequence_softmax(input, seq_len=None, use_cudnn=False, name=None):
    def _f(v, *rest):
        m = _mask(v, seq_len)
        logits = v if m is None else jnp.where(m[..., None] > 0 if v.ndim == 3
                                               else m > 0, v, -1e9)
        return jax.nn.softmax(logits, axis=1)

    return apply_op(_f, (input,), name="sequence_softmax")


def sequence_pool(input, pool_type, is_test=False, pad_value=0.0, seq_len=None):
    def _f(v):
        m = _mask(v, seq_len)
        if m is None:
            m = jnp.ones(v.shape[:2], jnp.float32)
        me = m[..., None] if v.ndim == 3 else m
        pt = pool_type.lower()
        if pt == "sum":
            return (v * me).sum(1)
        if pt in ("average", "mean"):
            return (v * me).sum(1) / jnp.maximum(me.sum(1), 1.0)
        if pt == "sqrt":
            return (v * me).sum(1) / jnp.sqrt(jnp.maximum(me.sum(1), 1.0))
        if pt == "max":
            return jnp.where(me > 0, v, -jnp.inf).max(1)
        if pt == "first":
            return v[:, 0]
        if pt == "last":
            idx = jnp.maximum(me.sum(1)[..., 0] if me.ndim == 3 else me.sum(1), 1
                              ).astype(jnp.int32) - 1
            idx = idx.reshape((-1,) + (1,) * (v.ndim - 1))
            return jnp.take_along_axis(v, idx, 1)[:, 0]
        raise ValueError(f"unknown pool_type {pool_type}")

    return apply_op(_f, (input,), name="sequence_pool")


def sequence_first_step(input, seq_len=None):
    return sequence_pool(input, "first", seq_len=seq_len)


def sequence_last_step(input, seq_len=None):
    return sequence_pool(input, "last", seq_len=seq_len)


def sequence_concat(input, name=None):
    from ..tensor import manipulation as M

    return M.concat(input, axis=1)


def sequence_conv(input, num_filters, filter_size=3, filter_stride=1,
                  padding=True, padding_start=None, bias_attr=None,
                  param_attr=None, act=None, name=None):
    """Context-window projection over time (ref sequence_conv): implemented
    as a same-padded 1-D convolution over the padded layout."""
    from .. import nn
    from ..nn import functional as F

    D = int(input.shape[-1])
    conv = _cached(name, f"seqconv:{D}:{num_filters}:{filter_size}",
                   lambda: nn.Conv1D(D, num_filters, filter_size,
                                     padding=(filter_size - 1) // 2,
                                     weight_attr=param_attr, bias_attr=bias_attr))
    out = conv(input.transpose([0, 2, 1])).transpose([0, 2, 1])
    return getattr(F, act)(out) if act else out


def sequence_slice(input, offset, length, name=None):
    def _f(v, off, ln):
        T = v.shape[1]
        idx = jnp.arange(T)
        keep = (idx[None, :] >= off.reshape(-1, 1)) & \
               (idx[None, :] < (off + ln).reshape(-1, 1))
        # static output length = max length (padded-dense contract)
        gath = jnp.where(keep[..., None] if v.ndim == 3 else keep, v, 0)
        # roll each row so the slice starts at 0
        return jax.vmap(lambda row, o: jnp.roll(row, -o, axis=0))(
            gath, off.reshape(-1).astype(jnp.int32))

    return apply_op(_f, (input, offset, length), name="sequence_slice")


def sequence_expand(x, y, ref_level=-1, name=None):
    """Repeat each row of x to y's time dimension (padded-dense analog)."""
    def _f(xv, yv):
        return jnp.broadcast_to(xv[:, None], (xv.shape[0], yv.shape[1]) + xv.shape[1:])

    return apply_op(_f, (x, y), name="sequence_expand")


def sequence_expand_as(x, y, name=None):
    return sequence_expand(x, y)


def sequence_pad(x, pad_value, maxlen=None, name=None):
    """Already-padded layout: optionally extend to maxlen; returns (data, len)."""
    def _f(v, pv):
        if maxlen is None or maxlen <= v.shape[1]:
            return v
        extra = maxlen - v.shape[1]
        fill = jnp.broadcast_to(pv.astype(v.dtype),
                                (v.shape[0], extra) + tuple(v.shape[2:]))
        return jnp.concatenate([v, fill], axis=1)

    out = apply_op(_f, (x, pad_value), name="sequence_pad")
    B, T = int(x.shape[0]), int(out.shape[1])
    return out, Tensor(jnp.full((B,), int(x.shape[1]), jnp.int32))


def sequence_unpad(x, length, name=None):
    """Mask out positions past each row's length (shape stays static)."""
    def _f(v, ln):
        m = (jnp.arange(v.shape[1])[None, :] < ln.reshape(-1, 1))
        return jnp.where(m[..., None] if v.ndim == 3 else m, v, 0)

    return apply_op(_f, (x, length), name="sequence_unpad")


def sequence_reshape(input, new_dim):
    def _f(v):
        B = v.shape[0]
        return v.reshape(B, -1, new_dim)

    return apply_op(_f, (input,), name="sequence_reshape")


def sequence_scatter(input, index, updates, name=None):
    def _f(v, idx, upd):
        B = v.shape[0]
        b = jnp.arange(B)[:, None].repeat(idx.shape[1], 1).reshape(-1)
        return v.at[b, idx.reshape(-1).astype(jnp.int32)].add(upd.reshape(b.shape[0], *v.shape[2:]))

    return apply_op(_f, (input, index, updates), name="sequence_scatter")


def sequence_enumerate(input, win_size, pad_value=0, name=None):
    def _f(v):
        T = v.shape[-1] if v.ndim == 2 else v.shape[1]
        v2 = v.reshape(v.shape[0], T)
        cols = []
        for k in range(win_size):
            shifted = jnp.concatenate(
                [v2[:, k:], jnp.full((v2.shape[0], k), pad_value, v2.dtype)], 1)
            cols.append(shifted)
        return jnp.stack(cols, -1)

    return apply_op(_f, (input,), name="sequence_enumerate")


def sequence_reverse(x, seq_len=None, name=None):
    def _f(v, *rest):
        if seq_len is None:
            return v[:, ::-1]
        lens = _unwrap(seq_len).reshape(-1).astype(jnp.int32)

        def rev_row(row, n):
            idx = jnp.where(jnp.arange(row.shape[0]) < n,
                            n - 1 - jnp.arange(row.shape[0]),
                            jnp.arange(row.shape[0]))
            return row[idx]

        return jax.vmap(rev_row)(v, lens)

    return apply_op(_f, (x,), name="sequence_reverse")


class StaticRNN:
    """Ref static/nn/control_flow.py StaticRNN — a recurrent step builder.

    The reference RECORDS ops appended inside `with rnn.step():` into a
    ProgramDesc block and replays them per timestep — exactly the legacy
    graph mechanism this build does not rebuild (SURVEY §7.4).  The
    TPU-native form is functional: pass the step as a function and it runs
    under ONE lax.scan:

        out = StaticRNN.run(step_fn, x, h0)
        # step_fn(x_t, h) -> (out_t, new_h);  x: [B, T, D] -> out: [B, T, H]
    """

    def step(self):
        raise NotImplementedError(
            "StaticRNN op-recording replays a ProgramDesc block — the legacy "
            "graph path (SURVEY §7.4). Use the functional form: "
            "StaticRNN.run(step_fn, x, init_states), which compiles the "
            "recurrence as one lax.scan.")

    step_input = memory = update_memory = step_output = output = step
    __call__ = step

    @staticmethod
    def run(step_fn, x, init_states):
        """Scan `step_fn(x_t, *states) -> (out_t, *new_states)` over the
        time axis of x [B, T, D]; returns outputs stacked [B, T, ...]."""
        inits = init_states if isinstance(init_states, (list, tuple)) else [init_states]

        def _f(v, *st):
            def body(carry, xt):
                out = step_fn(Tensor(xt), *[Tensor(c) for c in carry])
                out = out if isinstance(out, (list, tuple)) else (out, out)
                o, *new = out
                return tuple(_unwrap(n) for n in new), _unwrap(o)

            _, ys = jax.lax.scan(body, st, jnp.moveaxis(v, 0, 1))
            return jnp.moveaxis(ys, 0, 1)

        return apply_op(_f, (x, *inits), name="static_rnn")
