"""A real static-graph Program: record-then-replay over the apply_op spine.

Ref: python/paddle/fluid/framework.py (Program/Block capture via
program_guard), python/paddle/fluid/executor.py:1104 (Executor.run with
feed/fetch_list).  The reference records ProgramDesc protos op-by-op as
layer builders execute under `program_guard`, then an interpreter executes
the proto graph.

TPU-native translation: every op already funnels through ONE dispatch point
(`tensor.apply_op`), so static capture is a tape of (pure_fn, arg_refs)
nodes recorded WHILE the builders execute eagerly on placeholder values
(shape propagation and python-level branching behave exactly as at trace
time).  `Executor.run` replays the tape inside `jax.jit` against the fed
arrays — the "program interpreter" is XLA itself.  `optimizer.minimize`
under capture records a training objective instead of stepping eagerly;
the compiled replay then runs forward + jax.grad + the optimizer's
functional update (`_apply_update`) as one XLA program, reusing the exact
update math of the dygraph TrainStep.

Supported: the reference's canonical static workflow — program_guard
capture, per-batch exe.run(feed/fetch), minimize, clone(for_test=True),
save/load_inference_model.  Host-side buffer mutations (e.g. BatchNorm
running-stat writes via `buffer.set_value(new_val)`) ARE captured:
`_record_state_write` promotes the mutation to program state on the
`_state_writes` tape, the compiled replay emits the written value as an
extra output, and `Executor.run` rebinds the live buffer after each step —
so static BN training matches dygraph exactly (BN-parity test in
test_static_program.py).  Not captured: in-place rebinding of a tensor
that is not program state (plain Python variables reassigned mid-capture).
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ..tensor import tensor as _tensor_mod
from ..tensor.tensor import Tensor

__all__ = ["Program", "program_guard", "default_main_program",
           "default_startup_program", "static_data", "capture_active",
           "current_program"]


class _Node:
    __slots__ = ("fn", "kwargs", "in_refs", "out_ids", "out_is_tuple", "name")

    def __init__(self, fn, kwargs, in_refs, out_ids, out_is_tuple, name):
        self.fn = fn
        self.kwargs = kwargs
        self.in_refs = in_refs
        self.out_ids = out_ids
        self.out_is_tuple = out_is_tuple
        self.name = name


class Program:
    """An ordered op tape + feed/param leaves (ref framework.py Program)."""

    def __init__(self):
        self._nodes: list[_Node] = []
        self._next_id = 0
        self._feeds: dict[str, tuple[int, tuple, str]] = {}  # name -> (sym, shape, dtype)
        self._lives: list[Tensor] = []       # external tensors read at run time
        self._live_ids: dict[int, int] = {}  # id(tensor) -> index in _lives
        self._objective = None               # (loss_sym, optimizer)
        self._opt_state = None
        self._compiled: dict = {}
        # buffer mutations promoted to program state (ref batch_norm_op.cc
        # MeanOut/VarianceOut): live index -> sym of the latest recorded write;
        # the compiled train step outputs these and run() rebinds the buffers
        self._state_writes: dict[int, int] = {}
        self.random_seed = None

    # ---- capture ----------------------------------------------------------

    def _new_sym(self):
        i = self._next_id
        self._next_id += 1
        return i

    def _add_feed(self, name, shape, dtype):
        sym = self._new_sym()
        self._feeds[name] = (sym, tuple(shape), str(dtype))
        # placeholder value: builders run eagerly on it for shape propagation
        concrete = tuple(1 if (d is None or d < 0) else int(d) for d in shape)
        t = Tensor(jnp.zeros(concrete, jnp.dtype(dtype)))
        t.stop_gradient = True
        t._st_sym = (self, sym)
        t.name = name
        return t

    def _ref_of(self, a):
        """Classify one op argument for replay."""
        if isinstance(a, Tensor):
            sym = getattr(a, "_st_sym", None)
            if sym is not None:
                if sym[0] is self or sym[0]._nodes is self._nodes:
                    return ("sym", sym[1])
                # A var built under a DIFFERENT program_guard: capturing it as
                # a "live" leaf would silently bake in its build-time
                # placeholder value (zeros).  The reference errors on
                # cross-program variable use (fluid/framework.py Operator
                # input checks); so do we.
                raise ValueError(
                    f"static: tensor '{getattr(a, 'name', '?')}' was built "
                    "under a different Program and cannot be used here — "
                    "rebuild it inside this program_guard")
            j = self._live_ids.get(id(a))
            if j is None:
                j = len(self._lives)
                self._lives.append(a)
                self._live_ids[id(a)] = j
            w = self._state_writes.get(j)
            if w is not None:
                # the buffer was already written in this program: later reads
                # see the written value (Python read-after-write semantics)
                return ("sym", w)
            return ("live", j)
        return ("const", a)

    def _record(self, fn, args, kwargs, out, name):
        in_refs = [self._ref_of(a) for a in args]
        outs = out if isinstance(out, (tuple, list)) else (out,)
        out_ids = []
        for o in outs:
            sym = self._new_sym()
            out_ids.append(sym)
            if isinstance(o, Tensor):
                o._st_sym = (self, sym)
        self._nodes.append(_Node(fn, dict(kwargs or {}), in_refs, out_ids,
                                 isinstance(out, (tuple, list)), name))

    def _record_state_write(self, target, value):
        """set_value(captured_tensor) during capture: promote the mutation to
        program state instead of baking the build-time placeholder value (the
        analog of the reference's in-graph MeanOut/VarianceOut outputs,
        fluid/operators/batch_norm_op.cc).  Returns True when recorded (the
        caller then skips the eager rebind so the buffer keeps its
        pre-capture value as the step-1 input)."""
        sym = getattr(value, "_st_sym", None)
        if sym is None or sym[0]._nodes is not self._nodes:
            return False
        j = self._live_ids.get(id(target))
        if j is None:
            j = len(self._lives)
            self._lives.append(target)
            self._live_ids[id(target)] = j
        self._state_writes[j] = sym[1]
        return True

    def _set_objective(self, loss, optimizer):
        sym = getattr(loss, "_st_sym", None)
        if sym is None or sym[0]._nodes is not self._nodes:
            raise ValueError(
                "static: minimize() got a loss that was not built under this "
                "program_guard — construct the loss inside the guarded block")
        self._objective = (sym[1], optimizer)

    # ---- replay -----------------------------------------------------------

    def _trainable_live_idx(self):
        return [j for j, t in enumerate(self._lives) if not t.stop_gradient]

    def _prune(self, target_syms):
        """The sub-tape producing `target_syms` (backward slice over the op
        list — the reference's Program pruning before execution, ref
        framework.py Program._prune).  Feeds that only feed pruned-away
        nodes become unnecessary, so e.g. save_inference_model([x], [logits])
        on a training program drops the loss/label subgraph."""
        needed: set = set(s for s in target_syms if not isinstance(s, tuple))
        keep = []
        for node in reversed(self._nodes):
            if any(o in needed for o in node.out_ids):
                keep.append(node)
                for kind, v in node.in_refs:
                    if kind == "sym":
                        needed.add(v)
        keep.reverse()
        return keep, needed

    def _replay(self, env, live_vals, nodes=None):
        """Execute the tape; env maps sym -> raw array (seeded with feeds and
        trainable overrides come in through live_vals)."""
        for node in (self._nodes if nodes is None else nodes):
            raws = []
            for kind, v in node.in_refs:
                if kind == "sym":
                    raws.append(env[v])
                elif kind == "live":
                    raws.append(live_vals[v])
                else:
                    raws.append(v._value if isinstance(v, Tensor) else v)
            o = node.fn(*raws, **node.kwargs)
            outs = o if node.out_is_tuple else (o,)
            for sym, val in zip(node.out_ids, outs):
                env[sym] = val
        return env

    def _resolve_fetch(self, fetch_list):
        syms = []
        for f in fetch_list or []:
            if isinstance(f, str):
                if f in self._feeds:
                    syms.append(self._feeds[f][0])
                    continue
                raise KeyError(f"fetch name '{f}' is not a feed of this program")
            sym = getattr(f, "_st_sym", None)
            # clones share the tape: a var built under the original resolves
            # in the clone too
            if sym is None or sym[0]._nodes is not self._nodes:
                # a live tensor (e.g. a parameter): fetch its current value
                j = self._live_ids.get(id(f))
                if j is None:
                    raise ValueError(
                        "fetch_list entry was not produced by this program")
                syms.append(("live", j))
                continue
            syms.append(sym[1])
        return tuple(syms)

    def run(self, feed=None, fetch_list=None):
        """One compiled step (ref executor.py:1104).  Training programs run
        forward+backward+update; inference programs run forward only."""
        feed = feed or {}
        feed_arrays = {}
        for name, (sym, shape, dtype) in self._feeds.items():
            if name not in feed:
                raise KeyError(f"missing feed '{name}'")
            feed_arrays[sym] = jnp.asarray(np.asarray(feed[name]),
                                           jnp.dtype(dtype))
        fetch_syms = self._resolve_fetch(fetch_list)
        shapes_key = tuple(sorted((s, v.shape) for s, v in feed_arrays.items()))
        key = (shapes_key, fetch_syms, self._objective is not None)

        if self._objective is not None:
            loss_sym, opt = self._objective
            tr_idx = self._trainable_live_idx()
            if self._opt_state is None:
                self._opt_state = {j: opt._init_state(self._lives[j])
                                   for j in tr_idx}
            if key not in self._compiled:
                self._compiled[key] = self._compile_train(
                    loss_sym, opt, tr_idx, fetch_syms)
            live_vals = [t._value for t in self._lives]
            lr = jnp.asarray(opt.get_lr(), jnp.float32)
            fetched, new_train, new_opt, new_state = self._compiled[key](
                feed_arrays, live_vals, self._opt_state, lr)
            for j, v in new_train.items():
                self._lives[j]._rebind(v)
            for j, v in new_state.items():
                self._lives[j]._rebind(v)
            self._opt_state = new_opt
            opt._step_count += 1
        else:
            if key not in self._compiled:
                self._compiled[key] = self._compile_infer(fetch_syms)
            live_vals = [t._value for t in self._lives]
            fetched, new_state = self._compiled[key](feed_arrays, live_vals)
            for j, v in new_state.items():
                self._lives[j]._rebind(v)
        return [np.asarray(f) for f in fetched]

    def _compile_infer(self, fetch_syms):
        writes = dict(self._state_writes)
        nodes, _ = self._prune(tuple(fetch_syms) + tuple(writes.values()))

        def fn(feed_arrays, live_vals):
            env = dict(feed_arrays)
            self._replay(env, live_vals, nodes)
            fetched = tuple(live_vals[s[1]] if isinstance(s, tuple) else env[s]
                            for s in fetch_syms)
            return fetched, {j: env[s] for j, s in writes.items()}

        return jax.jit(fn)

    def _compile_train(self, loss_sym, opt, tr_idx, fetch_syms):
        # per-param decay specs are static python values — close over them
        decays = {j: opt._param_decay_coeff(self._lives[j]) for j in tr_idx}
        writes = dict(self._state_writes)

        nodes, _ = self._prune(tuple(fetch_syms) + (loss_sym,)
                               + tuple(writes.values()))

        def fn(feed_arrays, live_vals, opt_state, lr):
            def loss_of(train_vals):
                lv = list(live_vals)
                for j, v in train_vals.items():
                    lv[j] = v
                env = dict(feed_arrays)
                self._replay(env, lv, nodes)
                return env[loss_sym].astype(jnp.float32), env

            train_vals = {j: live_vals[j] for j in tr_idx}
            (loss, env), grads = jax.value_and_grad(loss_of, has_aux=True)(train_vals)
            clipped = opt._clipped_grads([(j, g) for j, g in grads.items()])
            new_train, new_opt = {}, {}
            for j, g in clipped:
                new_train[j], new_opt[j] = opt._apply_update(
                    train_vals[j], g, opt_state[j], lr, decays[j])
            fetched = tuple(
                live_vals[s[1]] if isinstance(s, tuple) else env[s]
                for s in fetch_syms)
            # buffer-state outputs (BN running stats): jax.lax.stop_gradient
            # is unnecessary — grads flow only to train_vals
            new_state = {j: env[s] for j, s in writes.items()}
            return fetched, new_train, new_opt, new_state

        return jax.jit(fn)

    # ---- reference Program surface ---------------------------------------

    def global_block(self):
        return self

    @property
    def ops(self):
        return self._nodes

    def all_parameters(self):
        return [t for t in self._lives if not t.stop_gradient]

    def list_vars(self):
        return list(self._lives)

    def clone(self, for_test=False):
        """Share the tape; a for_test clone drops the training objective
        (ref Program.clone pruning the backward ops)."""
        p = Program.__new__(Program)
        p._nodes = self._nodes
        p._next_id = self._next_id
        p._feeds = self._feeds
        p._lives = self._lives
        p._live_ids = self._live_ids
        p._objective = None if for_test else self._objective
        p._opt_state = None
        p._compiled = {}
        # a for_test clone must not update buffer state (BN running stats
        # stay frozen at evaluation — ref Program.clone is_test rewrite)
        p._state_writes = {} if for_test else dict(self._state_writes)
        p.random_seed = self.random_seed
        return p


# --------------------------------------------------------------- guard state

_MAIN = Program()
_STARTUP = Program()
_stack: list[tuple[Program, Program]] = []


def default_main_program():
    return _stack[-1][0] if _stack else _MAIN


def default_startup_program():
    return _stack[-1][1] if _stack else _STARTUP


# static-mode flag lives here so program_guard.__exit__ can restore
# default-main-program capture while enable_static() is in effect
_static_mode_on = False


class program_guard:
    def __init__(self, main_program=None, startup_program=None):
        self.main = main_program if main_program is not None else Program()
        self.startup = startup_program if startup_program is not None else Program()

    def __enter__(self):
        _stack.append((self.main, self.startup))
        _activate(self.main)
        return self.main

    def __exit__(self, *exc):
        _stack.pop()
        if _stack:
            _activate(_stack[-1][0])
        else:
            _activate(default_main_program() if _static_mode_on else None)
        return False


_active: Program | None = None


def _capture_hook(fn, args, kwargs, out, name):
    if _active is not None:
        _active._record(fn, args, kwargs, out, name)


def _state_write_hook(target, value):
    if _active is not None:
        return _active._record_state_write(target, value)
    return False


def _activate(program):
    global _active
    _active = program
    _tensor_mod._static_capture_hook = _capture_hook if program is not None else None
    _tensor_mod._static_state_write_hook = _state_write_hook if program is not None else None
    _tensor_mod._static_active_program = program


def capture_active():
    return _active is not None


def current_program():
    return _active


def static_data(name, shape, dtype="float32"):
    """`static.data` under an active capture: a feed placeholder node."""
    prog = _active if _active is not None else default_main_program()
    if _active is None:
        # data() outside program_guard attaches to the default main program
        # and activates capture for it (reference scripts rely on this)
        _activate(prog)
    return prog._add_feed(name, shape, dtype)
