"""paddle.static parity shims (ref: python/paddle/static/__init__.py).

The reference's static graph (ProgramDesc + Executor, §3.3 of SURVEY.md) has no
separate existence on TPU: a "static program" IS a jitted function.  We keep the
`enable_static`/`Executor`-shaped surface for script compatibility: `data` declares
InputSpec-like placeholders, `Executor.run` executes a to_static-compiled callable.
Control-flow ops (cond/while_loop/case) are real: they map to lax primitives and work
inside to_static traces — the TPU equivalent of conditional_block_op/while_op
(ref operators/controlflow/conditional_block_op.cc, while_op.cc).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..tensor.tensor import Tensor, apply_op
from ..jit import InputSpec  # noqa: F401
from .program import (  # noqa: F401
    Program,
    program_guard,
    default_main_program,
    default_startup_program,
    _activate,
    capture_active,
    current_program,
    static_data,
)

_static_mode = False


def enable_static():
    """Enter static-graph mode: ops now RECORD onto the default main program
    while executing eagerly on placeholder values (ref enable_static switches
    the global tracer into ProgramDesc capture)."""
    global _static_mode
    from . import program as _prog_mod

    _static_mode = True
    _prog_mod._static_mode_on = True
    _activate(default_main_program())


def disable_static():
    global _static_mode
    from . import program as _prog_mod

    _static_mode = False
    _prog_mod._static_mode_on = False
    _activate(None)


def in_static_mode():
    return _static_mode


def data(name, shape, dtype="float32", lod_level=0):
    """A feed placeholder.  Under static mode / program_guard it becomes a
    feed node of the current Program; otherwise it degrades to an InputSpec
    for the to_static path."""
    if _static_mode or capture_active():
        return static_data(name, shape, dtype)
    return InputSpec(shape, dtype, name)


class _LoadedProgram:
    """The triple returned by load_inference_model, runnable by Executor."""

    def __init__(self, layer, feed_names, fetch_count):
        self.layer = layer
        self.feed_names = list(feed_names)
        self.fetch_count = fetch_count


class Executor:
    """Compile-and-run front end (ref executor.py:1104 Executor.run)."""

    def __init__(self, place=None):
        self.place = place

    def run(self, program=None, feed=None, fetch_list=None, **kwargs):
        import numpy as _np2

        if isinstance(program, CompiledProgram):
            program = program.program
        if callable(program) and not isinstance(program, Program):
            out = program(**(feed or {}))
            return out if isinstance(out, (list, tuple)) else [out]
        if isinstance(program, _LoadedProgram):
            args = [Tensor(jnp.asarray(_np2.asarray((feed or {})[n])))
                    for n in program.feed_names]
            out = program.layer(*args)
            outs = out if isinstance(out, (tuple, list)) else (out,)
            return [_np2.asarray(o._value) for o in outs]
        if program is None:
            program = default_main_program()
        if not isinstance(program, Program):
            return []
        if not program._nodes and not (fetch_list or []):
            return []  # e.g. exe.run(startup): params initialize eagerly
        return program.run(feed=feed, fetch_list=fetch_list)


class nn:
    """Compiled control flow — the dy2static control-flow capture analog."""

    @staticmethod
    def cond(pred, true_fn, false_fn, name=None):
        def _f(p):
            return jax.lax.cond(jnp.all(p), lambda: _raw(true_fn()), lambda: _raw(false_fn()))

        return apply_op(_f, (pred,), name="cond")

    @staticmethod
    def while_loop(cond, body, loop_vars, name=None):
        raws = [v._value if isinstance(v, Tensor) else v for v in loop_vars]

        def _f(*vs):
            def c(vs_):
                r = cond(*[Tensor(v, stop_gradient=True) for v in vs_])
                return jnp.all(r._value if isinstance(r, Tensor) else r)

            def b(vs_):
                out = body(*[Tensor(v, stop_gradient=True) for v in vs_])
                out = out if isinstance(out, (list, tuple)) else [out]
                return tuple(o._value if isinstance(o, Tensor) else o for o in out)

            return jax.lax.while_loop(c, b, tuple(vs))

        return apply_op(_f, tuple(loop_vars), name="while_loop")

    @staticmethod
    def case(pred_fn_pairs, default=None, name=None):
        for pred, fn in pred_fn_pairs:
            v = pred.item() if isinstance(pred, Tensor) else bool(pred)
            if v:
                return fn()
        return default() if default is not None else None

    @staticmethod
    def switch_case(branch_index, branch_fns, default=None, name=None):
        idx = int(branch_index.item()) if isinstance(branch_index, Tensor) else int(branch_index)
        fns = dict(branch_fns) if isinstance(branch_fns, (list, tuple)) else branch_fns
        return fns.get(idx, default or (lambda: None))()


def _raw(x):
    if isinstance(x, (tuple, list)):
        return tuple(_raw(i) for i in x)
    return x._value if isinstance(x, Tensor) else x


def save(program, model_path, **kwargs):
    """Persist a Program's parameter values (ref static/io.py save)."""
    import pickle

    import numpy as _np2

    state = {f"param_{j}": _np2.asarray(t._value)
             for j, t in enumerate(program._lives) if not t.stop_gradient}
    with open(model_path + ".pdparams", "wb") as f:
        pickle.dump(state, f)


def load(program, model_path, **kwargs):
    """Restore parameter values saved by static.save into the Program's
    live parameter leaves (matched positionally, the save-time order)."""
    import pickle

    with open(model_path + ".pdparams", "rb") as f:
        state = pickle.load(f)
    for j, t in enumerate(program._lives):
        if not t.stop_gradient and f"param_{j}" in state:
            t._rebind(jnp.asarray(state[f"param_{j}"]))


def save_inference_model(path_prefix, feed_vars, fetch_vars, executor, *,
                         program=None, **kwargs):
    """AOT-export the captured forward graph (ref static/io.py
    save_inference_model -> serialized inference ProgramDesc; here the
    artifact is jax.export StableHLO in the jit.save format, so
    paddle.jit.load and inference.Predictor both load it)."""
    import os
    import pickle

    import numpy as _np2

    feed_vars = feed_vars if isinstance(feed_vars, (list, tuple)) else [feed_vars]
    fetch_vars = fetch_vars if isinstance(fetch_vars, (list, tuple)) else [fetch_vars]
    prog = program
    if prog is None:
        sym = getattr(fetch_vars[0], "_st_sym", None)
        if sym is None:
            raise ValueError("fetch_vars were not built under a static Program")
        prog = sym[0]
    from jax import export as jax_export

    feed_names, feed_specs = [], []
    for fv in feed_vars:
        name = getattr(fv, "name", None)
        if name not in prog._feeds:
            raise ValueError(f"feed var {name!r} is not a static.data of this program")
        sym_id, shape, dtype = prog._feeds[name]
        feed_names.append(name)
        # None dims export shape-polymorphic (one shared batch symbol 'b' —
        # the jax.export analog of the reference's -1 feed dims)
        spec = tuple("b" if (d is None or (isinstance(d, int) and d < 0)) else int(d)
                     for d in shape)
        feed_specs.append((sym_id, spec, dtype))
    fetch_syms = prog._resolve_fetch(fetch_vars)

    lives = prog._lives
    params = {f"v{j}": lives[j]._value for j in range(len(lives))}

    nodes, _ = prog._prune(fetch_syms)  # drop loss/label subgraphs the
    # exported forward does not need (their feeds are not inputs here)

    def infer_fn(params, buffers, *feeds):
        live_vals = [params[f"v{j}"] for j in range(len(lives))]
        env = {sym_id: f for (sym_id, _, _), f in zip(feed_specs, feeds)}
        prog._replay(env, live_vals, nodes)
        return tuple(live_vals[s[1]] if isinstance(s, tuple) else env[s]
                     for s in fetch_syms)

    shapes = []
    for (_, spec, d) in feed_specs:
        if any(isinstance(s, str) for s in spec):
            dims = jax_export.symbolic_shape(
                ",".join(str(s) for s in spec))
            shapes.append(jax.ShapeDtypeStruct(dims, jnp.dtype(d)))
        else:
            shapes.append(jax.ShapeDtypeStruct(spec, jnp.dtype(d)))
    exported = jax_export.export(jax.jit(infer_fn))(params, {}, *shapes)
    os.makedirs(os.path.dirname(path_prefix) or ".", exist_ok=True)
    with open(path_prefix + ".pdmodel", "wb") as f:
        f.write(exported.serialize())
    with open(path_prefix + ".pdiparams", "wb") as f:
        pickle.dump({k: _np2.asarray(v) for k, v in params.items()}, f)
    with open(path_prefix + ".pdiparams.info", "wb") as f:
        pickle.dump({
            "param_keys": sorted(params, key=lambda k: int(k[1:])),
            "buffer_keys": [],
            "inputs": [{"name": n,
                        "shape": [None if isinstance(s, str) else s for s in c],
                        "dtype": d}
                       for n, (_, c, d) in zip(feed_names, feed_specs)],
            "feed_names": feed_names,
        }, f)


def load_inference_model(path_prefix, executor, **kwargs):
    """Returns [program, feed_names, fetch_count-sized target list] (ref
    static/io.py load_inference_model); the program runs under
    Executor.run(program, feed=..., fetch_list=None)."""
    import pickle

    from ..jit import load as _jit_load

    layer = _jit_load(path_prefix)
    info = {}
    try:
        with open(path_prefix + ".pdiparams.info", "rb") as f:
            info = pickle.load(f)
    except OSError:
        pass
    feed_names = info.get("feed_names") or [
        i["name"] for i in info.get("inputs") or []]
    prog = _LoadedProgram(layer, feed_names, None)
    return [prog, feed_names, []]


# --------------------------------------------------------------- shim surface
# The legacy static-graph workflow (Program/Scope machinery) has no separate
# existence on TPU (SURVEY §7.1: a "static program" IS a jitted function).
# These keep reference training scripts importable; graph-construction
# primitives map onto their eager/jit equivalents or raise with guidance.
import contextlib as _ctx

import numpy as _np

from ..tensor.tensor import Tensor as Variable  # noqa: F401  (alias)


@_ctx.contextmanager
def scope_guard(scope=None):
    yield


@_ctx.contextmanager
def name_scope(prefix=None):
    yield


@_ctx.contextmanager
def device_guard(device=None):
    yield


@_ctx.contextmanager
def ipu_shard_guard(index=-1, stage=-1):
    yield


def set_ipu_shard(layer, index=-1, stage=-1):
    return layer


class _Scope:
    def find_var(self, name):
        return None

    def var(self, name):
        return None


def global_scope():
    return _Scope()


def cpu_places(device_count=None):
    import os

    from ..core.device import CPUPlace

    n = device_count or int(os.environ.get("CPU_NUM", 1))
    return [CPUPlace(i) for i in range(n)]


def cuda_places(device_ids=None):
    return []  # no CUDA devices on the TPU build


def xpu_places(device_ids=None):
    return []


def npu_places(device_ids=None):
    return []


def mlu_places(device_ids=None):
    return []


def create_parameter(shape, dtype, name=None, attr=None, is_bias=False,
                     default_initializer=None):
    from ..nn.layer.layers import Layer

    holder = Layer()
    return holder.create_parameter(list(shape), attr=attr, dtype=dtype,
                                   is_bias=is_bias,
                                   default_initializer=default_initializer)


def create_global_var(shape, value, dtype, persistable=False, force_cpu=False,
                      name=None):
    t = Tensor(jnp.full(tuple(shape), value, dtype=jnp.dtype(dtype)))
    t.persistable = persistable
    return t


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    """Static-graph autodiff entry -> the eager paddle.grad."""
    from ..autograd.tape import grad as _grad

    return _grad(targets, inputs, grad_outputs=target_gradients)


def append_backward(loss, parameter_list=None, no_grad_set=None, callbacks=None):
    loss.backward()
    params = parameter_list or []
    return [(p, p.grad) for p in params]


def accuracy(input, label, k=1, correct=None, total=None):
    """Ref static/nn accuracy op."""
    from ..metric import accuracy as _acc

    return _acc(input, label, k=k)


def auc(input, label, curve="ROC", num_thresholds=4095, **kw):
    from ..metric import Auc

    m = Auc(num_thresholds=num_thresholds)
    m.update(preds=_np.asarray(input._value), labels=_np.asarray(label._value))
    return Tensor(jnp.asarray(m.accumulate(), jnp.float32))


def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input=None):
    xs = x if isinstance(x, (list, tuple)) else [x]
    res = func(*xs)
    return res


def save_to_file(path, content):
    with open(path, "wb") as f:
        f.write(content)


def load_from_file(path):
    with open(path, "rb") as f:
        return f.read()


def save_program_state(*a, **k):
    raise NotImplementedError("use paddle.save(layer.state_dict(), path)")


def load_program_state(state_path, var_list=None):
    from ..framework.io import load as _load

    return _load(state_path, return_numpy=True)


def set_program_state(program, state):
    raise NotImplementedError(
        "static Programs hold no state on the TPU build — load into the Layer "
        "with set_state_dict")


def serialize_program(*a, **k):
    raise NotImplementedError("use paddle.jit.save for deployable programs")


def deserialize_program(*a, **k):
    raise NotImplementedError("use paddle.jit.load")


def serialize_persistables(*a, **k):
    raise NotImplementedError("use paddle.save(layer.state_dict(), path)")


def deserialize_persistables(*a, **k):
    raise NotImplementedError("use paddle.load")


def normalize_program(*a, **k):
    raise NotImplementedError("use paddle.jit.save for deployable programs")


def exponential_decay(learning_rate, decay_steps, decay_rate, staircase=False):
    """lr * decay_rate^(step/decay_steps); staircase floors the exponent."""
    from ..optimizer.lr import LambdaDecay

    def factor(step):
        e = step // decay_steps if staircase else step / decay_steps
        return decay_rate ** e

    return LambdaDecay(learning_rate, lr_lambda=factor)


def ctr_metric_bundle(*a, **k):
    raise NotImplementedError("parameter-server CTR metrics are out of scope")


def Print(input, first_n=-1, message=None, **kw):
    import jax as _jax

    _jax.debug.print((message or "") + "{x}", x=input._value)
    return input


class BuildStrategy:
    """Graph-build knobs (XLA owns fusion/memory on TPU; kept for scripts)."""

    def __init__(self):
        self.enable_inplace = True
        self.memory_optimize = True
        self.fuse_elewise_add_act_ops = True
        self.fuse_bn_act_ops = True
        self.enable_auto_fusion = True
        self.reduce_strategy = None
        self.gradient_scale_strategy = None


class ExecutionStrategy:
    def __init__(self):
        self.num_threads = 1
        self.num_iteration_per_drop_scope = 10


class CompiledProgram:
    """Ref compiler.py CompiledProgram — on TPU compilation IS jit; this wraps
    the callable unchanged (Executor.run already handles callables)."""

    def __init__(self, program, build_strategy=None):
        self.program = program
        self.build_strategy = build_strategy or BuildStrategy()

    def with_data_parallel(self, loss_name=None, build_strategy=None,
                           exec_strategy=None, places=None):
        return self

    def __call__(self, *args, **kwargs):
        if callable(self.program):
            return self.program(*args, **kwargs)
        raise TypeError("CompiledProgram wraps a non-callable placeholder")


class ParallelExecutor:
    def __init__(self, use_cuda=False, loss_name=None, main_program=None, **kw):
        raise NotImplementedError(
            "ParallelExecutor is superseded: jit/pjit with NamedShardings is "
            "the multi-device execution path (see ShardedTrainStep)")


class WeightNormParamAttr:
    def __init__(self, dim=None, name=None, **kw):
        self.dim = dim
        self.name = name


class ExponentialMovingAverage:
    """Ref static/ema.py — EMA of trainable parameters with apply/restore."""

    def __init__(self, decay=0.999, thres_steps=None, name=None):
        self.decay = float(decay)
        self._ema: dict[int, object] = {}
        self._backup: dict[int, object] = {}
        self._params = []
        self._step = 0

    def _track(self, params):
        for p in params:
            if id(p) not in self._ema:
                self._params.append(p)
                self._ema[id(p)] = jnp.asarray(p._value)

    def update(self, parameters=None):
        from ..nn.layer.layers import Layer

        if parameters is None:
            params = self._params
        elif isinstance(parameters, Layer):
            params = [p for p in parameters.parameters() if not p.stop_gradient]
        else:
            params = list(parameters)
        self._track(params)
        self._step += 1
        d = min(self.decay, (1 + self._step) / (10 + self._step))
        for p in params:
            self._ema[id(p)] = d * self._ema[id(p)] + (1 - d) * p._value

    @_ctx.contextmanager
    def apply(self, executor=None, need_restore=True):
        for p in self._params:
            self._backup[id(p)] = p._value
            p._rebind(self._ema[id(p)])
        try:
            yield
        finally:
            if need_restore:
                self.restore()

    def restore(self, executor=None):
        for p in self._params:
            if id(p) in self._backup:
                p._rebind(self._backup.pop(id(p)))


class IpuStrategy:
    def __init__(self, *a, **k):
        raise NotImplementedError("IPU support is out of scope for the TPU build")


class IpuCompiledProgram:
    def __init__(self, *a, **k):
        raise NotImplementedError("IPU support is out of scope for the TPU build")


# paddle.static.amp — the static-graph mixed-precision surface maps onto the
# same autocast/GradScaler machinery (ref static/amp re-exports
# fluid/contrib/mixed_precision; on TPU one amp implementation serves both
# eager and traced programs since @to_static traces through autocast).
# `decorate` keeps the STATIC signature (optimizer-first), unlike eager
# amp.decorate(models, ...).
import types as _types  # noqa: E402

from .. import amp as _amp_mod  # noqa: E402

amp = _types.ModuleType("paddle_tpu.static.amp")
amp.__dict__.update({k: v for k, v in _amp_mod.__dict__.items()
                     if not k.startswith("_")})


def _static_amp_decorate(optimizer, amp_lists=None, init_loss_scaling=2.0 ** 15,
                         incr_every_n_steps=1000, decr_every_n_nan_or_inf=2,
                         incr_ratio=2.0, decr_ratio=0.8,
                         use_dynamic_loss_scaling=True, use_pure_fp16=False,
                         use_fp16_guard=None):
    """Static-graph decorate (ref static/amp/decorator.py): wraps the optimizer
    so step() runs under autocast with a GradScaler.  Returns an object with
    the optimizer interface plus .amp_init (a no-op on TPU: bf16 needs no
    master-weight cast pass)."""
    scaler = _amp_mod.GradScaler(
        init_loss_scaling=init_loss_scaling,
        incr_every_n_steps=incr_every_n_steps,
        decr_every_n_nan_or_inf=decr_every_n_nan_or_inf,
        incr_ratio=incr_ratio, decr_ratio=decr_ratio,
        use_dynamic_loss_scaling=use_dynamic_loss_scaling)

    class _DecoratedOptimizer:
        def __init__(self, inner):
            self._inner = inner
            self._scaler = scaler
            self._level = "O2" if use_pure_fp16 else "O1"

        def __getattr__(self, name):
            return getattr(self._inner, name)

        def backward(self, loss, **kw):
            self._scaler.scale(loss).backward()
            return []

        def apply_gradients(self, params_grads=None):
            self._scaler.step(self._inner)
            self._scaler.update()

        def minimize(self, loss, startup_program=None, parameter_list=None,
                     no_grad_set=None):
            self.backward(loss)
            self.apply_gradients()
            return None, None

        def amp_init(self, place=None, scope=None, test_program=None,
                     use_fp16_test=False):
            pass

    return _DecoratedOptimizer(optimizer)


amp.decorate = _static_amp_decorate


# static.nn layer builders (name-keyed parameter cache; see nn_builders.py)
from . import nn_builders as _nnb  # noqa: E402

for _n in _nnb.__all__:
    setattr(nn, _n, staticmethod(getattr(_nnb, _n)))
del _n
