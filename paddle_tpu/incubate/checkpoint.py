"""Auto-checkpoint epoch ranges (ref:
fluid/incubate/checkpoint/auto_checkpoint.py:267,597 TrainEpochRange — an
epoch-range context that periodically snapshots training state keyed for job
restart; the reference wrote program+dataset position to HDFS).

TPU-native: state snapshots go through distributed.checkpoint (sharded save,
reshard-on-load), keyed by epoch.  On restart the range resumes from the last
saved epoch — the elastic manager's scale events use the same mechanism.
"""
from __future__ import annotations

import os

from ..distributed import checkpoint as _ckpt

__all__ = ["TrainEpochRange", "train_epoch_range"]


class TrainEpochRange:
    """for epoch in TrainEpochRange(30, path, model=m, optimizer=o): ...

    Resumes at `latest_saved_epoch + 1` when `path` holds a checkpoint, and
    saves model/optimizer (or train_step) state every `save_checkpoint_inter`
    epochs plus once at the end.
    """

    def __init__(self, max_epoch_num, path=None, name=None,
                 save_checkpoint_inter=1, model=None, optimizer=None,
                 train_step=None, keep=3):
        self.max_epoch_num = int(max_epoch_num)
        self.path = path or os.environ.get("PADDLE_TPU_CHECKPOINT_PATH") \
            or os.path.join(".", "auto_checkpoint", name or "default")
        self.inter = max(1, int(save_checkpoint_inter))
        # a train_step knows its model; either is enough to snapshot state
        self.model = model if model is not None else getattr(train_step, "model", None)
        self.optimizer = optimizer
        self.train_step = train_step
        self.manager = _ckpt.CheckpointManager(self.path, keep=keep)
        self._start = 0
        latest = self.manager.latest_step()
        if latest is not None and self.model is not None:
            meta = _ckpt.load_train_state(self.path, self.model,
                                          optimizer=self.optimizer,
                                          train_step=self.train_step)
            # the step recorded IN the restored state is authoritative: a
            # corrupt newest checkpoint makes the loader fall back to an
            # older one, and `latest` (read pre-load) would then resume too
            # far ahead, silently skipping epochs.  (`is not None`, not
            # truthiness — epoch 0 is falsy.)
            step = meta.get("step")
            self._start = (int(step) if step is not None else int(latest)) + 1

    @property
    def restored_epoch(self):
        """Last completed (saved) epoch, or -1 on a fresh start."""
        return self._start - 1

    def _save(self, epoch):
        if self.model is None:
            return
        import jax

        _ckpt.save_train_state(self.path, self.model, optimizer=self.optimizer,
                               train_step=self.train_step, step=epoch)
        if jax.process_index() == 0:   # retention is proc-0's job (see
            self.manager._gc()         # CheckpointManager.save)

    def __iter__(self):
        epoch = self._start
        for epoch in range(self._start, self.max_epoch_num):
            yield epoch
            if (epoch + 1) % self.inter == 0:
                self._save(epoch)
        if self._start < self.max_epoch_num and (epoch + 1) % self.inter != 0:
            self._save(epoch)


def train_epoch_range(max_epoch_num, save_checkpoint_inter=1, **kwargs):
    """Ref auto_checkpoint.py train_epoch_range generator."""
    return TrainEpochRange(max_epoch_num,
                           save_checkpoint_inter=save_checkpoint_inter, **kwargs)
