"""paddle.incubate.autotune (ref: python/paddle/incubate/autotune.py:75
set_config over the phi autotune cache, paddle/phi/kernels/autotune/).

The reference autotunes cuDNN algorithm choice per op signature.  The TPU
analog: XLA already autotunes fusions, so the tunable surface here is the
Pallas kernel launch configuration — flash attention block sizes are measured
per (seq_q, seq_k, head_dim) signature on first use and cached, exactly the
phi AlgorithmsCache pattern (kernels/autotune/cache.h).
"""
from __future__ import annotations

import json
import time

__all__ = ["set_config", "enable_autotune", "disable_autotune",
           "flash_attention_block_cache", "tune_flash_attention"]

_CONFIG = {
    "kernel": {"enable": False, "tuning_range": [1, 10]},
    "layout": {"enable": False},
    "dataloader": {"enable": False},
}
# (Sq, Sk, D, causal) -> (bq, bk); measured on first use when enabled
flash_attention_block_cache: dict = {}


def set_config(config=None):
    """Ref autotune.py:75 — dict or JSON file path with kernel/layout/
    dataloader sections."""
    global _CONFIG
    if config is None:
        for sec in _CONFIG.values():
            sec["enable"] = True
        return
    if isinstance(config, str):
        with open(config) as f:
            config = json.load(f)
    for key, val in config.items():
        if key not in _CONFIG:
            raise ValueError(f"unknown autotune section {key!r} "
                             f"(known: {sorted(_CONFIG)})")
        _CONFIG[key].update(val)


def enable_autotune():
    _CONFIG["kernel"]["enable"] = True


def disable_autotune():
    _CONFIG["kernel"]["enable"] = False


def kernel_autotune_enabled():
    return _CONFIG["kernel"]["enable"]


def measure_callable(fn, steps=3, warmup=1):
    """Best-of-`steps` wall time of `fn()` after `warmup` calls — the shared
    measuring primitive behind kernel autotune and the auto-parallel
    planner's measured rerank (ref tuner/profiler.py measuring candidates)."""
    for _ in range(warmup):
        fn()
    best = float("inf")
    for _ in range(steps):
        t0 = time.perf_counter()
        fn()
        best = min(best, time.perf_counter() - t0)
    return best


def tune_flash_attention(q, k, v, causal, scale, candidates=None, steps=20):
    """Measure candidate (block_q, block_k) configs for this attention
    signature and cache the fastest (phi AlgorithmsCache analog).

    Returns the chosen (bq, bk).  Called by ops.flash_attention when kernel
    autotune is enabled; measurement uses the real kernel on the attached
    backend and blocks on ONE scalar readback per window.  `steps` kernels
    run per window so candidate deltas dwarf the tunneled chip's ~100 ms
    per-sync latency (at steps=3 every candidate measured ~= the sync
    constant and the choice was effectively random)."""
    import importlib

    import jax
    import jax.numpy as jnp

    # the ops package re-exports the flash_attention FUNCTION under the same
    # name as its module; load the module explicitly
    fa = importlib.import_module("paddle_tpu.ops.flash_attention")

    Sq, Sk, D = q.shape[-2], k.shape[-2], q.shape[-1]
    key = (Sq, Sk, D, bool(causal))
    if key in flash_attention_block_cache:
        return flash_attention_block_cache[key]

    if candidates is None:
        opts = [b for b in (128, 256, 512) if Sq % b == 0 and Sk % b == 0]
        candidates = [(b, b) for b in opts] or [(fa._auto_block(Sq),
                                                fa._auto_block(Sk))]
    if len(candidates) == 1:
        # nothing to choose between — skip the warmup compile + timed sync
        flash_attention_block_cache[key] = candidates[0]
        return candidates[0]
    best, best_t, last_err = None, float("inf"), None
    for bq, bk in candidates:
        try:
            f = jax.jit(lambda a, b_, c: fa._flash_bhsd(
                a, b_, c, causal, scale, bq, bk, fa._interpret_default()))
            out = f(q, k, v)
            float(jnp.sum(out[..., :1]).astype(jnp.float32))  # compile+sync
            t0 = time.perf_counter()
            for _ in range(steps):
                out = f(q, k, v)
            float(jnp.sum(out[..., :1]).astype(jnp.float32))
            dt = time.perf_counter() - t0
            if dt < best_t:
                best, best_t = (bq, bk), dt
        except Exception as e:
            last_err = e
            continue
    if best is None:
        raise RuntimeError(
            f"flash-attention autotune: every candidate failed for signature "
            f"{key}; last error: {last_err!r}")
    flash_attention_block_cache[key] = best
    return best
