"""ASP — automatic structured (n:m) sparsity (ref:
python/paddle/fluid/contrib/sparsity/asp.py:39,125,214,300 + utils.py mask
algorithms).

Workflow parity with the reference: `prune_model` computes n:m masks for
supported weights and zeroes them; `decorate(optimizer)` wraps the optimizer
so masks are re-applied after every step (pruned weights stay zero through
training).  On TPU the masked weights still run on the dense MXU — the win is
model-size/regularization parity, and the masks are the artifact a
sparsity-aware deployment consumes.
"""
from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from ..tensor.tensor import Tensor

__all__ = ["set_excluded_layers", "reset_excluded_layers", "decorate",
           "prune_model", "calculate_density", "check_sparsity"]

_EXCLUDED: set[str] = set()
# the mask lives ON the Parameter (attribute _asp_mask): id()-keyed registries
# can apply a dead parameter's mask to a new object reusing its address, and
# Tensor.__eq__ is elementwise so Tensors cannot key dicts


def set_excluded_layers(param_names, main_program=None):
    """Ref asp.py:39 — names (prefix match) whose weights are never pruned."""
    _EXCLUDED.update(param_names)


def reset_excluded_layers(main_program=None):
    """Ref asp.py:125."""
    _EXCLUDED.clear()


def _nm_mask_1d(w, n, m):
    """Keep the n largest-|w| entries in every group of m along the REDUCTION
    axis (ref sparsity/utils.py get_mask_1d; the reference transposes FC
    weights first — hardware 2:4 sparsity is along the contraction dim).
    Paddle Linear weights are [in, out], so groups run along axis 0."""
    wt = w.T                                     # [out, in]
    flat = wt.reshape(-1, m)
    order = np.argsort(np.abs(flat), axis=1)     # ascending
    mask = np.ones_like(flat, dtype=bool)
    drop = order[:, : m - n]
    rows = np.arange(flat.shape[0])[:, None]
    mask[rows, drop] = False
    return mask.reshape(wt.shape).T


def _prunable(name, p):
    if any(name.startswith(e) or e in name for e in _EXCLUDED):
        return False
    shape = tuple(p.shape)
    # 2-D weights with input (reduction) dim divisible by m; biases/norms excluded
    return len(shape) == 2 and "weight" in name.rsplit(".", 1)[-1]


def prune_model(model, n=2, m=4, mask_algo="mask_1d", with_mask=True):
    """Ref asp.py:300 — compute masks, zero the pruned weights, return masks."""
    if mask_algo not in ("mask_1d", "mask_2d_greedy", "mask_2d_best"):
        raise ValueError(f"unknown mask_algo {mask_algo!r}")
    masks = {}
    for name, p in model.named_parameters():
        if not _prunable(name, p):
            continue
        w = np.asarray(p._value)
        if w.shape[0] % m:       # reduction dim of [in, out] Linear weights
            continue
        mask = _nm_mask_1d(w, n, m)
        p._rebind(jnp.asarray(w * mask, dtype=p._value.dtype))
        p._asp_mask = jnp.asarray(mask, p._value.dtype)
        masks[name] = mask
    return masks


def calculate_density(x):
    arr = np.asarray(x._value if isinstance(x, Tensor) else x)
    return float(np.count_nonzero(arr)) / max(arr.size, 1)


def check_sparsity(x, n=2, m=4):
    """True iff every m-group along the reduction axis (axis 0 of a 2-D
    [in, out] weight) has <= n nonzeros."""
    arr = np.asarray(x._value if isinstance(x, Tensor) else x)
    if arr.ndim == 2:
        arr = arr.T
    groups = arr.reshape(-1, m)
    return bool((np.count_nonzero(groups, axis=1) <= n).all())


class ASPOptimizerWrapper:
    """Ref asp.py:214 OptimizerWithSparsityGuarantee: after every step,
    re-apply the masks so pruned weights stay exactly zero."""

    def __init__(self, optimizer):
        self._inner = optimizer

    def __getattr__(self, item):
        return getattr(self._inner, item)

    def _apply_masks(self):
        for p in self._inner._params():
            mask = getattr(p, "_asp_mask", None)
            if mask is not None:
                p._rebind(p._value * mask)

    def step(self):
        self._inner.step()
        self._apply_masks()

    def minimize(self, loss, *args, **kwargs):
        out = self._inner.minimize(loss, *args, **kwargs)
        self._apply_masks()
        return out


def decorate(optimizer):
    """Ref asp.py:214."""
    return ASPOptimizerWrapper(optimizer)
