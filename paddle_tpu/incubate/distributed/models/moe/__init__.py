"""Path-parity alias: the reference exposes MoELayer at
paddle.incubate.distributed.models.moe (moe_layer.py:244); the implementation
lives in paddle_tpu/incubate/moe.py."""
from ....moe import MoELayer  # noqa: F401
