"""paddle.incubate.optimizer — LookAhead and ModelAverage.

Ref: python/paddle/incubate/optimizer/lookahead.py (LookAhead:48),
modelaverage.py (ModelAverage:29, over the average_accumulates op).
"""
from __future__ import annotations

import contextlib

import jax.numpy as jnp

from ..autograd import tape
from ..tensor.tensor import Tensor

__all__ = ["LookAhead", "ModelAverage"]


class LookAhead:
    """k fast steps with the inner optimizer, then pull the slow weights
    alpha of the way toward the fast ones and restart from there.

    slow = slow + alpha * (fast - slow);  fast = slow   (every k steps)
    """

    def __init__(self, inner_optimizer, alpha=0.5, k=5, name=None):
        if inner_optimizer is None:
            raise ValueError("inner_optimizer can not be None")
        if not 0.0 <= alpha <= 1.0:
            raise ValueError(f"alpha should be in [0, 1], got {alpha}")
        if k < 1:
            raise ValueError(f"k should be a positive integer, got {k}")
        self.inner_optimizer = inner_optimizer
        self.alpha = float(alpha)
        self.k = int(k)
        self._slow = None
        self._k_count = 0

    def _params(self):
        return self.inner_optimizer._params()

    @tape.no_grad()
    def step(self):
        if self._slow is None:
            self._slow = {id(p): p._value for p in self._params()}
        self.inner_optimizer.step()
        self._k_count += 1
        if self._k_count % self.k == 0:
            for p in self._params():
                slow = self._slow[id(p)]
                new_slow = slow + self.alpha * (p._value - slow)
                p._rebind(new_slow.astype(p._value.dtype))
                self._slow[id(p)] = new_slow

    def clear_grad(self, set_to_zero=False):
        self.inner_optimizer.clear_grad(set_to_zero)

    clear_gradients = clear_grad

    def get_lr(self):
        return self.inner_optimizer.get_lr()

    def set_lr(self, value):
        self.inner_optimizer.set_lr(value)

    def minimize(self, loss, startup_program=None, parameters=None, no_grad_set=None):
        loss.backward()
        self.step()
        return None, None

    def state_dict(self):
        sd = self.inner_optimizer.state_dict()
        sd["@LookAhead.k_count"] = self._k_count
        for i, p in enumerate(self._params()):
            if self._slow is not None:
                sd[f"@LookAhead.slow_{p.name or i}"] = Tensor(self._slow[id(p)])
        return sd

    def set_state_dict(self, state_dict):
        self._k_count = int(state_dict.pop("@LookAhead.k_count", 0))
        slow = {}
        for i, p in enumerate(self._params()):
            key = f"@LookAhead.slow_{p.name or i}"
            if key in state_dict:
                v = state_dict.pop(key)
                slow[id(p)] = v._value if isinstance(v, Tensor) else jnp.asarray(v)
        if slow:
            self._slow = slow
        self.inner_optimizer.set_state_dict(state_dict)


class ModelAverage:
    """Running average of parameter values over a trailing window; `apply()`
    swaps the averages in for evaluation, `restore()` swaps back.

    Window semantics follow the reference accumulator scheme: the target
    window is W = clip(num_updates * average_window_rate, min_average_window,
    max_average_window); a two-chunk (previous + current) accumulator bounds
    the actual averaged span to [W, 2W).
    """

    def __init__(self, average_window_rate, parameters=None,
                 min_average_window=10000, max_average_window=10000000,
                 name=None):
        if parameters is None:
            raise ValueError("ModelAverage needs an explicit parameters list")
        self.rate = float(average_window_rate)
        self.min_w = int(min_average_window)
        self.max_w = int(max_average_window)
        self._parameters = [p for p in parameters if not p.stop_gradient]
        self._old = {id(p): jnp.zeros_like(p._value, jnp.float32) for p in self._parameters}
        self._old_n = 0
        self._cur = {id(p): jnp.zeros_like(p._value, jnp.float32) for p in self._parameters}
        self._cur_n = 0
        self._updates = 0
        self._backup = None

    @tape.no_grad()
    def step(self):
        """Accumulate the current parameter values (call after optimizer.step())."""
        self._updates += 1
        for p in self._parameters:
            self._cur[id(p)] = self._cur[id(p)] + p._value.astype(jnp.float32)
        self._cur_n += 1
        window = int(min(max(self._updates * self.rate, self.min_w), self.max_w))
        if self._cur_n >= window:
            self._old, self._old_n = self._cur, self._cur_n
            self._cur = {id(p): jnp.zeros_like(p._value, jnp.float32)
                         for p in self._parameters}
            self._cur_n = 0

    def minimize(self, loss, startup_program=None, parameters=None, no_grad_set=None):
        self.step()
        return None, None

    @contextlib.contextmanager
    def apply(self, executor=None, need_restore=True):
        """Swap averaged params in (ref modelaverage.py apply())."""
        n = self._old_n + self._cur_n
        if n == 0:
            yield
            return
        self._backup = {id(p): p._value for p in self._parameters}
        for p in self._parameters:
            avg = (self._old[id(p)] + self._cur[id(p)]) / n
            p._rebind(avg.astype(p._value.dtype))
        try:
            yield
        finally:
            if need_restore:
                self.restore()

    def restore(self, executor=None):
        if self._backup is None:
            return
        for p in self._parameters:
            p._rebind(self._backup[id(p)])
        self._backup = None
