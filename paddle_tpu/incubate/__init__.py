"""paddle.incubate parity surface (ref: python/paddle/incubate/)."""
from . import autograd  # noqa: F401
from . import moe  # noqa: F401
from . import checkpoint  # noqa: F401
from . import asp  # noqa: F401
from . import autotune  # noqa: F401
from .moe import MoELayer  # noqa: F401
from ..autograd.tape import no_grad  # noqa: F401


def __getattr__(name):
    # sparse pulls jax.experimental.sparse (~2s import); load it lazily
    if name == "sparse":
        import importlib

        mod = importlib.import_module(".sparse", __name__)
        globals()["sparse"] = mod
        return mod
    raise AttributeError(name)


class nn:  # incubate.nn fused layers namespace (fused == XLA-fused on TPU)
    from ..nn import (  # noqa: F401
        MultiHeadAttention as FusedMultiHeadAttention,
        TransformerEncoderLayer as FusedTransformerEncoderLayer,
    )


def graph_send_recv(*args, **kwargs):
    raise NotImplementedError


def segment_sum(data, segment_ids):
    import jax

    from ..tensor.tensor import apply_op

    def _f(d, s):
        import jax.numpy as jnp

        n = int(s.max()) + 1 if hasattr(s, "max") else 1
        return jax.ops.segment_sum(d, s.astype(jnp.int32), num_segments=None)

    return apply_op(_f, (data, segment_ids), name="segment_sum")



