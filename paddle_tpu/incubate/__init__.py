"""paddle.incubate parity surface (ref: python/paddle/incubate/)."""
from . import autograd  # noqa: F401
from . import moe  # noqa: F401
from . import distributed  # noqa: F401
from . import checkpoint  # noqa: F401
from . import asp  # noqa: F401
from . import autotune  # noqa: F401
from .moe import MoELayer  # noqa: F401
from . import optimizer  # noqa: F401
from .optimizer import LookAhead, ModelAverage  # noqa: F401
from ..autograd.tape import no_grad  # noqa: F401


def __getattr__(name):
    # sparse pulls jax.experimental.sparse (~2s import); load it lazily
    if name == "sparse":
        import importlib

        mod = importlib.import_module(".sparse", __name__)
        globals()["sparse"] = mod
        return mod
    raise AttributeError(name)


class nn:  # incubate.nn fused layers namespace (fused == XLA-fused on TPU)
    from ..nn import (  # noqa: F401
        MultiHeadAttention as FusedMultiHeadAttention,
        TransformerEncoderLayer as FusedTransformerEncoderLayer,
    )

    class functional:
        """incubate.nn.functional fused ops (ref incubate/nn/functional/
        fused_transformer.py) — on TPU the fusion is XLA's job, so these
        compose the unfused primitives and compile to the same kernels."""

        @staticmethod
        def fused_feedforward(x, linear1_weight, linear2_weight, linear1_bias=None,
                              linear2_bias=None, ln1_scale=None, ln1_bias=None,
                              ln2_scale=None, ln2_bias=None, dropout1_rate=0.5,
                              dropout2_rate=0.5, activation="relu",
                              ln1_epsilon=1e-5, ln2_epsilon=1e-5,
                              pre_layer_norm=False, training=True, mode="upscale_in_train",
                              name=None):
            from ..nn import functional as F

            residual = x
            if pre_layer_norm:
                x = F.layer_norm(x, [x.shape[-1]], ln1_scale, ln1_bias, ln1_epsilon)
            x = getattr(F, activation)(F.linear(x, linear1_weight, linear1_bias))
            x = F.dropout(x, dropout1_rate, training=training, mode=mode)
            x = F.linear(x, linear2_weight, linear2_bias)
            x = F.dropout(x, dropout2_rate, training=training, mode=mode)
            x = residual + x
            if not pre_layer_norm:
                x = F.layer_norm(x, [x.shape[-1]], ln2_scale, ln2_bias, ln2_epsilon)
            return x

        @staticmethod
        def fused_multi_head_attention(x, qkv_weight, linear_weight,
                                       pre_layer_norm=False, pre_ln_scale=None,
                                       pre_ln_bias=None, ln_scale=None, ln_bias=None,
                                       pre_ln_epsilon=1e-5, qkv_bias=None,
                                       linear_bias=None, cache_kv=None,
                                       attn_mask=None, dropout_rate=0.5,
                                       attn_dropout_rate=0.5, ln_epsilon=1e-5,
                                       training=True, mode="upscale_in_train",
                                       ring_id=-1, name=None):
            """qkv_weight: [3, n_heads, head_dim, hidden]; linear_weight:
            [hidden, hidden] (the fused_attention_op layout)."""
            import jax.numpy as jnp

            from ..nn import functional as F
            from ..tensor.tensor import Tensor, apply_op

            residual = x
            if pre_layer_norm:
                x = F.layer_norm(x, [x.shape[-1]], pre_ln_scale, pre_ln_bias,
                                 pre_ln_epsilon)
            three, n_heads, head_dim, hidden = tuple(qkv_weight.shape)

            def _qkv(v, w, b):
                w2 = w.reshape(3 * n_heads * head_dim, hidden).T
                out = v @ w2.astype(v.dtype)
                if b is not None:
                    out = out + b.reshape(-1).astype(v.dtype)
                return out

            qkv = apply_op(_qkv, (x, qkv_weight, qkv_bias), name="fused_qkv")
            B, S = x.shape[0], x.shape[1]
            qkv = qkv.reshape([B, S, 3, n_heads, head_dim])
            q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
            out = F.scaled_dot_product_attention(
                q, k, v, attn_mask=attn_mask,
                dropout_p=attn_dropout_rate if training else 0.0)
            out = out.reshape([B, S, n_heads * head_dim])
            out = F.linear(out, linear_weight, linear_bias)
            out = F.dropout(out, dropout_rate, training=training, mode=mode)
            out = residual + out
            if not pre_layer_norm:
                out = F.layer_norm(out, [out.shape[-1]], ln_scale, ln_bias, ln_epsilon)
            return out

    class FusedFeedForward:
        """linear -> activation -> dropout -> linear -> dropout -> residual+LN
        (ref incubate/nn/layer/fused_transformer.py FusedFeedForward) — on TPU
        "fused" means XLA fuses the chain; one Layer keeps the API."""

        def __new__(cls, d_model, dim_feedforward, dropout_rate=0.1,
                    epsilon=1e-5, activation="relu", act_dropout_rate=None,
                    normalize_before=False, linear1_weight_attr=None,
                    linear1_bias_attr=None, linear2_weight_attr=None,
                    linear2_bias_attr=None, ln1_scale_attr=None,
                    ln1_bias_attr=None, ln2_scale_attr=None,
                    ln2_bias_attr=None, name=None):
            from .. import nn as _nn
            from ..nn import functional as _F

            class _FFN(_nn.Layer):
                def __init__(self):
                    super().__init__()
                    self.linear1 = _nn.Linear(d_model, dim_feedforward,
                                              weight_attr=linear1_weight_attr,
                                              bias_attr=linear1_bias_attr)
                    self.linear2 = _nn.Linear(dim_feedforward, d_model,
                                              weight_attr=linear2_weight_attr,
                                              bias_attr=linear2_bias_attr)
                    self.norm = _nn.LayerNorm(d_model, epsilon=epsilon)
                    self.dropout1 = _nn.Dropout(
                        dropout_rate if act_dropout_rate is None else act_dropout_rate)
                    self.dropout2 = _nn.Dropout(dropout_rate)
                    self._act = getattr(_F, activation)
                    self._pre = normalize_before

                def forward(self, x):
                    residual = x
                    if self._pre:
                        x = self.norm(x)
                    x = self.dropout2(self.linear2(self.dropout1(self._act(self.linear1(x)))))
                    x = residual + x
                    if not self._pre:
                        x = self.norm(x)
                    return x

            return _FFN()


def graph_send_recv(*args, **kwargs):
    raise NotImplementedError


def segment_sum(data, segment_ids):
    import jax

    from ..tensor.tensor import apply_op

    def _f(d, s):
        import jax.numpy as jnp

        n = int(s.max()) + 1 if hasattr(s, "max") else 1
        return jax.ops.segment_sum(d, s.astype(jnp.int32), num_segments=None)

    return apply_op(_f, (data, segment_ids), name="segment_sum")



