"""paddle.incubate parity surface (ref: python/paddle/incubate/)."""
from . import autograd  # noqa: F401
from . import moe  # noqa: F401
from . import checkpoint  # noqa: F401
from . import asp  # noqa: F401
from . import autotune  # noqa: F401
from .moe import MoELayer  # noqa: F401
from . import optimizer  # noqa: F401
from .optimizer import LookAhead, ModelAverage  # noqa: F401
from ..autograd.tape import no_grad  # noqa: F401


def __getattr__(name):
    # sparse pulls jax.experimental.sparse (~2s import); load it lazily
    if name == "sparse":
        import importlib

        mod = importlib.import_module(".sparse", __name__)
        globals()["sparse"] = mod
        return mod
    raise AttributeError(name)


class nn:  # incubate.nn fused layers namespace (fused == XLA-fused on TPU)
    from ..nn import (  # noqa: F401
        MultiHeadAttention as FusedMultiHeadAttention,
        TransformerEncoderLayer as FusedTransformerEncoderLayer,
    )

    class FusedFeedForward:
        """linear -> activation -> dropout -> linear -> dropout -> residual+LN
        (ref incubate/nn/layer/fused_transformer.py FusedFeedForward) — on TPU
        "fused" means XLA fuses the chain; one Layer keeps the API."""

        def __new__(cls, d_model, dim_feedforward, dropout_rate=0.1,
                    epsilon=1e-5, activation="relu", act_dropout_rate=None,
                    normalize_before=False, linear1_weight_attr=None,
                    linear1_bias_attr=None, linear2_weight_attr=None,
                    linear2_bias_attr=None, ln1_scale_attr=None,
                    ln1_bias_attr=None, ln2_scale_attr=None,
                    ln2_bias_attr=None, name=None):
            from .. import nn as _nn
            from ..nn import functional as _F

            class _FFN(_nn.Layer):
                def __init__(self):
                    super().__init__()
                    self.linear1 = _nn.Linear(d_model, dim_feedforward,
                                              weight_attr=linear1_weight_attr,
                                              bias_attr=linear1_bias_attr)
                    self.linear2 = _nn.Linear(dim_feedforward, d_model,
                                              weight_attr=linear2_weight_attr,
                                              bias_attr=linear2_bias_attr)
                    self.norm = _nn.LayerNorm(d_model, epsilon=epsilon)
                    self.dropout1 = _nn.Dropout(
                        dropout_rate if act_dropout_rate is None else act_dropout_rate)
                    self.dropout2 = _nn.Dropout(dropout_rate)
                    self._act = getattr(_F, activation)
                    self._pre = normalize_before

                def forward(self, x):
                    residual = x
                    if self._pre:
                        x = self.norm(x)
                    x = self.dropout2(self.linear2(self.dropout1(self._act(self.linear1(x)))))
                    x = residual + x
                    if not self._pre:
                        x = self.norm(x)
                    return x

            return _FFN()


def graph_send_recv(*args, **kwargs):
    raise NotImplementedError


def segment_sum(data, segment_ids):
    import jax

    from ..tensor.tensor import apply_op

    def _f(d, s):
        import jax.numpy as jnp

        n = int(s.max()) + 1 if hasattr(s, "max") else 1
        return jax.ops.segment_sum(d, s.astype(jnp.int32), num_segments=None)

    return apply_op(_f, (data, segment_ids), name="segment_sum")



