"""paddle.incubate parity surface (ref: python/paddle/incubate/)."""
from . import autograd  # noqa: F401
from . import moe  # noqa: F401
from . import distributed  # noqa: F401
from . import checkpoint  # noqa: F401
from . import asp  # noqa: F401
from . import autotune  # noqa: F401
from .moe import MoELayer  # noqa: F401
from . import optimizer  # noqa: F401
from .optimizer import LookAhead, ModelAverage  # noqa: F401
from ..autograd.tape import no_grad  # noqa: F401


def __getattr__(name):
    # sparse pulls jax.experimental.sparse (~2s import); load it lazily
    if name == "sparse":
        import importlib

        mod = importlib.import_module(".sparse", __name__)
        globals()["sparse"] = mod
        return mod
    raise AttributeError(name)


class nn:  # incubate.nn fused layers namespace (fused == XLA-fused on TPU)
    from ..nn import (  # noqa: F401
        MultiHeadAttention as FusedMultiHeadAttention,
        TransformerEncoderLayer as FusedTransformerEncoderLayer,
    )

    class functional:
        """incubate.nn.functional fused ops (ref incubate/nn/functional/
        fused_transformer.py) — on TPU the fusion is XLA's job, so these
        compose the unfused primitives and compile to the same kernels."""

        @staticmethod
        def fused_feedforward(x, linear1_weight, linear2_weight, linear1_bias=None,
                              linear2_bias=None, ln1_scale=None, ln1_bias=None,
                              ln2_scale=None, ln2_bias=None, dropout1_rate=0.5,
                              dropout2_rate=0.5, activation="relu",
                              ln1_epsilon=1e-5, ln2_epsilon=1e-5,
                              pre_layer_norm=False, training=True, mode="upscale_in_train",
                              name=None):
            from ..nn import functional as F

            residual = x
            if pre_layer_norm:
                x = F.layer_norm(x, [x.shape[-1]], ln1_scale, ln1_bias, ln1_epsilon)
            x = getattr(F, activation)(F.linear(x, linear1_weight, linear1_bias))
            x = F.dropout(x, dropout1_rate, training=training, mode=mode)
            x = F.linear(x, linear2_weight, linear2_bias)
            x = F.dropout(x, dropout2_rate, training=training, mode=mode)
            x = residual + x
            if not pre_layer_norm:
                x = F.layer_norm(x, [x.shape[-1]], ln2_scale, ln2_bias, ln2_epsilon)
            return x

        @staticmethod
        def fused_multi_head_attention(x, qkv_weight, linear_weight,
                                       pre_layer_norm=False, pre_ln_scale=None,
                                       pre_ln_bias=None, ln_scale=None, ln_bias=None,
                                       pre_ln_epsilon=1e-5, qkv_bias=None,
                                       linear_bias=None, cache_kv=None,
                                       attn_mask=None, dropout_rate=0.5,
                                       attn_dropout_rate=0.5, ln_epsilon=1e-5,
                                       training=True, mode="upscale_in_train",
                                       ring_id=-1, name=None):
            """qkv_weight: [3, n_heads, head_dim, hidden]; linear_weight:
            [hidden, hidden] (the fused_attention_op layout)."""
            import jax.numpy as jnp

            from ..nn import functional as F
            from ..tensor.tensor import Tensor, apply_op

            residual = x
            if pre_layer_norm:
                x = F.layer_norm(x, [x.shape[-1]], pre_ln_scale, pre_ln_bias,
                                 pre_ln_epsilon)
            three, n_heads, head_dim, hidden = tuple(qkv_weight.shape)

            def _qkv(v, w, b):
                w2 = w.reshape(3 * n_heads * head_dim, hidden).T
                out = v @ w2.astype(v.dtype)
                if b is not None:
                    out = out + b.reshape(-1).astype(v.dtype)
                return out

            qkv = apply_op(_qkv, (x, qkv_weight, qkv_bias), name="fused_qkv")
            B, S = x.shape[0], x.shape[1]
            qkv = qkv.reshape([B, S, 3, n_heads, head_dim])
            q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
            out = F.scaled_dot_product_attention(
                q, k, v, attn_mask=attn_mask,
                dropout_p=attn_dropout_rate if training else 0.0)
            out = out.reshape([B, S, n_heads * head_dim])
            out = F.linear(out, linear_weight, linear_bias)
            out = F.dropout(out, dropout_rate, training=training, mode=mode)
            out = residual + out
            if not pre_layer_norm:
                out = F.layer_norm(out, [out.shape[-1]], ln_scale, ln_bias, ln_epsilon)
            return out

    class FusedFeedForward:
        """linear -> activation -> dropout -> linear -> dropout -> residual+LN
        (ref incubate/nn/layer/fused_transformer.py FusedFeedForward) — on TPU
        "fused" means XLA fuses the chain; one Layer keeps the API."""

        def __new__(cls, d_model, dim_feedforward, dropout_rate=0.1,
                    epsilon=1e-5, activation="relu", act_dropout_rate=None,
                    normalize_before=False, linear1_weight_attr=None,
                    linear1_bias_attr=None, linear2_weight_attr=None,
                    linear2_bias_attr=None, ln1_scale_attr=None,
                    ln1_bias_attr=None, ln2_scale_attr=None,
                    ln2_bias_attr=None, name=None):
            from .. import nn as _nn
            from ..nn import functional as _F

            class _FFN(_nn.Layer):
                def __init__(self):
                    super().__init__()
                    self.linear1 = _nn.Linear(d_model, dim_feedforward,
                                              weight_attr=linear1_weight_attr,
                                              bias_attr=linear1_bias_attr)
                    self.linear2 = _nn.Linear(dim_feedforward, d_model,
                                              weight_attr=linear2_weight_attr,
                                              bias_attr=linear2_bias_attr)
                    self.norm = _nn.LayerNorm(d_model, epsilon=epsilon)
                    self.dropout1 = _nn.Dropout(
                        dropout_rate if act_dropout_rate is None else act_dropout_rate)
                    self.dropout2 = _nn.Dropout(dropout_rate)
                    self._act = getattr(_F, activation)
                    self._pre = normalize_before

                def forward(self, x):
                    residual = x
                    if self._pre:
                        x = self.norm(x)
                    x = self.dropout2(self.linear2(self.dropout1(self._act(self.linear1(x)))))
                    x = residual + x
                    if not self._pre:
                        x = self.norm(x)
                    return x

            return _FFN()


def graph_send_recv(x, src_index, dst_index, pool_type="sum", out_size=None,
                    name=None):
    """Gather rows at src_index, scatter-reduce into dst_index (ref
    incubate/operators/graph_send_recv.py — the message-passing primitive).
    TPU-native: one gather + one scatter-reduce, both XLA-native."""
    import jax.numpy as jnp

    from ..tensor.tensor import apply_op

    if pool_type not in ("sum", "mean", "max", "min"):
        raise ValueError(f"pool_type must be sum/mean/max/min, got {pool_type}")

    def _f(v, src, dst):
        n = int(out_size) if out_size is not None else v.shape[0]
        msgs = v[src.astype(jnp.int32)]
        shape = (n,) + v.shape[1:]
        dst = dst.astype(jnp.int32)
        if pool_type == "sum":
            return jnp.zeros(shape, v.dtype).at[dst].add(msgs)
        if pool_type == "mean":
            tot = jnp.zeros(shape, v.dtype).at[dst].add(msgs)
            cnt = jnp.zeros((n,), v.dtype).at[dst].add(1.0)
            return tot / jnp.maximum(cnt, 1.0).reshape((n,) + (1,) * (v.ndim - 1))
        if pool_type == "max":
            out = jnp.full(shape, -jnp.inf, v.dtype).at[dst].max(msgs)
            return jnp.where(jnp.isfinite(out), out, 0.0)
        out = jnp.full(shape, jnp.inf, v.dtype).at[dst].min(msgs)
        return jnp.where(jnp.isfinite(out), out, 0.0)

    return apply_op(_f, (x, src_index, dst_index), name="graph_send_recv")


def segment_sum(data, segment_ids):
    """Ref incubate/tensor/math.py segment_sum.  Eager with concrete ids
    returns the reference [max_id+1, ...] shape; under a trace the result is
    padded to the static row-count bound like the other segment reductions
    (XLA needs static shapes; callers slice)."""
    import jax
    import jax.numpy as jnp

    from ..tensor.tensor import apply_op

    def _f(d, s):
        s = s.astype(jnp.int32)
        n = d.shape[0]
        out = jnp.zeros((n,) + d.shape[1:], d.dtype).at[s].add(d)
        if not isinstance(s, jax.core.Tracer):
            out = out[:int(s.max()) + 1]
        return out

    return apply_op(_f, (data, segment_ids), name="segment_sum")





def _segment_reduce(data, segment_ids, mode):
    import jax.numpy as jnp

    from ..tensor.tensor import apply_op

    def _f(d, s):
        s = s.astype(jnp.int32)
        # static segment-count bound = number of rows (XLA needs a static
        # shape; ids are sorted per the reference contract, callers slice)
        n = d.shape[0]
        shape = (n,) + d.shape[1:]
        if mode == "sum":
            return jnp.zeros(shape, d.dtype).at[s].add(d)
        if mode == "mean":
            tot = jnp.zeros(shape, d.dtype).at[s].add(d)
            cnt = jnp.zeros((n,), d.dtype).at[s].add(1.0)
            return tot / jnp.maximum(cnt, 1.0).reshape((n,) + (1,) * (d.ndim - 1))
        if mode == "max":
            out = jnp.full(shape, -jnp.inf, d.dtype).at[s].max(d)
            return jnp.where(jnp.isfinite(out), out, 0.0)
        out = jnp.full(shape, jnp.inf, d.dtype).at[s].min(d)
        return jnp.where(jnp.isfinite(out), out, 0.0)

    return apply_op(_f, (data, segment_ids), name=f"segment_{mode}")


def segment_mean(data, segment_ids, name=None):
    """Ref incubate/tensor/math.py segment_mean."""
    return _segment_reduce(data, segment_ids, "mean")


def segment_max(data, segment_ids, name=None):
    return _segment_reduce(data, segment_ids, "max")


def segment_min(data, segment_ids, name=None):
    return _segment_reduce(data, segment_ids, "min")


def softmax_mask_fuse(x, mask, name=None):
    """softmax(x + mask) — the reference fuses these into one CUDA kernel
    (incubate/operators/softmax_mask_fuse.py); XLA fuses the composition."""
    import jax

    from ..tensor.tensor import apply_op

    return apply_op(lambda v, m: jax.nn.softmax(v + m.astype(v.dtype), axis=-1),
                    (x, mask), name="softmax_mask_fuse")


def softmax_mask_fuse_upper_triangle(x, name=None):
    """softmax with the causal (upper-triangular) mask fused
    (ref incubate/operators/softmax_mask_fuse_upper_triangle.py)."""
    import jax
    import jax.numpy as jnp

    from ..tensor.tensor import apply_op

    def _f(v):
        S1, S2 = v.shape[-2], v.shape[-1]
        mask = jnp.tril(jnp.ones((S1, S2), bool))
        return jax.nn.softmax(jnp.where(mask, v, -1e9), axis=-1)

    return apply_op(_f, (x,), name="softmax_mask_fuse_upper_triangle")


def identity_loss(x, reduction="none"):
    """Mark a tensor as the loss (IPU-era helper, ref incubate/nn/functional/
    identity_loss): reduces per `reduction` and stops nothing."""
    if reduction in (0, "sum"):
        return x.sum()
    if reduction in (1, "mean"):
        return x.mean()
    if reduction in (2, "none"):
        return x
    raise ValueError(f"reduction must be sum/mean/none, got {reduction}")


def graph_khop_sampler(row, colptr, input_nodes, sample_sizes,
                       sorted_eids=None, return_eids=False, name=None):
    """K-hop neighbor sampling on a CSC graph (ref incubate/operators/
    graph_khop_sampler.py).  Sampling is data-dependent (ragged) — an eager
    host-side op by design, like the reference's CPU kernel."""
    import numpy as np

    from ..tensor.tensor import Tensor

    rowv = np.asarray(_t2np(row))
    colptrv = np.asarray(_t2np(colptr))
    nodes = np.asarray(_t2np(input_nodes)).reshape(-1)
    rng = np.random.RandomState(0)
    edge_src, edge_dst = [], []
    layer_nodes = nodes
    seen = list(nodes)
    for k in sample_sizes:
        nxt = []
        for dst in layer_nodes:
            beg, end = int(colptrv[dst]), int(colptrv[dst + 1])
            neigh = rowv[beg:end]
            if len(neigh) > k:
                neigh = rng.choice(neigh, size=k, replace=False)
            for srcn in neigh:
                edge_src.append(int(srcn))
                edge_dst.append(int(dst))
                nxt.append(int(srcn))
        layer_nodes = np.unique(np.asarray(nxt, np.int64)) if nxt else np.empty(0, np.int64)
        seen.extend(layer_nodes.tolist())
    # reindex: unique nodes, input nodes first
    uniq, idx = np.unique(np.asarray(seen, np.int64), return_index=True)
    order = uniq[np.argsort(idx)]
    remap = {int(n): i for i, n in enumerate(order)}
    r_src = np.asarray([remap[s] for s in edge_src], np.int64)
    r_dst = np.asarray([remap[d] for d in edge_dst], np.int64)
    return (Tensor(_np2j(r_src)), Tensor(_np2j(r_dst)), Tensor(_np2j(order)),
            Tensor(_np2j(np.asarray([len(edge_src)], np.int64))))


def graph_sample_neighbors(row, colptr, input_nodes, eids=None,
                           perm_buffer=None, sample_size=-1, return_eids=False,
                           flag_perm_buffer=False, name=None):
    """One-hop neighbor sampling (ref incubate/operators/graph_sample_neighbors.py)."""
    import numpy as np

    from ..tensor.tensor import Tensor

    rowv = np.asarray(_t2np(row))
    colptrv = np.asarray(_t2np(colptr))
    nodes = np.asarray(_t2np(input_nodes)).reshape(-1)
    rng = np.random.RandomState(0)
    out, counts = [], []
    for dst in nodes:
        beg, end = int(colptrv[dst]), int(colptrv[dst + 1])
        neigh = rowv[beg:end]
        if sample_size > 0 and len(neigh) > sample_size:
            neigh = rng.choice(neigh, size=sample_size, replace=False)
        out.extend(int(x) for x in neigh)
        counts.append(len(neigh))
    return (Tensor(_np2j(np.asarray(out, np.int64))),
            Tensor(_np2j(np.asarray(counts, np.int64))))


def graph_reindex(x, neighbors, count, value_buffer=None, index_buffer=None,
                  flag_buffer_hashtable=False, name=None):
    """Reindex a sampled subgraph to contiguous local ids
    (ref incubate/operators/graph_reindex.py)."""
    import numpy as np

    from ..tensor.tensor import Tensor

    xs = np.asarray(_t2np(x)).reshape(-1)
    nb = np.asarray(_t2np(neighbors)).reshape(-1)
    cnt = np.asarray(_t2np(count)).reshape(-1)
    order = list(dict.fromkeys(list(xs) + list(nb)))
    remap = {int(n): i for i, n in enumerate(order)}
    re_nb = np.asarray([remap[int(n)] for n in nb], np.int64)
    re_src = np.repeat(np.asarray([remap[int(n)] for n in xs], np.int64), cnt)
    return (Tensor(_np2j(re_nb)), Tensor(_np2j(re_src)),
            Tensor(_np2j(np.asarray(order, np.int64))))


def _t2np(t):
    import jax
    import numpy as np

    from ..tensor.tensor import Tensor

    return np.asarray(jax.device_get(t._value)) if isinstance(t, Tensor) else np.asarray(t)


def _np2j(a):
    import jax.numpy as jnp

    return jnp.asarray(a)
