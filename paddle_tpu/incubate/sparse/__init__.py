"""paddle.incubate.sparse (ref: python/paddle/incubate/sparse/ — creation,
unary, binary; phi sparse COO/CSR tensors paddle/phi/core/sparse_coo_tensor.h,
sparse_csr_tensor.h).

TPU-native: sparse storage rides jax.experimental.sparse.BCOO — XLA lowers
sparse contractions to gather/scatter + dense MXU tiles, which is the honest
execution model on TPU (there is no sparse tensor core).  SparseCooTensor /
SparseCsrTensor wrap BCOO with the reference's method surface
(indices/values/crows/cols, to_dense, coalesce); ops below mirror the
reference's unary/binary files.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import sparse as jsparse

from ...tensor.tensor import Tensor

__all__ = [
    "sparse_coo_tensor", "sparse_csr_tensor", "SparseCooTensor",
    "SparseCsrTensor", "is_sparse_coo", "is_sparse_csr",
    # unary
    "sin", "tan", "asin", "atan", "sinh", "asinh", "atanh", "tanh", "square",
    "sqrt", "log1p", "abs", "neg", "pow", "cast", "coalesce", "relu",
    # binary
    "add", "subtract", "multiply", "divide", "matmul", "masked_matmul", "mv",
]


def _raw(x):
    if isinstance(x, Tensor):
        return x._value
    return jnp.asarray(x)


class SparseCooTensor:
    """COO sparse tensor (ref sparse_coo_tensor.h): [sparse_dim, nnz] indices
    + [nnz, ...] values."""

    def __init__(self, bcoo):
        self._bcoo = bcoo

    # --- reference-shaped accessors
    def indices(self):
        return Tensor(jnp.swapaxes(self._bcoo.indices, 0, 1))  # [ndim, nnz]

    def values(self):
        return Tensor(self._bcoo.data)

    def to_dense(self):
        return Tensor(self._bcoo.todense())

    def to_sparse_csr(self):
        if self._bcoo.ndim != 2:
            raise ValueError("to_sparse_csr needs a 2-D tensor")
        return SparseCsrTensor(jsparse.BCSR.from_bcoo(self._bcoo.sum_duplicates()))

    def nnz(self):
        return int(self._bcoo.nse)

    @property
    def shape(self):
        return list(self._bcoo.shape)

    @property
    def dtype(self):
        return self._bcoo.dtype

    def is_sparse_coo(self):
        return True

    def is_sparse_csr(self):
        return False

    def __repr__(self):
        return (f"SparseCooTensor(shape={self.shape}, nnz={self.nnz()}, "
                f"dtype={self.dtype})")


class SparseCsrTensor:
    """CSR sparse tensor (ref sparse_csr_tensor.h): crows/cols/values."""

    def __init__(self, bcsr):
        self._bcsr = bcsr

    def crows(self):
        return Tensor(self._bcsr.indptr)

    def cols(self):
        return Tensor(self._bcsr.indices)

    def values(self):
        return Tensor(self._bcsr.data)

    def to_dense(self):
        return Tensor(self._bcsr.todense())

    def to_sparse_coo(self, sparse_dim=2):
        return SparseCooTensor(self._bcsr.to_bcoo())

    def nnz(self):
        return int(self._bcsr.nse)

    @property
    def shape(self):
        return list(self._bcsr.shape)

    @property
    def dtype(self):
        return self._bcsr.dtype

    def is_sparse_coo(self):
        return False

    def is_sparse_csr(self):
        return True

    def __repr__(self):
        return (f"SparseCsrTensor(shape={self.shape}, nnz={self.nnz()}, "
                f"dtype={self.dtype})")


def is_sparse_coo(x):
    return isinstance(x, SparseCooTensor)


def is_sparse_csr(x):
    return isinstance(x, SparseCsrTensor)


# ------------------------------------------------------------------ creation
def sparse_coo_tensor(indices, values, shape=None, dtype=None, place=None,
                      stop_gradient=True):
    """Ref creation.py:68.  indices: [sparse_dim, nnz]; values: [nnz, ...]."""
    idx = np.asarray(_raw(indices))
    vals = _raw(values)
    if dtype is not None:
        vals = vals.astype(dtype)
    if shape is None:
        shape = tuple(int(i) + 1 for i in np.max(idx, axis=1)) + vals.shape[1:]
    bcoo = jsparse.BCOO((vals, jnp.asarray(idx.T)), shape=tuple(shape))
    return SparseCooTensor(bcoo)


def sparse_csr_tensor(crows, cols, values, shape, dtype=None, place=None,
                      stop_gradient=True):
    """Ref creation.py:175."""
    vals = _raw(values)
    if dtype is not None:
        vals = vals.astype(dtype)
    bcsr = jsparse.BCSR((vals, jnp.asarray(_raw(cols), jnp.int32),
                         jnp.asarray(_raw(crows), jnp.int32)),
                        shape=tuple(shape))
    return SparseCsrTensor(bcsr)


# --------------------------------------------------------------------- unary
def _unary(fn):
    def op(x, name=None):
        if isinstance(x, SparseCooTensor):
            b = x._bcoo
            return SparseCooTensor(jsparse.BCOO((fn(b.data), b.indices),
                                                shape=b.shape))
        if isinstance(x, SparseCsrTensor):
            b = x._bcsr
            return SparseCsrTensor(jsparse.BCSR((fn(b.data), b.indices, b.indptr),
                                                shape=b.shape))
        raise TypeError(f"expected a sparse tensor, got {type(x).__name__}")

    return op


sin = _unary(jnp.sin)
tan = _unary(jnp.tan)
asin = _unary(jnp.arcsin)
atan = _unary(jnp.arctan)
sinh = _unary(jnp.sinh)
asinh = _unary(jnp.arcsinh)
atanh = _unary(jnp.arctanh)
tanh = _unary(jnp.tanh)
square = _unary(jnp.square)
sqrt = _unary(jnp.sqrt)
log1p = _unary(jnp.log1p)
abs = _unary(jnp.abs)
neg = _unary(jnp.negative)
relu = _unary(lambda v: jnp.maximum(v, 0))


def pow(x, factor, name=None):
    return _unary(lambda v: jnp.power(v, factor))(x)


def cast(x, index_dtype=None, value_dtype=None, name=None):
    def f(v):
        return v.astype(value_dtype) if value_dtype else v

    out = _unary(f)(x)
    return out


def coalesce(x):
    """Ref unary.py:478: merge duplicate coordinates."""
    if not isinstance(x, SparseCooTensor):
        raise TypeError("coalesce expects a SparseCooTensor")
    return SparseCooTensor(x._bcoo.sum_duplicates())


# -------------------------------------------------------------------- binary
def _b(x):
    if isinstance(x, SparseCooTensor):
        return x._bcoo
    if isinstance(x, SparseCsrTensor):
        return x._bcsr
    return _raw(x)


def add(x, y, name=None):
    bx, by = _b(x), _b(y)
    if isinstance(x, SparseCooTensor) and isinstance(y, SparseCooTensor):
        out = jsparse.BCOO((jnp.concatenate([bx.data, by.data]),
                            jnp.concatenate([bx.indices, by.indices])),
                           shape=bx.shape).sum_duplicates()
        return SparseCooTensor(out)
    return Tensor(_dense(bx) + _dense(by))


def subtract(x, y, name=None):
    return add(x, neg(y) if isinstance(y, (SparseCooTensor, SparseCsrTensor))
               else Tensor(-_raw(y)))


def multiply(x, y, name=None):
    """Elementwise product; sparse x dense keeps sparsity."""
    if isinstance(x, SparseCooTensor) and not isinstance(y, (SparseCooTensor, SparseCsrTensor)):
        b = x._bcoo
        yv = _raw(y)
        gathered = yv[tuple(b.indices[:, i] for i in range(b.indices.shape[1]))]
        return SparseCooTensor(jsparse.BCOO((b.data * gathered, b.indices),
                                            shape=b.shape))
    return Tensor(_dense(_b(x)) * _dense(_b(y)))


def divide(x, y, name=None):
    return Tensor(_dense(_b(x)) / _dense(_b(y)))


def _dense(b):
    return b.todense() if hasattr(b, "todense") else b


def matmul(x, y, name=None):
    """Ref binary.py:31: sparse @ dense (and sparse @ sparse -> dense)."""
    bx, by = _b(x), _b(y)
    if hasattr(bx, "todense") and not hasattr(by, "todense"):
        if isinstance(x, SparseCsrTensor):
            bx = x._bcsr.to_bcoo()
        out = bx @ by          # BCOO dot_general: gather + dense MXU tiles
        return Tensor(out)
    return Tensor(_dense(bx) @ _dense(by))


def mv(x, vec, name=None):
    """Ref binary.py:161: sparse matrix @ dense vector."""
    return matmul(x, vec)


def masked_matmul(x, y, mask, name=None):
    """Ref binary.py:101: dense @ dense, sampled at `mask`'s sparsity (SDDMM)."""
    if not isinstance(mask, (SparseCooTensor, SparseCsrTensor)):
        raise TypeError("mask must be sparse")
    bm = mask._bcoo if isinstance(mask, SparseCooTensor) else mask._bcsr.to_bcoo()
    xv, yv = _raw(x), _raw(y)
    rows = bm.indices[:, 0]
    cols = bm.indices[:, 1]
    vals = jnp.einsum("nk,nk->n", xv[rows, :], jnp.swapaxes(yv, 0, 1)[cols, :])
    out = jsparse.BCOO((vals.astype(xv.dtype), bm.indices), shape=bm.shape)
    return SparseCooTensor(out)
