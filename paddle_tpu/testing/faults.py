"""Deterministic fault-injection harness.

Reference precedent: the reference's auto-checkpoint and elastic suites fake
etcd and kill trainer processes to exercise restart paths.  Here every
recovery path in the checkpoint, control-plane store, and serving layers is
unit-testable ON CPU by injecting faults at the filesystem and socket seams.
All schedules are call-count keyed — no wall clock, no RNG — so a failing
run reproduces exactly.

- ``FaultyFS`` — patches ``builtins.open``/``io.open`` (both names bind the
  same callable; zipfile/np.savez go through ``io.open``): write-mode opens
  of paths matching a glob consult a per-open schedule of ``torn`` (half the
  bytes land, then the "process dies"), ``enospc``, or ``eio`` faults.
- ``flip_bit`` — one-bit corruption of an already-committed file (simulated
  media decay); no patching involved.
- ``SocketFaults`` — patches ``socket.create_connection``: connections to a
  given port consult a per-connect schedule of ``drop`` (refused), ``stall``
  (recv times out), or ``reset`` (peer reset mid-exchange).
- ``preemption_schedule`` — raises ``Preemption`` the first time each listed
  step index is reached (the signal ``run_with_recovery`` heals from).
- ``ProcFaults`` — PROCESS-level faults for the multi-process serving
  fleet: a replica subprocess (``inference/replica_main.py``) loads a
  fault spec from its environment (or has one armed at runtime via its
  ``/faultz`` endpoint) and consults it at the same call-count-keyed
  seams: SIGKILL itself before answering the Nth ``/admitz`` or
  ``/pollz`` (kill -9 mid-request), wedge its SIGTERM drain (forcing the
  supervisor's SIGKILL escalation), delay readiness past the gate, or
  exit immediately at startup (a crash-looping replica).  ``sigstop`` /
  ``sigcont`` wrap the wedge where the process stays ALIVE but stops
  answering — `/healthz` stalls while the listening socket stays open.
"""
from __future__ import annotations

import builtins
import errno as _errno
import fnmatch
import io
import json as _json
import os
import signal as _signal
import socket as _socket

from ..distributed.fault_tolerance import Preemption

__all__ = [
    "InjectedFault", "TornWrite", "Preemption", "FaultyFS", "SocketFaults",
    "flip_bit", "preemption_schedule", "ProcFaults", "PROC_FAULTS_ENV",
    "proc_fault_env", "load_proc_faults", "sigstop", "sigcont",
]


class InjectedFault(OSError):
    """Base of all injected I/O faults (an OSError so production retry
    policies classify it exactly like the real thing)."""


class TornWrite(InjectedFault):
    """Simulated kill mid-write: part of the payload reached the disk."""


def flip_bit(path, byte_offset=None, bit=0):
    """Flip one bit in ``path`` (default: the middle byte) — simulated media
    corruption of a file that was written successfully."""
    with open(path, "r+b") as f:
        f.seek(0, os.SEEK_END)
        size = f.tell()
        if size == 0:
            raise ValueError(f"cannot bit-flip empty file {path}")
        off = size // 2 if byte_offset is None else int(byte_offset)
        f.seek(off)
        b = f.read(1)
        f.seek(off)
        f.write(bytes([b[0] ^ (1 << bit)]))


def preemption_schedule(*steps):
    """Return ``check(step)`` raising ``Preemption`` the FIRST time each
    listed step index is reached (replays after recovery pass through)."""
    pending = set(steps)

    def check(step):
        if step in pending:
            pending.discard(step)
            raise Preemption(f"injected preemption at step {step}")

    return check


class _TornFile:
    """File proxy whose first write tears: half the bytes land, then a
    TornWrite unwinds the writer — the in-process analog of SIGKILL
    mid-write (the partial file stays on disk)."""

    def __init__(self, raw):
        self._raw = raw
        self._torn = False

    def write(self, data):
        if not self._torn:
            self._torn = True
            self._raw.write(data[: max(1, len(data) // 2)])
            self._raw.flush()
            raise TornWrite(_errno.EIO,
                            "injected torn write (simulated kill mid-write)")
        return self._raw.write(data)

    def __getattr__(self, name):
        return getattr(self._raw, name)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self._raw.close()
        return False


class _EIOFile:
    """File proxy whose every write raises EIO (failing media)."""

    def __init__(self, raw):
        self._raw = raw

    def write(self, data):
        raise InjectedFault(_errno.EIO, "injected EIO on write")

    def __getattr__(self, name):
        return getattr(self._raw, name)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self._raw.close()
        return False


class FaultyFS:
    """Context manager injecting filesystem faults on write-mode opens.

    ``faults`` maps the index of a matching write-open (counted within this
    context, 0-based) to a kind:

    - ``"enospc"`` — the open itself raises OSError(ENOSPC);
    - ``"eio"`` — the open succeeds but every write raises OSError(EIO);
    - ``"torn"`` — the first write stores half its bytes then raises
      TornWrite, leaving a partial file behind.

    Read-mode opens and non-matching paths pass through untouched, so the
    interpreter / pytest internals are unaffected.  ``self.log`` records the
    (index, kind, path) of every fired fault.
    """

    def __init__(self, match="*", faults=None):
        self.match = match
        self.faults = dict(faults or {})
        self.write_opens = 0
        self.log = []
        self._real = None

    def _make_opener(self, real_open):
        harness = self

        def opener(file, mode="r", *args, **kwargs):
            if (isinstance(file, (str, os.PathLike))
                    and any(c in str(mode) for c in "wxa+")
                    and fnmatch.fnmatch(str(file), harness.match)):
                idx = harness.write_opens
                harness.write_opens += 1
                kind = harness.faults.get(idx)
                if kind:
                    harness.log.append((idx, kind, str(file)))
                if kind == "enospc":
                    raise InjectedFault(
                        _errno.ENOSPC, f"injected ENOSPC opening {file}")
                if kind == "eio":
                    return _EIOFile(real_open(file, mode, *args, **kwargs))
                if kind == "torn":
                    return _TornFile(real_open(file, mode, *args, **kwargs))
            return real_open(file, mode, *args, **kwargs)

        return opener

    def __enter__(self):
        self._real = builtins.open
        wrapped = self._make_opener(self._real)
        builtins.open = wrapped
        io.open = wrapped
        return self

    def __exit__(self, *exc):
        builtins.open = self._real
        io.open = self._real
        return False


class _FaultySocket:
    """Socket proxy simulating a stalled or reset peer."""

    def __init__(self, raw, kind):
        self._raw = raw
        self._kind = kind

    def sendall(self, data):
        if self._kind == "reset":
            raise ConnectionResetError(
                _errno.ECONNRESET, "injected connection reset")
        return self._raw.sendall(data)

    def recv(self, n):
        if self._kind == "stall":
            raise _socket.timeout("injected stall: recv timed out")
        if self._kind == "reset":
            raise ConnectionResetError(
                _errno.ECONNRESET, "injected connection reset")
        return self._raw.recv(n)

    def __getattr__(self, name):
        return getattr(self._raw, name)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self._raw.close()
        return False


class SocketFaults:
    """Context manager injecting socket faults on connections to ``port``.

    ``faults`` maps the index of a matching connect (counted within this
    context, 0-based) to a kind:

    - ``"drop"`` — the connect raises ConnectionRefusedError;
    - ``"stall"`` — the connection opens but recv raises socket.timeout
      (a hung peer, without spending the wall-clock);
    - ``"reset"`` — sendall/recv raise ConnectionResetError.

    Connections to other ports pass through untouched.
    """

    def __init__(self, port, faults=None):
        self.port = int(port)
        self.faults = dict(faults or {})
        self.connects = 0
        self.log = []
        self._real = None

    def __enter__(self):
        self._real = _socket.create_connection
        harness = self

        def create_connection(address, *args, **kwargs):
            if address[1] == harness.port:
                idx = harness.connects
                harness.connects += 1
                kind = harness.faults.get(idx)
                if kind:
                    harness.log.append((idx, kind, address))
                if kind == "drop":
                    raise ConnectionRefusedError(
                        _errno.ECONNREFUSED, "injected connect drop")
                if kind in ("stall", "reset"):
                    return _FaultySocket(
                        harness._real(address, *args, **kwargs), kind)
            return harness._real(address, *args, **kwargs)

        _socket.create_connection = create_connection
        return self

    def __exit__(self, *exc):
        _socket.create_connection = self._real
        return False


# ------------------------------------------------------------ process faults
#: Environment variable carrying the JSON fault spec into a replica
#: subprocess — set by the supervisor at spawn (per incarnation), read by
#: ``replica_main`` before it builds anything heavy.
PROC_FAULTS_ENV = "PADDLE_TPU_PROC_FAULTS"


class ProcFaults:
    """Deterministic process-level fault schedule for ONE replica process.

    The spec is a plain dict (JSON-serializable so it crosses the exec
    boundary via :data:`PROC_FAULTS_ENV`); all counters are call-count
    keyed within the process — no wall clock, no RNG:

    - ``kill_at_admit: n`` — SIGKILL this process immediately BEFORE
      answering its ``n``-th ``/admitz`` (0-based): the router's admit
      connection dies mid-exchange with nothing delivered — the real
      kill -9 mid-request.
    - ``kill_at_poll: n`` — SIGKILL before answering the ``n``-th
      ``/pollz``: the request was admitted (ack delivered) but the
      process dies before any result can be fetched.
    - ``wedge_drain: true`` — the SIGTERM drain handler never finishes
      (sleeps forever instead of draining), forcing the supervisor's
      SIGKILL escalation on its deadline.
    - ``slow_start_s: x`` — sleep ``x`` seconds before binding the
      telemetry port, delaying readiness past the supervisor's gate.
    - ``exit_at_start: true`` — exit(3) before serving anything: the
      crash-looping replica a restart-storm quarantine must bench.

    ``on_admit()`` / ``on_poll()`` are invoked by the replica entrypoint
    inside its endpoint wrappers; ``arm()`` merges a new spec at runtime
    (the ``/faultz`` seam — a test can arm the NEXT fault on a live
    fleet without respawning it).
    """

    def __init__(self, spec=None):
        self.spec = dict(spec or {})
        self.admits = 0
        self.polls = 0

    # -- schedule queries -------------------------------------------------
    @property
    def exit_at_start(self):
        return bool(self.spec.get("exit_at_start"))

    @property
    def slow_start_s(self):
        return float(self.spec.get("slow_start_s", 0.0))

    @property
    def wedge_drain(self):
        return bool(self.spec.get("wedge_drain"))

    def arm(self, spec):
        """Merge ``spec`` into the live schedule (counters keep running —
        a ``kill_at_admit`` armed mid-flight keys off the SAME admit
        counter the process has been advancing since birth)."""
        self.spec.update(spec or {})
        return dict(self.spec)

    # -- seams called by replica_main ------------------------------------
    def _kill_self(self):
        os.kill(os.getpid(), _signal.SIGKILL)

    def on_admit(self):
        """Call-counted /admitz seam: dies BEFORE the reply when armed."""
        idx = self.admits
        self.admits += 1
        if self.spec.get("kill_at_admit") == idx:
            self._kill_self()

    def on_poll(self):
        """Call-counted /pollz seam: dies BEFORE the reply when armed."""
        idx = self.polls
        self.polls += 1
        if self.spec.get("kill_at_poll") == idx:
            self._kill_self()


def proc_fault_env(spec, env=None):
    """Return a copy of ``env`` (default ``os.environ``) with the fault
    spec serialized into :data:`PROC_FAULTS_ENV` — what a supervisor
    passes to ``subprocess.Popen`` to arm faults for ONE incarnation."""
    out = dict(os.environ if env is None else env)
    if spec:
        out[PROC_FAULTS_ENV] = _json.dumps(spec)
    else:
        out.pop(PROC_FAULTS_ENV, None)
    return out


def load_proc_faults(environ=None):
    """Parse :data:`PROC_FAULTS_ENV` into a :class:`ProcFaults` (empty
    schedule when unset/corrupt — a replica never refuses to start over
    a bad fault spec; the faults are the test harness, not the product)."""
    raw = (os.environ if environ is None else environ).get(PROC_FAULTS_ENV)
    if not raw:
        return ProcFaults()
    try:
        return ProcFaults(_json.loads(raw))
    except (ValueError, TypeError):
        return ProcFaults()


def sigstop(pid):
    """Freeze a process (SIGSTOP): its sockets stay OPEN but nothing
    answers — the wedge that distinguishes 'dead' from 'unresponsive'."""
    os.kill(int(pid), _signal.SIGSTOP)


def sigcont(pid):
    """Thaw a SIGSTOPped process."""
    os.kill(int(pid), _signal.SIGCONT)
