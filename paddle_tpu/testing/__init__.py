"""Testing utilities: the deterministic fault-injection harness
(`paddle_tpu.testing.faults`) that makes every recovery path in the
checkpoint / store / serving layers unit-testable on CPU."""
from .faults import (  # noqa: F401
    FaultyFS, InjectedFault, Preemption, SocketFaults, TornWrite,
    flip_bit, preemption_schedule,
)
