"""paddle.jit parity (ref: python/paddle/jit/__init__.py:23 — to_static/save/load)."""
from __future__ import annotations

import os
import pickle

import jax
import numpy as np

from .to_static import to_static, declarative, not_to_static, StaticFunction  # noqa: F401
from .train_step import TrainStep  # noqa: F401
from ..nn.layer.layers import Layer
from ..tensor.tensor import Tensor


class InputSpec:
    """Ref: paddle.static.InputSpec."""

    def __init__(self, shape, dtype="float32", name=None):
        self.shape = tuple(shape)
        self.dtype = dtype
        self.name = name

    def __repr__(self):
        return f"InputSpec(shape={self.shape}, dtype={self.dtype})"


def save(layer, path, input_spec=None, **configs):
    """jit.save parity (ref fluid/dygraph/jit.py:649).

    Persists (a) the state_dict as .pdiparams and (b) an AOT-exported StableHLO
    program as .pdmodel when input_spec is given (jax.export replaces the reference's
    serialized inference ProgramDesc).
    """
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    state = {}
    param_keys, buffer_keys = [], []
    if isinstance(layer, Layer):
        # a stacked PipelineTrainStep keeps trained body weights in its own
        # sharded store until a state read — run the sync hook before snapshotting
        hook = getattr(layer, "_pre_state_hook", None)
        if hook is not None:
            hook()
        for k, v in layer.named_parameters():
            state[k] = np.asarray(v._value)
            param_keys.append(k)
        for k, v in layer.named_buffers():
            state[k] = np.asarray(v._value)
            buffer_keys.append(k)
    with open(path + ".pdiparams", "wb") as f:
        pickle.dump(state, f)
    # the exported closure was traced with the exact (params, buffers) pytree from
    # functional_state(); persist the key split so load() can rebuild it (the round-1
    # bug: stuffing everything into __params__ broke any model with buffers, e.g. BN)
    with open(path + ".pdiparams.info", "wb") as f:
        pickle.dump({
            "param_keys": param_keys, "buffer_keys": buffer_keys,
            "inputs": [
                {"name": getattr(s, "name", None) or f"x{i}",
                 "shape": list(s.shape), "dtype": str(s.dtype)}
                for i, s in enumerate(input_spec)
            ] if input_spec is not None else None,
        }, f)

    if input_spec is not None and isinstance(layer, Layer):
        from jax import export as jax_export

        was_training = layer.training
        layer.eval()
        try:
            params, buffers = layer.functional_state()

            def infer_fn(params, buffers, *xs):
                restore = layer.bind_functional_state(params, buffers)
                try:
                    outs = layer(*[Tensor(x) for x in xs])
                finally:
                    restore()
                if isinstance(outs, (tuple, list)):
                    return tuple(o._value for o in outs)
                return outs._value

            shapes = [jax.ShapeDtypeStruct(tuple(s.shape), np.dtype(s.dtype) if isinstance(s.dtype, str) else s.dtype)
                      for s in input_spec]
            exported = jax_export.export(jax.jit(infer_fn))(params, buffers, *shapes)
            with open(path + ".pdmodel", "wb") as f:
                f.write(exported.serialize())
        except Exception as e:  # platform may not support export; params remain usable
            with open(path + ".pdmodel.err", "w") as f:
                f.write(repr(e))
        finally:
            if was_training:
                layer.train()


class TranslatedLayer(Layer):
    """Ref: fluid/dygraph/io.py TranslatedLayer — a loaded inference program."""

    def __init__(self, exported, params, buffers, info=None):
        super().__init__()
        self._exported = exported
        self._params = params    # flat {name: jnp array}, the exact exported pytree
        self._buffers_tree = buffers
        self._info = info or {}

    def forward(self, *args):
        raw = tuple(a._value if isinstance(a, Tensor) else a for a in args)
        out = self._exported.call(self._params, self._buffers_tree, *raw)
        if isinstance(out, (tuple, list)):
            outs = tuple(Tensor(o) for o in out)
            return outs[0] if len(outs) == 1 else outs
        return Tensor(out)

    def state_dict(self, *a, **kw):
        import jax.numpy as jnp

        return {k: Tensor(jnp.asarray(v))
                for k, v in {**self._params, **self._buffers_tree}.items()}


def load(path, **configs):
    """jit.load parity (ref fluid/dygraph/jit.py:1069)."""
    import jax.numpy as jnp

    with open(path + ".pdiparams", "rb") as f:
        state = pickle.load(f)
    info_file = path + ".pdiparams.info"
    info = {}
    if os.path.exists(info_file):
        with open(info_file, "rb") as f:
            info = pickle.load(f)
        params = {k: jnp.asarray(state[k]) for k in info["param_keys"]}
        buffers = {k: jnp.asarray(state[k]) for k in info["buffer_keys"]}
    else:  # legacy save: assume everything is a parameter
        params = {k: jnp.asarray(v) for k, v in state.items()}
        buffers = {}
    model_file = path + ".pdmodel"
    if os.path.exists(model_file):
        from jax import export as jax_export

        with open(model_file, "rb") as f:
            exported = jax_export.deserialize(f.read())
        return TranslatedLayer(exported, params, buffers, info)
    raise FileNotFoundError(f"no serialized program at {model_file}; "
                            f"load params with paddle.load({path + '.pdiparams'!r}) instead")


def enable_to_static(flag: bool = True):
    global _to_static_enabled
    _to_static_enabled = flag


_to_static_enabled = True


# ---- dy2static debug-surface shims (ref jit/__init__.py exports)
class ProgramTranslator:
    """Ref program_translator.py:991 — singleton toggling dy2static."""

    _instance = None

    @classmethod
    def get_instance(cls):
        if cls._instance is None:
            cls._instance = cls()
        return cls._instance

    def enable(self, flag=True):
        enable_to_static(flag)

    @property
    def enable_to_static(self):
        return _to_static_enabled


class TracedLayer:
    """Ref fluid/dygraph/jit.py TracedLayer — trace+save in one object."""

    def __init__(self, layer, inputs):
        self._layer = layer
        self._inputs = inputs

    @staticmethod
    def trace(layer, inputs):
        out = layer(*inputs)
        return out, TracedLayer(layer, inputs)

    def save_inference_model(self, path, feed=None, fetch=None):
        specs = [InputSpec(list(i.shape), str(i.dtype)) for i in self._inputs]
        save(self._layer, path, input_spec=specs)

    def __call__(self, *args):
        return self._layer(*args)


_VERBOSITY = 0


def set_verbosity(level=0, also_to_stdout=False):
    global _VERBOSITY
    _VERBOSITY = int(level)


def set_code_level(level=100, also_to_stdout=False):
    set_verbosity(level, also_to_stdout)
