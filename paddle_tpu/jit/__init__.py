"""paddle.jit parity (ref: python/paddle/jit/__init__.py:23 — to_static/save/load)."""
from __future__ import annotations

import os
import pickle

import jax
import numpy as np

from .to_static import to_static, declarative, not_to_static, StaticFunction  # noqa: F401
from .train_step import TrainStep  # noqa: F401
from ..nn.layer.layers import Layer
from ..tensor.tensor import Tensor


class InputSpec:
    """Ref: paddle.static.InputSpec."""

    def __init__(self, shape, dtype="float32", name=None):
        self.shape = tuple(shape)
        self.dtype = dtype
        self.name = name

    def __repr__(self):
        return f"InputSpec(shape={self.shape}, dtype={self.dtype})"


def save(layer, path, input_spec=None, **configs):
    """jit.save parity (ref fluid/dygraph/jit.py:649).

    Persists (a) the state_dict as .pdiparams and (b) an AOT-exported StableHLO
    program as .pdmodel when input_spec is given (jax.export replaces the reference's
    serialized inference ProgramDesc).
    """
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    state = {}
    if isinstance(layer, Layer):
        for k, v in layer.state_dict().items():
            state[k] = np.asarray(v._value)
    with open(path + ".pdiparams", "wb") as f:
        pickle.dump(state, f)

    if input_spec is not None and isinstance(layer, Layer):
        from jax import export as jax_export

        was_training = layer.training
        layer.eval()
        try:
            params, buffers = layer.functional_state()

            def infer_fn(params, buffers, *xs):
                restore = layer.bind_functional_state(params, buffers)
                try:
                    outs = layer(*[Tensor(x) for x in xs])
                finally:
                    restore()
                if isinstance(outs, (tuple, list)):
                    return tuple(o._value for o in outs)
                return outs._value

            shapes = [jax.ShapeDtypeStruct(tuple(s.shape), np.dtype(s.dtype) if isinstance(s.dtype, str) else s.dtype)
                      for s in input_spec]
            exported = jax_export.export(jax.jit(infer_fn))(params, buffers, *shapes)
            with open(path + ".pdmodel", "wb") as f:
                f.write(exported.serialize())
        except Exception as e:  # platform may not support export; params remain usable
            with open(path + ".pdmodel.err", "w") as f:
                f.write(repr(e))
        finally:
            if was_training:
                layer.train()


class TranslatedLayer(Layer):
    """Ref: fluid/dygraph/io.py TranslatedLayer — a loaded inference program."""

    def __init__(self, exported, state):
        super().__init__()
        self._exported = exported
        self._state = state

    def forward(self, *args):
        params = {k: v for k, v in self._state.items()}
        raw = tuple(a._value if isinstance(a, Tensor) else a for a in args)
        out = self._exported.call(params["__params__"], params["__buffers__"], *raw)
        if isinstance(out, (tuple, list)):
            outs = tuple(Tensor(o) for o in out)
            return outs[0] if len(outs) == 1 else outs
        return Tensor(out)


def load(path, **configs):
    """jit.load parity (ref fluid/dygraph/jit.py:1069)."""
    with open(path + ".pdiparams", "rb") as f:
        state = pickle.load(f)
    model_file = path + ".pdmodel"
    if os.path.exists(model_file):
        from jax import export as jax_export

        with open(model_file, "rb") as f:
            exported = jax_export.deserialize(f.read())
        # reconstruct params/buffers trees the exported fn expects
        t = TranslatedLayer(exported, {"__params__": {}, "__buffers__": {}})
        # state keys were flattened from named_parameters/buffers; the exported call
        # closure needs exactly the same pytree: rebuild both dicts
        t._state["__params__"] = {k: v for k, v in state.items()}
        t._state["__buffers__"] = {}
        return t
    raise FileNotFoundError(f"no serialized program at {model_file}; "
                            f"load params with paddle.load({path + '.pdiparams'!r}) instead")


def enable_to_static(flag: bool = True):
    global _to_static_enabled
    _to_static_enabled = flag


_to_static_enabled = True
