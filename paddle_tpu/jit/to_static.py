"""@to_static: compile a dygraph function/Layer into one XLA program.

Reference analog: dy2static (`python/paddle/fluid/dygraph/dygraph_to_static/` —
`program_translator.py:239` StaticFunction, `partial_program.py:363` run_program) which
AST-transforms Python into a ProgramDesc and runs it via `run_program_op` with CINN as
the optional compiler (`paddle/fluid/framework/paddle2cinn/`).

TPU-native design: no AST surgery.  The dygraph code *is* traceable because every op is
a pure JAX call — `to_static` builds a pure function over (params, buffers, rng_key,
*args), `jax.jit`s it, and routes calls through the autograd tape via `jax.vjp` of the
jitted function, so `loss.backward()` runs a single compiled backward program.  Python
control flow is baked at trace time (same as the reference's static path); for traced
control flow users write lax.cond/scan via paddle_tpu.static.nn.cond/while_loop.

Buffer mutation (BN running stats) is captured functionally: the traced function
returns updated buffer values as auxiliary outputs, written back after each call.
"""
from __future__ import annotations

import collections
import functools
import warnings
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from ..tensor.tensor import Tensor, Parameter, apply_op
from ..autograd import tape
from ..framework import random as _random
from ..nn.layer.layers import Layer


def _static_key(x, keepalive):
    """A stable, hashable cache key for a non-tensor argument.

    repr() is NOT stable for arbitrary objects (default reprs embed addresses,
    so a config object rebuilt each call would silently recompile every call —
    the SURVEY §7.3.4 recompilation storm).  Primitives and containers key by
    value; arrays by shape/dtype/content hash; everything else by type + id.
    Objects keyed by id are appended to `keepalive`, which the cache entry
    retains — otherwise CPython could reuse a freed object's id and silently
    hit a stale compiled variant."""
    if x is None or isinstance(x, (bool, int, float, str, bytes)):
        return ("P", x)
    if isinstance(x, (list, tuple)):
        return ("L", type(x).__name__, tuple(_static_key(i, keepalive) for i in x))
    if isinstance(x, dict):
        return ("D", tuple(sorted((str(k), _static_key(v, keepalive))
                                  for k, v in x.items())))
    if isinstance(x, np.ndarray):
        return ("A", x.shape, str(x.dtype), hash(x.tobytes()))
    keepalive.append(x)
    return ("O", type(x).__qualname__, id(x))


def _tree_flatten_args(args, kwargs):
    """Split (args, kwargs) into (tensor_leaves, rebuild_fn, static_signature,
    keepalive-objects)."""
    leaves = []
    sig = []
    keepalive: list = []

    def go(x):
        if isinstance(x, Tensor):
            leaves.append(x)
            sig.append(("T", tuple(x._value.shape), str(x._value.dtype)))
            return ("__leaf__", len(leaves) - 1)
        if isinstance(x, (list, tuple)):
            return type(x)(go(i) for i in x)
        if isinstance(x, dict):
            return {k: go(v) for k, v in x.items()}
        sig.append(_static_key(x, keepalive))
        return x

    skeleton = (go(list(args)), go(dict(kwargs)))

    def rebuild(raw_leaves, wrap):
        def back(x):
            if isinstance(x, tuple) and len(x) == 2 and x[0] == "__leaf__":
                return wrap(raw_leaves[x[1]])
            if isinstance(x, (list, tuple)) and not (len(x) == 2 and x[0] == "__leaf__"):
                return type(x)(back(i) for i in x)
            if isinstance(x, dict):
                return {k: back(v) for k, v in x.items()}
            return x

        a, k = back(skeleton[0]), back(skeleton[1])
        return a, k

    return leaves, rebuild, tuple(sig), keepalive


class StaticFunction:
    """Ref: program_translator.py:239 StaticFunction."""

    MAX_CACHE = 64          # LRU bound on compiled variants per function
    STORM_WARN_EVERY = 16   # warn every N fresh compiles (recompilation storm)

    def __init__(self, function, input_spec=None, build_strategy=None, layer=None, backend=None):
        if not getattr(function, "_paddle_not_to_static", False):
            # dy2static AST pass: Tensor-condition if/while -> lax control flow
            from .dy2static import convert_control_flow

            function = convert_control_flow(function)
        self._function = function
        self._layer = layer
        self._input_spec = input_spec
        self._cache: "collections.OrderedDict[Any, Any]" = collections.OrderedDict()
        self._compile_count = 0
        self.__name__ = getattr(function, "__name__", "static_fn")

    def __get__(self, instance, owner):
        if instance is None:
            return self
        return functools.partial(self.__call__, instance)

    def _get_layer(self, args):
        if self._layer is not None:
            return self._layer, args
        if args and isinstance(args[0], Layer):
            return args[0], args[1:]
        return None, args

    def _build(self, layer, training, n_leaves, rebuild, out_template):
        fn = self._function

        def pure_fn(param_vals, buffer_vals, key, leaf_vals):
            with _random.rng_key_scope(key):
                restore = (layer.bind_functional_state(param_vals, buffer_vals)
                           if layer is not None else (lambda: None))
                try:
                    a, k = rebuild(leaf_vals, lambda raw: Tensor(raw, stop_gradient=True))
                    # inputs participate in grad: mark diff leaves non-stop so the
                    # inner tape links them (outer vjp supplies actual cotangents)
                    with tape.enable_grad():
                        if layer is not None and self._layer is None:
                            out = fn(layer, *a, **k)
                        else:
                            out = fn(*a, **k)
                    out_leaves, out_rebuild = _flatten_output(out)
                    new_buffers = ({kk: b._value for kk, b in layer.named_buffers()}
                                   if layer is not None else {})
                    out_template.append(out_rebuild)
                finally:
                    restore()
                return tuple(o._value if isinstance(o, Tensor) else o for o in out_leaves), new_buffers

        return jax.jit(pure_fn)

    def _entry_for(self, layer, training, leaves, rebuild, sig, keepalive):
        key = (training, sig)
        entry = self._cache.get(key)
        if entry is None:
            self._compile_count += 1
            if self._compile_count % self.STORM_WARN_EVERY == 0:
                warnings.warn(
                    f"to_static('{self.__name__}') compiled {self._compile_count} "
                    f"variants — each distinct input shape/dtype or non-tensor "
                    f"argument value triggers a fresh XLA compile. Pad/bucket "
                    f"dynamic shapes or hoist varying python args out of the "
                    f"traced function (SURVEY §7.3.4 recompilation storm).",
                    stacklevel=3)
            out_template: list = []
            jitted = self._build(layer, training, len(leaves), rebuild, out_template)
            # keepalive pins id()-keyed arg objects for the entry's lifetime
            entry = {"jitted": jitted, "template": out_template,
                     "keepalive": keepalive}
            self._cache[key] = entry
            if len(self._cache) > self.MAX_CACHE:
                self._cache.popitem(last=False)  # evict LRU compiled variant
        else:
            self._cache.move_to_end(key)
        return entry

    def __call__(self, *args, **kwargs):
        layer, fargs = self._get_layer(args)
        leaves, rebuild, sig, keepalive = _tree_flatten_args(fargs, kwargs)
        training = layer.training if layer is not None else False
        entry = self._entry_for(layer, training, leaves, rebuild, sig, keepalive)
        jitted = entry["jitted"]

        if layer is not None:
            param_items = list(layer.named_parameters())
            buffer_items = list(layer.named_buffers())
        else:
            param_items, buffer_items = [], []
        param_tensors = [p for _, p in param_items]
        buffer_vals = {k: b._value for k, b in buffer_items}
        rng = _random.get_rng_key()

        def closed(*flat):
            pvals = {k: v for (k, _), v in zip(param_items, flat[: len(param_items)])}
            lvals = list(flat[len(param_items):])
            outs, new_bufs = jitted(pvals, buffer_vals, rng, lvals)
            return (*outs, *[new_bufs[k] for k, _ in buffer_items])

        all_inputs = (*param_tensors, *leaves)
        result = apply_op(closed, all_inputs, name=f"to_static:{self.__name__}")
        result = result if isinstance(result, tuple) else (result,)
        n_buf = len(buffer_items)
        out_leaves = result[: len(result) - n_buf]
        # write updated buffers back (BN running stats etc.)
        for (k, b), new in zip(buffer_items, result[len(result) - n_buf:]):
            b.set_value(new._value)
        out_rebuild = entry["template"][0] if entry["template"] else None
        if out_rebuild is None:
            return out_leaves[0] if len(out_leaves) == 1 else out_leaves
        return out_rebuild(list(out_leaves))

    @property
    def code(self):
        import inspect

        try:
            return inspect.getsource(self._function)
        except Exception:
            return "<source unavailable>"

    def concrete_program(self, *args, **kwargs):
        """Reference ConcreteProgram analog: the lowered program + its I/O.
        Here 'main_program' is the StableHLO text of the traced function."""
        lowered, leaves = self._lowered(args, kwargs)
        Concrete = collections.namedtuple("ConcreteProgram",
                                          ["main_program", "inputs", "outputs"])
        return Concrete(main_program=lowered.as_text(),
                        inputs=[("x%d" % i, tuple(l._value.shape),
                                 str(l._value.dtype)) for i, l in enumerate(leaves)],
                        outputs=None)

    def get_lowered(self, *args, **kwargs):
        """Return the jax lowering (StableHLO) for inspection/AOT export
        (the slot where the reference captured a ProgramDesc; §3.4)."""
        return self._lowered(args, kwargs)[0]

    def _lowered(self, args, kwargs):
        layer, fargs = self._get_layer(args)
        leaves, rebuild, sig, keepalive = _tree_flatten_args(fargs, kwargs)
        training = layer.training if layer is not None else False
        entry = self._entry_for(layer, training, leaves, rebuild, sig, keepalive)
        param_vals = ({k: p._value for k, p in layer.named_parameters()}
                      if layer is not None else {})
        buffer_vals = ({k: b._value for k, b in layer.named_buffers()}
                       if layer is not None else {})
        key = _random.get_rng_key()
        lowered = entry["jitted"].lower(param_vals, buffer_vals, key,
                                        [l._value for l in leaves])
        return lowered, leaves


def _flatten_output(out):
    leaves = []

    def go(x):
        if isinstance(x, Tensor):
            leaves.append(x)
            return ("__leaf__", len(leaves) - 1)
        if isinstance(x, (list, tuple)):
            return type(x)(go(i) for i in x)
        if isinstance(x, dict):
            return {k: go(v) for k, v in x.items()}
        return x

    skeleton = go(out)

    def rebuild(ts):
        def back(x):
            if isinstance(x, tuple) and len(x) == 2 and x[0] == "__leaf__":
                return ts[x[1]]
            if isinstance(x, (list, tuple)) and not (len(x) == 2 and x[0] == "__leaf__"):
                return type(x)(back(i) for i in x)
            if isinstance(x, dict):
                return {k: back(v) for k, v in x.items()}
            return x

        return back(skeleton)

    return leaves, rebuild


def to_static(function=None, input_spec=None, build_strategy=None, backend=None, **kwargs):
    """@paddle.jit.to_static parity (ref fluid/dygraph/jit.py:163 declarative)."""

    def decorate(fn):
        if isinstance(fn, Layer):
            if getattr(fn.forward, "_paddle_not_to_static", False):
                return fn
            sf = StaticFunction(fn.forward, input_spec, build_strategy, layer=fn)
            fn.forward = sf
            return fn
        if getattr(fn, "_paddle_not_to_static", False):
            return fn
        return StaticFunction(fn, input_spec, build_strategy)

    if function is not None:
        return decorate(function)
    return decorate


declarative = to_static


def not_to_static(fn):
    """Exclude `fn` from to_static conversion (ref jit.py not_to_static):
    a later to_static(fn) returns it unchanged and it keeps running eagerly."""
    fn._paddle_not_to_static = True
    return fn


class ignore_module:
    def __init__(self, modules):
        pass
