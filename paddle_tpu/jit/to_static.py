"""@to_static: compile a dygraph function/Layer into one XLA program.

Reference analog: dy2static (`python/paddle/fluid/dygraph/dygraph_to_static/` —
`program_translator.py:239` StaticFunction, `partial_program.py:363` run_program) which
AST-transforms Python into a ProgramDesc and runs it via `run_program_op` with CINN as
the optional compiler (`paddle/fluid/framework/paddle2cinn/`).

TPU-native design: no AST surgery.  The dygraph code *is* traceable because every op is
a pure JAX call — `to_static` builds a pure function over (params, buffers, rng_key,
*args), `jax.jit`s it, and routes calls through the autograd tape via `jax.vjp` of the
jitted function, so `loss.backward()` runs a single compiled backward program.  Python
control flow is baked at trace time (same as the reference's static path); for traced
control flow users write lax.cond/scan via paddle_tpu.static.nn.cond/while_loop.

Buffer mutation (BN running stats) is captured functionally: the traced function
returns updated buffer values as auxiliary outputs, written back after each call.
"""
from __future__ import annotations

import functools
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from ..tensor.tensor import Tensor, Parameter, apply_op
from ..autograd import tape
from ..framework import random as _random
from ..nn.layer.layers import Layer


def _tree_flatten_args(args, kwargs):
    """Split (args, kwargs) into (tensor_leaves, rebuild_fn, static_signature)."""
    leaves = []
    sig = []

    def go(x):
        if isinstance(x, Tensor):
            leaves.append(x)
            sig.append(("T", tuple(x._value.shape), str(x._value.dtype)))
            return ("__leaf__", len(leaves) - 1)
        if isinstance(x, (list, tuple)):
            return type(x)(go(i) for i in x)
        if isinstance(x, dict):
            return {k: go(v) for k, v in x.items()}
        sig.append(("S", repr(x)))
        return x

    skeleton = (go(list(args)), go(dict(kwargs)))

    def rebuild(raw_leaves, wrap):
        def back(x):
            if isinstance(x, tuple) and len(x) == 2 and x[0] == "__leaf__":
                return wrap(raw_leaves[x[1]])
            if isinstance(x, (list, tuple)) and not (len(x) == 2 and x[0] == "__leaf__"):
                return type(x)(back(i) for i in x)
            if isinstance(x, dict):
                return {k: back(v) for k, v in x.items()}
            return x

        a, k = back(skeleton[0]), back(skeleton[1])
        return a, k

    return leaves, rebuild, tuple(sig)


class StaticFunction:
    """Ref: program_translator.py:239 StaticFunction."""

    def __init__(self, function, input_spec=None, build_strategy=None, layer=None, backend=None):
        self._function = function
        self._layer = layer
        self._input_spec = input_spec
        self._cache: dict[Any, Any] = {}
        self.__name__ = getattr(function, "__name__", "static_fn")

    def __get__(self, instance, owner):
        if instance is None:
            return self
        return functools.partial(self.__call__, instance)

    def _get_layer(self, args):
        if self._layer is not None:
            return self._layer, args
        if args and isinstance(args[0], Layer):
            return args[0], args[1:]
        return None, args

    def _build(self, layer, training, n_leaves, rebuild, out_template):
        fn = self._function

        def pure_fn(param_vals, buffer_vals, key, leaf_vals):
            with _random.rng_key_scope(key):
                restore = (layer.bind_functional_state(param_vals, buffer_vals)
                           if layer is not None else (lambda: None))
                try:
                    a, k = rebuild(leaf_vals, lambda raw: Tensor(raw, stop_gradient=True))
                    # inputs participate in grad: mark diff leaves non-stop so the
                    # inner tape links them (outer vjp supplies actual cotangents)
                    with tape.enable_grad():
                        if layer is not None and self._layer is None:
                            out = fn(layer, *a, **k)
                        else:
                            out = fn(*a, **k)
                    out_leaves, out_rebuild = _flatten_output(out)
                    new_buffers = ({kk: b._value for kk, b in layer.named_buffers()}
                                   if layer is not None else {})
                    out_template.append(out_rebuild)
                finally:
                    restore()
                return tuple(o._value if isinstance(o, Tensor) else o for o in out_leaves), new_buffers

        return jax.jit(pure_fn)

    def __call__(self, *args, **kwargs):
        layer, fargs = self._get_layer(args)
        leaves, rebuild, sig = _tree_flatten_args(fargs, kwargs)
        training = layer.training if layer is not None else False
        key = (training, sig)
        entry = self._cache.get(key)
        if entry is None:
            out_template: list = []
            jitted = self._build(layer, training, len(leaves), rebuild, out_template)
            entry = {"jitted": jitted, "template": out_template}
            self._cache[key] = entry
        jitted = entry["jitted"]

        if layer is not None:
            param_items = list(layer.named_parameters())
            buffer_items = list(layer.named_buffers())
        else:
            param_items, buffer_items = [], []
        param_tensors = [p for _, p in param_items]
        buffer_vals = {k: b._value for k, b in buffer_items}
        rng = _random.get_rng_key()

        def closed(*flat):
            pvals = {k: v for (k, _), v in zip(param_items, flat[: len(param_items)])}
            lvals = list(flat[len(param_items):])
            outs, new_bufs = jitted(pvals, buffer_vals, rng, lvals)
            return (*outs, *[new_bufs[k] for k, _ in buffer_items])

        all_inputs = (*param_tensors, *leaves)
        result = apply_op(closed, all_inputs, name=f"to_static:{self.__name__}")
        result = result if isinstance(result, tuple) else (result,)
        n_buf = len(buffer_items)
        out_leaves = result[: len(result) - n_buf]
        # write updated buffers back (BN running stats etc.)
        for (k, b), new in zip(buffer_items, result[len(result) - n_buf:]):
            b.set_value(new._value)
        out_rebuild = entry["template"][0] if entry["template"] else None
        if out_rebuild is None:
            return out_leaves[0] if len(out_leaves) == 1 else out_leaves
        return out_rebuild(list(out_leaves))

    @property
    def code(self):
        import inspect

        try:
            return inspect.getsource(self._function)
        except Exception:
            return "<source unavailable>"

    def concrete_program(self):
        return None

    def get_lowered(self, *args, **kwargs):
        """Return the jax lowering (StableHLO) for inspection/AOT export."""
        layer, fargs = self._get_layer(args)
        leaves, rebuild, sig = _tree_flatten_args(fargs, kwargs)
        raise NotImplementedError


def _flatten_output(out):
    leaves = []

    def go(x):
        if isinstance(x, Tensor):
            leaves.append(x)
            return ("__leaf__", len(leaves) - 1)
        if isinstance(x, (list, tuple)):
            return type(x)(go(i) for i in x)
        if isinstance(x, dict):
            return {k: go(v) for k, v in x.items()}
        return x

    skeleton = go(out)

    def rebuild(ts):
        def back(x):
            if isinstance(x, tuple) and len(x) == 2 and x[0] == "__leaf__":
                return ts[x[1]]
            if isinstance(x, (list, tuple)) and not (len(x) == 2 and x[0] == "__leaf__"):
                return type(x)(back(i) for i in x)
            if isinstance(x, dict):
                return {k: back(v) for k, v in x.items()}
            return x

        return back(skeleton)

    return leaves, rebuild


def to_static(function=None, input_spec=None, build_strategy=None, backend=None, **kwargs):
    """@paddle.jit.to_static parity (ref fluid/dygraph/jit.py:163 declarative)."""

    def decorate(fn):
        if isinstance(fn, Layer):
            sf = StaticFunction(fn.forward, input_spec, build_strategy, layer=fn)
            fn.forward = sf
            return fn
        return StaticFunction(fn, input_spec, build_strategy)

    if function is not None:
        return decorate(function)
    return decorate


declarative = to_static


def not_to_static(fn):
    return fn


class ignore_module:
    def __init__(self, modules):
        pass
