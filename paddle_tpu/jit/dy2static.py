"""dy2static: AST conversion of data-dependent Python control flow.

Ref: the dygraph_to_static transformer suite
(fluid/dygraph/dygraph_to_static/ast_transformer.py, ifelse_transformer.py,
loop_transformer.py, break_continue_transformer.py, return_transformer.py,
convert_operators.py) — `@to_static` functions get their `if`/`while`
statements rewritten so a Tensor-valued condition becomes graph control flow
instead of a silent single-branch trace.

TPU-native translation (SURVEY §7.1): the rewrite targets jax.lax.cond /
lax.while_loop directly.  The generated code uses the reference's
get_args/set_args closure pattern: branch bodies mutate the enclosing
function's locals through `nonlocal`, and the runtime converter snapshots /
restores them around each branch trace so both branches see the pre-branch
state.  Gradients flow natively: inside jit/to_static the whole program is
differentiated by jax.vjp, which understands lax.cond/while_loop.

Supported: `if`/`elif`/`else` and `while` over Tensor conditions, nested
arbitrarily, with Python-valued conditions keeping exact Python semantics;
`break`/`continue` in converted loops (compiled to carried flags — the lax
analog of the reference's BreakContinueTransformer: the loop test gains
`and not break_flag`, statements after a flag-set are guarded); early
`return` in Tensor-condition branches (the reference's ReturnTransformer,
done by restructuring: trailing code is pushed into the non-returning arm so
both lax.cond branches produce the return value); `return` inside converted
loop bodies (rewritten to a carried flag + zero-seeded value slot + break,
then merged after the loop — see _convert_loop_returns); and `for x in
tensor`, which compiles to an index-scan while (ONE lax.while_loop body
instead of S unrolled copies — the reference's ForNodeVisitor
canonicalization, loop_transformer.py).

Not converted (left as plain Python, which errors loudly on a traced
condition): `yield`, and `for` over non-range non-Tensor iterables
(trace-unrolled as before).
"""
from __future__ import annotations

import ast
import copy
import functools
import inspect
import textwrap
import types

import jax
import jax.numpy as jnp
import numpy as np

from ..tensor.tensor import Tensor

__all__ = ["convert_control_flow", "convert_ifelse", "convert_while"]

_HELPER = "__pt_jst__"
_PREFIX = "_pt_jst_"


class _Undefined:
    __slots__ = ()

    def __repr__(self):
        return "<undefined local>"


UNDEFINED = _Undefined()


class _PoisonedLocal:
    """Placeholder for a local whose value cannot escape compiled control
    flow (assigned in only one lax.cond branch, or first assigned inside a
    lax.while_loop body).  Any USE afterwards raises a targeted error naming
    the variable, instead of a confusing failure far from the cause —
    while legal branch-/loop-local temporaries stay silent."""

    __slots__ = ("name", "reason")

    def __init__(self, name, reason):
        object.__setattr__(self, "name", name)
        object.__setattr__(self, "reason", reason)

    def _err(self, *a, **k):
        raise ValueError(
            f"dy2static: variable '{self.name}' {self.reason}, so its value "
            "does not exist here; assign it on every path (or before the "
            "control flow) if you need it afterwards")

    def __getattr__(self, attr):
        self._err()

    def __repr__(self):
        return f"<local '{self.name}' (unavailable: {self.reason})>"

    __call__ = __bool__ = __len__ = __iter__ = __float__ = __int__ = _err
    __add__ = __radd__ = __sub__ = __rsub__ = __mul__ = __rmul__ = _err
    __truediv__ = __rtruediv__ = __getitem__ = __array__ = __index__ = _err
    __eq__ = __ne__ = __lt__ = __le__ = __gt__ = __ge__ = __neg__ = _err


# --------------------------------------------------------------------- runtime

def _raw(v):
    return v._value if isinstance(v, Tensor) else v


def _is_traced(v):
    return isinstance(_raw(v), jax.core.Tracer)


def _kind(v):
    if isinstance(v, Tensor):
        return "tensor"
    if isinstance(v, (bool, int, float, complex)) or hasattr(v, "dtype"):
        return "raw"
    return "static"


def _pack(vals, kinds):
    """Numeric leaves only, as raw arrays (the lax carry/branch output)."""
    return tuple(_raw(v) for v, k in zip(vals, kinds) if k != "static")


def _unpack(packed, kinds, statics):
    out = []
    it = iter(packed)
    st = iter(statics)
    for k in kinds:
        if k == "static":
            out.append(next(st))
        elif k == "tensor":
            out.append(Tensor(next(it)))
        else:
            out.append(next(it))
    return tuple(out)


def _truthy(v):
    return bool(jnp.all(v)) if hasattr(v, "dtype") else bool(v)


def and_not(test, flag):
    """`test and not flag` that stays Python when both are concrete — the
    rewritten loop test for loops containing `break`."""
    t, f = _raw(test), _raw(flag)
    if isinstance(t, jax.core.Tracer) or isinstance(f, jax.core.Tracer):
        return jnp.logical_and(jnp.all(t), jnp.logical_not(jnp.all(f)))
    return _truthy(t) and not _truthy(f)


def neither(a, b):
    """`not (a or b)` — the guard over statements following a possible
    break/continue flag-set."""
    av, bv = _raw(a), _raw(b)
    if isinstance(av, jax.core.Tracer) or isinstance(bv, jax.core.Tracer):
        return jnp.logical_not(jnp.logical_or(jnp.any(av), jnp.any(bv)))
    return not (_truthy(av) or _truthy(bv))


def not_flag(a):
    av = _raw(a)
    if isinstance(av, jax.core.Tracer):
        return jnp.logical_not(jnp.any(av))
    return not _truthy(av)


# ---- for-over-Tensor runtime (ref loop_transformer.py ForNodeVisitor: the
# reference canonicalizes `for x in tensor` to an indexed while; here the
# generated while compiles to ONE lax.while_loop body instead of S unrolled
# copies when traced, and stays a plain Python loop in eager mode)

def is_tensor_seq(x):
    return isinstance(x, Tensor) and getattr(x, "ndim", 0) >= 1


def all_tensor_seqs(*xs):
    return all(is_tensor_seq(x) for x in xs)


def index_add(i, start):
    """Loop counter for `enumerate(tensor, start)` bodies."""
    return _raw(i) + start


def index_lt_min(i, *seqs):
    """Loop test against the SHORTEST sequence (zip semantics)."""
    return _raw(i) < min(s.shape[0] for s in seqs)


def index0():
    # a RAW numpy scalar, deliberately not a jax array: jnp constants created
    # inside a trace are tracers on this JAX version, which would hide the
    # static trip count from seq_trips
    return np.int32(0)


def index_lt(i, seq):
    return _raw(i) < seq.shape[0]


def index_get(seq, i):
    return seq[i]


def index_incr(i):
    v = _raw(i)
    if isinstance(v, jax.core.Tracer):
        return v + 1
    return np.int32(v + 1)


def _is_loop_ret_name(nm):
    """Slots created by the return-in-loop rewrite (`_pt_lretv*`): their value
    is only ever READ under the paired flag, so zero-filling the not-assigned
    path is safe — it lets the value escape lax.cond/while_loop carries."""
    return isinstance(nm, str) and nm.startswith("_pt_lretv")


def trip_count(i, stop, step=1):
    """Remaining trip count of a desugared for-range/for-tensor loop, or None
    when any bound is traced.  A concrete count lets convert_while compile the
    loop as a masked lax.scan — which reverse-differentiates — instead of
    lax.while_loop (forward-only in JAX)."""
    vals = [_raw(i), _raw(stop), _raw(step)]
    if any(isinstance(v, jax.core.Tracer) for v in vals):
        return None
    iv, sv, st = (int(np.asarray(v)) for v in vals)
    if st == 0:
        return None
    import math as _math

    return max(0, _math.ceil((sv - iv) / st))


def seq_trips(i, *seqs):
    """Trip count for `for x in tensor` / zip-of-tensors: the (static)
    shortest leading dim minus the already-peeled prefix."""
    iv = _raw(i)
    if isinstance(iv, jax.core.Tracer):
        return None
    n = min(s.shape[0] for s in seqs)
    return max(0, n - int(np.asarray(iv)))


def convert_ifelse(pred, true_fn, false_fn, get_args, set_args, names=()):
    """Generated-code entry for a rewritten `if` (ref convert_operators.py
    convert_ifelse)."""
    pv = _raw(pred)
    if not isinstance(pv, jax.core.Tracer):
        if _truthy(pv):
            true_fn()
        else:
            false_fn()
        return

    init = get_args()

    def _probe(fn):
        """Trace the branch once in the OUTER trace to learn each slot's
        fate (the produced ops are dead code XLA removes).  Restores the
        pre-branch locals AND the framework RNG position: branch bodies
        execute twice at trace time (probe + lax.cond trace), so without
        the snapshot a dropout/randn inside a branch would consume an
        extra key split and silently shift the random stream."""
        from ..framework import random as _fr

        gen = _fr.default_generator()
        rng_snapshot = gen._key
        set_args(init)
        fn()
        out = get_args()
        set_args(init)
        gen._key = rng_snapshot
        return out

    out_t, out_f = _probe(true_fn), _probe(false_fn)
    kinds_t = [_kind(v) for v in out_t]
    kinds_f = [_kind(v) for v in out_f]
    carried, out_kind, dead, final_static = [], [], [], {}
    zero_fill = {}  # slot -> raw zeros for the branch that leaves it unset
    for i, (vt, vf, kt, kf) in enumerate(zip(out_t, out_f, kinds_t, kinds_f)):
        nm = names[i] if i < len(names) else f"#{i}"
        t_un, f_un = isinstance(vt, _Undefined), isinstance(vf, _Undefined)
        if t_un and f_un:
            final_static[i] = vt  # untouched by either branch
        elif (t_un or f_un) and _is_loop_ret_name(nm) \
                and _kind(vf if t_un else vt) != "static":
            # return-in-loop value slot assigned by one branch only: the
            # unassigned side carries zeros (never read — the paired flag
            # stays False on that path)
            defined = vf if t_un else vt
            zero_fill[i] = jnp.zeros_like(_raw(defined))
            carried.append(i)
            out_kind.append("tensor" if isinstance(defined, Tensor) else "raw")
        elif t_un or f_un:
            dead.append(i)  # branch-local temp: poisoned, errors only on use
        elif kt == "static" and kf == "static":
            if vt is not vf:
                raise ValueError(
                    f"dy2static: variable '{nm}' is bound to different "
                    "Python objects by the two branches of a "
                    "Tensor-condition `if`; only Tensor/numeric values can "
                    "be merged through compiled control flow")
            final_static[i] = vt
        elif kt != "static" and kf != "static":
            st, sf = jnp.shape(_raw(vt)), jnp.shape(_raw(vf))
            if st != sf:
                raise ValueError(
                    f"dy2static: variable '{nm}' has shape {st} in the true "
                    f"branch but {sf} in the false branch of a "
                    "Tensor-condition `if`; both branches must produce the "
                    "same shape")
            carried.append(i)
            out_kind.append("tensor" if "tensor" in (kt, kf) else "raw")
        else:
            raise ValueError(
                f"dy2static: variable '{nm}' is a Tensor/numeric in one "
                "branch of a Tensor-condition `if` but a plain Python object "
                "in the other; both branches must assign the same kind")

    def _branch(fn):
        def run():
            set_args(init)
            fn()
            out = get_args()
            return tuple(zero_fill[i] if isinstance(out[i], _Undefined)
                         else _raw(out[i]) for i in carried)
        return run

    res = jax.lax.cond(jnp.all(pv), _branch(true_fn), _branch(false_fn))
    if not isinstance(res, tuple):
        res = (res,)
    final = list(init)
    for j, i in enumerate(carried):
        final[i] = Tensor(res[j]) if out_kind[j] == "tensor" else res[j]
    for i, v in final_static.items():
        final[i] = v
    for i in dead:
        final[i] = _PoisonedLocal(
            names[i] if i < len(names) else f"#{i}",
            "is assigned in only one branch of a Tensor-condition `if`")
    set_args(tuple(final))


_NO_CONVERT_MODULE_PREFIXES = ("paddle_tpu", "jax", "numpy", "builtins",
                               "functools", "itertools", "math", "typing")


def convert_call(fn):
    """Resolve a callee at runtime (ref convert_operators.py convert_call):
    plain user-defined functions get the same control-flow conversion as the
    decorated function (cached on the function object); framework/builtin
    callables pass through untouched."""
    inner = fn.__func__ if isinstance(fn, types.MethodType) else fn
    if not isinstance(inner, types.FunctionType):
        return fn
    mod = inner.__module__ or ""
    if any(mod == p or mod.startswith(p + ".") for p in _NO_CONVERT_MODULE_PREFIXES):
        return fn
    cached = getattr(inner, "_pt_d2s_converted_fn", None)
    if cached is None:
        try:
            cached = convert_control_flow(inner)
        except Exception:
            cached = inner
        try:
            inner._pt_d2s_converted_fn = cached
        except (AttributeError, TypeError):
            cached = inner
    if isinstance(fn, types.MethodType):
        return types.MethodType(cached, fn.__self__)
    return cached


def convert_while(test_fn, body_fn, get_args, set_args, names=(), bound_fn=None,
                  force_compile=False):
    """Generated-code entry for a rewritten `while` (ref convert_while_loop).

    bound_fn (for-range / for-tensor desugar only) returns the loop's
    remaining trip count when it is statically known, else None.  A known
    bound compiles the loop as a masked lax.scan — reverse-differentiable —
    instead of lax.while_loop (which JAX cannot transpose).

    force_compile (for-tensor only): when the loop data is traced, go
    straight to the scan without eager peeling even though the index test is
    concrete — ONE compiled body instead of seq-len unrolled copies.  Plain
    `for i in range(n)` keeps unroll semantics on purpose: user bodies often
    index Python structures with the loop variable (layers[i])."""
    # Python semantics while the test stays concrete: iterate eagerly (the
    # loop unrolls under trace).  If the test BECOMES traced mid-loop (e.g.
    # `for i in range(10)` or `while True:` with a Tensor-condition break —
    # the flag enters the test), the executed iterations are already peeled
    # into the outer trace; compile the remainder as a lax.while_loop from
    # the current locals.
    # the trip bound must be read at LOOP ENTRY: once the body runs, carried
    # flags/index can become traced (lax.cond merges) and the count is lost.
    # Peeled iterations decrement it so the compiled remainder is exact.
    trips = bound_fn() if bound_fn is not None else None
    if not (force_compile and trips is not None and trips > 0
            and any(_is_traced(v) for v in get_args())):
        first = _raw(test_fn())
        while not isinstance(first, jax.core.Tracer):
            if not _truthy(first):
                return
            body_fn()
            if trips is not None:
                trips = max(0, trips - 1)
            first = _raw(test_fn())
        if trips is not None and trips <= 0:
            # bound exhausted while the test stayed concrete — but flags are
            # now traced; fall through to compile a zero-trip scan is wrong,
            # so recompute: the remaining-count is 0 only if the bound was
            # exact; guard against a stale bound by keeping the while path
            trips = None

    init_vals = get_args()
    # return-in-loop value slots (`_pt_lretv*`) start UNDEFINED but must be
    # carried: probe the body ONCE in the outer trace (dead code to XLA) to
    # learn their shape/dtype, then seed the carry with zeros — the paired
    # flag guards every read, so the zeros are never observed
    ret_slots = [j for j, v in enumerate(init_vals)
                 if isinstance(v, _Undefined) and j < len(names)
                 and _is_loop_ret_name(names[j])]
    if ret_slots:
        from ..framework import random as _fr

        gen = _fr.default_generator()
        rng_snapshot = gen._key
        set_args(init_vals)
        body_fn()
        probe_out = get_args()
        set_args(init_vals)
        gen._key = rng_snapshot
        init_list = list(init_vals)
        for j in ret_slots:
            pv = probe_out[j]
            if isinstance(pv, _Undefined) or _kind(pv) == "static":
                raise ValueError(
                    "dy2static: `return` inside a compiled Tensor-condition "
                    "loop must return a Tensor/numeric value")
            z = jnp.zeros_like(_raw(pv))
            init_list[j] = Tensor(z) if isinstance(pv, Tensor) else z
        init_vals = tuple(init_list)
    # vars undefined before the loop are loop-local temporaries: each
    # iteration reassigns them before use, so they are not carried (their
    # UNDEFINED placeholder classifies as "static" and round-trips untouched)
    kinds = [_kind(v) for v in init_vals]
    statics = [v for v, k in zip(init_vals, kinds) if k == "static"]
    promoted = set()  # static-slot indices that held tensors inside the body

    def _run_body_collect(carry_vals):
        set_args(_unpack(carry_vals, kinds, statics))
        body_fn()
        out = get_args()
        for j, (v, k) in enumerate(zip(out, kinds)):
            if k == "static" and isinstance(init_vals[j], _Undefined) \
                    and _kind(v) != "static":
                promoted.add(j)
        return _pack(out, kinds)

    if trips is not None:
        # bounded loop: masked scan.  Each step evaluates the (traced) test;
        # once it goes false the carry stops updating.  Runs exactly `trips`
        # steps — iterations past a break/early-exit are masked no-ops.
        def step(carry, _):
            done, vals = carry
            set_args(_unpack(vals, kinds, statics))
            t = jnp.all(_raw(test_fn()))
            active = jnp.logical_and(jnp.logical_not(done), t)
            new = _run_body_collect(vals)
            merged = tuple(jnp.where(active, n, o) for n, o in zip(new, vals))
            return (jnp.logical_or(done, jnp.logical_not(t)), merged), None

        (_, out), _ = jax.lax.scan(
            step, (jnp.asarray(False), _pack(init_vals, kinds)), None,
            length=int(trips))
    else:
        def cond(carry):
            set_args(_unpack(carry, kinds, statics))
            return jnp.all(_raw(test_fn()))

        out = jax.lax.while_loop(cond, _run_body_collect,
                                 _pack(init_vals, kinds))
    final = list(_unpack(out, kinds, statics))
    for j in promoted:
        final[j] = _PoisonedLocal(
            names[j] if j < len(names) else f"<local {j}>",
            "is first assigned inside a compiled Tensor-condition loop")
    set_args(tuple(final))


# ----------------------------------------------------------------- AST rewrite

class _AssignedNames(ast.NodeVisitor):
    """Names bound by a statement list, excluding nested scopes' internals."""

    def __init__(self):
        self.names = set()

    def visit_Name(self, node):
        if isinstance(node.ctx, (ast.Store, ast.Del)) and not node.id.startswith(_PREFIX):
            self.names.add(node.id)

    def visit_FunctionDef(self, node):
        if not node.name.startswith(_PREFIX):
            self.names.add(node.name)
        # don't descend: its body is a new scope

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_ClassDef(self, node):
        self.names.add(node.name)

    def visit_Lambda(self, node):
        pass


def _assigned(stmts):
    v = _AssignedNames()
    for s in stmts:
        v.visit(s)
    return v.names


class _BlockersFound(Exception):
    pass


class _FindBlockers(ast.NodeVisitor):
    """Return/Yield anywhere (excluding nested scopes); Break/Continue not
    enclosed in a nested loop."""

    def __init__(self):
        self.loop_depth = 0

    def visit_Return(self, node):
        raise _BlockersFound

    def visit_Yield(self, node):
        raise _BlockersFound

    visit_YieldFrom = visit_Return

    def visit_Break(self, node):
        if self.loop_depth == 0:
            raise _BlockersFound

    visit_Continue = visit_Break

    def visit_While(self, node):
        self.loop_depth += 1
        self.generic_visit(node)
        self.loop_depth -= 1

    visit_For = visit_While

    def visit_FunctionDef(self, node):
        pass

    visit_AsyncFunctionDef = visit_FunctionDef
    visit_Lambda = visit_FunctionDef


def _has_blockers(stmts, in_loop=False):
    f = _FindBlockers()
    if in_loop:
        # break/continue at this level belong to the loop being transformed
        f.loop_depth = 0
    try:
        for s in stmts:
            f.visit(s)
    except _BlockersFound:
        return True
    return False


def _has_ret_yield(stmts):
    """Return/Yield only — break/continue are convertible now."""
    f = _FindBlockers()
    f.loop_depth = 1 << 30  # break/continue never trip
    try:
        for s in stmts:
            f.visit(s)
    except _BlockersFound:
        return True
    return False


def _name(n, ctx=None):
    return ast.Name(id=n, ctx=ctx or ast.Load())


def _guard_init(var):
    """try: var \n except NameError: var = __pt_jst__.UNDEFINED — creates a
    local binding (so `nonlocal` resolves) without clobbering live values."""
    return ast.Try(
        body=[ast.Expr(value=_name(var))],
        handlers=[ast.ExceptHandler(
            type=_name("NameError"),
            name=None,
            body=[ast.Assign(
                targets=[_name(var, ast.Store())],
                value=ast.Attribute(value=_name(_HELPER), attr="UNDEFINED",
                                    ctx=ast.Load()))])],
        orelse=[], finalbody=[])


def _lambda0(body_expr):
    """A zero-arg lambda AST node wrapping `body_expr`."""
    return ast.Lambda(
        args=ast.arguments(posonlyargs=[], args=[], vararg=None,
                           kwonlyargs=[], kw_defaults=[], kwarg=None,
                           defaults=[]),
        body=body_expr)


def _fn_def(name, body, args=()):
    node = ast.FunctionDef(
        name=name,
        args=ast.arguments(posonlyargs=[], args=[ast.arg(arg=a) for a in args],
                           vararg=None, kwonlyargs=[], kw_defaults=[],
                           kwarg=None, defaults=[]),
        body=body, decorator_list=[], returns=None)
    node.type_params = []  # py3.12 ast field
    return node


def _get_set_defs(idx, varlist):
    tup = ast.Tuple(elts=[_name(v) for v in varlist], ctx=ast.Load())
    get = _fn_def(f"{_PREFIX}get_{idx}", [ast.Return(value=tup)])
    set_body = [ast.Nonlocal(names=list(varlist)),
                ast.Assign(
                    targets=[ast.Tuple(elts=[_name(v, ast.Store()) for v in varlist],
                                       ctx=ast.Store())],
                    value=_name(f"{_PREFIX}v"))]
    set_ = _fn_def(f"{_PREFIX}set_{idx}", set_body, args=(f"{_PREFIX}v",))
    return get, set_


def _names_const(varlist):
    return ast.Tuple(elts=[ast.Constant(value=v) for v in varlist], ctx=ast.Load())


def _helper_expr(fn_name, args):
    return ast.Call(
        func=ast.Attribute(value=_name(_HELPER), attr=fn_name, ctx=ast.Load()),
        args=args, keywords=[])


def _flag_set(name, val=True):
    return ast.Assign(targets=[_name(name, ast.Store())],
                      value=ast.Constant(value=val))


# ---- break/continue rewrite (ref break_continue_transformer.py, compiled
# into carried boolean flags instead of fill-constant variables)

def _rewrite_bc(stmts, brk, cnt):
    """Replace this loop's break/continue with flag-sets; guard statements
    that follow a possible flag-set with `if neither(brk, cnt):`.  Returns
    the rewritten list.  Nested loops own their break/continue (Python binds
    them to the innermost loop), so they are not descended into."""
    out = []
    for idx, s in enumerate(stmts):
        if isinstance(s, ast.Break):
            out.append(_flag_set(brk))
            return out  # rest of the block is unreachable
        if isinstance(s, ast.Continue):
            out.append(_flag_set(cnt))
            return out
        if isinstance(s, ast.If):
            body = _rewrite_bc(s.body, brk, cnt)
            orelse = _rewrite_bc(s.orelse, brk, cnt)
            changed = body != s.body or orelse != s.orelse
            out.append(ast.If(test=s.test, body=body, orelse=orelse))
            if changed:
                rest = _rewrite_bc(stmts[idx + 1:], brk, cnt)
                if rest:
                    out.append(ast.If(
                        test=_helper_expr("neither", [_name(brk), _name(cnt)]),
                        body=rest, orelse=[]))
                return out
            continue
        out.append(s)
    return out


class _HasBC(ast.NodeVisitor):
    def __init__(self):
        self.found = False
        self.depth = 0

    def visit_Break(self, node):
        if self.depth == 0:
            self.found = True

    visit_Continue = visit_Break

    def visit_While(self, node):
        self.depth += 1
        self.generic_visit(node)
        self.depth -= 1

    visit_For = visit_While

    def visit_FunctionDef(self, node):
        pass

    visit_AsyncFunctionDef = visit_Lambda = visit_FunctionDef


def _has_bc(stmts):
    v = _HasBC()
    for s in stmts:
        v.visit(s)
    return v.found


def _bc_rewritable(stmts):
    """True when every break/continue of THIS loop sits under plain
    If-chains only — the shapes _rewrite_bc handles.  A break inside
    try/with/except stays un-rewritten (it would be a SyntaxError in the
    extracted body function), so such loops keep Python semantics."""
    for s in stmts:
        if isinstance(s, (ast.Break, ast.Continue)):
            continue
        if isinstance(s, ast.If):
            if not _bc_rewritable(s.body) or not _bc_rewritable(s.orelse):
                return False
            continue
        if isinstance(s, (ast.While, ast.For)):
            continue  # nested loop owns its break/continue
        if _has_bc([s]):  # try/with/match... containing this loop's b/c
            return False
    return True


# ---- early-return restructuring (ref return_transformer.py, done by
# pushing trailing code into the non-returning arm so both lax.cond
# branches produce the return value)

class _HasReturn(ast.NodeVisitor):
    def __init__(self):
        self.found = False

    def visit_Return(self, node):
        self.found = True

    def visit_FunctionDef(self, node):
        pass

    visit_AsyncFunctionDef = visit_Lambda = visit_ClassDef = visit_FunctionDef


def _contains_return(stmts):
    v = _HasReturn()
    for s in stmts:
        v.visit(s)
    return v.found


def _always_returns(stmts):
    if not stmts:
        return False
    last = stmts[-1]
    if isinstance(last, (ast.Return, ast.Raise)):
        return True
    if isinstance(last, ast.If):
        return bool(last.orelse) and _always_returns(last.body) \
            and _always_returns(last.orelse)
    return False


def _restructure_returns(stmts):
    """Push statements following a return-containing `if` into its arms, so
    every such `if` ends (in all arms) with an explicit Return.  The control
    flow transformer then merges the arms' return values through lax.cond.
    Semantics-preserving for plain Python too (fall-off-the-end == explicit
    `return None`)."""
    out = []
    for idx, s in enumerate(stmts):
        if isinstance(s, ast.If) and _contains_return([s]):
            rest = stmts[idx + 1:]
            body = list(s.body)
            if not _always_returns(body):
                body = body + copy.deepcopy(rest)
            orelse = list(s.orelse)
            if not _always_returns(orelse):
                orelse = orelse + copy.deepcopy(rest)
            body = _restructure_returns(body)
            orelse = _restructure_returns(orelse)
            if not _always_returns(body):
                body.append(ast.Return(value=None))
            if not _always_returns(orelse):
                orelse.append(ast.Return(value=None))
            out.append(ast.If(test=s.test, body=body, orelse=orelse))
            return out  # rest was absorbed into the arms
        out.append(s)
    return out


# ---- return-in-loop rewrite (ref return_transformer.py): `return V` inside
# a loop becomes  _pt_lretvN = V; _pt_lretfN = True; break  — riding the
# existing break machinery — and the loop gains `if _pt_lretfN: return
# _pt_lretvN` right after it.  _restructure_returns (which runs AFTER this
# pre-pass) then pushes trailing code into that if's arms so a traced flag
# merges through lax.cond.  Nested loops compose bottom-up: the inner loop's
# after-if return is itself a return inside the outer loop's body.

def _replace_returns(stmts, flag, val):
    """Rewrite Return at this loop's own level (descending plain If chains
    only).  Returns under try/with or other compound statements are left —
    the caller detects the leftover and abandons the rewrite."""
    out = []
    for s in stmts:
        if isinstance(s, ast.Return):
            out.append(ast.Assign(targets=[_name(val, ast.Store())],
                                  value=s.value or ast.Constant(value=None)))
            out.append(_flag_set(flag))
            out.append(ast.Break())
            return out  # rest of the block is unreachable
        if isinstance(s, ast.If):
            out.append(ast.If(test=s.test,
                              body=_replace_returns(s.body, flag, val),
                              orelse=_replace_returns(s.orelse, flag, val)))
            continue
        out.append(s)  # nested loops were already cleaned (bottom-up)
    return out


def _convert_loop_returns(stmts, counter=None):
    """Pre-pass over a statement list: eliminate `return` from loop bodies
    (bottom-up) so the loop transformer can convert those loops."""
    counter = counter if counter is not None else [0]
    out = []
    for s in stmts:
        if isinstance(s, (ast.While, ast.For)) and not s.orelse:
            body = _convert_loop_returns(s.body, counter)
            if _contains_return(body):
                i = counter[0]
                flag, val = f"_pt_lretf{i}", f"_pt_lretv{i}"
                new_body = _replace_returns(body, flag, val)
                if not _contains_return(new_body):
                    counter[0] += 1
                    s2 = copy.copy(s)
                    s2.body = new_body
                    out.append(_flag_set(flag, False))
                    out.append(s2)
                    out.append(ast.If(test=_name(flag),
                                      body=[ast.Return(value=_name(val))],
                                      orelse=[]))
                    continue
            s2 = copy.copy(s)
            s2.body = body
            out.append(s2)
            continue
        if isinstance(s, ast.If):
            s2 = ast.If(test=s.test,
                        body=_convert_loop_returns(s.body, counter),
                        orelse=_convert_loop_returns(s.orelse, counter))
            out.append(s2)
            continue
        out.append(s)
    return out


_BUILTIN_SKIP = {"range", "super", "len", "print", "isinstance", "type",
                 "getattr", "setattr", "hasattr", "enumerate", "zip", "list",
                 "tuple", "dict", "set", "int", "float", "bool", "str", "max",
                 "min", "sum", "abs", "sorted"}


class _ControlFlowTransformer(ast.NodeTransformer):
    def __init__(self):
        self.idx = 0

    def visit_Call(self, node):
        """Route callees through convert_call so helper functions get the
        same conversion (ref convert_call in convert_operators.py)."""
        self.generic_visit(node)
        f = node.func
        if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name) \
                and f.value.id == _HELPER:
            return node
        if isinstance(f, ast.Name) and (f.id.startswith(_PREFIX)
                                        or f.id in _BUILTIN_SKIP):
            return node
        node.func = ast.Call(
            func=ast.Attribute(value=_name(_HELPER), attr="convert_call",
                               ctx=ast.Load()),
            args=[f], keywords=[])
        return node

    def _helper_call(self, fn_name, args):
        return ast.Expr(value=_helper_expr(fn_name, args))

    def _convert_if(self, node):
        """The core if-conversion; `node`'s arms must be blocker-free."""
        varlist = sorted(_assigned(node.body) | _assigned(node.orelse))
        if not varlist:
            return node
        i = self.idx
        self.idx += 1
        inits = [_guard_init(v) for v in varlist]
        nl = ast.Nonlocal(names=list(varlist))
        true_fn = _fn_def(f"{_PREFIX}true_{i}", [nl] + node.body)
        false_fn = _fn_def(f"{_PREFIX}false_{i}",
                           [ast.Nonlocal(names=list(varlist))]
                           + (node.orelse or [ast.Pass()]))
        get, set_ = _get_set_defs(i, varlist)
        call = self._helper_call("convert_ifelse", [
            node.test,
            _name(true_fn.name), _name(false_fn.name),
            _name(get.name), _name(set_.name), _names_const(varlist)])
        return inits + [true_fn, false_fn, get, set_, call]

    def visit_If(self, node):
        self.generic_visit(node)
        # terminal-return if (produced by _restructure_returns): both arms
        # end with Return — strip them into a merged return variable
        if (node.body and isinstance(node.body[-1], ast.Return)
                and node.orelse and isinstance(node.orelse[-1], ast.Return)
                and not _has_blockers(node.body[:-1])
                and not _has_blockers(node.orelse[:-1])):
            retv = f"_pt_ret{self.idx}"
            def _arm(stmts):
                val = stmts[-1].value or ast.Constant(value=None)
                return stmts[:-1] + [ast.Assign(
                    targets=[_name(retv, ast.Store())], value=val)]
            node2 = ast.If(test=node.test, body=_arm(node.body),
                           orelse=_arm(node.orelse))
            out = self._convert_if(node2)
            out = out if isinstance(out, list) else [out]
            return out + [ast.Return(value=_name(retv))]
        if _has_blockers(node.body) or _has_blockers(node.orelse):
            return node
        return self._convert_if(node)

    def _prep_loop(self, node, extra_tail=None):
        """Rewrite this loop's break/continue into carried flags.  Returns
        (loop_node, pre_stmts).  `extra_tail` (the for-range increment) runs
        at the end of every non-broken iteration — including `continue`d
        ones, matching Python's for semantics."""
        if not _has_bc(node.body):
            body = list(node.body) + list(extra_tail or [])
            return ast.While(test=node.test, body=body, orelse=[]), []
        if not _bc_rewritable(node.body):
            return None, None  # caller leaves the loop as plain Python
        i = self.idx
        self.idx += 1
        brk, cnt = f"_pt_brk{i}", f"_pt_cnt{i}"
        body = _rewrite_bc(node.body, brk, cnt)
        body = [_flag_set(cnt, False)] + body
        if extra_tail:
            body.append(ast.If(test=_helper_expr("not_flag", [_name(brk)]),
                               body=list(extra_tail), orelse=[]))
        test = _helper_expr("and_not", [node.test, _name(brk)])
        return ast.While(test=test, body=body, orelse=[]), [_flag_set(brk, False)]

    def visit_For(self, node):
        """`for i in range(...)` desugars to a while (then converts like one);
        `for x in <expr>` gets a runtime dispatch: a Tensor iterable runs an
        index-scan while (ONE compiled body — ref loop_transformer.py
        ForNodeVisitor), anything else keeps Python semantics
        (trace-unrolled)."""
        if (node.orelse
                or not isinstance(node.target, (ast.Name, ast.Tuple))
                or _has_ret_yield(node.body)):
            self.generic_visit(node)
            return node
        if (not isinstance(node.target, ast.Name)
                or not isinstance(node.iter, ast.Call)
                or not isinstance(node.iter.func, ast.Name)
                or node.iter.func.id != "range"
                or node.iter.keywords
                or not 1 <= len(node.iter.args) <= 3):
            return self._convert_for_iterable(node)
        i = self.idx  # unique temp-name suffix (shared counter)
        self.idx += 1
        a = node.iter.args
        start = a[0] if len(a) >= 2 else ast.Constant(value=0)
        stop = a[1] if len(a) >= 2 else a[0]
        step = a[2] if len(a) == 3 else ast.Constant(value=1)
        it = node.target.id
        stop_n, step_n = f"{_PREFIX}stop{i}", f"{_PREFIX}step{i}"
        assigns = [
            ast.Assign(targets=[_name(it, ast.Store())], value=start),
            ast.Assign(targets=[_name(stop_n, ast.Store())], value=stop),
            ast.Assign(targets=[_name(step_n, ast.Store())], value=step),
        ]
        test = ast.IfExp(
            test=ast.Compare(left=_name(step_n), ops=[ast.Gt()],
                             comparators=[ast.Constant(value=0)]),
            body=ast.Compare(left=_name(it), ops=[ast.Lt()],
                             comparators=[_name(stop_n)]),
            orelse=ast.Compare(left=_name(it), ops=[ast.Gt()],
                               comparators=[_name(stop_n)]))
        incr = ast.AugAssign(target=_name(it, ast.Store()), op=ast.Add(),
                             value=_name(step_n))
        loop = ast.While(test=test, body=node.body, orelse=[])
        loop, pre = self._prep_loop(loop, extra_tail=[incr])
        if loop is None:  # break/continue in a non-rewritable position
            self.generic_visit(node)
            return node
        loop._pt_bound_expr = _lambda0(_helper_expr(
            "trip_count", [_name(it), _name(stop_n), _name(step_n)]))
        self.generic_visit(loop)
        out = self.visit_While(loop, skip_children=True)
        return assigns + pre + (out if isinstance(out, list) else [out])

    def _convert_for_iterable(self, node):
        """`for x in seq` (also `for i, x in enumerate(seq[, start])` and
        `for a, b in zip(s1, s2, ...)`): emit a runtime type dispatch —

            _pt_seqN = seq ...
            if __pt_jst__.all_tensor_seqs(_pt_seqN, ...):  # concrete test
                <index-scan while over rows, convertible to lax.scan>
            else:
                <the original Python for, trace-unrolled>

        Only the Tensor arm pays the while-conversion machinery; lists,
        dicts, generators take the untouched Python loop.  Ref: the
        ForNodeVisitor canonicalization (loop_transformer.py) covers the
        same three iterator forms."""
        it = node.iter
        enum_start = None
        enum_name = None
        if (isinstance(it, ast.Call) and isinstance(it.func, ast.Name)
                and it.func.id == "enumerate" and not it.keywords
                and 1 <= len(it.args) <= 2
                and isinstance(node.target, ast.Tuple)
                and len(node.target.elts) == 2
                and all(isinstance(e, ast.Name) for e in node.target.elts)):
            seq_exprs = [it.args[0]]
            row_names = [node.target.elts[1].id]
            enum_name = node.target.elts[0].id
            enum_start = it.args[1] if len(it.args) == 2 \
                else ast.Constant(value=0)
        elif (isinstance(it, ast.Call) and isinstance(it.func, ast.Name)
                and it.func.id == "zip" and not it.keywords
                and len(it.args) >= 2
                and isinstance(node.target, ast.Tuple)
                and len(node.target.elts) == len(it.args)
                and all(isinstance(e, ast.Name) for e in node.target.elts)):
            seq_exprs = list(it.args)
            row_names = [e.id for e in node.target.elts]
        elif isinstance(node.target, ast.Name):
            seq_exprs = [it]
            row_names = [node.target.id]
        else:
            self.generic_visit(node)
            return node
        i = self.idx
        self.idx += 1
        # the index must be a CARRIED loop var (plain `_pt_` prefix — the
        # `_pt_jst_` machinery prefix is excluded from carry varlists); the
        # sequences are read-only and resolve through the closure
        idx_n = f"_pt_ti{i}"
        seq_names = [f"{_PREFIX}seq{i}_{j}" for j in range(len(seq_exprs))]
        assigns = [ast.Assign(targets=[_name(sn, ast.Store())], value=se)
                   for sn, se in zip(seq_names, seq_exprs)]
        rows = []
        if enum_name is not None:
            start_n = f"{_PREFIX}start{i}"
            assigns.append(ast.Assign(targets=[_name(start_n, ast.Store())],
                                      value=enum_start))
            rows.append(ast.Assign(
                targets=[_name(enum_name, ast.Store())],
                value=_helper_expr("index_add", [_name(idx_n),
                                                 _name(start_n)])))
        rows += [ast.Assign(
            targets=[_name(rn, ast.Store())],
            value=_helper_expr("index_get", [_name(sn), _name(idx_n)]))
            for rn, sn in zip(row_names, seq_names)]
        incr = ast.Assign(targets=[_name(idx_n, ast.Store())],
                          value=_helper_expr("index_incr", [_name(idx_n)]))
        loop = ast.While(
            test=_helper_expr("index_lt_min",
                              [_name(idx_n)] + [_name(s) for s in seq_names]),
            body=rows + copy.deepcopy(node.body), orelse=[])
        loop, pre_bc = self._prep_loop(loop, extra_tail=[incr])
        if loop is None:  # break/continue in a non-rewritable position
            self.generic_visit(node)
            return node
        loop._pt_bound_expr = _lambda0(_helper_expr(
            "seq_trips", [_name(idx_n)] + [_name(s) for s in seq_names]))
        loop._pt_force_compile = True
        self.generic_visit(loop)
        out_t = self.visit_While(loop, skip_children=True)
        tensor_arm = (
            [ast.Assign(targets=[_name(idx_n, ast.Store())],
                        value=_helper_expr("index0", []))]
            + pre_bc + (out_t if isinstance(out_t, list) else [out_t]))
        if enum_name is not None:
            py_iter = ast.Call(func=_name("enumerate"),
                               args=[_name(seq_names[0]), _name(start_n)],
                               keywords=[])
        elif len(seq_names) > 1:
            py_iter = ast.Call(func=_name("zip"),
                               args=[_name(s) for s in seq_names],
                               keywords=[])
        else:
            py_iter = _name(seq_names[0])
        py_for = ast.For(target=node.target, iter=py_iter,
                         body=node.body, orelse=[])
        self.generic_visit(py_for)
        dispatch = ast.If(
            test=_helper_expr("all_tensor_seqs",
                              [_name(s) for s in seq_names]),
            body=tensor_arm, orelse=[py_for])
        return assigns + [dispatch]

    def visit_While(self, node, skip_children=False):
        pre = []
        if not skip_children:
            if node.orelse or _has_ret_yield(node.body):
                self.generic_visit(node)
                return node
            new_node, pre = self._prep_loop(node)
            if new_node is None:  # break/continue in a non-rewritable position
                self.generic_visit(node)
                return node
            node = new_node
            self.generic_visit(node)
        varlist = sorted(_assigned(node.body))
        if not varlist:
            return pre + [node] if pre else node
        i = self.idx
        self.idx += 1
        inits = [_guard_init(v) for v in varlist]
        test_fn = _fn_def(f"{_PREFIX}test_{i}", [ast.Return(value=node.test)])
        body_fn = _fn_def(f"{_PREFIX}body_{i}",
                          [ast.Nonlocal(names=list(varlist))] + node.body)
        get, set_ = _get_set_defs(i, varlist)
        call_args = [
            _name(test_fn.name), _name(body_fn.name),
            _name(get.name), _name(set_.name), _names_const(varlist)]
        bound_expr = getattr(node, "_pt_bound_expr", None)
        if bound_expr is not None:  # for-range / for-tensor: static trip count
            call_args.append(bound_expr)
            if getattr(node, "_pt_force_compile", False):
                call_args.append(ast.Constant(value=True))
        call = self._helper_call("convert_while", call_args)
        return pre + inits + [test_fn, body_fn, get, set_, call]


def _needs_conversion(tree):
    for node in ast.walk(tree):
        if isinstance(node, (ast.If, ast.While, ast.For)):
            return True
    return False


def convert_control_flow(fn):
    """Rewrite `fn`'s if/while statements for graph capture.  Falls back to
    the original function when the source is unavailable or the transform
    does not apply (no control flow, lambdas, builtins)."""
    if isinstance(fn, functools.partial) or not isinstance(
            fn, (types.FunctionType, types.MethodType)):
        return fn
    inner = fn.__func__ if isinstance(fn, types.MethodType) else fn
    if getattr(inner, "_pt_dy2static_converted", False):
        return fn
    try:
        src = textwrap.dedent(inspect.getsource(inner))
        tree = ast.parse(src)
    except (OSError, TypeError, SyntaxError, IndentationError):
        return fn
    fdef = tree.body[0]
    if not isinstance(fdef, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return fn
    if not _needs_conversion(fdef):
        return fn
    fdef.decorator_list = []  # don't re-apply @to_static etc. on exec
    fdef.body = _convert_loop_returns(fdef.body)
    fdef.body = _restructure_returns(fdef.body)
    new_body = _ControlFlowTransformer().visit(fdef)
    ast.fix_missing_locations(tree)

    from . import dy2static as _self_mod

    class _LiveGlobals(dict):
        """Overlay over the function's REAL globals: unknown names resolve
        live (so later-defined helpers / monkeypatching keep working),
        while the overlay carries the helper module + closure snapshot."""

        def __missing__(self, key):
            return inner.__globals__[key]

    glb = _LiveGlobals()
    # the import machinery reads these via raw dict lookups (no __missing__)
    for dunder in ("__name__", "__package__", "__spec__", "__loader__",
                   "__builtins__", "__file__"):
        if dunder in inner.__globals__:
            glb[dunder] = inner.__globals__[dunder]
    if inner.__closure__:
        try:
            glb.update({name: cell.cell_contents
                        for name, cell in zip(inner.__code__.co_freevars,
                                              inner.__closure__)})
        except ValueError:
            # an empty cell (recursive/forward-referencing nested function):
            # the snapshot can't represent it — leave the function alone
            return fn
    glb[_HELPER] = _self_mod
    try:
        code = compile(tree, filename=f"<dy2static {inner.__qualname__}>",
                       mode="exec")
        exec(code, glb)
    except SyntaxError:
        return fn
    new_fn = glb[fdef.name]
    new_fn.__defaults__ = inner.__defaults__
    new_fn.__kwdefaults__ = inner.__kwdefaults__
    new_fn._pt_dy2static_converted = True
    functools.update_wrapper(new_fn, inner, updated=())
    if isinstance(fn, types.MethodType):
        return types.MethodType(new_fn, fn.__self__)
    return new_fn
