"""dy2static: AST conversion of data-dependent Python control flow.

Ref: the dygraph_to_static transformer suite
(fluid/dygraph/dygraph_to_static/ast_transformer.py, ifelse_transformer.py,
loop_transformer.py, convert_operators.py) — `@to_static` functions get their
`if`/`while` statements rewritten so a Tensor-valued condition becomes graph
control flow instead of a silent single-branch trace.

TPU-native translation (SURVEY §7.1): the rewrite targets jax.lax.cond /
lax.while_loop directly.  The generated code uses the reference's
get_args/set_args closure pattern: branch bodies mutate the enclosing
function's locals through `nonlocal`, and the runtime converter snapshots /
restores them around each branch trace so both branches see the pre-branch
state.  Gradients flow natively: inside jit/to_static the whole program is
differentiated by jax.vjp, which understands lax.cond/while_loop.

Supported: `if`/`elif`/`else` and `while` over Tensor conditions, nested
arbitrarily, with Python-valued conditions keeping exact Python semantics.
Not converted (left as plain Python, which errors loudly on a traced
condition): branches containing `return`/`yield`, loops containing
`break`/`continue`, and `for` loops (trace-unrolled as before).
"""
from __future__ import annotations

import ast
import functools
import inspect
import textwrap
import types

import jax
import jax.numpy as jnp

from ..tensor.tensor import Tensor

__all__ = ["convert_control_flow", "convert_ifelse", "convert_while"]

_HELPER = "__pt_jst__"
_PREFIX = "_pt_jst_"


class _Undefined:
    __slots__ = ()

    def __repr__(self):
        return "<undefined local>"


UNDEFINED = _Undefined()


# --------------------------------------------------------------------- runtime

def _raw(v):
    return v._value if isinstance(v, Tensor) else v


def _is_traced(v):
    return isinstance(_raw(v), jax.core.Tracer)


def _kind(v):
    if isinstance(v, Tensor):
        return "tensor"
    if isinstance(v, (bool, int, float, complex)) or hasattr(v, "dtype"):
        return "raw"
    return "static"


def _pack(vals, kinds):
    """Numeric leaves only, as raw arrays (the lax carry/branch output)."""
    return tuple(_raw(v) for v, k in zip(vals, kinds) if k != "static")


def _unpack(packed, kinds, statics):
    out = []
    it = iter(packed)
    st = iter(statics)
    for k in kinds:
        if k == "static":
            out.append(next(st))
        elif k == "tensor":
            out.append(Tensor(next(it)))
        else:
            out.append(next(it))
    return tuple(out)


def convert_ifelse(pred, true_fn, false_fn, get_args, set_args):
    """Generated-code entry for a rewritten `if` (ref convert_operators.py
    convert_ifelse)."""
    pv = _raw(pred)
    if not isinstance(pv, jax.core.Tracer):
        if (bool(jnp.all(pv)) if hasattr(pv, "dtype") else bool(pv)):
            true_fn()
        else:
            false_fn()
        return

    init = get_args()
    observed = {}

    def _branch(fn, tag):
        def run():
            set_args(init)
            fn()
            out = get_args()
            if any(isinstance(v, _Undefined) for v in out):
                raise ValueError(
                    "dy2static: a variable is assigned in only one branch "
                    "of a Tensor-condition `if`; assign it in both branches "
                    "(or before the if)")
            kinds = [_kind(v) for v in out]
            observed[tag] = (kinds, [v for v, k in zip(out, kinds) if k == "static"])
            return _pack(out, kinds)

        return run

    # branches trace sequentially; jax enforces matching output structures
    out = jax.lax.cond(jnp.all(pv), _branch(true_fn, "t"), _branch(false_fn, "f"))
    if not isinstance(out, tuple):
        out = (out,)
    kinds, statics = observed["t"]
    kinds_f, statics_f = observed["f"]
    if kinds != kinds_f or any(a is not b for a, b in zip(statics, statics_f)):
        raise ValueError(
            "dy2static: the two branches of a Tensor-condition `if` produce "
            "different variable kinds/objects — both must assign the same "
            "tensor/python structure")
    set_args(_unpack(out, kinds, statics))


_NO_CONVERT_MODULE_PREFIXES = ("paddle_tpu", "jax", "numpy", "builtins",
                               "functools", "itertools", "math", "typing")


def convert_call(fn):
    """Resolve a callee at runtime (ref convert_operators.py convert_call):
    plain user-defined functions get the same control-flow conversion as the
    decorated function (cached on the function object); framework/builtin
    callables pass through untouched."""
    inner = fn.__func__ if isinstance(fn, types.MethodType) else fn
    if not isinstance(inner, types.FunctionType):
        return fn
    mod = inner.__module__ or ""
    if any(mod == p or mod.startswith(p + ".") for p in _NO_CONVERT_MODULE_PREFIXES):
        return fn
    cached = getattr(inner, "_pt_d2s_converted_fn", None)
    if cached is None:
        try:
            cached = convert_control_flow(inner)
        except Exception:
            cached = inner
        try:
            inner._pt_d2s_converted_fn = cached
        except (AttributeError, TypeError):
            cached = inner
    if isinstance(fn, types.MethodType):
        return types.MethodType(cached, fn.__self__)
    return cached


def convert_while(test_fn, body_fn, get_args, set_args):
    """Generated-code entry for a rewritten `while` (ref convert_while_loop)."""
    first = _raw(test_fn())
    if not isinstance(first, jax.core.Tracer):
        # Python semantics: the loop unrolls under trace if the BODY produces
        # tracers while the test stays concrete — exactly like before
        while (bool(jnp.all(first)) if hasattr(first, "dtype") else bool(first)):
            body_fn()
            first = _raw(test_fn())
        return

    init_vals = get_args()
    # vars undefined before the loop are loop-local temporaries: each
    # iteration reassigns them before use, so they are not carried (their
    # UNDEFINED placeholder classifies as "static" and round-trips untouched)
    kinds = [_kind(v) for v in init_vals]
    statics = [v for v, k in zip(init_vals, kinds) if k == "static"]

    def cond(carry):
        set_args(_unpack(carry, kinds, statics))
        return jnp.all(_raw(test_fn()))

    def body(carry):
        set_args(_unpack(carry, kinds, statics))
        body_fn()
        return _pack(get_args(), kinds)

    out = jax.lax.while_loop(cond, body, _pack(init_vals, kinds))
    set_args(_unpack(out, kinds, statics))


# ----------------------------------------------------------------- AST rewrite

class _AssignedNames(ast.NodeVisitor):
    """Names bound by a statement list, excluding nested scopes' internals."""

    def __init__(self):
        self.names = set()

    def visit_Name(self, node):
        if isinstance(node.ctx, (ast.Store, ast.Del)) and not node.id.startswith(_PREFIX):
            self.names.add(node.id)

    def visit_FunctionDef(self, node):
        if not node.name.startswith(_PREFIX):
            self.names.add(node.name)
        # don't descend: its body is a new scope

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_ClassDef(self, node):
        self.names.add(node.name)

    def visit_Lambda(self, node):
        pass


def _assigned(stmts):
    v = _AssignedNames()
    for s in stmts:
        v.visit(s)
    return v.names


class _BlockersFound(Exception):
    pass


class _FindBlockers(ast.NodeVisitor):
    """Return/Yield anywhere (excluding nested scopes); Break/Continue not
    enclosed in a nested loop."""

    def __init__(self):
        self.loop_depth = 0

    def visit_Return(self, node):
        raise _BlockersFound

    def visit_Yield(self, node):
        raise _BlockersFound

    visit_YieldFrom = visit_Return

    def visit_Break(self, node):
        if self.loop_depth == 0:
            raise _BlockersFound

    visit_Continue = visit_Break

    def visit_While(self, node):
        self.loop_depth += 1
        self.generic_visit(node)
        self.loop_depth -= 1

    visit_For = visit_While

    def visit_FunctionDef(self, node):
        pass

    visit_AsyncFunctionDef = visit_FunctionDef
    visit_Lambda = visit_FunctionDef


def _has_blockers(stmts, in_loop=False):
    f = _FindBlockers()
    if in_loop:
        # break/continue at this level belong to the loop being transformed
        f.loop_depth = 0
    try:
        for s in stmts:
            f.visit(s)
    except _BlockersFound:
        return True
    return False


def _name(n, ctx=None):
    return ast.Name(id=n, ctx=ctx or ast.Load())


def _guard_init(var):
    """try: var \n except NameError: var = __pt_jst__.UNDEFINED — creates a
    local binding (so `nonlocal` resolves) without clobbering live values."""
    return ast.Try(
        body=[ast.Expr(value=_name(var))],
        handlers=[ast.ExceptHandler(
            type=_name("NameError"),
            name=None,
            body=[ast.Assign(
                targets=[_name(var, ast.Store())],
                value=ast.Attribute(value=_name(_HELPER), attr="UNDEFINED",
                                    ctx=ast.Load()))])],
        orelse=[], finalbody=[])


def _fn_def(name, body, args=()):
    node = ast.FunctionDef(
        name=name,
        args=ast.arguments(posonlyargs=[], args=[ast.arg(arg=a) for a in args],
                           vararg=None, kwonlyargs=[], kw_defaults=[],
                           kwarg=None, defaults=[]),
        body=body, decorator_list=[], returns=None)
    node.type_params = []  # py3.12 ast field
    return node


def _get_set_defs(idx, varlist):
    tup = ast.Tuple(elts=[_name(v) for v in varlist], ctx=ast.Load())
    get = _fn_def(f"{_PREFIX}get_{idx}", [ast.Return(value=tup)])
    set_body = [ast.Nonlocal(names=list(varlist)),
                ast.Assign(
                    targets=[ast.Tuple(elts=[_name(v, ast.Store()) for v in varlist],
                                       ctx=ast.Store())],
                    value=_name(f"{_PREFIX}v"))]
    set_ = _fn_def(f"{_PREFIX}set_{idx}", set_body, args=(f"{_PREFIX}v",))
    return get, set_


_BUILTIN_SKIP = {"range", "super", "len", "print", "isinstance", "type",
                 "getattr", "setattr", "hasattr", "enumerate", "zip", "list",
                 "tuple", "dict", "set", "int", "float", "bool", "str", "max",
                 "min", "sum", "abs", "sorted"}


class _ControlFlowTransformer(ast.NodeTransformer):
    def __init__(self):
        self.idx = 0

    def visit_Call(self, node):
        """Route callees through convert_call so helper functions get the
        same conversion (ref convert_call in convert_operators.py)."""
        self.generic_visit(node)
        f = node.func
        if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name) \
                and f.value.id == _HELPER:
            return node
        if isinstance(f, ast.Name) and (f.id.startswith(_PREFIX)
                                        or f.id in _BUILTIN_SKIP):
            return node
        node.func = ast.Call(
            func=ast.Attribute(value=_name(_HELPER), attr="convert_call",
                               ctx=ast.Load()),
            args=[f], keywords=[])
        return node

    def _helper_call(self, fn_name, args):
        return ast.Expr(value=ast.Call(
            func=ast.Attribute(value=_name(_HELPER), attr=fn_name, ctx=ast.Load()),
            args=args, keywords=[]))

    def visit_If(self, node):
        self.generic_visit(node)
        if _has_blockers(node.body) or _has_blockers(node.orelse):
            return node
        varlist = sorted(_assigned(node.body) | _assigned(node.orelse))
        if not varlist:
            return node
        i = self.idx
        self.idx += 1
        inits = [_guard_init(v) for v in varlist]
        nl = ast.Nonlocal(names=list(varlist))
        true_fn = _fn_def(f"{_PREFIX}true_{i}", [nl] + node.body)
        false_fn = _fn_def(f"{_PREFIX}false_{i}",
                           [ast.Nonlocal(names=list(varlist))]
                           + (node.orelse or [ast.Pass()]))
        get, set_ = _get_set_defs(i, varlist)
        call = self._helper_call("convert_ifelse", [
            node.test,
            _name(true_fn.name), _name(false_fn.name),
            _name(get.name), _name(set_.name)])
        return inits + [true_fn, false_fn, get, set_, call]

    def visit_For(self, node):
        """`for i in range(...)` desugars to a while (then converts like one);
        any other iterable keeps Python semantics (trace-unrolled)."""
        self.generic_visit(node)
        if (node.orelse
                or not isinstance(node.target, ast.Name)
                or not isinstance(node.iter, ast.Call)
                or not isinstance(node.iter.func, ast.Name)
                or node.iter.func.id != "range"
                or node.iter.keywords
                or not 1 <= len(node.iter.args) <= 3
                or _has_blockers(node.body, in_loop=True)):
            return node
        i = self.idx  # unique temp-name suffix (shared counter)
        self.idx += 1
        a = node.iter.args
        start = a[0] if len(a) >= 2 else ast.Constant(value=0)
        stop = a[1] if len(a) >= 2 else a[0]
        step = a[2] if len(a) == 3 else ast.Constant(value=1)
        it = node.target.id
        stop_n, step_n = f"{_PREFIX}stop{i}", f"{_PREFIX}step{i}"
        assigns = [
            ast.Assign(targets=[_name(it, ast.Store())], value=start),
            ast.Assign(targets=[_name(stop_n, ast.Store())], value=stop),
            ast.Assign(targets=[_name(step_n, ast.Store())], value=step),
        ]
        test = ast.IfExp(
            test=ast.Compare(left=_name(step_n), ops=[ast.Gt()],
                             comparators=[ast.Constant(value=0)]),
            body=ast.Compare(left=_name(it), ops=[ast.Lt()],
                             comparators=[_name(stop_n)]),
            orelse=ast.Compare(left=_name(it), ops=[ast.Gt()],
                               comparators=[_name(stop_n)]))
        incr = ast.AugAssign(target=_name(it, ast.Store()), op=ast.Add(),
                             value=_name(step_n))
        loop = ast.While(test=test, body=node.body + [incr], orelse=[])
        out = self.visit_While(loop, skip_children=True)
        return assigns + (out if isinstance(out, list) else [out])

    def visit_While(self, node, skip_children=False):
        if not skip_children:
            self.generic_visit(node)
        if node.orelse or _has_blockers(node.body, in_loop=True):
            return node
        varlist = sorted(_assigned(node.body))
        if not varlist:
            return node
        i = self.idx
        self.idx += 1
        inits = [_guard_init(v) for v in varlist]
        test_fn = _fn_def(f"{_PREFIX}test_{i}", [ast.Return(value=node.test)])
        body_fn = _fn_def(f"{_PREFIX}body_{i}",
                          [ast.Nonlocal(names=list(varlist))] + node.body)
        get, set_ = _get_set_defs(i, varlist)
        call = self._helper_call("convert_while", [
            _name(test_fn.name), _name(body_fn.name),
            _name(get.name), _name(set_.name)])
        return inits + [test_fn, body_fn, get, set_, call]


def _needs_conversion(tree):
    for node in ast.walk(tree):
        if isinstance(node, (ast.If, ast.While, ast.For)):
            return True
    return False


def convert_control_flow(fn):
    """Rewrite `fn`'s if/while statements for graph capture.  Falls back to
    the original function when the source is unavailable or the transform
    does not apply (no control flow, lambdas, builtins)."""
    if isinstance(fn, functools.partial) or not isinstance(
            fn, (types.FunctionType, types.MethodType)):
        return fn
    inner = fn.__func__ if isinstance(fn, types.MethodType) else fn
    if getattr(inner, "_pt_dy2static_converted", False):
        return fn
    try:
        src = textwrap.dedent(inspect.getsource(inner))
        tree = ast.parse(src)
    except (OSError, TypeError, SyntaxError, IndentationError):
        return fn
    fdef = tree.body[0]
    if not isinstance(fdef, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return fn
    if not _needs_conversion(fdef):
        return fn
    fdef.decorator_list = []  # don't re-apply @to_static etc. on exec
    new_body = _ControlFlowTransformer().visit(fdef)
    ast.fix_missing_locations(tree)

    from . import dy2static as _self_mod

    class _LiveGlobals(dict):
        """Overlay over the function's REAL globals: unknown names resolve
        live (so later-defined helpers / monkeypatching keep working),
        while the overlay carries the helper module + closure snapshot."""

        def __missing__(self, key):
            return inner.__globals__[key]

    glb = _LiveGlobals()
    # the import machinery reads these via raw dict lookups (no __missing__)
    for dunder in ("__name__", "__package__", "__spec__", "__loader__",
                   "__builtins__", "__file__"):
        if dunder in inner.__globals__:
            glb[dunder] = inner.__globals__[dunder]
    if inner.__closure__:
        try:
            glb.update({name: cell.cell_contents
                        for name, cell in zip(inner.__code__.co_freevars,
                                              inner.__closure__)})
        except ValueError:
            # an empty cell (recursive/forward-referencing nested function):
            # the snapshot can't represent it — leave the function alone
            return fn
    glb[_HELPER] = _self_mod
    try:
        code = compile(tree, filename=f"<dy2static {inner.__qualname__}>",
                       mode="exec")
        exec(code, glb)
    except SyntaxError:
        return fn
    new_fn = glb[fdef.name]
    new_fn.__defaults__ = inner.__defaults__
    new_fn.__kwdefaults__ = inner.__kwdefaults__
    new_fn._pt_dy2static_converted = True
    functools.update_wrapper(new_fn, inner, updated=())
    if isinstance(fn, types.MethodType):
        return types.MethodType(new_fn, fn.__self__)
    return new_fn
