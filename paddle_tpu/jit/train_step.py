"""Compiled training step: forward+backward+optimizer in ONE XLA program.

Reference analog: the whole-Program path (`Executor.run` over a Program containing
forward, appended grad ops and optimizer ops — python/paddle/fluid/backward.py +
optimizer.minimize).  TPU-native: `jax.value_and_grad` over the model's functional
state, optimizer update rules applied in-graph, buffers donated so XLA updates
parameters in place (no host round-trip, no per-op dispatch).

This is the throughput path used by bench.py and hapi.Model.fit(jit=True).
"""
from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp

from ..tensor.tensor import Tensor
from ..autograd import tape
from ..framework import random as _random
from ..optimizer.optimizer import Optimizer


class TrainStep:
    """train_step = TrainStep(model, loss_fn, optimizer); loss = train_step(x, y)."""

    def __init__(self, model, loss_fn: Callable, optimizer: Optimizer, donate: bool = True):
        self.model = model
        self.loss_fn = loss_fn
        self.optimizer = optimizer
        self._jitted = None
        self._param_names = None
        self._opt_state = None
        self._donate = donate

    def _init(self):
        params, buffers = self.model.functional_state()
        self._param_names = list(params.keys())
        named = dict(self.model.named_parameters())
        self._opt_state = {
            k: self.optimizer._init_state(named[k]) for k in self._param_names
            if not named[k].stop_gradient
        }
        opt = self.optimizer
        model = self.model
        loss_fn = self.loss_fn
        trainable = {k for k in self._param_names if not named[k].stop_gradient}

        def step(params, buffers, opt_state, lr, key, *batch):
            t_params = {k: v for k, v in params.items() if k in trainable}
            frozen = {k: v for k, v in params.items() if k not in trainable}

            def pure_loss(tp):
                allp = {**tp, **frozen}
                with _random.rng_key_scope(key):
                    restore = model.bind_functional_state(allp, buffers)
                    try:
                        with tape.no_grad():
                            args = tuple(Tensor(b, stop_gradient=True) for b in batch)
                            out = loss_fn(*args)
                        loss_t = out[0] if isinstance(out, (tuple, list)) else out
                        aux_out = tuple(o._value if isinstance(o, Tensor) else o
                                        for o in (out[1:] if isinstance(out, (tuple, list)) else ()))
                        new_buffers = {kk: b._value for kk, b in model.named_buffers()}
                    finally:
                        restore()
                return loss_t._value, (new_buffers, aux_out)

            (loss, (new_buffers, aux)), grads = jax.value_and_grad(pure_loss, has_aux=True)(t_params)
            clipped = opt._clipped_grads(list(grads.items()))
            new_params = dict(frozen)
            new_opt = {}
            for k, g in clipped:
                new_params[k], new_opt[k] = opt._apply_update(
                    params[k], g, opt_state[k], lr, opt._param_decay_coeff(named[k])
                )
            return new_params, new_buffers, new_opt, loss, aux

        donate = (0, 2) if self._donate else ()
        self._jitted = jax.jit(step, donate_argnums=donate)

    def __call__(self, *batch):
        if self._jitted is None:
            self._init()
        params, buffers = self.model.functional_state()
        lr = jnp.asarray(self.optimizer.get_lr(), jnp.float32)
        key = _random.get_rng_key()
        raw = tuple(b._value if isinstance(b, Tensor) else jnp.asarray(b) for b in batch)
        new_params, new_buffers, new_opt, loss, aux = self._jitted(
            params, buffers, self._opt_state, lr, key, *raw
        )
        self._opt_state = new_opt
        self.model.load_functional_state(new_params, new_buffers)
        self.optimizer._step_count += 1
        if isinstance(self.optimizer._learning_rate, object) and hasattr(self.optimizer._learning_rate, "step"):
            pass  # schedulers stepped by the user per paddle convention
        loss_t = Tensor(loss)
        if aux:
            return (loss_t, *[Tensor(a) for a in aux])
        return loss_t
