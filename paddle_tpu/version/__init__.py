"""paddle.version parity (ref: python/paddle/version.py, generated at build time
from setup.py; here maintained by hand alongside pyproject.toml)."""
from __future__ import annotations

full_version = "2.3.0"
major = "2"
minor = "3"
patch = "0"
rc = "0"
istaged = True
commit = "tpu-native"
with_mkl = "OFF"

cuda_version = "False"
cudnn_version = "False"


def show():
    """Print the version info (ref version.py show())."""
    print("full_version:", full_version)
    print("major:", major)
    print("minor:", minor)
    print("patch:", patch)
    print("rc:", rc)
    print("commit:", commit)


def mkl():
    return with_mkl


def cuda():
    """TPU build: no CUDA. Kept for scripts that branch on it."""
    return cuda_version


def cudnn():
    return cudnn_version
