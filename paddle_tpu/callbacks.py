"""paddle.callbacks — re-export of the hapi callback zoo.

Ref: python/paddle/callbacks.py (pure re-export of hapi/callbacks.py).
"""
from .hapi.callbacks import (  # noqa: F401
    Callback,
    EarlyStopping,
    LRScheduler,
    ModelCheckpoint,
    ProgBarLogger,
    ReduceLROnPlateau,
    VisualDL,
)

__all__ = ["Callback", "ProgBarLogger", "ModelCheckpoint", "VisualDL",
           "LRScheduler", "EarlyStopping", "ReduceLROnPlateau"]
