"""paddle.optimizer parity surface."""
from .optimizer import (  # noqa: F401
    Optimizer, SGD, Momentum, Adam, AdamW, Adagrad, Adadelta, Adamax, RMSProp, Lamb, Lars,
)
from . import lr  # noqa: F401
