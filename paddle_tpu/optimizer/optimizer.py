"""Optimizers (ref: python/paddle/optimizer/optimizer.py:91 base; step:1232, minimize:1167).

Design: each optimizer defines a pure functional update rule
`_update_rule(p, g, state, lr) -> (new_p, new_state)` over raw jax arrays.  The eager
`step()` walks parameters and rebinds values; the same rule is reused verbatim inside
jitted train steps (jit/train_step.py) — one source of truth, no divergence between
eager and compiled training.
"""
from __future__ import annotations

from collections import OrderedDict

import jax
import jax.numpy as jnp
import numpy as np

from ..tensor.tensor import Tensor, Parameter
from ..autograd import tape
from .lr import LRScheduler


class Optimizer:
    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        self._learning_rate = learning_rate
        self._parameter_list = list(parameters) if parameters is not None else None
        self._grad_clip = grad_clip
        self._weight_decay = weight_decay
        self._accumulators: dict[int, dict] = {}
        self._step_count = 0
        self.helper = None

    # ------------------------------------------------------------------ lr
    def get_lr(self) -> float:
        if isinstance(self._learning_rate, LRScheduler):
            return float(self._learning_rate())
        return float(self._learning_rate)

    def set_lr(self, value):
        if isinstance(self._learning_rate, LRScheduler):
            raise RuntimeError("cannot set_lr when using an LRScheduler")
        self._learning_rate = float(value)

    def set_lr_scheduler(self, scheduler):
        self._learning_rate = scheduler

    # ------------------------------------------------------------------ state
    def _state_for(self, p: Parameter) -> dict:
        st = self._accumulators.get(id(p))
        if st is None:
            st = self._init_state(p)
            self._accumulators[id(p)] = st
        return st

    def _init_state(self, p: Parameter) -> dict:
        return {}

    def state_dict(self):
        sd = {"_step_count": self._step_count}
        if isinstance(self._learning_rate, LRScheduler):
            sd["LR_Scheduler"] = self._learning_rate.state_dict()
        for i, p in enumerate(self._params()):
            for k, v in self._state_for(p).items():
                sd[f"{p.name or i}_{k}"] = Tensor(v) if not isinstance(v, Tensor) else v
        return sd

    def set_state_dict(self, state_dict):
        self._step_count = int(state_dict.get("_step_count", 0))
        if "LR_Scheduler" in state_dict and isinstance(self._learning_rate, LRScheduler):
            self._learning_rate.set_state_dict(state_dict["LR_Scheduler"])
        for i, p in enumerate(self._params()):
            st = self._state_for(p)
            for k in list(st.keys()):
                key = f"{p.name or i}_{k}"
                if key in state_dict:
                    v = state_dict[key]
                    st[k] = v._value if isinstance(v, Tensor) else jnp.asarray(np.asarray(v))

    # ------------------------------------------------------------------ step
    def _params(self):
        if self._parameter_list is None:
            raise RuntimeError("optimizer constructed without a parameters list")
        return [p for p in self._parameter_list if isinstance(p, Tensor)]

    def _decay_coeff(self):
        wd = self._weight_decay
        if wd is None:
            return 0.0
        if hasattr(wd, "_coeff"):  # L1Decay/L2Decay regularizer
            return float(wd._coeff)
        return float(wd)

    def _decay_spec(self, p):
        """(coeff, mode, lr_scale) for one parameter.  A ``ParamAttr``
        regularizer outranks the optimizer-level ``weight_decay`` (ref
        regularizer.py priority rule); an L1Decay anywhere selects the l1
        penalty; lr_scale is ParamAttr(learning_rate=...) (ref
        optimizer.py _create_param_lr)."""
        lr_scale = 1.0
        oa = getattr(p, "optimize_attr", None)
        if isinstance(oa, dict):
            lr_scale = float(oa.get("learning_rate", 1.0))
        reg = getattr(p, "regularizer", None)
        if reg is not None and hasattr(reg, "_coeff"):
            return float(reg._coeff), getattr(reg, "_mode", "l2"), lr_scale
        wd = self._weight_decay
        if wd is not None and hasattr(wd, "_mode") and wd._mode == "l1":
            return float(wd._coeff), "l1", lr_scale
        return self._decay_coeff(), self._decay_mode(), lr_scale

    def _clipped_grads(self, params_and_grads):
        clip = self._grad_clip
        if clip is None:
            return params_and_grads
        cname = type(clip).__name__
        if cname == "ClipGradByGlobalNorm":
            sq = sum(jnp.sum(jnp.square(g.astype(jnp.float32))) for _, g in params_and_grads)
            gnorm = jnp.sqrt(sq)
            scale = jnp.where(gnorm > clip.clip_norm, clip.clip_norm / (gnorm + 1e-6), 1.0)
            return [(p, (g.astype(jnp.float32) * scale).astype(g.dtype)) for p, g in params_and_grads]
        if cname == "ClipGradByNorm":
            out = []
            for p, g in params_and_grads:
                n = jnp.linalg.norm(g.astype(jnp.float32))
                scale = jnp.where(n > clip.clip_norm, clip.clip_norm / (n + 1e-6), 1.0)
                out.append((p, (g * scale.astype(g.dtype))))
            return out
        if cname == "ClipGradByValue":
            return [(p, jnp.clip(g, clip.min, clip.max)) for p, g in params_and_grads]
        return params_and_grads

    def _apply_update(self, p_val, g, state, lr, decay):
        """The single update path shared by eager step, TrainStep and
        ShardedTrainStep: decay + rule + dtype restore (an f32 lr array must not
        promote bf16 params or optimizer state — that would silently retrace/
        un-donate the jitted step every call)."""
        if g.dtype != p_val.dtype:
            g = g.astype(p_val.dtype)
        if isinstance(decay, tuple):
            coeff, mode, lr_scale = decay if len(decay) == 3 else (*decay, 1.0)
        else:
            coeff, mode, lr_scale = decay, self._decay_mode(), 1.0
        if lr_scale != 1.0:
            lr = lr * lr_scale
        if coeff and mode == "l2":
            g = g + coeff * p_val
        elif coeff and mode == "l1":
            g = g + coeff * jnp.sign(p_val)
        new_p, new_state = self._update_rule(p_val, g, state, lr)
        if coeff and mode == "decoupled":
            new_p = new_p - lr * coeff * p_val
        if new_p.dtype != p_val.dtype:
            new_p = new_p.astype(p_val.dtype)
        new_state = {
            k: (v.astype(state[k].dtype)
                if hasattr(v, "dtype") and hasattr(state[k], "dtype") and v.dtype != state[k].dtype
                else v)
            for k, v in new_state.items()
        }
        return new_p, new_state

    @tape.no_grad()
    def step(self):
        """Apply one update (ref optimizer.py:1232)."""
        lr = self.get_lr()
        self._step_count += 1
        pg = [(p, p._grad) for p in self._params() if p._grad is not None and not p.stop_gradient]
        pg = self._clipped_grads(pg)
        for p, g in pg:
            state = self._state_for(p)
            new_p, new_state = self._apply_update(p._value, g, state, lr, self._param_decay_coeff(p))
            p._rebind(new_p)
            self._accumulators[id(p)] = new_state

    def _param_decay_coeff(self, p):
        """Per-parameter (coeff, mode) decay spec (overridden by AdamW's
        apply_decay_param_fun)."""
        return self._decay_spec(p)

    def _decay_mode(self):
        return "l2"

    def _update_rule(self, p, g, state, lr):
        raise NotImplementedError

    def clear_grad(self, set_to_zero=False):
        for p in self._params():
            p.clear_grad()

    clear_gradients = clear_grad

    def minimize(self, loss, startup_program=None, parameters=None, no_grad_set=None):
        """Ref optimizer.py:1167 — backward + step.  Under static-graph
        capture this RECORDS the training objective on the current Program
        instead of stepping eagerly (the reference appends backward + update
        ops to the ProgramDesc here); Executor.run then compiles
        forward+grad+update as one XLA program."""
        from ..static import program as _prog

        if _prog.capture_active():
            _prog.current_program()._set_objective(loss, self)
            return None, None
        loss.backward()
        self.step()
        return None, None

    def _apply_optimize(self, loss, startup_program=None, params_grads=None):
        self.step()


class SGD(Optimizer):
    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)

    def _update_rule(self, p, g, state, lr):
        return p - lr * g, state


class Momentum(Optimizer):
    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._momentum = momentum
        self._nesterov = use_nesterov

    def _init_state(self, p):
        return {"velocity": jnp.zeros_like(p._value)}

    def _update_rule(self, p, g, state, lr):
        v = self._momentum * state["velocity"] + g
        if self._nesterov:
            new_p = p - lr * (g + self._momentum * v)
        else:
            new_p = p - lr * v
        return new_p, {"velocity": v}


class Adam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 parameters=None, weight_decay=None, grad_clip=None, lazy_mode=False,
                 multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._beta1, self._beta2, self._eps = beta1, beta2, epsilon

    def _init_state(self, p):
        return {
            "moment1": jnp.zeros_like(p._value),
            "moment2": jnp.zeros_like(p._value),
            "beta1_pow": jnp.ones([], jnp.float32),
            "beta2_pow": jnp.ones([], jnp.float32),
        }

    def _update_rule(self, p, g, state, lr):
        b1, b2 = self._beta1, self._beta2
        m = b1 * state["moment1"] + (1 - b1) * g
        v = b2 * state["moment2"] + (1 - b2) * jnp.square(g)
        b1p = state["beta1_pow"] * b1
        b2p = state["beta2_pow"] * b2
        mhat = m / (1 - b1p).astype(m.dtype)
        vhat = v / (1 - b2p).astype(v.dtype)
        new_p = p - lr * mhat / (jnp.sqrt(vhat) + self._eps)
        return new_p, {"moment1": m, "moment2": v, "beta1_pow": b1p, "beta2_pow": b2p}


class AdamW(Adam):
    """Decoupled weight decay (ref optimizer/adamw.py)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 parameters=None, weight_decay=0.01, lr_ratio=None, apply_decay_param_fun=None,
                 grad_clip=None, lazy_mode=False, multi_precision=False, name=None):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         weight_decay, grad_clip, lazy_mode, multi_precision, name)
        self._apply_decay_param_fun = apply_decay_param_fun

    def _decay_mode(self):
        return "decoupled"

    def _param_decay_coeff(self, p):
        if self._apply_decay_param_fun is not None and not self._apply_decay_param_fun(p.name):
            _, _, lr_scale = self._decay_spec(p)
            return 0.0, "decoupled", lr_scale
        return self._decay_spec(p)


class Adagrad(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, parameters=None, weight_decay=None,
                 grad_clip=None, initial_accumulator_value=0.0, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._eps = epsilon
        self._init_acc = initial_accumulator_value

    def _init_state(self, p):
        return {"moment": jnp.full_like(p._value, self._init_acc)}

    def _update_rule(self, p, g, state, lr):
        acc = state["moment"] + jnp.square(g)
        return p - lr * g / (jnp.sqrt(acc) + self._eps), {"moment": acc}


class Adamax(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 parameters=None, weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._beta1, self._beta2, self._eps = beta1, beta2, epsilon

    def _init_state(self, p):
        return {"moment": jnp.zeros_like(p._value), "inf_norm": jnp.zeros_like(p._value),
                "beta1_pow": jnp.ones([], jnp.float32)}

    def _update_rule(self, p, g, state, lr):
        m = self._beta1 * state["moment"] + (1 - self._beta1) * g
        u = jnp.maximum(self._beta2 * state["inf_norm"], jnp.abs(g))
        b1p = state["beta1_pow"] * self._beta1
        new_p = p - (lr / (1 - b1p)).astype(p.dtype) * m / (u + self._eps)
        return new_p, {"moment": m, "inf_norm": u, "beta1_pow": b1p}


class RMSProp(Optimizer):
    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0, centered=False,
                 parameters=None, weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._rho, self._eps, self._momentum, self._centered = rho, epsilon, momentum, centered

    def _init_state(self, p):
        st = {"mean_square": jnp.zeros_like(p._value), "momentum": jnp.zeros_like(p._value)}
        if self._centered:
            st["mean_grad"] = jnp.zeros_like(p._value)
        return st

    def _update_rule(self, p, g, state, lr):
        ms = self._rho * state["mean_square"] + (1 - self._rho) * jnp.square(g)
        st = dict(state, mean_square=ms)
        if self._centered:
            mg = self._rho * state["mean_grad"] + (1 - self._rho) * g
            st["mean_grad"] = mg
            denom = jnp.sqrt(ms - jnp.square(mg) + self._eps)
        else:
            denom = jnp.sqrt(ms + self._eps)
        mom = self._momentum * state["momentum"] + lr * g / denom
        st["momentum"] = mom
        return p - mom, st


class Lamb(Optimizer):
    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01, beta1=0.9, beta2=0.999,
                 epsilon=1e-6, parameters=None, grad_clip=None, exclude_from_weight_decay_fn=None,
                 name=None):
        super().__init__(learning_rate, parameters, None, grad_clip, name)
        self._wd = lamb_weight_decay
        self._beta1, self._beta2, self._eps = beta1, beta2, epsilon
        self._exclude_fn = exclude_from_weight_decay_fn

    def _init_state(self, p):
        return {"moment1": jnp.zeros_like(p._value), "moment2": jnp.zeros_like(p._value),
                "beta1_pow": jnp.ones([], jnp.float32), "beta2_pow": jnp.ones([], jnp.float32)}

    def _update_rule(self, p, g, state, lr):
        b1, b2 = self._beta1, self._beta2
        m = b1 * state["moment1"] + (1 - b1) * g
        v = b2 * state["moment2"] + (1 - b2) * jnp.square(g)
        b1p = state["beta1_pow"] * b1
        b2p = state["beta2_pow"] * b2
        mhat = m / (1 - b1p).astype(m.dtype)
        vhat = v / (1 - b2p).astype(v.dtype)
        r = mhat / (jnp.sqrt(vhat) + self._eps)
        wd = 0.0 if (self._exclude_fn is not None and self._exclude_fn(p)) else self._wd
        update = r + wd * p
        wnorm = jnp.linalg.norm(p.astype(jnp.float32))
        unorm = jnp.linalg.norm(update.astype(jnp.float32))
        trust = jnp.where((wnorm > 0) & (unorm > 0), wnorm / unorm, 1.0).astype(p.dtype)
        new_p = p - lr * trust * update
        return new_p, {"moment1": m, "moment2": v, "beta1_pow": b1p, "beta2_pow": b2p}


class Lars(Momentum):
    def __init__(self, learning_rate=0.001, momentum=0.9, lars_coeff=0.001,
                 lars_weight_decay=0.0005, parameters=None, grad_clip=None, name=None, **kw):
        super().__init__(learning_rate, momentum, parameters, False, None, grad_clip, name)
        self._lars_coeff = lars_coeff
        self._lars_wd = lars_weight_decay

    def _update_rule(self, p, g, state, lr):
        wnorm = jnp.linalg.norm(p.astype(jnp.float32))
        gnorm = jnp.linalg.norm(g.astype(jnp.float32))
        local_lr = jnp.where(
            (wnorm > 0) & (gnorm > 0),
            self._lars_coeff * wnorm / (gnorm + self._lars_wd * wnorm + 1e-9),
            1.0,
        ).astype(p.dtype)
        g = g + self._lars_wd * p
        v = self._momentum * state["velocity"] + lr * local_lr * g
        return p - v, {"velocity": v}


class Adadelta(Optimizer):
    """Ref optimizer/adadelta.py."""

    def __init__(self, learning_rate=0.001, epsilon=1e-6, rho=0.95,
                 parameters=None, weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._rho = rho
        self._eps = epsilon

    def _init_state(self, p):
        return {"avg_squared_grad": jnp.zeros_like(p._value),
                "avg_squared_update": jnp.zeros_like(p._value)}

    def _update_rule(self, p, g, state, lr):
        rho, eps = self._rho, self._eps
        sq = rho * state["avg_squared_grad"] + (1 - rho) * jnp.square(g)
        update = g * jnp.sqrt(state["avg_squared_update"] + eps) / jnp.sqrt(sq + eps)
        su = rho * state["avg_squared_update"] + (1 - rho) * jnp.square(update)
        return p - lr * update, {"avg_squared_grad": sq, "avg_squared_update": su}
