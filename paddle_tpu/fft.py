"""paddle.fft (ref: python/paddle/fft.py — the full discrete-transform
family).  Every transform is a differentiable apply_op over jnp.fft; XLA
lowers FFTs to the TPU's native FFT HLO.
"""
from __future__ import annotations

import jax.numpy as jnp

from .tensor.tensor import Tensor, apply_op

__all__ = [
    "fft", "ifft", "rfft", "irfft", "hfft", "ihfft",
    "fft2", "ifft2", "rfft2", "irfft2",
    "fftn", "ifftn", "rfftn", "irfftn",
    "fftfreq", "rfftfreq", "fftshift", "ifftshift",
]


def _norm(norm):
    # paddle uses "backward"/"forward"/"ortho" like numpy
    if norm not in (None, "backward", "forward", "ortho"):
        raise ValueError(f"invalid norm {norm!r}")
    return norm or "backward"


def _wrap1(jfn, opname):
    def op(x, n=None, axis=-1, norm="backward", name=None):
        return apply_op(lambda v: jfn(v, n=n, axis=axis, norm=_norm(norm)),
                        (x,), name=opname)

    op.__name__ = opname
    return op


def _wrap2(jfn, opname):
    def op(x, s=None, axes=(-2, -1), norm="backward", name=None):
        return apply_op(lambda v: jfn(v, s=s, axes=axes, norm=_norm(norm)),
                        (x,), name=opname)

    op.__name__ = opname
    return op


def _wrapn(jfn, opname):
    def op(x, s=None, axes=None, norm="backward", name=None):
        return apply_op(lambda v: jfn(v, s=s, axes=axes, norm=_norm(norm)),
                        (x,), name=opname)

    op.__name__ = opname
    return op


fft = _wrap1(jnp.fft.fft, "fft")
ifft = _wrap1(jnp.fft.ifft, "ifft")
rfft = _wrap1(jnp.fft.rfft, "rfft")
irfft = _wrap1(jnp.fft.irfft, "irfft")
hfft = _wrap1(jnp.fft.hfft, "hfft")
ihfft = _wrap1(jnp.fft.ihfft, "ihfft")
fft2 = _wrap2(jnp.fft.fft2, "fft2")
ifft2 = _wrap2(jnp.fft.ifft2, "ifft2")
rfft2 = _wrap2(jnp.fft.rfft2, "rfft2")
irfft2 = _wrap2(jnp.fft.irfft2, "irfft2")
fftn = _wrapn(jnp.fft.fftn, "fftn")
ifftn = _wrapn(jnp.fft.ifftn, "ifftn")
rfftn = _wrapn(jnp.fft.rfftn, "rfftn")
irfftn = _wrapn(jnp.fft.irfftn, "irfftn")


def fftfreq(n, d=1.0, dtype=None, name=None):
    out = jnp.fft.fftfreq(n, d)
    return Tensor(out.astype(dtype) if dtype else out)


def rfftfreq(n, d=1.0, dtype=None, name=None):
    out = jnp.fft.rfftfreq(n, d)
    return Tensor(out.astype(dtype) if dtype else out)


def fftshift(x, axes=None, name=None):
    return apply_op(lambda v: jnp.fft.fftshift(v, axes=axes), (x,), name="fftshift")


def ifftshift(x, axes=None, name=None):
    return apply_op(lambda v: jnp.fft.ifftshift(v, axes=axes), (x,), name="ifftshift")


def _split_axes(x, s, axes, nd_default):
    if axes is None:
        axes = tuple(range(-nd_default, 0)) if nd_default else tuple(range(x.ndim))
    axes = tuple(axes)
    if s is not None:
        s = tuple(s)
    return s, axes


def _hfftn_impl(v, s, axes, norm):
    """FFT of Hermitian-symmetric input -> real output: full ffts over the
    leading axes, hermitian fft over the LAST axis (the truncated one) —
    ref python/paddle/fft.py hfftn composition."""
    lead, last = axes[:-1], axes[-1]
    if lead:
        v = jnp.fft.fftn(v, s=(s[:-1] if s else None), axes=lead, norm=norm)
    return jnp.fft.hfft(v, n=(s[-1] if s else None), axis=last, norm=norm)


def _ihfftn_impl(v, s, axes, norm):
    lead, last = axes[:-1], axes[-1]
    v = jnp.fft.ihfft(v, n=(s[-1] if s else None), axis=last, norm=norm)
    if lead:
        v = jnp.fft.ifftn(v, s=(s[:-1] if s else None), axes=lead, norm=norm)
    return v


def hfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    from .tensor.tensor import apply_op

    return apply_op(lambda v: _hfftn_impl(v, s, tuple(axes), _norm(norm)),
                    (x,), name="hfft2")


def ihfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    from .tensor.tensor import apply_op

    return apply_op(lambda v: _ihfftn_impl(v, s, tuple(axes), _norm(norm)),
                    (x,), name="ihfft2")


def hfftn(x, s=None, axes=None, norm="backward", name=None):
    from .tensor.tensor import apply_op

    def _f(v):
        s2, ax = _split_axes(v, s, axes, 0)
        return _hfftn_impl(v, s2, ax, _norm(norm))

    return apply_op(_f, (x,), name="hfftn")


def ihfftn(x, s=None, axes=None, norm="backward", name=None):
    from .tensor.tensor import apply_op

    def _f(v):
        s2, ax = _split_axes(v, s, axes, 0)
        return _ihfftn_impl(v, s2, ax, _norm(norm))

    return apply_op(_f, (x,), name="ihfftn")


__all__ += ["hfft2", "ihfft2", "hfftn", "ihfftn"]
