"""paddle.hub — load entrypoints from a hubconf.py.

Ref: python/paddle/hub.py (list/help/load with github|gitee|local sources).
This build has no network egress, so only ``source='local'`` is supported;
remote sources raise with guidance rather than silently failing mid-download.
"""
from __future__ import annotations

import importlib.util
import os
import sys

__all__ = ["list", "help", "load"]

_HUBCONF = "hubconf.py"


def _load_hubconf(repo_dir):
    path = os.path.join(repo_dir, _HUBCONF)
    if not os.path.isfile(path):
        raise FileNotFoundError(f"no {_HUBCONF} in {repo_dir}")
    spec = importlib.util.spec_from_file_location("hubconf", path)
    mod = importlib.util.module_from_spec(spec)
    sys.path.insert(0, repo_dir)
    try:
        spec.loader.exec_module(mod)
    finally:
        sys.path.remove(repo_dir)
    return mod


def _check_source(source):
    if source not in ("local",):
        raise RuntimeError(
            f"hub source '{source}' needs network access, which this build does not "
            f"have; clone the repo and use source='local' with repo_dir=<path>")


def list(repo_dir, source="local", force_reload=False):  # noqa: A001 (paddle API name)
    """List callable entrypoints defined by the repo's hubconf.py."""
    _check_source(source)
    mod = _load_hubconf(repo_dir)
    return [n for n, v in vars(mod).items()
            if callable(v) and not n.startswith("_")]


def help(repo_dir, model, source="local", force_reload=False):  # noqa: A001
    """Return the docstring of one entrypoint."""
    _check_source(source)
    mod = _load_hubconf(repo_dir)
    if not hasattr(mod, model):
        raise RuntimeError(f"entrypoint {model} not found in {repo_dir}/{_HUBCONF}")
    return getattr(mod, model).__doc__


def load(repo_dir, model, source="local", force_reload=False, **kwargs):
    """Instantiate an entrypoint: ``hub.load('/path/to/repo', 'resnet50')``."""
    _check_source(source)
    mod = _load_hubconf(repo_dir)
    if not hasattr(mod, model):
        raise RuntimeError(f"entrypoint {model} not found in {repo_dir}/{_HUBCONF}")
    return getattr(mod, model)(**kwargs)
