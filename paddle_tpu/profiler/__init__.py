"""paddle.profiler parity (ref: python/paddle/profiler/profiler.py:271).

Two collectors, mirroring the reference's host-tracer + device-tracer split
(platform/profiler/host_tracer.cc + cuda_tracer.cc):
  - device/XLA side: jax.profiler XPlane traces (TensorBoard/Perfetto), the CUPTI
    analog — enabled when a Profiler context is active;
  - host side: `RecordEvent` spans collected by the native C++ trace buffer
    (core/native, ref event_tracing.h:49 RAII spans + chrometracing_logger.cc),
    exported as chrome://tracing JSON via `Profiler.export(path)`.
"""
from __future__ import annotations

import contextlib
import json
import os
import time

import jax

_native_tracer = None


def _tracer():
    global _native_tracer
    if _native_tracer is None:
        try:
            from ..core.native import NativeTracer

            _native_tracer = NativeTracer()
        except Exception:
            _native_tracer = False
    return _native_tracer or None


class ProfilerTarget:
    CPU = "cpu"
    GPU = "gpu"
    CUSTOM_DEVICE = "tpu"


class ProfilerState:
    CLOSED = 0
    READY = 1
    RECORD = 2
    RECORD_AND_RETURN = 3


def make_scheduler(*, closed=0, ready=0, record=1, repeat=0, skip_first=0):
    """Ref profiler.py make_scheduler — step-phase state machine."""
    cycle = closed + ready + record

    def scheduler(step):
        if step < skip_first:
            return ProfilerState.CLOSED
        s = step - skip_first
        if repeat and s >= repeat * cycle:
            return ProfilerState.CLOSED
        pos = s % cycle if cycle else 0
        if pos < closed:
            return ProfilerState.CLOSED
        if pos < closed + ready:
            return ProfilerState.READY
        if pos == cycle - 1:
            return ProfilerState.RECORD_AND_RETURN
        return ProfilerState.RECORD

    return scheduler


def export_chrome_tracing(dir_name, worker_name=None):
    def handler(prof):
        os.makedirs(dir_name, exist_ok=True)
        prof.export(os.path.join(dir_name, f"{worker_name or 'worker'}.json"))

    handler._dir = dir_name
    return handler


_RECORD_STATES = (ProfilerState.RECORD, ProfilerState.RECORD_AND_RETURN)


class Profiler:
    """with Profiler(targets=[...], on_trace_ready=export_chrome_tracing('./log')): ...

    With a ``scheduler`` (see `make_scheduler`) the profiler drives the
    reference's CLOSED/READY/RECORD/RECORD_AND_RETURN step-phase state
    machine from ``step()``: tracing runs only during RECORD phases, the
    host-trace buffer is cleared at each record-window start, and
    ``on_trace_ready`` fires once per window when its RECORD_AND_RETURN
    step completes (ref profiler.py Profiler._trigger_action).  Without a
    scheduler the whole start()..stop() range records, as before.
    """

    def __init__(self, targets=None, scheduler=None, on_trace_ready=None, timer_only=False,
                 record_shapes=False, profile_memory=False, with_flops=False):
        self._dir = "./paddle_tpu_profile"
        self._on_trace_ready = on_trace_ready
        if on_trace_ready is not None and hasattr(on_trace_ready, "_dir"):
            self._dir = on_trace_ready._dir
        self._timer_only = timer_only
        self._started = False
        self._step_num = 0
        self._step_t0 = None
        self._step_times: list[float] = []
        self._scheduler = scheduler
        self.current_state = ProfilerState.CLOSED
        self._record_windows = 0  # completed record windows (handler fires)

    def is_recording(self) -> bool:
        """True while the tracers collect (always inside start()..stop()
        without a scheduler; only during RECORD phases with one)."""
        return self.current_state in _RECORD_STATES

    def _tracing_on(self):
        tr = _tracer()
        if tr is not None:
            tr.clear()  # each record window exports only its own spans
            tr.enable(True)
        if not self._timer_only:
            os.makedirs(self._dir, exist_ok=True)
            try:
                jax.profiler.start_trace(self._dir)
                self._started = True
            except Exception:
                self._started = False

    def _tracing_off(self):
        if self._started:
            jax.profiler.stop_trace()
            self._started = False
        tr = _tracer()
        if tr is not None:
            tr.enable(False)

    def _fire_trace_ready(self):
        self._record_windows += 1
        if self._on_trace_ready is not None:
            try:
                self._on_trace_ready(self)
            except Exception:
                pass

    def start(self):
        if self._scheduler is None:
            self.current_state = ProfilerState.RECORD
            self._tracing_on()
        else:
            self.current_state = self._scheduler(0)
            if self.current_state in _RECORD_STATES:
                self._tracing_on()
        self._step_t0 = time.perf_counter()

    def stop(self):
        was_recording = self.is_recording()
        self._tracing_off()
        if self._scheduler is None or was_recording:
            # a scheduler-driven profiler whose window already closed (state
            # CLOSED/READY) exported via its RECORD_AND_RETURN step; firing
            # again here would hand the handler an empty buffer
            self._fire_trace_ready()
        self.current_state = ProfilerState.CLOSED

    def step(self, num_samples=None):
        now = time.perf_counter()
        if self._step_t0 is not None:
            self._step_times.append(now - self._step_t0)
        self._step_t0 = now
        self._step_num += 1
        if self._scheduler is None:
            return
        prev = self.current_state
        new = self._scheduler(self._step_num)
        if prev in _RECORD_STATES and (new not in _RECORD_STATES
                                       or prev == ProfilerState.RECORD_AND_RETURN):
            # record window closed (RECORD_AND_RETURN step just completed,
            # or the schedule left the record phase): export + notify
            self._tracing_off()
            self._fire_trace_ready()
            if new in _RECORD_STATES:  # back-to-back windows
                self._tracing_on()
        elif prev not in _RECORD_STATES and new in _RECORD_STATES:
            self._tracing_on()
        self.current_state = new

    def step_info(self, unit=None):
        if not self._step_times:
            return ""
        import numpy as np

        arr = np.asarray(self._step_times)
        return (f"step {self._step_num}: avg {arr.mean()*1000:.3f} ms, "
                f"min {arr.min()*1000:.3f} ms, max {arr.max()*1000:.3f} ms")

    def summary(self, sorted_by=None, op_detail=True, thread_sep=False, time_unit="ms"):
        tr = _tracer()
        host = f"{tr.count()} host spans collected" if tr is not None else "host tracer off"
        return f"{host}; XLA trace in {self._dir} (TensorBoard/Perfetto)"

    def export(self, path, format="json"):
        """Write collected host spans as chrome://tracing JSON
        (ref chrometracing_logger.cc output contract)."""
        tr = _tracer()
        doc = tr.dump_json() if tr is not None else '{"traceEvents":[]}'
        with open(path, "w") as f:
            f.write(doc)
        return path

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()
        return False


class RecordEvent:
    """RAII span (ref platform/profiler/event_tracing.h:49): recorded into the native
    host-trace buffer AND as a jax TraceAnnotation so spans show up in both the
    chrome-trace export and the XPlane timeline."""

    def __init__(self, name, event_type=None):
        self._name = name
        self._ann = jax.profiler.TraceAnnotation(name)
        self._t0 = None

    def begin(self):
        self.__enter__()

    def end(self):
        self.__exit__(None, None, None)

    def __enter__(self):
        self._ann.__enter__()
        tr = _tracer()
        if tr is not None:
            self._t0 = tr.now_us()
        return self

    def __exit__(self, *exc):
        self._ann.__exit__(None, None, None)
        tr = _tracer()
        if tr is not None and self._t0 is not None:
            tr.complete(self._name, self._t0, tr.now_us() - self._t0)
            self._t0 = None
        return False


def load_profiler_result(filename):
    with open(filename) as f:
        return json.load(f)


@contextlib.contextmanager
def profiler(state="All", sorted_key=None, profile_path="/tmp/profile"):
    """legacy fluid.profiler.profiler shim."""
    p = Profiler()
    p.start()
    try:
        yield
    finally:
        p.stop()


def start_profiler(state="All", tracer_option="Default"):
    jax.profiler.start_trace("./paddle_tpu_profile")


def stop_profiler(sorted_key=None, profile_path="/tmp/profile"):
    jax.profiler.stop_trace()


class SortedKeys:
    """Summary sort orders (ref profiler/profiler_statistic.py SortedKeys)."""

    CPUTotal = 0
    CPUAvg = 1
    CPUMax = 2
    CPUMin = 3
    GPUTotal = 4
    GPUAvg = 5
    GPUMax = 6
    GPUMin = 7


def export_protobuf(dir_name, worker_name=None):
    """The reference serializes its own profiler protobuf; this build's
    native trace format is the Chrome trace (and XPlane via jax.profiler) —
    raise with that guidance instead of writing a file that is not the
    advertised format."""
    raise NotImplementedError(
        "protobuf profiler export is not supported on the TPU build; use "
        "export_chrome_tracing(dir_name) (Perfetto/chrome://tracing-ready) "
        "or jax.profiler.trace for XPlane/TensorBoard")
