"""paddle.profiler parity over jax.profiler (ref: python/paddle/profiler/profiler.py:271).

The reference's host/CUPTI tracers + chrome-trace export (platform/profiler/,
chrometracing_logger.cc) map to JAX's XPlane trace collection, viewable in
TensorBoard/Perfetto; `RecordEvent` maps to jax.profiler.TraceAnnotation
(the RAII span of event_tracing.h:49).
"""
from __future__ import annotations

import contextlib
import os

import jax


class ProfilerTarget:
    CPU = "cpu"
    GPU = "gpu"
    CUSTOM_DEVICE = "tpu"


class ProfilerState:
    CLOSED = 0
    READY = 1
    RECORD = 2
    RECORD_AND_RETURN = 3


def make_scheduler(*, closed=0, ready=0, record=1, repeat=0, skip_first=0):
    def scheduler(step):
        return ProfilerState.RECORD

    return scheduler


def export_chrome_tracing(dir_name, worker_name=None):
    def handler(prof):
        pass

    handler._dir = dir_name
    return handler


class Profiler:
    """with Profiler(targets=[...], on_trace_ready=export_chrome_tracing('./log')): ..."""

    def __init__(self, targets=None, scheduler=None, on_trace_ready=None, timer_only=False,
                 record_shapes=False, profile_memory=False, with_flops=False):
        self._dir = "./paddle_tpu_profile"
        if on_trace_ready is not None and hasattr(on_trace_ready, "_dir"):
            self._dir = on_trace_ready._dir
        self._timer_only = timer_only
        self._started = False

    def start(self):
        if not self._timer_only:
            os.makedirs(self._dir, exist_ok=True)
            try:
                jax.profiler.start_trace(self._dir)
                self._started = True
            except Exception:
                self._started = False

    def stop(self):
        if self._started:
            jax.profiler.stop_trace()
            self._started = False

    def step(self, num_samples=None):
        pass

    def step_info(self, unit=None):
        return ""

    def summary(self, sorted_by=None, op_detail=True, thread_sep=False, time_unit="ms"):
        return "see TensorBoard / Perfetto trace in " + self._dir

    def export(self, path, format="json"):
        pass

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()
        return False


class RecordEvent:
    """RAII span (ref platform/profiler/event_tracing.h:49) -> TraceAnnotation."""

    def __init__(self, name, event_type=None):
        self._ann = jax.profiler.TraceAnnotation(name)

    def begin(self):
        self._ann.__enter__()

    def end(self):
        self._ann.__exit__(None, None, None)

    def __enter__(self):
        self._ann.__enter__()
        return self

    def __exit__(self, *exc):
        self._ann.__exit__(None, None, None)
        return False


def load_profiler_result(filename):
    raise NotImplementedError


@contextlib.contextmanager
def profiler(state="All", sorted_key=None, profile_path="/tmp/profile"):
    """legacy fluid.profiler.profiler shim."""
    p = Profiler()
    p.start()
    try:
        yield
    finally:
        p.stop()


def start_profiler(state="All", tracer_option="Default"):
    jax.profiler.start_trace("./paddle_tpu_profile")


def stop_profiler(sorted_key=None, profile_path="/tmp/profile"):
    jax.profiler.stop_trace()
