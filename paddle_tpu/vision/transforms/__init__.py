"""vision transforms (ref: python/paddle/vision/transforms/) — numpy/CHW based."""
from __future__ import annotations

import numbers

import numpy as np

from ...tensor.tensor import Tensor


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, img):
        for t in self.transforms:
            img = t(img)
        return img


class BaseTransform:
    def __init__(self, keys=None):
        self.keys = keys

    def __call__(self, img):
        return self._apply_image(img)


class ToTensor(BaseTransform):
    def __init__(self, data_format="CHW", keys=None):
        super().__init__(keys)
        self.data_format = data_format

    def _apply_image(self, img):
        arr = np.asarray(img, np.float32)
        if arr.max() > 1.5:
            arr = arr / 255.0
        if arr.ndim == 2:
            arr = arr[None] if self.data_format == "CHW" else arr[..., None]
        elif arr.ndim == 3 and self.data_format == "CHW" and arr.shape[-1] in (1, 3, 4):
            arr = arr.transpose(2, 0, 1)
        return arr


class Normalize(BaseTransform):
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False, keys=None):
        super().__init__(keys)
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32)
        self.data_format = data_format

    def _apply_image(self, img):
        arr = np.asarray(img, np.float32)
        if self.data_format == "CHW":
            m = self.mean.reshape(-1, 1, 1) if self.mean.ndim else self.mean
            s = self.std.reshape(-1, 1, 1) if self.std.ndim else self.std
        else:
            m, s = self.mean, self.std
        return (arr - m) / s


class Resize(BaseTransform):
    def __init__(self, size, interpolation="bilinear", keys=None):
        super().__init__(keys)
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def _apply_image(self, img):
        import jax

        arr = np.asarray(img, np.float32)
        chw = arr.ndim == 3 and arr.shape[0] in (1, 3, 4)
        if chw:
            out_shape = (arr.shape[0], *self.size)
        elif arr.ndim == 3:
            out_shape = (*self.size, arr.shape[-1])
        else:
            out_shape = self.size
        return np.asarray(jax.image.resize(arr, out_shape, method="bilinear"))


class CenterCrop(BaseTransform):
    def __init__(self, size, keys=None):
        super().__init__(keys)
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def _apply_image(self, img):
        arr = np.asarray(img)
        h_axis, w_axis = (1, 2) if (arr.ndim == 3 and arr.shape[0] in (1, 3, 4)) else (0, 1)
        h, w = arr.shape[h_axis], arr.shape[w_axis]
        th, tw = self.size
        i = max((h - th) // 2, 0)
        j = max((w - tw) // 2, 0)
        sl = [slice(None)] * arr.ndim
        sl[h_axis] = slice(i, i + th)
        sl[w_axis] = slice(j, j + tw)
        return arr[tuple(sl)]


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=None, pad_if_needed=False, keys=None):
        super().__init__(keys)
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.padding = padding

    def _apply_image(self, img):
        arr = np.asarray(img)
        h_axis, w_axis = (1, 2) if (arr.ndim == 3 and arr.shape[0] in (1, 3, 4)) else (0, 1)
        if self.padding:
            p = self.padding if isinstance(self.padding, (list, tuple)) else [self.padding] * 2
            pad = [(0, 0)] * arr.ndim
            pad[h_axis] = (p[0], p[0])
            pad[w_axis] = (p[1], p[1])
            arr = np.pad(arr, pad)
        h, w = arr.shape[h_axis], arr.shape[w_axis]
        th, tw = self.size
        i = np.random.randint(0, h - th + 1)
        j = np.random.randint(0, w - tw + 1)
        sl = [slice(None)] * arr.ndim
        sl[h_axis] = slice(i, i + th)
        sl[w_axis] = slice(j, j + tw)
        return arr[tuple(sl)]


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        super().__init__(keys)
        self.prob = prob

    def _apply_image(self, img):
        arr = np.asarray(img)
        if np.random.rand() < self.prob:
            w_axis = 2 if (arr.ndim == 3 and arr.shape[0] in (1, 3, 4)) else 1
            arr = np.flip(arr, axis=w_axis).copy()
        return arr


class RandomResizedCrop(BaseTransform):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3), interpolation="bilinear", keys=None):
        super().__init__(keys)
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.scale = scale
        self.ratio = ratio

    def _apply_image(self, img):
        arr = np.asarray(img, np.float32)
        chw = arr.ndim == 3 and arr.shape[0] in (1, 3, 4)
        h_axis, w_axis = (1, 2) if chw else (0, 1)
        h, w = arr.shape[h_axis], arr.shape[w_axis]
        area = h * w
        for _ in range(10):
            target = area * np.random.uniform(*self.scale)
            ar = np.exp(np.random.uniform(np.log(self.ratio[0]), np.log(self.ratio[1])))
            tw = int(round(np.sqrt(target * ar)))
            th = int(round(np.sqrt(target / ar)))
            if 0 < tw <= w and 0 < th <= h:
                i = np.random.randint(0, h - th + 1)
                j = np.random.randint(0, w - tw + 1)
                sl = [slice(None)] * arr.ndim
                sl[h_axis] = slice(i, i + th)
                sl[w_axis] = slice(j, j + tw)
                arr = arr[tuple(sl)]
                break
        return Resize(self.size)._apply_image(arr)


class Transpose(BaseTransform):
    def __init__(self, order=(2, 0, 1), keys=None):
        super().__init__(keys)
        self.order = order

    def _apply_image(self, img):
        arr = np.asarray(img)
        if arr.ndim == 2:
            arr = arr[..., None]
        return arr.transpose(self.order)


def to_tensor(pic, data_format="CHW"):
    return ToTensor(data_format)(pic)


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    return Normalize(mean, std, data_format)(img)


def resize(img, size, interpolation="bilinear"):
    return Resize(size, interpolation)(img)


def hflip(img):
    arr = np.asarray(img)
    w_axis = 2 if (arr.ndim == 3 and arr.shape[0] in (1, 3, 4)) else 1
    return np.flip(arr, axis=w_axis).copy()


# --------------------------------------------------------------- color/geom
def _axes(arr):
    """(channel_axis | None, h_axis, w_axis) for CHW/HWC/HW arrays."""
    if arr.ndim == 2:
        return None, 0, 1
    if arr.shape[0] in (1, 3, 4):
        return 0, 1, 2
    return 2, 0, 1


def _as_float(img):
    return np.asarray(img, np.float32)


def adjust_brightness(img, brightness_factor):
    """Ref transforms/functional.py adjust_brightness: scale toward black."""
    return _as_float(img) * float(brightness_factor)


def adjust_contrast(img, contrast_factor):
    arr = _as_float(img)
    c, _, _ = _axes(arr)
    if c is not None and arr.shape[c] >= 3:
        # paddle blends toward the mean of the GRAYSCALE image, not the raw mean
        w = np.asarray([0.299, 0.587, 0.114], np.float32)
        chw = np.moveaxis(arr, c, 0)
        mean = float((chw[:3] * w[:, None, None]).sum(0).mean())
    else:
        mean = arr.mean()
    return (arr - mean) * float(contrast_factor) + mean


def adjust_saturation(img, saturation_factor):
    arr = _as_float(img)
    c, h, w = _axes(arr)
    gray = arr.mean(axis=c, keepdims=True) if c is not None else arr
    return (arr - gray) * float(saturation_factor) + gray


def _rgb_to_hsv(r, g, b):
    maxc = np.maximum(np.maximum(r, g), b)
    minc = np.minimum(np.minimum(r, g), b)
    v = maxc
    delta = maxc - minc
    s = np.where(maxc > 0, delta / np.maximum(maxc, 1e-12), 0.0)
    rc = (maxc - r) / np.maximum(delta, 1e-12)
    gc = (maxc - g) / np.maximum(delta, 1e-12)
    bc = (maxc - b) / np.maximum(delta, 1e-12)
    h = np.where(r == maxc, bc - gc, np.where(g == maxc, 2.0 + rc - bc, 4.0 + gc - rc))
    h = np.where(delta == 0, 0.0, h / 6.0 % 1.0)
    return h, s, v


def _hsv_to_rgb(h, s, v):
    i = np.floor(h * 6.0)
    f = h * 6.0 - i
    p = v * (1.0 - s)
    q = v * (1.0 - s * f)
    t = v * (1.0 - s * (1.0 - f))
    i = i.astype(np.int32) % 6
    r = np.choose(i, [v, q, p, p, t, v])
    g = np.choose(i, [t, v, v, q, p, p])
    b = np.choose(i, [p, p, t, v, v, q])
    return r, g, b


def adjust_hue(img, hue_factor):
    """Ref adjust_hue: rotate the hue channel by hue_factor in [-0.5, 0.5]."""
    if not -0.5 <= hue_factor <= 0.5:
        raise ValueError("hue_factor must be in [-0.5, 0.5]")
    arr = _as_float(img)
    c, hax, wax = _axes(arr)
    if c is None or arr.shape[c] == 1:
        return arr
    scale = 255.0 if arr.max() > 1.5 else 1.0
    chw = np.moveaxis(arr, c, 0) / scale
    h, s, v = _rgb_to_hsv(chw[0], chw[1], chw[2])
    h = (h + hue_factor) % 1.0
    r, g, b = _hsv_to_rgb(h, s, v)
    planes = [r, g, b] + [chw[i] for i in range(3, chw.shape[0])]  # keep alpha
    out = np.stack(planes) * scale
    return np.moveaxis(out, 0, c)


def to_grayscale(img, num_output_channels=1):
    arr = _as_float(img)
    c, _, _ = _axes(arr)
    if c is None:   # (H, W): broadcast to the requested channel count (CHW)
        return np.repeat(arr[None], num_output_channels, axis=0)
    weights = np.asarray([0.299, 0.587, 0.114], np.float32)
    chw = np.moveaxis(arr, c, 0)
    gray = (chw[:3] * weights[:, None, None]).sum(0, keepdims=True)
    gray = np.repeat(gray, num_output_channels, axis=0)
    return np.moveaxis(gray, 0, c)


def vflip(img):
    arr = np.asarray(img)
    _, h_axis, _ = _axes(arr)
    return np.flip(arr, axis=h_axis).copy()


def pad(img, padding, fill=0, padding_mode="constant"):
    arr = np.asarray(img)
    c, hax, wax = _axes(arr)
    if isinstance(padding, numbers.Number):
        pl = pr = pt = pb = int(padding)
    elif len(padding) == 2:
        pl, pt = padding
        pr, pb = padding
    else:
        pl, pt, pr, pb = padding
    spec = [(0, 0)] * arr.ndim
    spec[hax] = (pt, pb)
    spec[wax] = (pl, pr)
    mode = {"constant": "constant", "edge": "edge", "reflect": "reflect",
            "symmetric": "symmetric"}[padding_mode]
    kw = {"constant_values": fill} if mode == "constant" else {}
    return np.pad(arr, spec, mode=mode, **kw)


def crop(img, top, left, height, width):
    arr = np.asarray(img)
    _, hax, wax = _axes(arr)
    sl = [slice(None)] * arr.ndim
    sl[hax] = slice(top, top + height)
    sl[wax] = slice(left, left + width)
    return arr[tuple(sl)]


def center_crop(img, output_size):
    return CenterCrop(output_size)(img)


def rotate(img, angle, interpolation="nearest", expand=False, center=None, fill=0):
    """Ref functional.py rotate — inverse-mapped bilinear/nearest rotation.
    expand=True grows the canvas to contain the whole rotated image."""
    arr = _as_float(img)
    c, hax, wax = _axes(arr)
    h, w = arr.shape[hax], arr.shape[wax]
    cy, cx = ((h - 1) / 2.0, (w - 1) / 2.0) if center is None else (center[1], center[0])
    theta = np.deg2rad(angle)
    cos, sin = np.cos(theta), np.sin(theta)
    if expand:
        oh = int(np.ceil(abs(h * cos) + abs(w * sin) - 1e-9))
        ow = int(np.ceil(abs(w * cos) + abs(h * sin) - 1e-9))
        ocy, ocx = (oh - 1) / 2.0, (ow - 1) / 2.0
    else:
        oh, ow, ocy, ocx = h, w, cy, cx
    ys, xs = np.meshgrid(np.arange(oh), np.arange(ow), indexing="ij")
    # inverse rotation: output pixel -> source location
    sx = cos * (xs - ocx) + sin * (ys - ocy) + cx
    sy = -sin * (xs - ocx) + cos * (ys - ocy) + cy
    if interpolation == "nearest":
        sxi = np.round(sx).astype(np.int64)
        syi = np.round(sy).astype(np.int64)
        valid = (sx >= -0.5) & (sx <= w - 0.5) & (sy >= -0.5) & (sy <= h - 0.5)
        sxi = np.clip(sxi, 0, w - 1)
        syi = np.clip(syi, 0, h - 1)

        def sample(plane):
            out = plane[syi, sxi]
            return np.where(valid, out, fill)
    else:  # bilinear
        x0 = np.floor(sx).astype(np.int64)
        y0 = np.floor(sy).astype(np.int64)
        fx, fy = sx - x0, sy - y0
        eps = 1e-3  # cos/sin roundoff must not invalidate border pixels
        valid = (sx >= -eps) & (sx <= w - 1 + eps) & (sy >= -eps) & (sy <= h - 1 + eps)

        def sample(plane):
            def at(yy, xx):
                return plane[np.clip(yy, 0, h - 1), np.clip(xx, 0, w - 1)]

            out = ((1 - fy) * (1 - fx) * at(y0, x0) + (1 - fy) * fx * at(y0, x0 + 1)
                   + fy * (1 - fx) * at(y0 + 1, x0) + fy * fx * at(y0 + 1, x0 + 1))
            return np.where(valid, out, fill)

    if c is None:
        return sample(arr)
    chw = np.moveaxis(arr, c, 0)
    out = np.stack([sample(p) for p in chw])
    return np.moveaxis(out, 0, c)


def erase(img, i, j, h, w, v, inplace=False):
    """Ref functional.py erase — fill a rectangle with value(s); a per-channel
    value broadcasts along the channel axis."""
    arr = np.asarray(img) if inplace else np.array(img)
    c, hax, wax = _axes(arr)
    sl = [slice(None)] * arr.ndim
    sl[hax] = slice(i, i + h)
    sl[wax] = slice(j, j + w)
    val = np.asarray(v, arr.dtype)
    if val.ndim == 1 and c is not None:
        shape = [1] * arr.ndim
        shape[c] = val.shape[0]
        val = val.reshape(shape)
    arr[tuple(sl)] = val
    return arr


def _jitter_range(value, name):
    """paddle accepts a non-negative float (-> [max(0,1-v), 1+v]) or an
    explicit (min, max) pair."""
    if isinstance(value, (list, tuple)):
        lo, hi = float(value[0]), float(value[1])
        if lo > hi or lo < 0:
            raise ValueError(f"{name} range must satisfy 0 <= min <= max")
        return lo, hi
    value = float(value)
    if value < 0:
        raise ValueError(f"{name} value must be non-negative")
    return max(0.0, 1.0 - value), 1.0 + value


class BrightnessTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.range = _jitter_range(value, "brightness")

    def _apply_image(self, img):
        if self.range == (1.0, 1.0):
            return _as_float(img)
        return adjust_brightness(img, np.random.uniform(*self.range))


class ContrastTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.range = _jitter_range(value, "contrast")

    def _apply_image(self, img):
        if self.range == (1.0, 1.0):
            return _as_float(img)
        return adjust_contrast(img, np.random.uniform(*self.range))


class SaturationTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.range = _jitter_range(value, "saturation")

    def _apply_image(self, img):
        if self.range == (1.0, 1.0):
            return _as_float(img)
        return adjust_saturation(img, np.random.uniform(*self.range))


class HueTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        if isinstance(value, (list, tuple)):
            lo, hi = float(value[0]), float(value[1])
            if not (-0.5 <= lo <= hi <= 0.5):
                raise ValueError("hue range must be within [-0.5, 0.5]")
            self.range = (lo, hi)
        else:
            if not 0 <= value <= 0.5:
                raise ValueError("hue value must be in [0, 0.5]")
            self.range = (-float(value), float(value))

    def _apply_image(self, img):
        if self.range == (0.0, 0.0):
            return _as_float(img)
        return adjust_hue(img, np.random.uniform(*self.range))


class ColorJitter(BaseTransform):
    """Ref transforms.py ColorJitter: random brightness/contrast/saturation/hue
    in random order."""

    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0, keys=None):
        super().__init__(keys)
        self.transforms = [BrightnessTransform(brightness),
                           ContrastTransform(contrast),
                           SaturationTransform(saturation),
                           HueTransform(hue)]

    def _apply_image(self, img):
        order = np.random.permutation(len(self.transforms))
        for i in order:
            img = self.transforms[i]._apply_image(img)
        return img


class Grayscale(BaseTransform):
    def __init__(self, num_output_channels=1, keys=None):
        super().__init__(keys)
        self.num_output_channels = num_output_channels

    def _apply_image(self, img):
        return to_grayscale(img, self.num_output_channels)


class Pad(BaseTransform):
    def __init__(self, padding, fill=0, padding_mode="constant", keys=None):
        super().__init__(keys)
        self.padding, self.fill, self.mode = padding, fill, padding_mode

    def _apply_image(self, img):
        return pad(img, self.padding, self.fill, self.mode)


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        super().__init__(keys)
        self.prob = prob

    def _apply_image(self, img):
        return vflip(img) if np.random.rand() < self.prob else np.asarray(img)


class RandomRotation(BaseTransform):
    def __init__(self, degrees, interpolation="nearest", expand=False,
                 center=None, fill=0, keys=None):
        super().__init__(keys)
        if isinstance(degrees, numbers.Number):
            degrees = (-abs(degrees), abs(degrees))
        self.degrees = degrees
        self.interpolation = interpolation
        self.expand = expand
        self.center = center
        self.fill = fill

    def _apply_image(self, img):
        angle = np.random.uniform(*self.degrees)
        return rotate(img, angle, self.interpolation, expand=self.expand,
                      center=self.center, fill=self.fill)


class RandomErasing(BaseTransform):
    """Ref transforms.py RandomErasing (Zhong et al.)."""

    def __init__(self, prob=0.5, scale=(0.02, 0.33), ratio=(0.3, 3.3),
                 value=0, inplace=False, keys=None):
        super().__init__(keys)
        self.prob, self.scale, self.ratio = prob, scale, ratio
        self.value, self.inplace = value, inplace

    def _apply_image(self, img):
        arr = np.asarray(img)
        if np.random.rand() >= self.prob:
            return arr
        _, hax, wax = _axes(arr)
        h, w = arr.shape[hax], arr.shape[wax]
        area = h * w
        for _ in range(10):
            target = area * np.random.uniform(*self.scale)
            ar = np.exp(np.random.uniform(np.log(self.ratio[0]),
                                          np.log(self.ratio[1])))
            eh = int(round(np.sqrt(target * ar)))
            ew = int(round(np.sqrt(target / ar)))
            if eh < h and ew < w:
                i = np.random.randint(0, h - eh + 1)
                j = np.random.randint(0, w - ew + 1)
                v = (np.random.standard_normal() if self.value == "random"
                     else self.value)
                return erase(arr, i, j, eh, ew, v, self.inplace)
        return arr


def _affine_sample(arr, inv_mat, fill=0.0, interpolation="bilinear"):
    """Inverse-map sampling with a 3x3 homography (shared by affine/perspective)."""
    c, hax, wax = _axes(arr)
    h, w = arr.shape[hax], arr.shape[wax]
    ys, xs = np.meshgrid(np.arange(h, dtype=np.float64),
                         np.arange(w, dtype=np.float64), indexing="ij")
    ones = np.ones_like(xs)
    pts = np.stack([xs, ys, ones])                       # [3, H, W]
    src = np.tensordot(inv_mat, pts.reshape(3, -1), 1).reshape(3, h, w)
    sx = src[0] / np.maximum(np.abs(src[2]), 1e-9) * np.sign(src[2])
    sy = src[1] / np.maximum(np.abs(src[2]), 1e-9) * np.sign(src[2])
    if interpolation == "nearest":
        xi = np.round(sx).astype(np.int64)
        yi = np.round(sy).astype(np.int64)
        valid = (xi >= 0) & (xi < w) & (yi >= 0) & (yi < h)
        xi = np.clip(xi, 0, w - 1)
        yi = np.clip(yi, 0, h - 1)

        def sample(plane):
            return np.where(valid, plane[yi, xi], fill)
    else:
        x0 = np.floor(sx).astype(np.int64)
        y0 = np.floor(sy).astype(np.int64)
        fx, fy = sx - x0, sy - y0
        eps = 1e-3
        valid = (sx >= -eps) & (sx <= w - 1 + eps) & (sy >= -eps) & (sy <= h - 1 + eps)

        def sample(plane):
            def at(yy, xx):
                return plane[np.clip(yy, 0, h - 1), np.clip(xx, 0, w - 1)]

            out = ((1 - fy) * (1 - fx) * at(y0, x0) + (1 - fy) * fx * at(y0, x0 + 1)
                   + fy * (1 - fx) * at(y0 + 1, x0) + fy * fx * at(y0 + 1, x0 + 1))
            return np.where(valid, out, fill)

    if c is None:
        return sample(arr).astype(np.float32)
    chw = np.moveaxis(arr, c, 0)
    out = np.stack([sample(p) for p in chw]).astype(np.float32)
    return np.moveaxis(out, 0, c)


def affine(img, angle, translate, scale, shear, interpolation="nearest",
           fill=0, center=None):
    """Ref functional.py affine(img, angle, translate, scale, shear, ...) —
    the paddle signature; the forward map is composed like RandomAffine."""
    arr = _as_float(img)
    _, hax, wax = _axes(arr)
    h, w = arr.shape[hax], arr.shape[wax]
    if isinstance(shear, numbers.Number):
        shear = (float(shear), 0.0)
    ctr = center or ((w - 1) / 2.0, (h - 1) / 2.0)
    fwd = _build_affine(angle, tuple(translate), float(scale),
                        tuple(shear)[:2], ctr)
    inv = np.linalg.inv(fwd)
    return _affine_sample(arr, inv, fill=fill, interpolation=interpolation)


def _build_affine(angle, translate, scale, shear, center):
    cx, cy = center
    rot = np.deg2rad(angle)
    sx, sy = np.deg2rad(shear[0]), np.deg2rad(shear[1])
    # torchvision/paddle composition: T * C * RotShearScale * C^-1
    a = np.cos(rot - sy) / np.cos(sy)
    b = -np.cos(rot - sy) * np.tan(sx) / np.cos(sy) - np.sin(rot)
    c = np.sin(rot - sy) / np.cos(sy)
    d = -np.sin(rot - sy) * np.tan(sx) / np.cos(sy) + np.cos(rot)
    m = np.array([[a, b, 0.0], [c, d, 0.0], [0, 0, 1]]) * 1.0
    m[:2, :] *= scale
    m[0, 2] = cx + translate[0] - m[0, 0] * cx - m[0, 1] * cy
    m[1, 2] = cy + translate[1] - m[1, 0] * cx - m[1, 1] * cy
    return m


class RandomAffine(BaseTransform):
    """Ref transforms.py RandomAffine."""

    def __init__(self, degrees, translate=None, scale=None, shear=None,
                 interpolation="nearest", fill=0, center=None, keys=None):
        super().__init__(keys)
        if isinstance(degrees, numbers.Number):
            degrees = (-abs(degrees), abs(degrees))
        self.degrees = degrees
        self.translate = translate
        self.scale = scale
        self.shear = shear
        self.interpolation = interpolation
        self.fill = fill
        self.center = center

    def _apply_image(self, img):
        arr = _as_float(img)
        _, hax, wax = _axes(arr)
        h, w = arr.shape[hax], arr.shape[wax]
        angle = np.random.uniform(*self.degrees)
        tx = ty = 0.0
        if self.translate is not None:
            tx = np.random.uniform(-self.translate[0], self.translate[0]) * w
            ty = np.random.uniform(-self.translate[1], self.translate[1]) * h
        sc = np.random.uniform(*self.scale) if self.scale is not None else 1.0
        sh = (0.0, 0.0)
        if self.shear is not None:
            shv = (self.shear if isinstance(self.shear, (list, tuple))
                   else (-self.shear, self.shear))
            if len(shv) == 4:       # paddle's [x_min, x_max, y_min, y_max]
                sh = (np.random.uniform(shv[0], shv[1]),
                      np.random.uniform(shv[2], shv[3]))
            else:
                sh = (np.random.uniform(shv[0], shv[1]), 0.0)
        center = self.center or ((w - 1) / 2.0, (h - 1) / 2.0)
        fwd = _build_affine(angle, (tx, ty), sc, sh, center)
        inv = np.linalg.inv(fwd)
        return _affine_sample(arr, inv, fill=self.fill,
                              interpolation=self.interpolation)


def perspective(img, startpoints, endpoints, interpolation="nearest", fill=0):
    """Ref functional.py perspective: map the quad `startpoints` onto
    `endpoints` (each 4 [x, y] corners)."""
    src = np.asarray(startpoints, np.float64)
    dst = np.asarray(endpoints, np.float64)
    # solve the homography dst -> src (inverse map for sampling)
    A = []
    for (xd, yd), (xs, ys) in zip(dst, src):
        A.append([xd, yd, 1, 0, 0, 0, -xs * xd, -xs * yd])
        A.append([0, 0, 0, xd, yd, 1, -ys * xd, -ys * yd])
    A = np.asarray(A)
    b = src.reshape(-1)
    coeffs = np.linalg.solve(A, b)
    inv = np.vstack([coeffs.reshape(-1)[:6].reshape(2, 3),
                     [coeffs[6], coeffs[7], 1.0]])
    return _affine_sample(_as_float(img), inv, fill=fill,
                          interpolation=interpolation)


class RandomPerspective(BaseTransform):
    """Ref transforms.py RandomPerspective."""

    def __init__(self, prob=0.5, distortion_scale=0.5, interpolation="nearest",
                 fill=0, keys=None):
        super().__init__(keys)
        self.prob = prob
        self.distortion_scale = distortion_scale
        self.interpolation = interpolation
        self.fill = fill

    def _apply_image(self, img):
        arr = _as_float(img)
        if np.random.rand() >= self.prob:
            return arr
        _, hax, wax = _axes(arr)
        h, w = arr.shape[hax], arr.shape[wax]
        d = self.distortion_scale
        hw, hh = int(w * d / 2), int(h * d / 2)
        start = [[0, 0], [w - 1, 0], [w - 1, h - 1], [0, h - 1]]
        end = [[np.random.randint(0, hw + 1), np.random.randint(0, hh + 1)],
               [w - 1 - np.random.randint(0, hw + 1), np.random.randint(0, hh + 1)],
               [w - 1 - np.random.randint(0, hw + 1), h - 1 - np.random.randint(0, hh + 1)],
               [np.random.randint(0, hw + 1), h - 1 - np.random.randint(0, hh + 1)]]
        return perspective(arr, start, end, self.interpolation, self.fill)
