"""paddle.vision.ops (ref: python/paddle/vision/ops.py — detection-pipeline
primitives: nms:1515, roi_align:1301, roi_pool:1173, yolo_box:253).

TPU split: box decode and ROI feature extraction are traced jnp (they sit
inside jitted forward passes and roi_align is differentiable); greedy NMS is
host-side numpy — it is sequential post-processing over a handful of boxes,
exactly where the reference ran it relative to the hot path.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ..tensor.tensor import Tensor, apply_op, _unwrap

__all__ = ["nms", "roi_align", "roi_pool", "yolo_box", "box_iou"]


def box_iou(boxes1, boxes2):
    """IoU matrix between [N,4] and [M,4] xyxy boxes."""

    def _f(a, b):
        area1 = (a[:, 2] - a[:, 0]) * (a[:, 3] - a[:, 1])
        area2 = (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])
        lt = jnp.maximum(a[:, None, :2], b[None, :, :2])
        rb = jnp.minimum(a[:, None, 2:], b[None, :, 2:])
        wh = jnp.clip(rb - lt, 0)
        inter = wh[..., 0] * wh[..., 1]
        return inter / (area1[:, None] + area2[None, :] - inter + 1e-10)

    return apply_op(_f, (boxes1, boxes2), name="box_iou")


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None, categories=None,
        top_k=None):
    """Ref ops.py:1515 — greedy NMS; returns kept indices (int64 Tensor).

    Host-side: NMS is inherently sequential; it post-processes a few hundred
    boxes after the jitted forward."""
    b = np.asarray(_unwrap(boxes), np.float32)
    n = b.shape[0]
    s = (np.asarray(_unwrap(scores), np.float32) if scores is not None
         else np.ones((n,), np.float32))
    cats = (np.asarray(_unwrap(category_idxs)) if category_idxs is not None
            else np.zeros((n,), np.int64))

    keep_all = []
    for c in np.unique(cats):
        idx = np.nonzero(cats == c)[0]
        order = idx[np.argsort(-s[idx])]
        kept = []
        suppressed = np.zeros(len(order), bool)
        for i in range(len(order)):
            if suppressed[i]:
                continue
            kept.append(order[i])
            bi = b[order[i]]
            for j in range(i + 1, len(order)):
                if suppressed[j]:
                    continue
                bj = b[order[j]]
                lt = np.maximum(bi[:2], bj[:2])
                rb = np.minimum(bi[2:], bj[2:])
                wh = np.clip(rb - lt, 0, None)
                inter = wh[0] * wh[1]
                a1 = (bi[2] - bi[0]) * (bi[3] - bi[1])
                a2 = (bj[2] - bj[0]) * (bj[3] - bj[1])
                if inter / (a1 + a2 - inter + 1e-10) > iou_threshold:
                    suppressed[j] = True
        keep_all += kept
    keep_all = sorted(keep_all, key=lambda i: -s[i])
    if top_k is not None:
        keep_all = keep_all[:top_k]
    return Tensor(jnp.asarray(np.asarray(keep_all, np.int64)))


def _roi_sample(feat, rois, output_size, spatial_scale, sampling_ratio, mode):
    """Shared bilinear ROI sampler: feat [C,H,W], rois [R,4] xyxy."""
    ph, pw = output_size
    sr = max(int(sampling_ratio), 1)

    def one_roi(roi):
        x1, y1, x2, y2 = roi * spatial_scale
        rw = jnp.maximum(x2 - x1, 1.0)
        rh = jnp.maximum(y2 - y1, 1.0)
        bin_h = rh / ph
        bin_w = rw / pw
        # sr x sr sample points per bin (ref roi_align sampling_ratio)
        iy = (jnp.arange(ph * sr) + 0.5) / sr
        ix = (jnp.arange(pw * sr) + 0.5) / sr
        ys = y1 + iy * bin_h
        xs = x1 + ix * bin_w
        gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
        coords = jnp.stack([jnp.broadcast_to(gy, gy.shape),
                            jnp.broadcast_to(gx, gx.shape)])

        def per_channel(ch):
            samp = jax.scipy.ndimage.map_coordinates(ch, coords, order=1,
                                                     mode="nearest")
            samp = samp.reshape(ph, sr, pw, sr)
            if mode == "max":
                return samp.max(axis=(1, 3))
            return samp.mean(axis=(1, 3))

        return jax.vmap(per_channel)(feat)      # [C, ph, pw]

    return jax.vmap(one_roi)(rois)              # [R, C, ph, pw]


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True, name=None):
    """Ref ops.py:1301 — differentiable bilinear ROI pooling.

    x: [N,C,H,W]; boxes: [R,4] xyxy (concatenated over the batch);
    boxes_num: [N] rois per image."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    sr = 2 if sampling_ratio in (-1, None) else sampling_ratio

    def _f(feat, rois):
        off = 0.5 if aligned else 0.0
        rois = rois - off / spatial_scale
        counts = np.asarray(_unwrap(boxes_num), np.int64)
        outs = []
        start = 0
        for img, cnt in enumerate(counts):     # static per-image partition
            r = rois[start:start + int(cnt)]
            outs.append(_roi_sample(feat[img], r, output_size, spatial_scale,
                                    sr, "avg"))
            start += int(cnt)
        return jnp.concatenate(outs, axis=0)

    return apply_op(_f, (x, boxes), name="roi_align")


def roi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0, name=None):
    """Ref ops.py:1173 — max-pooled ROI features (dense 4x4-sample max per
    bin; the reference's integer quantization is shape-dynamic and anti-TPU)."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)

    def _f(feat, rois):
        counts = np.asarray(_unwrap(boxes_num), np.int64)
        outs = []
        start = 0
        for img, cnt in enumerate(counts):
            r = rois[start:start + int(cnt)]
            outs.append(_roi_sample(feat[img], r, output_size, spatial_scale,
                                    4, "max"))
            start += int(cnt)
        return jnp.concatenate(outs, axis=0)

    return apply_op(_f, (x, boxes), name="roi_pool")


def yolo_box(x, img_size, anchors, class_num, conf_thresh, downsample_ratio,
             clip_bbox=True, scale_x_y=1.0, iou_aware=False,
             iou_aware_factor=0.5, name=None):
    """Ref ops.py:253 — decode a YOLO head [N, A*(5+C), H, W] into boxes+scores.

    Returns (boxes [N, A*H*W, 4] xyxy in image coords, scores [N, A*H*W, C])."""
    anchors = np.asarray(anchors, np.float32).reshape(-1, 2)
    A = anchors.shape[0]

    def _f(pred, imgs):
        N, _, H, W = pred.shape
        p = pred.reshape(N, A, 5 + class_num, H, W)
        gx = jnp.arange(W, dtype=jnp.float32)
        gy = jnp.arange(H, dtype=jnp.float32)
        cx = (jax.nn.sigmoid(p[:, :, 0]) * scale_x_y
              - (scale_x_y - 1) / 2 + gx[None, None, None, :]) / W
        cy = (jax.nn.sigmoid(p[:, :, 1]) * scale_x_y
              - (scale_x_y - 1) / 2 + gy[None, None, :, None]) / H
        in_w, in_h = W * downsample_ratio, H * downsample_ratio
        bw = jnp.exp(p[:, :, 2]) * anchors[None, :, 0, None, None] / in_w
        bh = jnp.exp(p[:, :, 3]) * anchors[None, :, 1, None, None] / in_h
        obj = jax.nn.sigmoid(p[:, :, 4])
        cls = jax.nn.sigmoid(p[:, :, 5:])
        score = obj[:, :, None] * cls
        score = jnp.where(score >= conf_thresh, score, 0.0)

        imw = imgs[:, 1].astype(jnp.float32)[:, None, None, None]
        imh = imgs[:, 0].astype(jnp.float32)[:, None, None, None]
        x1 = (cx - bw / 2) * imw
        y1 = (cy - bh / 2) * imh
        x2 = (cx + bw / 2) * imw
        y2 = (cy + bh / 2) * imh
        if clip_bbox:
            x1 = jnp.clip(x1, 0, imw - 1)
            y1 = jnp.clip(y1, 0, imh - 1)
            x2 = jnp.clip(x2, 0, imw - 1)
            y2 = jnp.clip(y2, 0, imh - 1)
        boxes = jnp.stack([x1, y1, x2, y2], axis=-1).reshape(N, -1, 4)
        scores = jnp.moveaxis(score, 2, -1).reshape(N, -1, class_num)
        return boxes, scores

    return apply_op(_f, (x, img_size), name="yolo_box")
