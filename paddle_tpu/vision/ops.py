"""paddle.vision.ops (ref: python/paddle/vision/ops.py — detection-pipeline
primitives: nms:1515, roi_align:1301, roi_pool:1173, yolo_box:253).

TPU split: box decode and ROI feature extraction are traced jnp (they sit
inside jitted forward passes and roi_align is differentiable); greedy NMS is
host-side numpy — it is sequential post-processing over a handful of boxes,
exactly where the reference ran it relative to the hot path.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ..tensor.tensor import Tensor, apply_op, _unwrap

__all__ = ["nms", "roi_align", "roi_pool", "yolo_box", "box_iou",
           "deform_conv2d"]


def box_iou(boxes1, boxes2):
    """IoU matrix between [N,4] and [M,4] xyxy boxes."""

    def _f(a, b):
        area1 = (a[:, 2] - a[:, 0]) * (a[:, 3] - a[:, 1])
        area2 = (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])
        lt = jnp.maximum(a[:, None, :2], b[None, :, :2])
        rb = jnp.minimum(a[:, None, 2:], b[None, :, 2:])
        wh = jnp.clip(rb - lt, 0)
        inter = wh[..., 0] * wh[..., 1]
        return inter / (area1[:, None] + area2[None, :] - inter + 1e-10)

    return apply_op(_f, (boxes1, boxes2), name="box_iou")


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None, categories=None,
        top_k=None):
    """Ref ops.py:1515 — greedy NMS; returns kept indices (int64 Tensor).

    Host-side: NMS is inherently sequential; it post-processes a few hundred
    boxes after the jitted forward."""
    b = np.asarray(_unwrap(boxes), np.float32)
    n = b.shape[0]
    s = (np.asarray(_unwrap(scores), np.float32) if scores is not None
         else np.ones((n,), np.float32))
    cats = (np.asarray(_unwrap(category_idxs)) if category_idxs is not None
            else np.zeros((n,), np.int64))

    areas = (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])
    keep_all = []
    for c in np.unique(cats):
        idx = np.nonzero(cats == c)[0]
        order = idx[np.argsort(-s[idx])]
        suppressed = np.zeros(len(order), bool)
        for i in range(len(order)):
            if suppressed[i]:
                continue
            keep_all.append(order[i])
            bi = b[order[i]]
            rest = order[i + 1:]
            # vectorized IoU of the kept box vs all remaining candidates
            lt = np.maximum(bi[:2], b[rest, :2])
            rb = np.minimum(bi[2:], b[rest, 2:])
            wh = np.clip(rb - lt, 0, None)
            inter = wh[:, 0] * wh[:, 1]
            iou = inter / (areas[order[i]] + areas[rest] - inter + 1e-10)
            suppressed[i + 1:] |= iou > iou_threshold
    keep_all = sorted(keep_all, key=lambda i: -s[i])
    if top_k is not None:
        keep_all = keep_all[:top_k]
    return Tensor(jnp.asarray(np.asarray(keep_all, np.int64)))


def _roi_sample(feat, rois, output_size, spatial_scale, sampling_ratio, mode):
    """Shared bilinear ROI sampler: feat [C,H,W], rois [R,4] xyxy."""
    ph, pw = output_size
    sr = max(int(sampling_ratio), 1)

    def one_roi(roi):
        x1, y1, x2, y2 = roi * spatial_scale
        rw = jnp.maximum(x2 - x1, 1.0)
        rh = jnp.maximum(y2 - y1, 1.0)
        bin_h = rh / ph
        bin_w = rw / pw
        # sr x sr sample points per bin (ref roi_align sampling_ratio)
        iy = (jnp.arange(ph * sr) + 0.5) / sr
        ix = (jnp.arange(pw * sr) + 0.5) / sr
        ys = y1 + iy * bin_h
        xs = x1 + ix * bin_w
        gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
        coords = jnp.stack([jnp.broadcast_to(gy, gy.shape),
                            jnp.broadcast_to(gx, gx.shape)])

        def per_channel(ch):
            samp = jax.scipy.ndimage.map_coordinates(ch, coords, order=1,
                                                     mode="nearest")
            samp = samp.reshape(ph, sr, pw, sr)
            if mode == "max":
                return samp.max(axis=(1, 3))
            return samp.mean(axis=(1, 3))

        return jax.vmap(per_channel)(feat)      # [C, ph, pw]

    return jax.vmap(one_roi)(rois)              # [R, C, ph, pw]


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True, name=None):
    """Ref ops.py:1301 — differentiable bilinear ROI pooling.

    x: [N,C,H,W]; boxes: [R,4] xyxy (concatenated over the batch);
    boxes_num: [N] rois per image."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    sr = 2 if sampling_ratio in (-1, None) else sampling_ratio

    def _f(feat, rois):
        off = 0.5 if aligned else 0.0
        rois = rois - off / spatial_scale
        counts = np.asarray(_unwrap(boxes_num), np.int64)
        outs = []
        start = 0
        for img, cnt in enumerate(counts):     # static per-image partition
            r = rois[start:start + int(cnt)]
            outs.append(_roi_sample(feat[img], r, output_size, spatial_scale,
                                    sr, "avg"))
            start += int(cnt)
        return jnp.concatenate(outs, axis=0)

    return apply_op(_f, (x, boxes), name="roi_align")


def roi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0, name=None):
    """Ref ops.py:1173 — max-pooled ROI features (dense 4x4-sample max per
    bin; the reference's integer quantization is shape-dynamic and anti-TPU)."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)

    def _f(feat, rois):
        counts = np.asarray(_unwrap(boxes_num), np.int64)
        outs = []
        start = 0
        for img, cnt in enumerate(counts):
            r = rois[start:start + int(cnt)]
            outs.append(_roi_sample(feat[img], r, output_size, spatial_scale,
                                    4, "max"))
            start += int(cnt)
        return jnp.concatenate(outs, axis=0)

    return apply_op(_f, (x, boxes), name="roi_pool")


def yolo_box(x, img_size, anchors, class_num, conf_thresh, downsample_ratio,
             clip_bbox=True, scale_x_y=1.0, iou_aware=False,
             iou_aware_factor=0.5, name=None):
    """Ref ops.py:253 — decode a YOLO head [N, A*(5+C), H, W] into boxes+scores.

    Returns (boxes [N, A*H*W, 4] xyxy in image coords, scores [N, A*H*W, C])."""
    anchors = np.asarray(anchors, np.float32).reshape(-1, 2)
    A = anchors.shape[0]

    def _f(pred, imgs):
        N, _, H, W = pred.shape
        if iou_aware:
            # layout (ref yolo_box_op): first A iou channels, then A*(5+C)
            iou_pred = pred[:, :A]
            p = pred[:, A:].reshape(N, A, 5 + class_num, H, W)
        else:
            p = pred.reshape(N, A, 5 + class_num, H, W)
        gx = jnp.arange(W, dtype=jnp.float32)
        gy = jnp.arange(H, dtype=jnp.float32)
        cx = (jax.nn.sigmoid(p[:, :, 0]) * scale_x_y
              - (scale_x_y - 1) / 2 + gx[None, None, None, :]) / W
        cy = (jax.nn.sigmoid(p[:, :, 1]) * scale_x_y
              - (scale_x_y - 1) / 2 + gy[None, None, :, None]) / H
        in_w, in_h = W * downsample_ratio, H * downsample_ratio
        bw = jnp.exp(p[:, :, 2]) * anchors[None, :, 0, None, None] / in_w
        bh = jnp.exp(p[:, :, 3]) * anchors[None, :, 1, None, None] / in_h
        obj = jax.nn.sigmoid(p[:, :, 4])
        if iou_aware:
            iou_q = jax.nn.sigmoid(iou_pred)
            obj = obj ** (1.0 - iou_aware_factor) * iou_q ** iou_aware_factor
        cls = jax.nn.sigmoid(p[:, :, 5:])
        score = obj[:, :, None] * cls
        score = jnp.where(score >= conf_thresh, score, 0.0)

        imw = imgs[:, 1].astype(jnp.float32)[:, None, None, None]
        imh = imgs[:, 0].astype(jnp.float32)[:, None, None, None]
        x1 = (cx - bw / 2) * imw
        y1 = (cy - bh / 2) * imh
        x2 = (cx + bw / 2) * imw
        y2 = (cy + bh / 2) * imh
        if clip_bbox:
            x1 = jnp.clip(x1, 0, imw - 1)
            y1 = jnp.clip(y1, 0, imh - 1)
            x2 = jnp.clip(x2, 0, imw - 1)
            y2 = jnp.clip(y2, 0, imh - 1)
        boxes = jnp.stack([x1, y1, x2, y2], axis=-1).reshape(N, -1, 4)
        scores = jnp.moveaxis(score, 2, -1).reshape(N, -1, class_num)
        return boxes, scores

    return apply_op(_f, (x, img_size), name="yolo_box")


def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0, dilation=1,
                  deformable_groups=1, groups=1, mask=None, name=None):
    """Ref ops.py:431 — deformable convolution v1/v2 (v2 when `mask` given).

    Implemented as offset-shifted bilinear sampling (im2col with learned
    offsets) + a dense matmul on the MXU: for each output position and kernel
    tap, sample x at (base + offset), multiply by the modulation mask (v2),
    then contract with the weights — the gather-heavy half runs on the VPU,
    the contraction on the MXU.

    x: [N, Cin, H, W]; offset: [N, 2*dg*kh*kw, Hout, Wout];
    weight: [Cout, Cin//groups, kh, kw]; mask: [N, dg*kh*kw, Hout, Wout].
    """
    stride = (stride, stride) if isinstance(stride, int) else tuple(stride)
    padding = (padding, padding) if isinstance(padding, int) else tuple(padding)
    dilation = (dilation, dilation) if isinstance(dilation, int) else tuple(dilation)

    def _f(xv, off, w, *rest):
        m = rest[0] if mask is not None else None
        b = rest[-1] if bias is not None else None
        N, Cin, H, W = xv.shape
        Cout, Cin_g, kh, kw = w.shape
        Hout, Wout = off.shape[2], off.shape[3]
        dg = deformable_groups
        ch_per_dg = Cin // dg

        # base sampling grid per output position and tap
        oy = jnp.arange(Hout) * stride[0] - padding[0]
        ox = jnp.arange(Wout) * stride[1] - padding[1]
        ky = jnp.arange(kh) * dilation[0]
        kx = jnp.arange(kw) * dilation[1]
        base_y = oy[:, None, None, None] + ky[None, None, :, None]   # [Hout,1,kh,1]
        base_x = ox[None, :, None, None] + kx[None, None, None, :]   # [1,Wout,1,kw]

        off = off.reshape(N, dg, kh * kw, 2, Hout, Wout)
        dy = jnp.moveaxis(off[:, :, :, 0], 2, -1).reshape(N, dg, Hout, Wout, kh, kw)
        dx = jnp.moveaxis(off[:, :, :, 1], 2, -1).reshape(N, dg, Hout, Wout, kh, kw)
        sy = base_y[None, None] + dy                                  # [N,dg,Hout,Wout,kh,kw]
        sx = base_x[None, None] + dx

        def sample_plane(plane, yy, xxc):
            # bilinear with zero padding outside
            y0 = jnp.floor(yy)
            x0 = jnp.floor(xxc)
            fy, fx = yy - y0, xxc - x0
            out = 0.0
            for ddy, wy in ((0, 1 - fy), (1, fy)):
                for ddx, wx in ((0, 1 - fx), (1, fx)):
                    yi = (y0 + ddy).astype(jnp.int32)
                    xi = (x0 + ddx).astype(jnp.int32)
                    valid = (yi >= 0) & (yi < H) & (xi >= 0) & (xi < W)
                    v = plane[jnp.clip(yi, 0, H - 1), jnp.clip(xi, 0, W - 1)]
                    out = out + jnp.where(valid, v, 0.0) * wy * wx
            return out

        # vmap: batch over N, then channels within each deformable group
        def per_image(img, syi, sxi, mi):
            cols = []
            for g in range(dg):
                ch = img[g * ch_per_dg:(g + 1) * ch_per_dg]
                samp = jax.vmap(lambda p: sample_plane(p, syi[g], sxi[g]))(ch)
                if mi is not None:
                    samp = samp * mi[g][None]
                cols.append(samp)                 # [ch_per_dg, Hout, Wout, kh, kw]
            return jnp.concatenate(cols, 0)       # [Cin, Hout, Wout, kh, kw]

        if m is not None:
            m = jnp.moveaxis(m.reshape(N, dg, kh * kw, Hout, Wout), 2, -1) \
                .reshape(N, dg, Hout, Wout, kh, kw)
        if m is not None:
            cols = jax.vmap(per_image)(xv, sy, sx, m)
        else:
            cols = jax.vmap(lambda img, syi, sxi: per_image(img, syi, sxi, None))(
                xv, sy, sx)

        # contract: out[n, co, ho, wo] = sum_{ci, kh, kw} w * cols
        wg = w.reshape(groups, Cout // groups, Cin_g, kh, kw)
        colsg = cols.reshape(N, groups, Cin // groups, Hout, Wout, kh, kw)
        out = jnp.einsum("ngihwkl,goikl->ngohw", colsg, wg,
                         preferred_element_type=jnp.float32)
        out = out.reshape(N, Cout, Hout, Wout).astype(xv.dtype)
        if b is not None:
            out = out + b[None, :, None, None]
        return out

    extra = []
    if mask is not None:
        extra.append(mask)
    if bias is not None:
        extra.append(bias)
    return apply_op(_f, (x, offset, weight, *extra), name="deform_conv2d")


class RoIAlign:
    """Layer wrapper over roi_align (ref vision/ops.py RoIAlign:1398)."""

    def __init__(self, output_size, spatial_scale=1.0):
        self.output_size = output_size
        self.spatial_scale = spatial_scale

    def __call__(self, x, boxes, boxes_num, aligned=True):
        return roi_align(x, boxes, boxes_num, self.output_size,
                         self.spatial_scale, aligned=aligned)


class RoIPool:
    """Layer wrapper over roi_pool (ref vision/ops.py RoIPool:1251)."""

    def __init__(self, output_size, spatial_scale=1.0):
        self.output_size = output_size
        self.spatial_scale = spatial_scale

    def __call__(self, x, boxes, boxes_num):
        return roi_pool(x, boxes, boxes_num, self.output_size, self.spatial_scale)


def psroi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0, name=None):
    """Position-sensitive ROI pooling (ref vision/ops.py psroi_pool:1073):
    input channels C = out_channels*h*w; bin (i, j) reads its OWN channel
    group — average-pooled per bin."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    oh, ow = output_size
    C = x.shape[1]
    if C % (oh * ow) != 0:
        raise ValueError(
            f"psroi_pool: input channels {C} must be divisible by "
            f"output_size^2 {oh * ow}")
    oc = C // (oh * ow)

    # reuse the bilinear ROI sampler per channel-group: sample a fine grid,
    # then average within each bin, taking bin (i,j)'s group of channels
    feats = roi_align(x, boxes, boxes_num, (oh, ow), spatial_scale,
                      sampling_ratio=2, aligned=False)  # [R, C, oh, ow]

    def _f(v):
        R = v.shape[0]
        v = v.reshape(R, oc, oh, ow, oh, ow)  # [R, oc, bin_i, bin_j, i, j]
        idx_i = jnp.arange(oh)
        idx_j = jnp.arange(ow)
        # select the diagonal: output[i, j] from channel group (i, j)
        v = v[:, :, idx_i[:, None], idx_j[None, :], idx_i[:, None], idx_j[None, :]]
        return v

    return apply_op(_f, (feats,), name="psroi_pool")


class PSRoIPool:
    """Layer wrapper over psroi_pool (ref vision/ops.py PSRoIPool:1137)."""

    def __init__(self, output_size, spatial_scale=1.0):
        self.output_size = output_size
        self.spatial_scale = spatial_scale

    def __call__(self, x, boxes, boxes_num):
        return psroi_pool(x, boxes, boxes_num, self.output_size, self.spatial_scale)


class DeformConv2D:
    """Layer wrapper over deform_conv2d (ref vision/ops.py DeformConv2D:694)."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, deformable_groups=1, groups=1,
                 weight_attr=None, bias_attr=None):
        from .. import nn
        from ..nn.initializer import Constant, XavierUniform

        ks = (kernel_size, kernel_size) if isinstance(kernel_size, int) else tuple(kernel_size)
        self.stride = stride
        self.padding = padding
        self.dilation = dilation
        self.deformable_groups = deformable_groups
        self.groups = groups
        helper = nn.Layer()
        self.weight = helper.create_parameter(
            [out_channels, in_channels // groups, *ks], attr=weight_attr,
            default_initializer=XavierUniform())
        self.bias = (None if bias_attr is False else helper.create_parameter(
            [out_channels], attr=bias_attr, is_bias=True,
            default_initializer=Constant(0.0)))
        self._helper_layer = helper  # keeps the params registered/trainable

    def parameters(self):
        return [p for p in (self.weight, self.bias) if p is not None]

    def __call__(self, x, offset, mask=None):
        return deform_conv2d(x, offset, self.weight, bias=self.bias,
                             stride=self.stride, padding=self.padding,
                             dilation=self.dilation, mask=mask,
                             deformable_groups=self.deformable_groups,
                             groups=self.groups)


def distribute_fpn_proposals(fpn_rois, min_level, max_level, refer_level,
                             refer_scale, pixel_offset=False, rois_num=None,
                             name=None):
    """Assign ROIs to FPN levels by scale (ref vision/ops.py
    distribute_fpn_proposals:60): level = floor(log2(sqrt(area)/refer_scale)
    + refer_level), clamped.  Ragged per-level outputs -> eager host op."""
    rois = np.asarray(jax.device_get(_unwrap(fpn_rois)))
    off = 1.0 if pixel_offset else 0.0
    w = rois[:, 2] - rois[:, 0] + off
    h = rois[:, 3] - rois[:, 1] + off
    scale = np.sqrt(np.maximum(w * h, 1e-12))
    lvl = np.floor(np.log2(scale / refer_scale + 1e-8)) + refer_level
    lvl = np.clip(lvl, min_level, max_level).astype(np.int64)

    multi_rois, restore_parts = [], []
    for level in range(min_level, max_level + 1):
        idx = np.nonzero(lvl == level)[0]
        multi_rois.append(Tensor(jnp.asarray(rois[idx])))
        restore_parts.append(idx)
    order = np.concatenate(restore_parts) if restore_parts else np.empty(0, np.int64)
    restore = np.empty_like(order)
    restore[order] = np.arange(len(order))
    rois_num_per_level = None
    if rois_num is not None:
        rois_num_per_level = [Tensor(jnp.asarray(np.asarray([len(p)], np.int64)))
                              for p in restore_parts]
    return multi_rois, Tensor(jnp.asarray(restore.astype(np.int32)[:, None])), rois_num_per_level


def yolo_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
              ignore_thresh, downsample_ratio, gt_score=None,
              use_label_smooth=True, name=None, scale_x_y=1.0):
    """YOLOv3 loss (ref vision/ops.py yolo_loss:392).

    Target assignment (which anchor owns which gt box) is data-dependent
    bookkeeping — built on host from the (stop-gradient) gt boxes, exactly
    like the reference kernel's precompute; the differentiable loss over the
    prediction tensor is traced jnp."""
    xv = _unwrap(x)
    B, _, H, W = x.shape
    an_mask = list(anchor_mask)
    n_anch = len(an_mask)
    anchors_xy = [(anchors[2 * i], anchors[2 * i + 1]) for i in range(len(anchors) // 2)]
    masked_anchors = [anchors_xy[i] for i in an_mask]
    gt = np.asarray(jax.device_get(_unwrap(gt_box)))      # [B, M, 4] cx,cy,w,h (normalized)
    gl = np.asarray(jax.device_get(_unwrap(gt_label)))    # [B, M]
    gs = (np.asarray(jax.device_get(_unwrap(gt_score)))
          if gt_score is not None else np.ones(gl.shape, np.float32))

    in_w = W * downsample_ratio
    in_h = H * downsample_ratio
    tobj = np.zeros((B, n_anch, H, W), np.float32)
    tscale = np.zeros((B, n_anch, H, W), np.float32)
    txy = np.zeros((B, n_anch, H, W, 2), np.float32)
    twh = np.zeros((B, n_anch, H, W, 2), np.float32)
    tcls = np.zeros((B, n_anch, H, W, class_num), np.float32)
    for b in range(B):
        for m in range(gt.shape[1]):
            gw, gh = gt[b, m, 2] * in_w, gt[b, m, 3] * in_h
            if gw <= 0 or gh <= 0:
                continue
            # best anchor across ALL anchors by wh-IoU at the origin
            best_iou, best_a = 0.0, -1
            for ai, (aw, ah) in enumerate(anchors_xy):
                inter = min(gw, aw) * min(gh, ah)
                iou = inter / (gw * gh + aw * ah - inter)
                if iou > best_iou:
                    best_iou, best_a = iou, ai
            if best_a not in an_mask:
                continue
            a = an_mask.index(best_a)
            gi = min(int(gt[b, m, 0] * W), W - 1)
            gj = min(int(gt[b, m, 1] * H), H - 1)
            aw, ah = masked_anchors[a]
            tobj[b, a, gj, gi] = gs[b, m]
            tscale[b, a, gj, gi] = 2.0 - gt[b, m, 2] * gt[b, m, 3]
            txy[b, a, gj, gi] = [gt[b, m, 0] * W - gi, gt[b, m, 1] * H - gj]
            twh[b, a, gj, gi] = [np.log(max(gw / aw, 1e-9)), np.log(max(gh / ah, 1e-9))]
            smooth = 1.0 / class_num if use_label_smooth else 0.0
            tcls[b, a, gj, gi, :] = smooth
            tcls[b, a, gj, gi, int(gl[b, m])] = 1.0 - smooth

    gtb = jnp.asarray(gt.astype(np.float32))          # [B, M, 4] normalized cx,cy,w,h
    gt_valid = jnp.asarray((gt[:, :, 2] > 0) & (gt[:, :, 3] > 0))  # [B, M]
    aw_m = jnp.asarray(np.asarray([a for a, _ in masked_anchors], np.float32))
    ah_m = jnp.asarray(np.asarray([a for _, a in masked_anchors], np.float32))

    def _f(v):
        p = v.reshape(B, n_anch, 5 + class_num, H, W)
        x_logit, y_logit = p[:, :, 0], p[:, :, 1]
        pw = p[:, :, 2]
        ph = p[:, :, 3]
        pobj = p[:, :, 4]
        pcls = p[:, :, 5:].transpose(0, 1, 3, 4, 2)
        obj = jnp.asarray(tobj)
        sc = jnp.asarray(tscale)
        bce = lambda z, t: jnp.maximum(z, 0) - z * t + jnp.log1p(jnp.exp(-jnp.abs(z)))  # noqa: E731
        # xy: sigmoid cross-entropy on the raw logits vs the [0,1] cell offset
        # (ref kernel SigmoidCrossEntropy); wh: L1 (ref kernel's abs-diff term)
        loss_xy = (sc * obj * (bce(x_logit, txy[..., 0]) + bce(y_logit, txy[..., 1]))).sum((1, 2, 3))
        loss_wh = (sc * obj * (jnp.abs(pw - twh[..., 0]) + jnp.abs(ph - twh[..., 1]))).sum((1, 2, 3))
        # objectness ignore mask (ref CalcObjnessLoss): decode every predicted
        # box (stop-gradient — target assignment is bookkeeping, not a grad
        # path), IoU it against all gt boxes; negatives whose best IoU exceeds
        # ignore_thresh are excluded from the no-object loss.
        sg = jax.lax.stop_gradient
        bx = (jnp.arange(W, dtype=jnp.float32) + jax.nn.sigmoid(sg(x_logit))) / W
        by = (jnp.arange(H, dtype=jnp.float32)[:, None] + jax.nn.sigmoid(sg(y_logit))) / H
        bw = jnp.exp(jnp.clip(sg(pw), -20, 20)) * aw_m[None, :, None, None] / in_w
        bh = jnp.exp(jnp.clip(sg(ph), -20, 20)) * ah_m[None, :, None, None] / in_h
        px1, px2 = bx - bw / 2, bx + bw / 2
        py1, py2 = by - bh / 2, by + bh / 2
        gx1 = (gtb[:, :, 0] - gtb[:, :, 2] / 2)[:, None, None, None, :]  # [B,1,1,1,M]
        gx2 = (gtb[:, :, 0] + gtb[:, :, 2] / 2)[:, None, None, None, :]
        gy1 = (gtb[:, :, 1] - gtb[:, :, 3] / 2)[:, None, None, None, :]
        gy2 = (gtb[:, :, 1] + gtb[:, :, 3] / 2)[:, None, None, None, :]
        iw = jnp.maximum(jnp.minimum(px2[..., None], gx2) - jnp.maximum(px1[..., None], gx1), 0.0)
        ih = jnp.maximum(jnp.minimum(py2[..., None], gy2) - jnp.maximum(py1[..., None], gy1), 0.0)
        inter = iw * ih
        union = (bw * bh)[..., None] + (gtb[:, :, 2] * gtb[:, :, 3])[:, None, None, None, :] - inter
        iou = jnp.where(gt_valid[:, None, None, None, :], inter / jnp.maximum(union, 1e-10), 0.0)
        best_iou = iou.max(-1) if gt.shape[1] else jnp.zeros_like(obj)
        pos = obj > 0
        keep = pos | (best_iou <= ignore_thresh)
        loss_obj = (bce(pobj, obj) * keep.astype(pobj.dtype)).sum((1, 2, 3))
        loss_cls = (obj[..., None] * bce(pcls, jnp.asarray(tcls))).sum((1, 2, 3, 4))
        return loss_xy + loss_wh + loss_obj + loss_cls

    return apply_op(_f, (x,), name="yolo_loss")


def read_file(filename, name=None):
    """Read raw bytes into a uint8 tensor (ref vision/ops.py read_file)."""
    with open(filename, "rb") as f:
        data = f.read()
    return Tensor(jnp.asarray(np.frombuffer(data, np.uint8)))


def decode_jpeg(x, mode="unchanged", name=None):
    """Decode an encoded JPEG byte tensor to CHW uint8 (ref decode_jpeg;
    host-side via PIL — image decode stays on CPU feeding the device)."""
    import io

    from PIL import Image

    raw = np.asarray(jax.device_get(_unwrap(x))).astype(np.uint8).tobytes()
    img = Image.open(io.BytesIO(raw))
    if mode not in ("unchanged",):
        img = img.convert(mode.upper() if mode != "gray" else "L")
    arr = np.asarray(img)
    if arr.ndim == 2:
        arr = arr[None]
    else:
        arr = arr.transpose(2, 0, 1)
    return Tensor(jnp.asarray(arr))


__all__ += ["RoIAlign", "RoIPool", "psroi_pool", "PSRoIPool", "DeformConv2D",
            "distribute_fpn_proposals", "yolo_loss", "read_file", "decode_jpeg"]
