"""vision datasets (ref: python/paddle/vision/datasets/mnist.py etc.).

Zero-egress environment: datasets load from local files when present
(PADDLE_TPU_DATA_HOME or ~/.cache/paddle_tpu) and otherwise fall back to a
deterministic synthetic sample generator with the real shapes/dtypes — enough for
pipelines, tests, and throughput benchmarking.
"""
from __future__ import annotations

import gzip
import os
import struct

import numpy as np

from ...io import Dataset


def _data_home():
    return os.environ.get("PADDLE_TPU_DATA_HOME", os.path.expanduser("~/.cache/paddle_tpu"))


class MNIST(Dataset):
    """Ref: vision/datasets/mnist.py.  Reads idx files if present, else synthesizes."""

    NUM_TRAIN = 60000
    NUM_TEST = 10000

    def __init__(self, image_path=None, label_path=None, mode="train", transform=None,
                 download=True, backend=None):
        self.mode = mode
        self.transform = transform
        n = self.NUM_TRAIN if mode == "train" else self.NUM_TEST
        img_file = image_path or os.path.join(_data_home(), "mnist", f"{mode}-images-idx3-ubyte.gz")
        lbl_file = label_path or os.path.join(_data_home(), "mnist", f"{mode}-labels-idx1-ubyte.gz")
        if os.path.exists(img_file) and os.path.exists(lbl_file):
            self.images = self._read_images(img_file)
            self.labels = self._read_labels(lbl_file)
        else:
            import warnings

            warnings.warn(
                f"{type(self).__name__}: '{img_file}' not found and this build "
                "cannot download — using GENERATED stand-in digits (pipeline "
                "smoke tests only; place the real idx files there for metrics)",
                stacklevel=2)
            rng = np.random.RandomState(0 if mode == "train" else 1)
            n_syn = min(n, 4096)
            self.labels = rng.randint(0, 10, n_syn).astype(np.int64)
            base = rng.rand(10, 28, 28).astype(np.float32)
            noise = rng.rand(n_syn, 28, 28).astype(np.float32) * 0.3
            self.images = ((base[self.labels] * 0.7 + noise) * 255).astype(np.uint8)

    @staticmethod
    def _read_images(path):
        op = gzip.open if path.endswith(".gz") else open
        with op(path, "rb") as f:
            magic, num, rows, cols = struct.unpack(">IIII", f.read(16))
            return np.frombuffer(f.read(), np.uint8).reshape(num, rows, cols)

    @staticmethod
    def _read_labels(path):
        op = gzip.open if path.endswith(".gz") else open
        with op(path, "rb") as f:
            magic, num = struct.unpack(">II", f.read(8))
            return np.frombuffer(f.read(), np.uint8).astype(np.int64)

    def __getitem__(self, idx):
        img = self.images[idx].astype(np.float32)[None] / 255.0  # CHW in [0,1]
        if self.transform is not None:
            img = self.transform(self.images[idx])
        return img, np.asarray(self.labels[idx], np.int64)

    def __len__(self):
        return len(self.labels)


class FashionMNIST(MNIST):
    pass


class Cifar10(Dataset):
    """Ref: vision/datasets/cifar.py — synthetic fallback with CIFAR shapes."""

    def __init__(self, data_file=None, mode="train", transform=None, download=True, backend=None):
        self.transform = transform
        if data_file is not None:
            if not os.path.exists(data_file):
                raise FileNotFoundError(
                    f"{type(self).__name__}: data_file '{data_file}' does not "
                    "exist (an explicitly given path never falls back to "
                    "generated data)")
            self._load_pickled(data_file, mode)
        else:
            import warnings

            warnings.warn(
                f"{type(self).__name__}: no data_file given and this build cannot "
                "download — using GENERATED stand-in images (pipeline smoke tests "
                "only; pass data_file=<cifar npz with images/labels> for metrics)",
                stacklevel=2)
            n = 2048
            rng = np.random.RandomState(0 if mode == "train" else 1)
            self.labels = rng.randint(0, 10, n).astype(np.int64)
            self.images = (rng.rand(n, 3, 32, 32) * 255).astype(np.uint8)

    def _load_pickled(self, data_file, mode):
        data = np.load(data_file)
        if f"{mode}_images" in data:         # mode-split archive
            self.images = data[f"{mode}_images"].astype(np.uint8)
            self.labels = data[f"{mode}_labels"].astype(np.int64)
        else:                                # combined archive: 80/20 split
            images = data["images"].astype(np.uint8)
            labels = data["labels"].astype(np.int64)
            split = int(len(labels) * 0.8)
            sl = slice(0, split) if mode == "train" else slice(split, None)
            self.images, self.labels = images[sl], labels[sl]

    def __getitem__(self, idx):
        img = self.images[idx].astype(np.float32) / 255.0
        if self.transform is not None:
            img = self.transform(self.images[idx].transpose(1, 2, 0))
        return img, np.asarray(self.labels[idx], np.int64)

    def __len__(self):
        return len(self.labels)


class Cifar100(Cifar10):
    def __init__(self, data_file=None, mode="train", transform=None, download=True, backend=None):
        super().__init__(data_file, mode, transform, download, backend)
        rng = np.random.RandomState(2)
        self.labels = rng.randint(0, 100, len(self.labels)).astype(np.int64)


class ImageFolder(Dataset):
    """Ref: vision/datasets/folder.py — reads image files under root by class dir."""

    def __init__(self, root, loader=None, extensions=None, transform=None, is_valid_file=None):
        self.root = root
        self.transform = transform
        self.samples = []
        if os.path.isdir(root):
            for dirpath, _, files in os.walk(root):
                for fn in sorted(files):
                    if fn.lower().endswith((".png", ".jpg", ".jpeg", ".bmp", ".npy")):
                        self.samples.append(os.path.join(dirpath, fn))

    def __getitem__(self, idx):
        path = self.samples[idx]
        if path.endswith(".npy"):
            img = np.load(path)
        else:
            img = _read_image(path)
        if self.transform:
            img = self.transform(img)
        return (img,)

    def __len__(self):
        return len(self.samples)


class DatasetFolder(Dataset):
    def __init__(self, root, loader=None, extensions=None, transform=None, is_valid_file=None):
        self.root = root
        self.transform = transform
        self.classes = sorted(
            d for d in os.listdir(root) if os.path.isdir(os.path.join(root, d))
        ) if os.path.isdir(root) else []
        self.class_to_idx = {c: i for i, c in enumerate(self.classes)}
        self.samples = []
        for c in self.classes:
            cdir = os.path.join(root, c)
            for fn in sorted(os.listdir(cdir)):
                if fn.lower().endswith((".png", ".jpg", ".jpeg", ".bmp", ".npy")):
                    self.samples.append((os.path.join(cdir, fn), self.class_to_idx[c]))

    def __getitem__(self, idx):
        path, target = self.samples[idx]
        img = np.load(path) if path.endswith(".npy") else _read_image(path)
        if self.transform:
            img = self.transform(img)
        return img, target

    def __len__(self):
        return len(self.samples)


def _read_image(path):
    try:
        from PIL import Image

        return np.asarray(Image.open(path).convert("RGB"))
    except ImportError as e:
        raise RuntimeError("PIL unavailable; provide .npy images") from e


class FakeImageNet(Dataset):
    """Synthetic ImageNet-shaped dataset for throughput benchmarking (224x224x3)."""

    def __init__(self, n=8192, num_classes=1000, image_size=224, transform=None, seed=0):
        rng = np.random.RandomState(seed)
        self.n = n
        self.num_classes = num_classes
        self.image_size = image_size
        self._rng_state = seed

    def __getitem__(self, idx):
        rng = np.random.RandomState(idx)
        img = rng.rand(3, self.image_size, self.image_size).astype(np.float32)
        label = np.asarray(idx % self.num_classes, np.int64)
        return img, label

    def __len__(self):
        return self.n


class Flowers(Dataset):
    """Oxford-102 flowers (ref vision/datasets/flowers.py).  Reads an extracted
    layout `<root>/jpg/*.jpg` + `imagelabels.npy` if present; else warns and
    synthesizes (this build cannot download)."""

    def __init__(self, data_file=None, label_file=None, setid_file=None,
                 mode="train", transform=None, download=True, backend=None):
        self.transform = transform
        self.backend = backend
        root = data_file or os.path.join(_data_home(), "flowers")
        labels_np = os.path.join(root, "imagelabels.npy")
        jpg_dir = os.path.join(root, "jpg")
        if os.path.isdir(jpg_dir) and os.path.exists(labels_np):
            names = sorted(n for n in os.listdir(jpg_dir) if n.endswith(".jpg"))
            labels = np.load(labels_np).astype(np.int64)
            split = int(len(names) * 0.8)
            sel = slice(0, split) if mode == "train" else slice(split, None)
            self.files = [os.path.join(jpg_dir, n) for n in names[sel]]
            self.labels = labels[sel]
            self.images = None
        else:
            import warnings

            warnings.warn(
                f"Flowers: '{jpg_dir}' not found and this build cannot download "
                "— using GENERATED stand-in images (pipeline smoke tests only)",
                stacklevel=2)
            rng = np.random.RandomState(0 if mode == "train" else 1)
            n = 512 if mode == "train" else 128
            self.labels = rng.randint(0, 102, n).astype(np.int64)
            self.images = (rng.rand(n, 64, 64, 3) * 255).astype(np.uint8)
            self.files = None

    def __getitem__(self, idx):
        if self.images is not None:
            img = self.images[idx]
        else:
            from PIL import Image

            img = np.asarray(Image.open(self.files[idx]).convert("RGB"))
        if self.transform is not None:
            img = self.transform(img)
        else:
            img = img.astype(np.float32).transpose(2, 0, 1) / 255.0
        return img, np.asarray(self.labels[idx], np.int64)

    def __len__(self):
        return len(self.labels)


class VOC2012(Dataset):
    """Pascal VOC 2012 segmentation pairs (ref vision/datasets/voc2012.py:
    yields (image CHW float, label mask HW int64)).  Reads the extracted
    VOCdevkit layout if present; else warns and synthesizes."""

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend=None):
        self.transform = transform
        root = data_file or os.path.join(_data_home(), "voc2012", "VOCdevkit", "VOC2012")
        img_dir = os.path.join(root, "JPEGImages")
        seg_dir = os.path.join(root, "SegmentationClass")
        lst = os.path.join(root, "ImageSets", "Segmentation",
                           ("train.txt" if mode == "train" else "val.txt"))
        if os.path.isdir(img_dir) and os.path.isdir(seg_dir) and os.path.exists(lst):
            with open(lst) as f:
                ids = [ln.strip() for ln in f if ln.strip()]
            self.pairs = [(os.path.join(img_dir, i + ".jpg"),
                           os.path.join(seg_dir, i + ".png")) for i in ids]
            self.images = self.masks = None
        else:
            import warnings

            warnings.warn(
                f"VOC2012: '{root}' not found and this build cannot download "
                "— using GENERATED stand-in segmentation pairs (pipeline "
                "smoke tests only)", stacklevel=2)
            rng = np.random.RandomState(0 if mode == "train" else 1)
            n = 128 if mode == "train" else 32
            self.images = (rng.rand(n, 64, 64, 3) * 255).astype(np.uint8)
            self.masks = rng.randint(0, 21, (n, 64, 64)).astype(np.int64)
            self.pairs = None

    def __getitem__(self, idx):
        if self.images is not None:
            img, mask = self.images[idx], self.masks[idx]
        else:
            from PIL import Image

            ip, mp = self.pairs[idx]
            img = np.asarray(Image.open(ip).convert("RGB"))
            mask = np.asarray(Image.open(mp)).astype(np.int64)
        if self.transform is not None:
            img = self.transform(img)
        else:
            img = img.astype(np.float32).transpose(2, 0, 1) / 255.0
        return img, mask

    def __len__(self):
        return len(self.images) if self.images is not None else len(self.pairs)
