"""ResNet family (ref: python/paddle/vision/models/resnet.py — BASELINE config #2).

Same BasicBlock/BottleneckBlock structure and layer counts as the reference.
`data_format="NHWC"` (net-new vs the reference's NCHW-only model zoo) selects
the TPU channels-minor layout; in NHWC training mode on TPU, bottleneck blocks
run the fused Pallas conv+BN fast path (`_fused_resnet.py` /
`ops/fused_conv_bn.py`): bn2's normalize+ReLU folds into conv3's input read,
BN batch stats accumulate in kernel epilogues, and the backward combines
dX/dW/stats into single kernels.  Numerics match the composed path to bf16
rounding (tests/test_fused_conv_bn.py).  bf16 via `model.bfloat16()` or
amp.auto_cast.
"""
from __future__ import annotations

import functools

from ... import nn


def _fused_path_ok(model, x):
    """NHWC + training + bottleneck blocks + (TPU or forced) + aligned input
    + every block's 1x1 convs admissible to the fused kernel.  Nonstandard
    widths (e.g. base_width not a multiple of 64) degrade gracefully to the
    composed forward instead of raising mid-forward."""
    from . import _fused_resnet as FR

    if model._data_format != "NHWC" or not model.training:
        return False
    if not FR.FORCE:
        from ...core.device import is_tpu_backend

        if not is_tpu_backend():
            return False
    if str(x.dtype) not in ("paddle.bfloat16", "paddle.float32", "bfloat16", "float32"):
        return False
    shape = x.shape
    if not (len(shape) == 4 and shape[3] == 3
            and shape[1] % 32 == 0 and shape[2] % 32 == 0):
        return False
    return _fused_blocks_supported(model)


def _fused_blocks_supported(model):
    """Per-block channel alignment for the fused path: conv1/conv3 of every
    bottleneck must pass ops.fused_conv_bn.supported (lane-aligned Cin/Cout).
    Cached on the model — channel widths are fixed at construction."""
    ok = model.__dict__.get("_fused_blocks_ok")
    if ok is None:
        from ...ops.fused_conv_bn import supported

        ok = True
        for stage in (model.layer1, model.layer2, model.layer3, model.layer4):
            for block in stage:
                for conv in (block.conv1, block.conv3):
                    cout, cin = int(conv.weight.shape[0]), int(conv.weight.shape[1])
                    if not supported((1, 1, 8, cin), (1, 1, cin, cout)):
                        ok = False
        model.__dict__["_fused_blocks_ok"] = ok
    return ok


class BasicBlock(nn.Layer):
    expansion = 1

    def __init__(self, inplanes, planes, stride=1, downsample=None, groups=1,
                 base_width=64, dilation=1, norm_layer=None, data_format="NCHW"):
        super().__init__()
        norm_layer = norm_layer or functools.partial(nn.BatchNorm2D, data_format=data_format)
        self.conv1 = nn.Conv2D(inplanes, planes, 3, padding=1, stride=stride,
                               bias_attr=False, data_format=data_format)
        self.bn1 = norm_layer(planes)
        self.relu = nn.ReLU()
        self.conv2 = nn.Conv2D(planes, planes, 3, padding=1, bias_attr=False,
                               data_format=data_format)
        self.bn2 = norm_layer(planes)
        self.downsample = downsample
        self.stride = stride

    def forward(self, x):
        identity = x
        out = self.relu(self.bn1(self.conv1(x)))
        out = self.bn2(self.conv2(out))
        if self.downsample is not None:
            identity = self.downsample(x)
        return self.relu(out + identity)


class BottleneckBlock(nn.Layer):
    expansion = 4

    def __init__(self, inplanes, planes, stride=1, downsample=None, groups=1,
                 base_width=64, dilation=1, norm_layer=None, data_format="NCHW"):
        super().__init__()
        norm_layer = norm_layer or functools.partial(nn.BatchNorm2D, data_format=data_format)
        width = int(planes * (base_width / 64.0)) * groups
        self.conv1 = nn.Conv2D(inplanes, width, 1, bias_attr=False, data_format=data_format)
        self.bn1 = norm_layer(width)
        self.conv2 = nn.Conv2D(width, width, 3, padding=dilation, stride=stride,
                               groups=groups, dilation=dilation, bias_attr=False,
                               data_format=data_format)
        self.bn2 = norm_layer(width)
        self.conv3 = nn.Conv2D(width, planes * self.expansion, 1, bias_attr=False,
                               data_format=data_format)
        self.bn3 = norm_layer(planes * self.expansion)
        self.relu = nn.ReLU()
        self.downsample = downsample
        self._groups = groups
        self._stride = stride

    def forward(self, x):
        identity = x
        out = self.relu(self.bn1(self.conv1(x)))
        out = self.relu(self.bn2(self.conv2(out)))
        out = self.bn3(self.conv3(out))
        if self.downsample is not None:
            identity = self.downsample(x)
        return self.relu(out + identity)

    def forward_fused(self, x, wv_in, wv_out, wp_out):
        """NHWC fused fast path (see module docstring).  x: [N, H, W'_in, C]
        with zero pad columns; returns the block output at [N, Ho, W'_out, C']."""
        from functools import partial

        from ...tensor.tensor import apply_op
        from . import _fused_resnet as FR

        eps = float(self.bn1._epsilon)
        N, H = x.shape[0], x.shape[1]
        Ho = H // self._stride
        cnt_out = N * Ho * wv_out
        if self.downsample is not None:
            convd, bnd = self.downsample[0], self.downsample[1]
            identity, md, vd = apply_op(
                partial(FR.downsample_step, stride=self._stride, wv_out=wv_out,
                        wp_out=wp_out, eps=float(bnd._epsilon)),
                (x, convd.weight, bnd.weight, bnd.bias), name="resnet_downsample_fused")
            FR.update_running_stats(bnd, md, vd, cnt_out)
        else:
            identity = x
        z, m1, v1, m2, v2, m3, v3 = apply_op(
            partial(FR.bottleneck_step, stride=self._stride, groups=self._groups,
                    wv_in=wv_in, wv_out=wv_out, wp_out=wp_out, eps=eps),
            (x, identity, self.conv1.weight, self.bn1.weight, self.bn1.bias,
             self.conv2.weight, self.bn2.weight, self.bn2.bias,
             self.conv3.weight, self.bn3.weight, self.bn3.bias),
            name="resnet_bottleneck_fused")
        FR.update_running_stats(self.bn1, m1, v1, N * H * wv_in)
        FR.update_running_stats(self.bn2, m2, v2, cnt_out)
        FR.update_running_stats(self.bn3, m3, v3, cnt_out)
        return z


class ResNet(nn.Layer):
    """Ref resnet.py ResNet(Block, depth)."""

    def __init__(self, block, depth=50, width=64, num_classes=1000, with_pool=True,
                 groups=1, data_format="NCHW"):
        super().__init__()
        layer_cfg = {18: [2, 2, 2, 2], 34: [3, 4, 6, 3], 50: [3, 4, 6, 3],
                     101: [3, 4, 23, 3], 152: [3, 8, 36, 3]}
        layers = layer_cfg[depth]
        self.groups = groups
        self.base_width = width
        self.num_classes = num_classes
        self.with_pool = with_pool
        self._data_format = data_format
        self._norm_layer = functools.partial(nn.BatchNorm2D, data_format=data_format)
        self._block_cls = block
        self.inplanes = 64
        self.dilation = 1

        self.conv1 = nn.Conv2D(3, self.inplanes, 7, stride=2, padding=3,
                               bias_attr=False, data_format=data_format)
        self.bn1 = self._norm_layer(self.inplanes)
        self.relu = nn.ReLU()
        self.maxpool = nn.MaxPool2D(3, stride=2, padding=1, data_format=data_format)
        self.layer1 = self._make_layer(block, 64, layers[0])
        self.layer2 = self._make_layer(block, 128, layers[1], stride=2)
        self.layer3 = self._make_layer(block, 256, layers[2], stride=2)
        self.layer4 = self._make_layer(block, 512, layers[3], stride=2)
        if with_pool:
            self.avgpool = nn.AdaptiveAvgPool2D((1, 1), data_format=data_format)
        if num_classes > 0:
            self.fc = nn.Linear(512 * block.expansion, num_classes)

    def _make_layer(self, block, planes, blocks, stride=1):
        norm_layer = self._norm_layer
        downsample = None
        if stride != 1 or self.inplanes != planes * block.expansion:
            downsample = nn.Sequential(
                nn.Conv2D(self.inplanes, planes * block.expansion, 1, stride=stride,
                          bias_attr=False, data_format=self._data_format),
                norm_layer(planes * block.expansion),
            )
        layers = [block(self.inplanes, planes, stride, downsample, self.groups,
                        self.base_width, 1, norm_layer, data_format=self._data_format)]
        self.inplanes = planes * block.expansion
        for _ in range(1, blocks):
            layers.append(block(self.inplanes, planes, groups=self.groups,
                                base_width=self.base_width, norm_layer=norm_layer,
                                data_format=self._data_format))
        return nn.Sequential(*layers)

    def _forward_fused(self, x):
        """NHWC TPU fast path: stem + fused bottleneck stages + masked head."""
        from functools import partial

        from ...tensor.tensor import apply_op
        from . import _fused_resnet as FR

        x = self.relu(self.bn1(self.conv1(x)))
        x = self.maxpool(x)
        wv = x.shape[2]  # 56 for a 224 input; gate guarantees w0 % 8 == 0
        for stage in (self.layer1, self.layer2, self.layer3, self.layer4):
            for block in stage:
                stride = block._stride
                wv_out = wv // stride
                wp_out = wv_out if wv_out % 8 == 0 else wv_out + (8 - wv_out % 8)
                x = block.forward_fused(x, wv, wv_out, wp_out)
                wv = wv_out
        if self.with_pool:
            x = apply_op(partial(FR.masked_gap, wv=wv), (x,), name="resnet_masked_gap")
        if self.num_classes > 0:
            from ...tensor.manipulation import flatten

            x = flatten(x, 1)
            x = self.fc(x)
        return x

    def forward(self, x):
        if self._block_cls is BottleneckBlock and _fused_path_ok(self, x):
            return self._forward_fused(x)
        x = self.relu(self.bn1(self.conv1(x)))
        x = self.maxpool(x)
        x = self.layer1(x)
        x = self.layer2(x)
        x = self.layer3(x)
        x = self.layer4(x)
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            from ...tensor.manipulation import flatten

            x = flatten(x, 1)
            x = self.fc(x)
        return x


def _resnet(block, depth, **kwargs):
    return ResNet(block, depth, **kwargs)


def resnet18(pretrained=False, **kwargs):
    return _resnet(BasicBlock, 18, **kwargs)


def resnet34(pretrained=False, **kwargs):
    return _resnet(BasicBlock, 34, **kwargs)


def resnet50(pretrained=False, **kwargs):
    return _resnet(BottleneckBlock, 50, **kwargs)


def resnet101(pretrained=False, **kwargs):
    return _resnet(BottleneckBlock, 101, **kwargs)


def resnet152(pretrained=False, **kwargs):
    return _resnet(BottleneckBlock, 152, **kwargs)


def wide_resnet50_2(pretrained=False, **kwargs):
    return _resnet(BottleneckBlock, 50, width=128, **kwargs)


def wide_resnet101_2(pretrained=False, **kwargs):
    return _resnet(BottleneckBlock, 101, width=128, **kwargs)


def resnext50_32x4d(pretrained=False, **kwargs):
    return _resnet(BottleneckBlock, 50, groups=32, width=4, **kwargs)


def resnext50_64x4d(pretrained=False, **kwargs):
    return _resnet(BottleneckBlock, 50, groups=64, width=4, **kwargs)


def resnext101_32x4d(pretrained=False, **kwargs):
    return _resnet(BottleneckBlock, 101, groups=32, width=4, **kwargs)


def resnext101_64x4d(pretrained=False, **kwargs):
    return _resnet(BottleneckBlock, 101, groups=64, width=4, **kwargs)


def resnext152_32x4d(pretrained=False, **kwargs):
    return _resnet(BottleneckBlock, 152, groups=32, width=4, **kwargs)


def resnext152_64x4d(pretrained=False, **kwargs):
    return _resnet(BottleneckBlock, 152, groups=64, width=4, **kwargs)
