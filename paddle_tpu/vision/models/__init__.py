"""vision model zoo (ref: python/paddle/vision/models/__init__.py)."""
from .lenet import LeNet  # noqa: F401

# resnet / vgg / mobilenet / vit land as they are built; import lazily to keep import light
def __getattr__(name):
    if name in ("ResNet", "resnet18", "resnet34", "resnet50", "resnet101", "resnet152",
                "wide_resnet50_2", "wide_resnet101_2"):
        from . import resnet

        return getattr(resnet, name)
    if name in ("VGG", "vgg11", "vgg13", "vgg16", "vgg19"):
        from . import vgg

        return getattr(vgg, name)
    if name in ("MobileNetV2", "mobilenet_v2", "MobileNetV3Small", "MobileNetV3Large"):
        from . import mobilenet

        return getattr(mobilenet, name)
    if name in ("VisionTransformer", "vit_b_16", "vit_b_32", "vit_l_16"):
        from . import vit

        return getattr(vit, name)
    raise AttributeError(name)
