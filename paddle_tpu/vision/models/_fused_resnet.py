"""TPU fast path for ResNet bottlenecks: fused 1x1-conv+BN Pallas kernels.

Glue between the model and `paddle_tpu.ops.fused_conv_bn` (see that module's
docstring for the memory-pass accounting).  Everything here is pure-JAX and
runs inside one `apply_op` per block so the tape records a single node; the
BatchNorm batch stats come back as extra outputs so the Layer can update its
running buffers with the exact `F.batch_norm` momentum semantics.

Layout contract: NHWC activations with the W axis padded to a multiple of 8
("W'") from stage 2 on (wv = valid columns); pad columns hold zeros.  The
per-stage (wv, W') ladder for a 224 input is 56/56, 28/32, 14/16, 7/8.

Reference parity anchor: python/paddle/vision/models/resnet.py
BottleneckBlock.forward — identical math (conv1x1 -> BN -> relu -> conv3x3 ->
BN -> relu -> conv1x1 -> BN -> +identity -> relu), restructured so the
normalize of bn2 folds into conv3's input read and never materializes.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...ops.fused_conv_bn import conv1x1_bn

# Tests set this to exercise the fused path off-TPU (Pallas interpret mode).
FORCE = False


def masked_gap(x, *, wv):
    """Global average pool over the VALID spatial region of a W-padded NHWC
    activation -> [N, 1, 1, C] (AdaptiveAvgPool2D((1,1)) parity)."""
    s = jnp.sum(x.astype(jnp.float32), axis=(1, 2), keepdims=True)
    return (s / (x.shape[1] * wv)).astype(x.dtype)


def update_running_stats(bn, mean_t, var_t, cnt):
    """Write batch stats back to a BatchNorm layer's buffers with the exact
    `F.batch_norm` momentum semantics (momentum * rm + (1-m) * stat, var
    debiased by n/(n-1)).  The n/(n-1) debias matches THIS repo's
    cuDNN-style `F.batch_norm` running-var update; the reference CPU
    batch_norm_kernel.cc stores the biased batch variance instead."""
    from ...tensor.tensor import Tensor, apply_op

    if not isinstance(bn._mean, Tensor):
        return
    momentum = bn._momentum
    factor = cnt / max(cnt - 1, 1)
    new_mean = apply_op(
        lambda rm, m: momentum * rm + (1 - momentum) * m,
        (bn._mean, mean_t.detach()), name="bn_moving_mean")
    new_var = apply_op(
        lambda rv, v: momentum * rv + (1 - momentum) * (v * factor),
        (bn._variance, var_t.detach()), name="bn_moving_var")
    bn._mean.set_value(new_mean)
    bn._variance.set_value(new_var)


def _w1x1(w):
    """[Cout, Cin, 1, 1] (paddle layout) -> [1, 1, Cin, Cout] (kernel layout)."""
    return jnp.transpose(w, (2, 3, 1, 0))


def _whwio(w):
    """[Cout, Cin/g, kh, kw] -> [kh, kw, Cin/g, Cout]."""
    return jnp.transpose(w, (2, 3, 1, 0))


def _affine(s1, s2, cnt, gamma, beta, eps):
    """Batch stats -> (mean, biased var, f32 scale/offset row vectors)."""
    m = s1 / cnt
    v = jnp.maximum(s2 / cnt - m * m, 0.0)
    sc = gamma.astype(jnp.float32) * jax.lax.rsqrt(v + eps)
    of = beta.astype(jnp.float32) - m * sc
    return m, v, sc.reshape(1, -1), of.reshape(1, -1)


def _colmask(Wp, wv, ndim_last):
    col = jnp.arange(Wp) < wv
    return col.reshape(1, 1, Wp, 1) if ndim_last else col.reshape(1, 1, Wp)


def _sums(y):
    yf = y.astype(jnp.float32)
    return jnp.sum(yf, (0, 1, 2)), jnp.sum(yf * yf, (0, 1, 2))


def downsample_step(x, wd, gd, bd, *, stride, wv_out, wp_out, eps):
    """conv1x1(stride) + BN (no relu) for the projection shortcut.

    x may be W-padded: a strided 1x1 conv maps zero pad columns to zero pad
    columns, so only a possible re-pad (stage-2 entry, 28 -> 32) is needed.
    """
    y = jax.lax.conv_general_dilated(
        x, _w1x1(wd), (stride, stride), [(0, 0), (0, 0)],
        dimension_numbers=("NHWC", "HWIO", "NHWC"))
    if y.shape[2] < wp_out:
        y = jnp.pad(y, ((0, 0), (0, 0), (0, wp_out - y.shape[2]), (0, 0)))
    s1, s2 = _sums(y)
    cnt = y.shape[0] * y.shape[1] * wv_out
    m, v, sc, of = _affine(s1, s2, cnt, gd, bd, eps)
    idn = y.astype(jnp.float32) * sc.reshape(-1) + of.reshape(-1)
    if wv_out != wp_out:
        idn = jnp.where(_colmask(wp_out, wv_out, True), idn, 0.0)
    return idn.astype(x.dtype), m, v


def bottleneck_step(x, identity, w1, g1, b1, w2, g2, b2, w3, g3, b3,
                    *, stride, groups, wv_in, wv_out, wp_out, eps):
    """One fused bottleneck block.  Returns (z, m1, v1, m2, v2, m3, v3)."""
    N, H, wp_in, _ = x.shape
    dt = x.dtype

    # conv1 (1x1, stride 1, input already normalized) + bn1 stats epilogue
    y1, s11, s12 = conv1x1_bn(x, _w1x1(w1), wv=wv_in)
    m1, v1, sc1, of1 = _affine(s11, s12, N * H * wv_in, g1, b1, eps)

    # bn1 normalize + relu materializes z1 (conv2 is an XLA 3x3: producers
    # cannot fold into its input read)
    z1 = jnp.maximum(y1.astype(jnp.float32) * sc1.reshape(-1) + of1.reshape(-1), 0.0)
    if wv_in != wp_in:
        z1 = jnp.where(_colmask(wp_in, wv_in, True), z1, 0.0)
    z1 = z1.astype(dt)

    # conv2: 3x3 XLA, explicit (1,1) padding — on a padded-W input the zero
    # columns reproduce SAME-pad semantics for the valid region
    y2 = jax.lax.conv_general_dilated(
        z1, _whwio(w2), (stride, stride), [(1, 1), (1, 1)],
        dimension_numbers=("NHWC", "HWIO", "NHWC"), feature_group_count=groups)
    Ho = y2.shape[1]
    if y2.shape[2] < wp_out:
        y2 = jnp.pad(y2, ((0, 0), (0, 0), (0, wp_out - y2.shape[2]), (0, 0)))
    if wv_out != wp_out:
        # garbage appears at pad columns (last valid column's window reaches
        # into real data); re-zero them before stats / conv3
        y2 = jnp.where(_colmask(wp_out, wv_out, True), y2, jnp.zeros((), dt))
    s21, s22 = _sums(y2)
    m2, v2, sc2, of2 = _affine(s21, s22, N * Ho * wv_out, g2, b2, eps)

    # conv3 (1x1) with bn2's normalize+relu FOLDED into the input read
    y3, s31, s32 = conv1x1_bn(y2, _w1x1(w3), sc2, of2, wv=wv_out)
    m3, v3, sc3, of3 = _affine(s31, s32, N * Ho * wv_out, g3, b3, eps)

    z = (y3.astype(jnp.float32) * sc3.reshape(-1) + of3.reshape(-1)
         + identity.astype(jnp.float32))
    z = jnp.maximum(z, 0.0)
    if wv_out != wp_out:
        z = jnp.where(_colmask(wp_out, wv_out, True), z, 0.0)
    return z.astype(dt), m1, v1, m2, v2, m3, v3
