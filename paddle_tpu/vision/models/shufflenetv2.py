"""ShuffleNetV2 (ref: python/paddle/vision/models/shufflenetv2.py:191)."""
from __future__ import annotations

import paddle_tpu as paddle
from ... import nn


def channel_shuffle(x, groups):
    """Interleave channel groups (ref shufflenetv2.py:72) — delegates to the
    functional op so there is one implementation."""
    return paddle.nn.functional.channel_shuffle(x, groups)


def _act(act):
    return nn.Swish() if act == "swish" else nn.ReLU()


def _conv_bn(in_c, out_c, kernel, stride=1, groups=1, act="relu"):
    pad = (kernel - 1) // 2
    layers = [nn.Conv2D(in_c, out_c, kernel, stride=stride, padding=pad,
                        groups=groups, bias_attr=False),
              nn.BatchNorm2D(out_c)]
    if act:
        layers.append(_act(act))
    return nn.Sequential(*layers)


class InvertedResidual(nn.Layer):
    """Stride-1 unit: split channels, transform one half, concat+shuffle
    (ref shufflenetv2.py:88)."""

    def __init__(self, channels, act="relu"):
        super().__init__()
        c = channels // 2
        self.branch = nn.Sequential(
            _conv_bn(c, c, 1, act=act),
            _conv_bn(c, c, 3, groups=c, act=None),     # depthwise
            _conv_bn(c, c, 1, act=act))

    def forward(self, x):
        c = x.shape[1] // 2
        x1, x2 = x[:, :c], x[:, c:]
        out = paddle.concat([x1, self.branch(x2)], axis=1)
        return channel_shuffle(out, 2)


class InvertedResidualDS(nn.Layer):
    """Downsampling unit: both branches strided, channels double
    (ref shufflenetv2.py:131)."""

    def __init__(self, in_c, out_c, act="relu"):
        super().__init__()
        c = out_c // 2
        self.branch1 = nn.Sequential(
            _conv_bn(in_c, in_c, 3, stride=2, groups=in_c, act=None),
            _conv_bn(in_c, c, 1, act=act))
        self.branch2 = nn.Sequential(
            _conv_bn(in_c, c, 1, act=act),
            _conv_bn(c, c, 3, stride=2, groups=c, act=None),
            _conv_bn(c, c, 1, act=act))

    def forward(self, x):
        out = paddle.concat([self.branch1(x), self.branch2(x)], axis=1)
        return channel_shuffle(out, 2)


class ShuffleNetV2(nn.Layer):
    def __init__(self, scale=1.0, act="relu", num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        stage_repeats = [4, 8, 4]
        out_channels = {
            0.25: [24, 24, 48, 96, 512], 0.33: [24, 32, 64, 128, 512],
            0.5: [24, 48, 96, 192, 1024], 1.0: [24, 116, 232, 464, 1024],
            1.5: [24, 176, 352, 704, 1024], 2.0: [24, 244, 488, 976, 2048],
        }.get(scale)
        if out_channels is None:
            raise ValueError(f"unsupported ShuffleNetV2 scale {scale}")
        self.conv1 = _conv_bn(3, out_channels[0], 3, stride=2, act=act)
        self.pool1 = nn.MaxPool2D(3, stride=2, padding=1)
        stages = []
        in_c = out_channels[0]
        for stage_i, repeats in enumerate(stage_repeats):
            out_c = out_channels[stage_i + 1]
            units = [InvertedResidualDS(in_c, out_c, act)]
            units += [InvertedResidual(out_c, act) for _ in range(repeats - 1)]
            stages.append(nn.Sequential(*units))
            in_c = out_c
        self.stages = nn.Sequential(*stages)
        self.conv_last = _conv_bn(in_c, out_channels[-1], 1, act=act)
        if with_pool:
            self.avgpool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.fc = nn.Linear(out_channels[-1], num_classes)

    def forward(self, x):
        x = self.conv_last(self.stages(self.pool1(self.conv1(x))))
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            x = x.flatten(1)
            x = self.fc(x)
        return x


def _shufflenet(scale, act, pretrained, **kwargs):
    if pretrained:
        raise NotImplementedError("pretrained weights are not bundled")
    return ShuffleNetV2(scale=scale, act=act, **kwargs)


def shufflenet_v2_x0_25(pretrained=False, **kwargs):
    return _shufflenet(0.25, "relu", pretrained, **kwargs)


def shufflenet_v2_x0_33(pretrained=False, **kwargs):
    return _shufflenet(0.33, "relu", pretrained, **kwargs)


def shufflenet_v2_x0_5(pretrained=False, **kwargs):
    return _shufflenet(0.5, "relu", pretrained, **kwargs)


def shufflenet_v2_x1_0(pretrained=False, **kwargs):
    return _shufflenet(1.0, "relu", pretrained, **kwargs)


def shufflenet_v2_x1_5(pretrained=False, **kwargs):
    return _shufflenet(1.5, "relu", pretrained, **kwargs)


def shufflenet_v2_x2_0(pretrained=False, **kwargs):
    return _shufflenet(2.0, "relu", pretrained, **kwargs)


def shufflenet_v2_swish(pretrained=False, **kwargs):
    return _shufflenet(1.0, "swish", pretrained, **kwargs)
