"""paddle.vision parity surface."""
from . import transforms  # noqa: F401
from . import datasets  # noqa: F401
from . import models  # noqa: F401
from .models import LeNet  # noqa: F401


_image_backend = "pil"


def set_image_backend(backend):
    global _image_backend
    if backend not in ("pil", "cv2", "tensor", "numpy"):
        raise ValueError(f"unknown image backend {backend!r}")
    _image_backend = backend


def get_image_backend():
    return _image_backend


from . import ops  # noqa: F401


def image_load(path, backend=None):
    """Ref vision/image.py image_load — reads an image file to an array
    (PIL when available, else raw numpy formats)."""
    import os

    import numpy as np

    ext = os.path.splitext(path)[1].lower()
    if ext in (".npy",):
        return np.load(path)
    if ext in (".npz",):
        data = np.load(path)
        return data[list(data.keys())[0]]
    try:
        from PIL import Image

        return Image.open(path)
    except ImportError as e:
        raise RuntimeError(
            f"image_load: reading {ext} files needs Pillow, which is not "
            "bundled — save arrays as .npy/.npz or install pillow") from e


