"""paddle.vision parity surface."""
from . import transforms  # noqa: F401
from . import datasets  # noqa: F401
from . import models  # noqa: F401
from .models import LeNet  # noqa: F401


_image_backend = "pil"


def set_image_backend(backend):
    global _image_backend
    if backend not in ("pil", "cv2", "tensor", "numpy"):
        raise ValueError(f"unknown image backend {backend!r}")
    _image_backend = backend


def get_image_backend():
    return _image_backend


from . import ops  # noqa: F401


def image_load(path, backend=None):
    """Ref vision/image.py image_load.  backend: 'pil' -> PIL.Image,
    'numpy'/'cv2' -> HWC ndarray (cv2 flips RGB->BGR), 'tensor' -> Tensor."""
    import os

    import numpy as np

    backend = backend or get_image_backend()
    ext = os.path.splitext(path)[1].lower()
    if ext in (".npy",):
        arr = np.load(path)
    elif ext in (".npz",):
        data = np.load(path)
        arr = data[list(data.keys())[0]]
    else:
        try:
            from PIL import Image
        except ImportError as e:
            raise RuntimeError(
                f"image_load: reading {ext} files needs Pillow, which is not "
                "bundled — save arrays as .npy/.npz or install pillow") from e
        img = Image.open(path)
        if backend == "pil":
            return img
        arr = np.asarray(img)
    if backend == "pil":
        return arr          # array files have no PIL form; return the array
    if backend == "cv2":
        return arr[..., ::-1] if arr.ndim == 3 and arr.shape[-1] == 3 else arr
    if backend == "tensor":
        from ..tensor.tensor import Tensor

        return Tensor(arr)
    return arr


