"""paddle.onnx — ONNX export surface.

Ref: python/paddle/onnx/export.py (thin shim over the external paddle2onnx
package).  This build has no paddle2onnx and no network egress; the portable
AOT artifact on TPU is StableHLO via `paddle.jit.save` (loadable by
`paddle.jit.load` and `paddle.inference`).  `export()` raises with that
guidance instead of writing a file that silently is not ONNX.
"""
from __future__ import annotations

__all__ = ["export"]


def export(layer, path, input_spec=None, opset_version=9, **configs):
    raise NotImplementedError(
        "ONNX export requires paddle2onnx, which is not available in this "
        "build. For a deployable AOT artifact on TPU use paddle.jit.save"
        f"(layer, {path!r}, input_spec=...) — it serializes StableHLO that "
        "paddle.jit.load / paddle.inference.create_predictor can run.")
