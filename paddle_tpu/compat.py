"""paddle.compat — py2/3 string + math compatibility helpers
(ref: python/paddle/compat.py:25,121,206,232,249)."""
from __future__ import annotations

import math

__all__ = []


def to_text(obj, encoding="utf-8", inplace=False):
    """Decode bytes (recursively through list/set/dict) to str."""
    if obj is None:
        return obj
    if isinstance(obj, list):
        if inplace:
            obj[:] = [to_text(x, encoding) for x in obj]
            return obj
        return [to_text(x, encoding) for x in obj]
    if isinstance(obj, set):
        if inplace:
            new = {to_text(x, encoding) for x in obj}
            obj.clear()
            obj.update(new)
            return obj
        return {to_text(x, encoding) for x in obj}
    if isinstance(obj, dict):
        if inplace:
            for k in list(obj):
                obj[to_text(k, encoding)] = to_text(obj.pop(k), encoding)
            return obj
        return {to_text(k, encoding): to_text(v, encoding) for k, v in obj.items()}
    if isinstance(obj, bytes):
        return obj.decode(encoding)
    return obj


def to_bytes(obj, encoding="utf-8", inplace=False):
    """Encode str (recursively through list/set/dict) to bytes."""
    if obj is None:
        return obj
    if isinstance(obj, list):
        if inplace:
            obj[:] = [to_bytes(x, encoding) for x in obj]
            return obj
        return [to_bytes(x, encoding) for x in obj]
    if isinstance(obj, set):
        if inplace:
            new = {to_bytes(x, encoding) for x in obj}
            obj.clear()
            obj.update(new)
            return obj
        return {to_bytes(x, encoding) for x in obj}
    if isinstance(obj, dict):
        if inplace:
            for k in list(obj):
                obj[to_bytes(k, encoding)] = to_bytes(obj.pop(k), encoding)
            return obj
        return {to_bytes(k, encoding): to_bytes(v, encoding) for k, v in obj.items()}
    if isinstance(obj, str):
        return obj.encode(encoding)
    return obj


def round(x, d=0):  # noqa: A001 — paddle API name
    """Python-2-style round (half away from zero)."""
    p = 10 ** d
    if x > 0:
        return float(math.floor((x * p) + math.copysign(0.5, x))) / p
    if x < 0:
        return float(math.ceil((x * p) + math.copysign(0.5, x))) / p
    return math.copysign(0.0, x)


def floor_division(x, y):
    return x // y


def get_exception_message(exc):
    return str(exc)
