"""paddle.cost_model — per-program cost estimation.

Ref: python/paddle/cost_model/cost_model.py:23 (CostModel.profile_measure runs
the program under the profiler and reports per-op time).

TPU-native: XLA already computes an analytical cost model for every compiled
executable; `CostModel.static_cost` surfaces it (flops / bytes accessed /
estimated optimal seconds) from `jit(fn).lower().compile().cost_analysis()`,
and `profile_measure` wall-clocks the compiled program.
"""
from __future__ import annotations

import time

import jax
import jax.numpy as jnp

from .tensor.tensor import Tensor

__all__ = ["CostModel"]


def _unwrap(args):
    return tuple(a._value if isinstance(a, Tensor) else a for a in args)


class CostModel:
    def static_cost(self, fn, *args, **kwargs):
        """Compile `fn` on example args and return XLA's analytical cost:
        {'flops': ..., 'bytes accessed': ..., 'optimal_seconds': ...} (keys as
        reported by the backend; missing entries are 0.0)."""
        lowered = jax.jit(fn).lower(*_unwrap(args), **kwargs)
        analysis = lowered.compile().cost_analysis()
        if isinstance(analysis, (list, tuple)):  # older jax: one dict per device
            analysis = analysis[0] if analysis else {}
        out = dict(analysis or {})
        for key in ("flops", "bytes accessed", "optimal_seconds"):
            out.setdefault(key, 0.0)
        return out

    def profile_measure(self, fn, *args, steps=10, warmup=3, **kwargs):
        """Wall-clock the compiled program (ref profile_measure returns
        measured per-op cost; here the whole fused program is the op).
        Compiles ONCE: the same executable serves both the cost analysis
        and the timed calls."""
        raw = _unwrap(args)
        compiled = jax.jit(fn).lower(*raw, **kwargs).compile()
        analysis = compiled.cost_analysis()
        if isinstance(analysis, (list, tuple)):
            analysis = analysis[0] if analysis else {}
        analysis = dict(analysis or {})
        r = None
        for _ in range(warmup):
            r = compiled(*raw, **kwargs)
        jax.tree.map(lambda x: jax.block_until_ready(x), r)
        t0 = time.perf_counter()
        for _ in range(steps):
            r = compiled(*raw, **kwargs)
        jax.tree.map(lambda x: jax.block_until_ready(x), r)
        dt = (time.perf_counter() - t0) / steps
        return {"time_s": dt,
                "flops": analysis.get("flops", 0.0),
                "achieved_flops_per_s": (analysis.get("flops", 0.0) / dt) if dt > 0 else 0.0,
                "bytes_accessed": analysis.get("bytes accessed", 0.0)}
