"""paddle.cost_model — per-program cost estimation.

Ref: python/paddle/cost_model/cost_model.py:23 (CostModel.profile_measure runs
the program under the profiler and reports per-op time).

TPU-native: XLA already computes an analytical cost model for every compiled
executable; `CostModel.static_cost` surfaces it (flops / bytes accessed /
estimated optimal seconds) from `jit(fn).lower().compile().cost_analysis()`,
and `profile_measure` wall-clocks the compiled program.
"""
from __future__ import annotations

import os
import time

import jax
import jax.numpy as jnp

from .tensor.tensor import Tensor

__all__ = ["CostModel", "peak_flops_per_device", "peak_hbm_bytes_per_sec"]

#: Dense bf16 peak FLOP/s per chip, by device_kind substring (public TPU
#: spec sheets; the MFU denominator).  Unknown kinds (CPU hosts, new
#: generations) return 0.0 unless PADDLE_TPU_PEAK_FLOPS overrides.
_PEAK_FLOPS_BY_KIND = (
    # jax reports the "lite" chips as e.g. "TPU v5 lite" / "TPU v5e"
    # depending on runtime version — match both spellings
    ("v6 lite", 918e12), ("v6e", 918e12),
    ("v5p", 459e12), ("v5 lite", 197e12), ("v5e", 197e12),
    ("v4", 275e12), ("v3", 123e12), ("v2", 45e12),
)


def peak_flops_per_device(device=None) -> float:
    """Peak dense FLOP/s of one attached device (0.0 when unknown).

    ``PADDLE_TPU_PEAK_FLOPS`` overrides — the escape hatch for CPU hosts,
    dryruns projecting a different pod, and future device kinds.  Used by
    the train-step instrumentation to turn HLO-estimated step FLOPs into an
    MFU gauge (`train_mfu_ratio`).
    """
    env = os.environ.get("PADDLE_TPU_PEAK_FLOPS")
    if env:
        try:
            return float(env)
        except ValueError:
            pass
    try:
        kind = (device or jax.devices()[0]).device_kind.lower()
    except Exception:
        return 0.0
    for sub, peak in _PEAK_FLOPS_BY_KIND:
        if sub in kind:
            return peak
    return 0.0


#: HBM bandwidth per chip in bytes/s, by device_kind substring (public TPU
#: spec sheets; the roofline's memory-term denominator).  Same shape and
#: lookup order as _PEAK_FLOPS_BY_KIND.
_PEAK_HBM_BW_BY_KIND = (
    ("v6 lite", 1640e9), ("v6e", 1640e9),
    ("v5p", 2765e9), ("v5 lite", 819e9), ("v5e", 819e9),
    ("v4", 1228e9), ("v3", 900e9), ("v2", 700e9),
)

#: One-shot microbench cache: the measured fallback touches hundreds of MB
#: of HBM, so it runs at most once per process.
_MEASURED_HBM_BW: float | None = None


def peak_hbm_bytes_per_sec(device=None, measure=False) -> float:
    """Peak HBM bytes/s of one attached device (0.0 when unknown).

    Same contract as :func:`peak_flops_per_device`:
    ``PADDLE_TPU_PEAK_HBM_BW`` overrides everything, then the device-kind
    spec table.  When the kind is unknown (CPU hosts, new generations), a
    microbench fallback — timing a large on-device ``jnp.copy`` — can
    stand in, but ONLY behind explicit opt-in (``measure=True`` or
    ``PADDLE_TPU_MEASURE_HBM_BW=1``): tier-1 predictions must stay
    deterministic, and a measured "peak" silently becoming the roofline
    denominator would make every residual ratio ~1.0 by construction.
    The measurement is cached for the process.
    """
    env = os.environ.get("PADDLE_TPU_PEAK_HBM_BW")
    if env:
        try:
            return float(env)
        except ValueError:
            pass
    try:
        dev = device or jax.devices()[0]
        kind = dev.device_kind.lower()
    except Exception:
        return 0.0
    for sub, peak in _PEAK_HBM_BW_BY_KIND:
        if sub in kind:
            return peak
    if measure or os.environ.get("PADDLE_TPU_MEASURE_HBM_BW") == "1":
        return _measure_hbm_bytes_per_sec(dev)
    return 0.0


def _measure_hbm_bytes_per_sec(device, mbytes=256, reps=4) -> float:
    """Time a large device-to-device copy: ``mbytes`` read + ``mbytes``
    written per rep, best-of-``reps`` (bandwidth microbenches take the max:
    stragglers are scheduling noise, not the memory system)."""
    global _MEASURED_HBM_BW
    if _MEASURED_HBM_BW is not None:
        return _MEASURED_HBM_BW
    n = mbytes * (1 << 20) // 4
    src = jax.device_put(jnp.zeros((n,), jnp.float32), device)
    copy = jax.jit(lambda x: jnp.copy(x))  # runs where the operand lives
    jax.block_until_ready(copy(src))  # compile + warm
    best = 0.0
    for _ in range(reps):
        t0 = time.perf_counter()
        jax.block_until_ready(copy(src))
        dt = time.perf_counter() - t0
        if dt > 0:
            best = max(best, 2 * n * 4 / dt)
    _MEASURED_HBM_BW = best
    return best


def _unwrap(args):
    return tuple(a._value if isinstance(a, Tensor) else a for a in args)


class CostModel:
    def static_cost(self, fn, *args, **kwargs):
        """Compile `fn` on example args and return XLA's analytical cost:
        {'flops': ..., 'bytes accessed': ..., 'optimal_seconds': ...} (keys as
        reported by the backend; missing entries are 0.0)."""
        lowered = jax.jit(fn).lower(*_unwrap(args), **kwargs)
        analysis = lowered.compile().cost_analysis()
        if isinstance(analysis, (list, tuple)):  # older jax: one dict per device
            analysis = analysis[0] if analysis else {}
        out = dict(analysis or {})
        for key in ("flops", "bytes accessed", "optimal_seconds"):
            out.setdefault(key, 0.0)
        return out

    def profile_measure(self, fn, *args, steps=10, warmup=3, **kwargs):
        """Wall-clock the compiled program (ref profile_measure returns
        measured per-op cost; here the whole fused program is the op).
        Compiles ONCE: the same executable serves both the cost analysis
        and the timed calls."""
        raw = _unwrap(args)
        compiled = jax.jit(fn).lower(*raw, **kwargs).compile()
        analysis = compiled.cost_analysis()
        if isinstance(analysis, (list, tuple)):
            analysis = analysis[0] if analysis else {}
        analysis = dict(analysis or {})
        r = None
        for _ in range(warmup):
            r = compiled(*raw, **kwargs)
        jax.tree.map(lambda x: jax.block_until_ready(x), r)
        t0 = time.perf_counter()
        for _ in range(steps):
            r = compiled(*raw, **kwargs)
        jax.tree.map(lambda x: jax.block_until_ready(x), r)
        dt = (time.perf_counter() - t0) / steps
        return {"time_s": dt,
                "flops": analysis.get("flops", 0.0),
                "achieved_flops_per_s": (analysis.get("flops", 0.0) / dt) if dt > 0 else 0.0,
                "bytes_accessed": analysis.get("bytes accessed", 0.0)}
