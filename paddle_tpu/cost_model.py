"""paddle.cost_model — per-program cost estimation.

Ref: python/paddle/cost_model/cost_model.py:23 (CostModel.profile_measure runs
the program under the profiler and reports per-op time).

TPU-native: XLA already computes an analytical cost model for every compiled
executable; `CostModel.static_cost` surfaces it (flops / bytes accessed /
estimated optimal seconds) from `jit(fn).lower().compile().cost_analysis()`,
and `profile_measure` wall-clocks the compiled program.
"""
from __future__ import annotations

import os
import time

import jax
import jax.numpy as jnp

from .tensor.tensor import Tensor

__all__ = ["CostModel", "peak_flops_per_device"]

#: Dense bf16 peak FLOP/s per chip, by device_kind substring (public TPU
#: spec sheets; the MFU denominator).  Unknown kinds (CPU hosts, new
#: generations) return 0.0 unless PADDLE_TPU_PEAK_FLOPS overrides.
_PEAK_FLOPS_BY_KIND = (
    # jax reports the "lite" chips as e.g. "TPU v5 lite" / "TPU v5e"
    # depending on runtime version — match both spellings
    ("v6 lite", 918e12), ("v6e", 918e12),
    ("v5p", 459e12), ("v5 lite", 197e12), ("v5e", 197e12),
    ("v4", 275e12), ("v3", 123e12), ("v2", 45e12),
)


def peak_flops_per_device(device=None) -> float:
    """Peak dense FLOP/s of one attached device (0.0 when unknown).

    ``PADDLE_TPU_PEAK_FLOPS`` overrides — the escape hatch for CPU hosts,
    dryruns projecting a different pod, and future device kinds.  Used by
    the train-step instrumentation to turn HLO-estimated step FLOPs into an
    MFU gauge (`train_mfu_ratio`).
    """
    env = os.environ.get("PADDLE_TPU_PEAK_FLOPS")
    if env:
        try:
            return float(env)
        except ValueError:
            pass
    try:
        kind = (device or jax.devices()[0]).device_kind.lower()
    except Exception:
        return 0.0
    for sub, peak in _PEAK_FLOPS_BY_KIND:
        if sub in kind:
            return peak
    return 0.0


def _unwrap(args):
    return tuple(a._value if isinstance(a, Tensor) else a for a in args)


class CostModel:
    def static_cost(self, fn, *args, **kwargs):
        """Compile `fn` on example args and return XLA's analytical cost:
        {'flops': ..., 'bytes accessed': ..., 'optimal_seconds': ...} (keys as
        reported by the backend; missing entries are 0.0)."""
        lowered = jax.jit(fn).lower(*_unwrap(args), **kwargs)
        analysis = lowered.compile().cost_analysis()
        if isinstance(analysis, (list, tuple)):  # older jax: one dict per device
            analysis = analysis[0] if analysis else {}
        out = dict(analysis or {})
        for key in ("flops", "bytes accessed", "optimal_seconds"):
            out.setdefault(key, 0.0)
        return out

    def profile_measure(self, fn, *args, steps=10, warmup=3, **kwargs):
        """Wall-clock the compiled program (ref profile_measure returns
        measured per-op cost; here the whole fused program is the op).
        Compiles ONCE: the same executable serves both the cost analysis
        and the timed calls."""
        raw = _unwrap(args)
        compiled = jax.jit(fn).lower(*raw, **kwargs).compile()
        analysis = compiled.cost_analysis()
        if isinstance(analysis, (list, tuple)):
            analysis = analysis[0] if analysis else {}
        analysis = dict(analysis or {})
        r = None
        for _ in range(warmup):
            r = compiled(*raw, **kwargs)
        jax.tree.map(lambda x: jax.block_until_ready(x), r)
        t0 = time.perf_counter()
        for _ in range(steps):
            r = compiled(*raw, **kwargs)
        jax.tree.map(lambda x: jax.block_until_ready(x), r)
        dt = (time.perf_counter() - t0) / steps
        return {"time_s": dt,
                "flops": analysis.get("flops", 0.0),
                "achieved_flops_per_s": (analysis.get("flops", 0.0) / dt) if dt > 0 else 0.0,
                "bytes_accessed": analysis.get("bytes accessed", 0.0)}
