"""paddle.signal (ref: python/paddle/signal.py — frame/overlap_add/stft/istft).

All jnp compositions: framing is a strided gather, overlap-add a scatter-add,
and STFT/iSTFT compose them with paddle.fft — everything fuses under jit.
"""
from __future__ import annotations

import jax.numpy as jnp

from .tensor.tensor import Tensor, apply_op
from . import fft as _fft

__all__ = ["frame", "overlap_add", "stft", "istft"]


def _frame(v, frame_length, hop_length, axis=-1):
    if axis not in (-1, v.ndim - 1):
        raise NotImplementedError(
            "frame: only axis=-1 (time-last, the paddle default) is supported")
    n = v.shape[-1]
    n_frames = 1 + (n - frame_length) // hop_length
    idx = (jnp.arange(frame_length)[None, :]
           + hop_length * jnp.arange(n_frames)[:, None])   # [F, L]
    out = v[..., idx]                                       # [..., F, L]
    return jnp.swapaxes(out, -2, -1)                        # [..., L, F] (paddle layout)


def frame(x, frame_length, hop_length, axis=-1, name=None):
    """Ref signal.py frame: slide a window of `frame_length` by `hop_length`;
    returns [..., frame_length, num_frames]."""
    if hop_length <= 0:
        raise ValueError("hop_length must be positive")
    sig_len = (x.shape[axis] if hasattr(x, "shape") else
               __import__("numpy").asarray(x).shape[axis])
    if frame_length > sig_len:
        raise ValueError(
            f"frame_length ({frame_length}) exceeds the signal length "
            f"({sig_len}) along the framed axis")
    return apply_op(lambda v: _frame(v, frame_length, hop_length, axis),
                    (x,), name="frame")


def _overlap_add(v, hop_length):
    # v: [..., frame_length, n_frames]
    L, F = v.shape[-2], v.shape[-1]
    n = (F - 1) * hop_length + L
    out = jnp.zeros(v.shape[:-2] + (n,), v.dtype)
    for f in range(F):   # unrolled under jit: F is static and small for audio
        out = out.at[..., f * hop_length: f * hop_length + L].add(v[..., :, f])
    return out


def overlap_add(x, hop_length, axis=-1, name=None):
    """Ref signal.py overlap_add — inverse of frame.  axis=-1 takes
    [..., frame_length, n_frames]; axis=0 takes [n_frames, frame_length, ...]
    (the two layouts paddle supports)."""
    if axis in (-1, getattr(x, "ndim", None) and x.ndim - 1):
        return apply_op(lambda v: _overlap_add(v, hop_length), (x,),
                        name="overlap_add")
    if axis == 0:
        def _f(v):
            # [n_frames, frame_length, ...] -> [..., frame_length, n_frames]
            moved = jnp.moveaxis(jnp.moveaxis(v, 0, -1), 0, -2)
            return _overlap_add(moved, hop_length)

        return apply_op(_f, (x,), name="overlap_add")
    raise NotImplementedError("overlap_add: axis must be -1 or 0")


def stft(x, n_fft, hop_length=None, win_length=None, window=None, center=True,
         pad_mode="reflect", normalized=False, onesided=True, name=None):
    """Ref signal.py stft: returns [..., n_fft//2+1 (or n_fft), n_frames]."""
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft

    def _f(v, w=None):
        if center:
            pad = n_fft // 2
            v = jnp.pad(v, [(0, 0)] * (v.ndim - 1) + [(pad, pad)], mode=pad_mode)
        frames = _frame(v, n_fft, hop_length)          # [..., n_fft, F]
        # no window given: paddle uses a RECTANGULAR window of win_length
        # zero-padded to n_fft (win_length < n_fft must not be a no-op)
        win = w if w is not None else jnp.ones((win_length,), frames.dtype)
        if win.shape[0] < n_fft:                        # center-pad the window
            lp = (n_fft - win.shape[0]) // 2
            win = jnp.pad(win, (lp, n_fft - win.shape[0] - lp))
        frames = frames * win[:, None]
        spec = (jnp.fft.rfft(frames, axis=-2) if onesided
                else jnp.fft.fft(frames, axis=-2))
        if normalized:
            spec = spec / jnp.sqrt(jnp.asarray(n_fft, spec.real.dtype))
        return spec

    args = (x,) if window is None else (x, window)
    return apply_op(_f, args, name="stft")


def istft(x, n_fft, hop_length=None, win_length=None, window=None, center=True,
          normalized=False, onesided=True, length=None, return_complex=False,
          name=None):
    """Ref signal.py istft — least-squares inverse with window normalization."""
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft

    def _f(v, w=None):
        if normalized:
            v = v * jnp.sqrt(jnp.asarray(n_fft, v.real.dtype))
        if onesided:
            frames = jnp.fft.irfft(v, n=n_fft, axis=-2)
        else:
            frames = jnp.fft.ifft(v, axis=-2)
            if not return_complex:
                frames = frames.real
        win = w if w is not None else jnp.ones((win_length,), jnp.float32)
        if win.shape[0] < n_fft:
            lp = (n_fft - win.shape[0]) // 2
            win = jnp.pad(win, (lp, n_fft - win.shape[0] - lp))
        sig = _overlap_add(frames * win[:, None], hop_length)
        # window envelope normalization (the least-squares denominator)
        env = _overlap_add(jnp.broadcast_to((win * win)[:, None],
                                            (n_fft, v.shape[-1])), hop_length)
        sig = sig / jnp.maximum(env, 1e-11)
        if center:
            pad = n_fft // 2
            sig = sig[..., pad: sig.shape[-1] - pad]
        if length is not None:
            sig = sig[..., :length]
        return sig

    args = (x,) if window is None else (x, window)
    return apply_op(_f, args, name="istft")
