"""Constrained decoding: grammar -> host-side token automaton -> [V] masks.

A schema-constrained request decodes against a deterministic automaton
over the TOKEN vocabulary, compiled once per (grammar, vocab) pair:

1. the grammar — a regex string or a JSON-schema dict (compiled to a
   regex by :func:`regex_from_schema`) — is parsed into a Thompson NFA
   and determinised lazily over characters;
2. :class:`TokenConstraint` lifts the character DFA to token level by
   walking every vocab token's string from every reachable state,
   producing a dense ``[n_states, V]`` bool mask table and an int32
   transition table (disallowed tokens route to a sink state that admits
   only ``eos``);
3. per request, a :class:`Cursor` tracks the automaton state on the
   HOST; the engine uploads ``masks[state]`` rows per slot per tick
   exactly like the per-slot top-k/top-p knob arrays (device-array
   values, never program shapes), and advances the cursor with each
   emitted token.

Automaton contract (see README §Multi-tenant serving): ``eos`` is
allowed exactly in accepting states; a state from which no token can
make progress additionally admits ``eos`` so a wedged grammar terminates
the request instead of the slot; after ``eos`` (or any disallowed
token) the automaton sits in the sink.  The solo-parity path
(``generate(token_mask_fn=...)``) ships the SAME two tables to the
device and carries the state through the decode scan, so engine and
solo runs mask identically bit for bit.

Everything here is stdlib + numpy on the hot path; jax is touched only
by :meth:`TokenConstraint.device_tables` for the solo path.
"""
from __future__ import annotations

import json
import threading

import numpy as np

from ..observability import metrics as _obs

__all__ = ["compile_constraint", "regex_from_schema", "TokenConstraint",
           "Cursor"]

_M_MASKED_TOKENS = _obs.counter(
    "llm_constraint_masked_tokens_total",
    "Tokens emitted while a constraint mask was active on the row")
_M_REJECTS = _obs.counter(
    "llm_constraint_rejects_total",
    "Constraint violations: submissions rejected at validation plus "
    "automaton advances fed a token the mask disallowed")


def count_masked_token(n=1):
    _M_MASKED_TOKENS.inc(n)


def count_reject(n=1):
    _M_REJECTS.inc(n)


# The '.' / negated-class universe: printable ASCII.
_ALL_CHARS = frozenset(chr(c) for c in range(0x20, 0x7F))
_DIGITS = frozenset("0123456789")
_WORD = frozenset(
    "abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789_")
_SPACE = frozenset(" \t\n\r")


# ------------------------------------------------------------------ NFA
class _Nfa:
    """Thompson construction: integer states, char-set edges, eps edges."""

    def __init__(self):
        self.edges = []  # state -> [(frozenset chars, target)]
        self.eps = []    # state -> [target]

    def state(self):
        self.edges.append([])
        self.eps.append([])
        return len(self.edges) - 1


class _RegexParser:
    """Recursive-descent parser for the grammar subset the schema
    compiler emits: literals, escapes (``\\d \\w \\s \\n \\t`` + escaped
    metachars), ``[...]`` classes with ranges and negation, ``.``,
    grouping, alternation, and ``* + ?``.  No counted repetition."""

    def __init__(self, pattern):
        self.p = pattern
        self.i = 0
        self.nfa = _Nfa()

    def parse(self):
        start, end = self._alt()
        if self.i != len(self.p):
            raise ValueError(
                f"regex: unexpected {self.p[self.i]!r} at {self.i}")
        return self.nfa, start, end

    def _peek(self):
        return self.p[self.i] if self.i < len(self.p) else None

    def _alt(self):
        frags = [self._concat()]
        while self._peek() == "|":
            self.i += 1
            frags.append(self._concat())
        if len(frags) == 1:
            return frags[0]
        s, e = self.nfa.state(), self.nfa.state()
        for fs, fe in frags:
            self.nfa.eps[s].append(fs)
            self.nfa.eps[fe].append(e)
        return s, e

    def _concat(self):
        frags = []
        while self._peek() not in (None, "|", ")"):
            frags.append(self._repeat())
        if not frags:
            s = self.nfa.state()
            e = self.nfa.state()
            self.nfa.eps[s].append(e)
            return s, e
        for (_, ae), (bs, _) in zip(frags, frags[1:]):
            self.nfa.eps[ae].append(bs)
        return frags[0][0], frags[-1][1]

    def _repeat(self):
        fs, fe = self._atom()
        while self._peek() in ("*", "+", "?"):
            op = self.p[self.i]
            self.i += 1
            if op == "*":
                s, e = self.nfa.state(), self.nfa.state()
                self.nfa.eps[s] += [fs, e]
                self.nfa.eps[fe] += [fs, e]
                fs, fe = s, e
            elif op == "+":
                e = self.nfa.state()
                self.nfa.eps[fe] += [fs, e]
                fe = e
            else:  # '?'
                s, e = self.nfa.state(), self.nfa.state()
                self.nfa.eps[s] += [fs, e]
                self.nfa.eps[fe].append(e)
                fs, fe = s, e
        return fs, fe

    def _char_frag(self, chars):
        s, e = self.nfa.state(), self.nfa.state()
        self.nfa.edges[s].append((frozenset(chars), e))
        return s, e

    def _escape_set(self, c):
        if c == "d":
            return _DIGITS
        if c == "w":
            return _WORD
        if c == "s":
            return _SPACE
        if c == "n":
            return frozenset("\n")
        if c == "t":
            return frozenset("\t")
        return frozenset(c)  # escaped metachar / literal

    def _atom(self):
        c = self._peek()
        if c is None:
            raise ValueError("regex: unexpected end of pattern")
        if c == "(":
            self.i += 1
            frag = self._alt()
            if self._peek() != ")":
                raise ValueError("regex: unbalanced '('")
            self.i += 1
            return frag
        if c == "[":
            return self._char_frag(self._char_class())
        if c == ".":
            self.i += 1
            return self._char_frag(_ALL_CHARS)
        if c == "\\":
            self.i += 1
            if self.i >= len(self.p):
                raise ValueError("regex: trailing backslash")
            s = self._escape_set(self.p[self.i])
            self.i += 1
            return self._char_frag(s)
        if c in "*+?)|":
            raise ValueError(f"regex: unexpected {c!r} at {self.i}")
        self.i += 1
        return self._char_frag(frozenset(c))

    def _char_class(self):
        assert self.p[self.i] == "["
        self.i += 1
        negate = self._peek() == "^"
        if negate:
            self.i += 1
        chars = set()
        while True:
            c = self._peek()
            if c is None:
                raise ValueError("regex: unbalanced '['")
            if c == "]":
                self.i += 1
                break
            if c == "\\":
                self.i += 1
                chars |= self._escape_set(self.p[self.i])
                self.i += 1
                continue
            # range a-z (a trailing '-' is a literal)
            if (self.i + 2 < len(self.p) and self.p[self.i + 1] == "-"
                    and self.p[self.i + 2] != "]"):
                lo, hi = c, self.p[self.i + 2]
                chars |= {chr(x) for x in range(ord(lo), ord(hi) + 1)}
                self.i += 3
                continue
            chars.add(c)
            self.i += 1
        return (_ALL_CHARS - chars) if negate else frozenset(chars)


class _CharDfa:
    """Lazy subset-construction over the NFA; states are frozensets of
    NFA states, memoised per (state, char)."""

    def __init__(self, nfa, start, accept):
        self.nfa = nfa
        self.accept_nfa = accept
        self.start = self._closure({start})
        self._memo = {}

    def _closure(self, states):
        stack, seen = list(states), set(states)
        while stack:
            s = stack.pop()
            for t in self.nfa.eps[s]:
                if t not in seen:
                    seen.add(t)
                    stack.append(t)
        return frozenset(seen)

    def step(self, dstate, ch):
        """Next DFA state for one char, or None (dead)."""
        key = (dstate, ch)
        hit = self._memo.get(key, _MISS)
        if hit is not _MISS:
            return hit
        nxt = set()
        for s in dstate:
            for chars, t in self.nfa.edges[s]:
                if ch in chars:
                    nxt.add(t)
        out = self._closure(nxt) if nxt else None
        self._memo[key] = out
        return out

    def accepting(self, dstate):
        return self.accept_nfa in dstate


_MISS = object()


# ------------------------------------------------------ token automaton
class TokenConstraint:
    """A grammar lifted to the token vocabulary: dense per-state ``[V]``
    bool masks + int32 transitions, shared (immutable) across requests.

    ``vocab`` maps token id -> string; empty-string tokens are never
    allowed (they cannot make progress).  State ``n_states - 1`` is the
    sink: only ``eos`` survives there, and every transition out of it
    returns to it.
    """

    def __init__(self, dfa, vocab, eos_token_id):
        V = len(vocab)
        eos = int(eos_token_id)
        if not 0 <= eos < V:
            raise ValueError(
                f"eos_token_id {eos} outside vocab of {V} tokens")
        self.V = V
        self.eos_token_id = eos
        index = {dfa.start: 0}
        order = [dfa.start]
        masks, trans = [], []
        qi = 0
        while qi < len(order):
            dstate = order[qi]
            qi += 1
            mask = np.zeros(V, np.bool_)
            dests = [None] * V
            for tok in range(V):
                cur = dstate
                text = vocab[tok]
                if not text:
                    continue
                for ch in text:
                    cur = dfa.step(cur, ch)
                    if cur is None:
                        break
                if cur is None:
                    continue
                mask[tok] = True
                dests[tok] = cur
                if cur not in index:
                    index[cur] = len(order)
                    order.append(cur)
            if dfa.accepting(dstate):
                mask[eos] = True
            elif not mask.any():
                # dead end: the grammar cannot be completed from here —
                # admit eos so the request terminates instead of wedging
                mask[eos] = True
            masks.append(mask)
            trans.append(dests)
        self.n_states = len(order) + 1  # + sink
        sink = self.n_states - 1
        self.masks = np.zeros((self.n_states, V), np.bool_)
        self.trans = np.full((self.n_states, V), sink, np.int32)
        for i, (mask, dests) in enumerate(zip(masks, trans)):
            self.masks[i] = mask
            for tok, d in enumerate(dests):
                if d is not None:
                    self.trans[i, tok] = index[d]
        self.masks[sink, eos] = True  # sink admits only eos
        self.start_state = 0
        self._dev = None
        self._dev_lock = threading.Lock()

    def cursor(self):
        return Cursor(self)

    def device_tables(self):
        """``(masks, trans)`` as device arrays for the solo scan path.

        The host->device upload happens OUTSIDE the lock: a first-use
        upload must not stall every concurrent mask/cursor caller behind
        the transfer.  Two racing first callers may both upload; the
        loser's copy is dropped (the tables are immutable, so either copy
        is correct) — publish-under-lock keeps the winner stable."""
        with self._dev_lock:
            dev = self._dev
        if dev is None:
            import jax.numpy as jnp

            dev = (jnp.asarray(self.masks), jnp.asarray(self.trans))
            with self._dev_lock:
                if self._dev is None:
                    self._dev = dev
                dev = self._dev
        return dev


class Cursor:
    """Per-request automaton state (host side, engine-owned)."""

    __slots__ = ("tc", "state", "rejects")

    def __init__(self, tc):
        self.tc = tc
        self.state = tc.start_state
        self.rejects = 0

    def mask(self):
        """The current state's ``[V]`` bool mask (shared row — copy
        before mutating)."""
        return self.tc.masks[self.state]

    def advance(self, tok):
        """Consume one emitted token; returns False (and counts a
        reject) when the mask disallowed it — the state still moves, to
        the sink, so decoding stays well-defined."""
        tok = int(tok)
        ok = bool(self.tc.masks[self.state, tok])
        self.state = int(self.tc.trans[self.state, tok])
        if not ok:
            self.rejects += 1
            count_reject()
        return ok


# ------------------------------------------------------ schema -> regex
_RX_SPECIALS = set("\\.^$*+?()[]{}|")
_STRING_BODY = "[A-Za-z0-9_ .,:;!@#%&/='<>-]*"
_INTEGER = "-?(0|[1-9][0-9]*)"
_NUMBER = "-?(0|[1-9][0-9]*)(\\.[0-9]+)?"


def _rx_literal(text):
    return "".join("\\" + c if c in _RX_SPECIALS else c for c in text)


def regex_from_schema(schema):
    """A regex for the JSON serialisation of a practical schema subset:
    ``string`` / ``integer`` / ``number`` / ``boolean`` / ``null`` /
    ``enum`` / homogeneous ``array`` / ``object``.  Objects serialise
    with EVERY declared property, in declaration order, no whitespace —
    the canonical form the automaton accepts (the usual constrained-JSON
    simplification).  Strings admit a conservative printable charset
    without quotes/backslashes."""
    if "enum" in schema:
        opts = "|".join(_rx_literal(json.dumps(v, separators=(",", ":")))
                        for v in schema["enum"])
        return f"({opts})"
    t = schema.get("type")
    if t == "string":
        return '"' + _STRING_BODY + '"'
    if t == "integer":
        return _INTEGER
    if t == "number":
        return _NUMBER
    if t == "boolean":
        return "(true|false)"
    if t == "null":
        return "null"
    if t == "array":
        item = regex_from_schema(schema.get("items", {"type": "string"}))
        return f"\\[({item}(,{item})*)?\\]"
    if t == "object":
        props = schema.get("properties", {})
        parts = []
        for name, sub in props.items():
            parts.append(f'"{_rx_literal(name)}":{regex_from_schema(sub)}')
        return "\\{" + ",".join(parts) + "\\}"
    raise ValueError(f"unsupported schema: {schema!r}")


def compile_constraint(spec, vocab, eos_token_id):
    """Compile ``spec`` (regex string or JSON-schema dict) over ``vocab``
    (token id -> string) into a shared :class:`TokenConstraint`."""
    if isinstance(spec, dict):
        pattern = regex_from_schema(spec)
    elif isinstance(spec, str):
        pattern = spec
    else:
        raise TypeError(
            f"constraint spec must be a regex str or schema dict, "
            f"got {type(spec).__name__}")
    nfa, start, end = _RegexParser(pattern).parse()
    return TokenConstraint(_CharDfa(nfa, start, end), list(vocab),
                           eos_token_id)
