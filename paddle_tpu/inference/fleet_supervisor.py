"""Process-level fleet supervision: real replica subprocesses, crash-safe.

``ReplicaSupervisor`` closes the gap the in-process fleet (PRs 10–15)
could not: replicas here are REAL processes (``python -m
paddle_tpu.inference.replica_main``) that can be kill -9'd, SIGSTOPped,
or OOM-killed — and the fleet keeps serving.  The supervisor owns the
process lifecycle; the existing ``Router``/``FleetController`` pair
keeps owning traffic and policy:

- **Spawn**: each replica gets an assigned port, PINNED across restarts
  (the router's target list stays valid), and enters rotation only after
  its ``/healthz`` answers 200 within the readiness gate.
- **Supervise**: ``tick()`` reaps dead children and respawns them on a
  jittered-exponential-backoff schedule; a replica that dies more than
  ``restart_limit`` times inside ``restart_window_s`` (the PR-10
  FleetController thresholds) is QUARANTINED — killed, benched in the
  router, affinity dropped.  A child that is alive but unresponsive
  (SIGSTOP wedge: the socket accepts, nothing answers) is SIGKILLed and
  respawned after ``unhealthy_after_s`` of failed probes.
- **Witness**: the supervisor is the router's *death witness* — it
  exports ``witness(name) -> incarnation | None`` (None = no live
  process).  The router captures the incarnation at admit time; any later
  change CONFIRMS the admitted process died, making a mid-request kill -9
  retry-safe (the dead incarnation can never deliver, so re-routing
  cannot double-deliver).
- **Scale**: ``apply_scale(+1)`` spawns a fresh replica and atomically
  adds it to the router's rotation + scrape targets; ``apply_scale(-1)``
  removes a victim from rotation first, drains it (bounded), SIGTERMs,
  and escalates to SIGKILL only on deadline expiry.  Feed it the
  FleetController's sustained ``scale_signal``.
- **Shutdown**: ``stop()`` SIGTERMs every child (the entrypoint drains
  bounded by its ``--drain-deadline``), waits the grace window on a
  monotonic deadline, then SIGKILLs stragglers — counted on
  ``fleet_proc_sigkill_escalations_total`` because every escalation is a
  drain that failed its contract.

Deterministic under an injected ``clock`` for the backoff/quarantine
arithmetic; the actual process waits are bounded by monotonic deadlines
(the tpulint wall-clock discipline).
"""
from __future__ import annotations

import os
import signal
import socket
import subprocess
import sys
import threading
import time
from collections import deque

from ..distributed.fault_tolerance import ExponentialBackoff
from ..observability import flight_recorder as _flight
from ..observability import goodput as _goodput
from ..observability import metrics as _obs
from .router import _http_json

__all__ = ["ReplicaSupervisor", "SupervisedReplica"]

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# Process-fleet telemetry (README §Observability catalogue).
_M_SPAWNS = _obs.counter(
    "fleet_proc_spawns_total",
    "Replica processes spawned by the supervisor (launches + respawns)")
_M_RESTARTS = _obs.counter(
    "fleet_proc_restarts_total",
    "Replica processes respawned after death or unresponsiveness")
_M_BACKOFF = _obs.gauge(
    "fleet_proc_backoff_seconds",
    "Current restart backoff delay per replica (0 while running)",
    labelnames=("replica",))
_M_SIGKILLS = _obs.counter(
    "fleet_proc_sigkill_escalations_total",
    "Shutdowns escalated to SIGKILL after the drain/term grace deadline")
_M_READY = _obs.histogram(
    "fleet_proc_ready_seconds",
    "Spawn-to-ready latency: exec to the first /healthz 200")


class SupervisedReplica:
    """Supervisor-side state of one replica process."""

    __slots__ = ("name", "port", "proc", "incarnation", "state",
                 "spawned_at", "restart_marks", "backoff_attempt",
                 "next_spawn_at", "unhealthy_since", "fault_spec",
                 "fault_incarnations")

    def __init__(self, name, port):
        self.name = str(name)
        self.port = int(port)
        self.proc = None
        self.incarnation = 0       # bumped at every spawn
        self.state = "init"        # init|starting|ready|backoff|
        #                            quarantined|stopping|stopped
        self.spawned_at = 0.0
        self.restart_marks = deque()   # mono stamps of observed deaths
        self.backoff_attempt = 0
        self.next_spawn_at = 0.0
        self.unhealthy_since = None
        self.fault_spec = None         # ProcFaults spec for future spawns
        self.fault_incarnations = None  # None = every future incarnation

    @property
    def pid(self):
        return self.proc.pid if self.proc is not None else None

    def alive(self):
        return self.proc is not None and self.proc.poll() is None

    def target(self):
        return f"127.0.0.1:{self.port}"

    def to_dict(self):
        return {"name": self.name, "port": self.port, "pid": self.pid,
                "incarnation": self.incarnation, "state": self.state,
                "restarts": max(0, self.incarnation - 1),
                "deaths_in_window": len(self.restart_marks)}


class ReplicaSupervisor:
    """Spawn + supervise N ``replica_main`` subprocesses (module doc)."""

    def __init__(self, count=2, *, model="tiny", page_size=16, slots=2,
                 max_seq_len=128, seed=7, drain_deadline_s=5.0,
                 term_grace_s=5.0, ready_timeout_s=180.0,
                 unhealthy_after_s=10.0, probe_timeout_s=1.0,
                 restart_limit=3, restart_window_s=600.0, backoff=None,
                 max_replicas=8, min_replicas=1, faults_enabled=False,
                 name_prefix="replica", log_dir=None,
                 clock=time.monotonic):
        self.model = str(model)
        self.page_size = int(page_size)
        self.slots = int(slots)
        self.max_seq_len = int(max_seq_len)
        self.seed = int(seed)
        self.drain_deadline_s = float(drain_deadline_s)
        self.term_grace_s = float(term_grace_s)
        self.ready_timeout_s = float(ready_timeout_s)
        self.unhealthy_after_s = float(unhealthy_after_s)
        self.probe_timeout_s = float(probe_timeout_s)
        self.restart_limit = int(restart_limit)
        self.restart_window_s = float(restart_window_s)
        self.backoff = backoff if backoff is not None else \
            ExponentialBackoff(base=0.25, factor=2.0, max_delay=5.0)
        self.max_replicas = int(max_replicas)
        self.min_replicas = max(1, int(min_replicas))
        self.faults_enabled = bool(faults_enabled)
        self.name_prefix = str(name_prefix)
        self.log_dir = log_dir
        self._clock = clock
        self._lock = threading.Lock()
        self._replicas: dict[str, SupervisedReplica] = {}
        self._next_idx = int(count)
        self._router = None
        self.escalations = 0
        for i in range(int(count)):
            name = f"{self.name_prefix}-{i}"
            self._replicas[name] = SupervisedReplica(name,
                                                     self._free_port())

    # ------------------------------------------------------------- plumbing
    @staticmethod
    def _free_port():
        with socket.socket() as s:
            s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            s.bind(("127.0.0.1", 0))
            return s.getsockname()[1]

    def replicas(self):
        with self._lock:
            return list(self._replicas.values())

    def get(self, name):
        with self._lock:  # scale_up/_down mutate the table concurrently
            return self._replicas[str(name)]

    def targets(self):
        """(name, host:port) pairs for Router construction."""
        return [(r.name, r.target()) for r in self.replicas()
                if r.state not in ("stopping", "stopped")]

    def attach(self, router):
        """Wire the router: membership changes flow supervisor -> router,
        and the router gains this supervisor as its death witness (the
        incarnation check that makes process death retry-safe)."""
        self._router = router
        router.set_process_witness(self.witness)
        return self

    def witness(self, name):
        """Router death-witness: the live incarnation serving ``name``,
        or None when no live process exists.  A captured value that later
        DIFFERS (or goes None) proves the admit-time process is gone."""
        with self._lock:
            rep = self._replicas.get(str(name))
        if rep is None or not rep.alive():
            return None
        return rep.incarnation

    # -------------------------------------------------------------- spawning
    def _spawn(self, rep, now):
        rep.incarnation += 1
        argv = [sys.executable, "-m", "paddle_tpu.inference.replica_main",
                "--name", rep.name, "--port", str(rep.port),
                "--model", self.model,
                "--page-size", str(self.page_size),
                "--slots", str(self.slots),
                "--max-seq-len", str(self.max_seq_len),
                "--seed", str(self.seed),
                "--drain-deadline", str(self.drain_deadline_s)]
        if self.faults_enabled:
            argv.append("--allow-faultz")
        env = dict(os.environ)
        env.setdefault("JAX_PLATFORMS", "cpu")
        env["PYTHONPATH"] = _REPO_ROOT + os.pathsep + \
            env.get("PYTHONPATH", "")
        if rep.fault_spec and (rep.fault_incarnations is None
                               or rep.incarnation in rep.fault_incarnations):
            from ..testing.faults import proc_fault_env
            env = proc_fault_env(rep.fault_spec, env)
        out = subprocess.DEVNULL
        if self.log_dir:
            os.makedirs(self.log_dir, exist_ok=True)
            out = open(os.path.join(
                self.log_dir, f"{rep.name}.{rep.incarnation}.log"), "ab")
        rep.proc = subprocess.Popen(argv, env=env, stdout=out,
                                    stderr=subprocess.STDOUT)
        if out is not subprocess.DEVNULL:
            out.close()  # the child holds its own fd now
        rep.state = "starting"
        rep.spawned_at = now
        rep.unhealthy_since = None
        _M_SPAWNS.inc()
        _flight.record_event("fleet_proc_spawn", replica=rep.name,
                             incarnation=rep.incarnation, pid=rep.proc.pid)

    def _wait_ready(self, rep, deadline):
        """Poll ``/healthz`` until 200, death, or the deadline.  Returns
        True when the replica entered rotation-ready state."""
        while True:
            if rep.proc is None or rep.proc.poll() is not None:
                return False  # died before readiness
            now = self._clock()
            if now >= deadline:
                # slow-start past the gate: this incarnation is a failure
                self._kill(rep)
                _flight.record_event("fleet_proc_ready_timeout",
                                     replica=rep.name)
                return False
            try:
                status, _doc = _http_json(
                    "127.0.0.1", rep.port, "GET", "/healthz",
                    timeout=min(self.probe_timeout_s,
                                max(0.05, deadline - now)))
                if status == 200:
                    rep.state = "ready"
                    rep.backoff_attempt = 0
                    _M_BACKOFF.labels(replica=rep.name).set(0.0)
                    spawn_to_ready = max(0.0,
                                         self._clock() - rep.spawned_at)
                    _M_READY.observe(spawn_to_ready)
                    # goodput ledger (ISSUE 20): spawn->ready window is
                    # fleet capacity lost to the respawn — counter-only
                    # (replica windows overlap one supervisor wall clock)
                    _goodput.fleet_attribute("respawn", spawn_to_ready)
                    return True
            except Exception:
                pass  # not bound yet / not healthy yet: keep gating
            time.sleep(0.05)

    def start(self):
        """Spawn every replica concurrently, then gate on readiness.  A
        replica that fails its gate is left scheduled for backoff respawn
        (``tick()`` picks it up) — start() never wedges on one bad child."""
        now = self._clock()
        for rep in self.replicas():
            if rep.proc is None:
                self._spawn(rep, now)
        deadline = self._clock() + self.ready_timeout_s
        for rep in self.replicas():
            if rep.state == "starting" and not self._wait_ready(rep,
                                                                deadline):
                self._record_death(rep, self._clock(),
                                   reason="failed readiness gate")
        return self

    def ready(self):
        return all(r.state == "ready" for r in self.replicas()
                   if r.state not in ("stopping", "stopped"))

    # ----------------------------------------------------------- supervision
    def _kill(self, rep):
        if rep.proc is not None and rep.proc.poll() is None:
            try:
                rep.proc.kill()
                rep.proc.wait(timeout=10)
            except (ProcessLookupError, subprocess.TimeoutExpired):
                pass

    def _record_death(self, rep, now, reason=""):
        """One observed death: mark the flap window, schedule the backoff
        respawn, and mark the replica down in the router (affinity pages
        died with the process)."""
        rep.restart_marks.append(now)
        rep.state = "backoff"
        rep.backoff_attempt += 1
        delay = self.backoff.delay(rep.backoff_attempt)
        rep.next_spawn_at = now + delay
        _M_BACKOFF.labels(replica=rep.name).set(delay)
        # goodput ledger: the scheduled backoff window is capacity the
        # fleet will not have — attributed at scheduling time (the window
        # is fully determined here; tick() only waits it out)
        _goodput.fleet_attribute("restart_backoff", delay)
        _flight.record_event("fleet_proc_death", replica=rep.name,
                             incarnation=rep.incarnation, reason=reason,
                             backoff_s=round(delay, 3))
        router = self._router
        if router is not None and rep.name in router._replicas:
            router._replicas[rep.name].up = False
            router.affinity.drop_replica(rep.name)
            router._publish_up()

    def _flapping(self, rep, now):
        while rep.restart_marks and \
                now - rep.restart_marks[0] > self.restart_window_s:
            rep.restart_marks.popleft()
        return len(rep.restart_marks) > self.restart_limit

    def _quarantine(self, rep, now):
        self._kill(rep)
        rep.state = "quarantined"
        _M_BACKOFF.labels(replica=rep.name).set(0.0)
        _flight.record_event("fleet_proc_quarantine", replica=rep.name,
                             deaths=len(rep.restart_marks))
        router = self._router
        if router is not None and rep.name in router._replicas:
            router.quarantine(rep.name)

    def _respawn(self, rep, now):
        self._spawn(rep, now)
        _M_RESTARTS.inc()
        ok = self._wait_ready(rep, self._clock() + self.ready_timeout_s)
        if not ok:
            self._record_death(rep, self._clock(),
                               reason="respawn failed readiness")
        elif self._router is not None \
                and rep.name not in self._router._replicas:
            self._router.add_replica((rep.name, rep.target()))
        return ok

    def _probe_alive(self, rep, now):
        """Liveness probe of a RUNNING child: any HTTP answer counts (a
        draining 503 is alive); only no-answer-at-all (the SIGSTOP wedge)
        accrues unhealthiness."""
        try:
            _http_json("127.0.0.1", rep.port, "GET", "/healthz",
                       timeout=self.probe_timeout_s)
        except Exception:
            if rep.unhealthy_since is None:
                rep.unhealthy_since = now
            return False
        rep.unhealthy_since = None
        return True

    def tick(self, now=None):
        """One supervision turn: reap deaths, respawn on schedule,
        quarantine flappers, SIGKILL+respawn wedged children.  Returns a
        summary dict (what a controller loop logs)."""
        now = self._clock() if now is None else now
        acted = {"respawned": [], "quarantined": [], "killed": []}
        for rep in self.replicas():
            if rep.state in ("quarantined", "stopping", "stopped", "init"):
                continue
            if not rep.alive():
                if rep.state != "backoff":
                    rc = rep.proc.returncode if rep.proc is not None \
                        else None
                    self._record_death(rep, now, reason=f"exit {rc}")
                if self._flapping(rep, now):
                    self._quarantine(rep, now)
                    acted["quarantined"].append(rep.name)
                elif now >= rep.next_spawn_at:
                    if self._respawn(rep, now):
                        acted["respawned"].append(rep.name)
                continue
            # alive: detect the alive-but-wedged state (SIGSTOP et al.)
            if not self._probe_alive(rep, now) and \
                    now - rep.unhealthy_since >= self.unhealthy_after_s:
                self._kill(rep)
                acted["killed"].append(rep.name)
                self._record_death(rep, now, reason="unresponsive")
        return acted

    def restart_replica(self, name):
        """FleetController ``restart_hook``: kill + immediate respawn
        (policy already decided this replica is sick — no backoff wait)."""
        with self._lock:  # lookup only — kill/respawn must not hold the lock
            rep = self._replicas[str(name)]
        if rep.state in ("quarantined", "stopping", "stopped"):
            return False
        now = self._clock()
        self._kill(rep)
        rep.restart_marks.append(now)
        return self._respawn(rep, now)

    # ---------------------------------------------------------------- faults
    def set_fault(self, name, spec, incarnations=None):
        """Arm a ProcFaults spec for FUTURE spawns of ``name`` (passed via
        the environment); ``incarnations`` limits it to specific
        incarnation numbers (None = all future)."""
        with self._lock:
            rep = self._replicas[str(name)]
        rep.fault_spec = dict(spec) if spec else None
        rep.fault_incarnations = set(incarnations) \
            if incarnations is not None else None

    def arm_fault(self, name, spec):
        """Arm a ProcFaults spec on the LIVE process of ``name`` via its
        /faultz endpoint (requires ``faults_enabled=True`` spawns)."""
        with self._lock:  # lookup only — the HTTP round-trip runs unlocked
            rep = self._replicas[str(name)]
        status, doc = _http_json("127.0.0.1", rep.port, "POST", "/faultz",
                                 body=dict(spec), timeout=5.0)
        if status != 200:
            raise RuntimeError(f"arm_fault({name}) failed: {doc}")
        return doc

    # ----------------------------------------------------------------- scale
    def apply_scale(self, sig, now=None):
        """Actuate one controller scale signal: +1 spawns a replica into
        rotation, -1 drains and reaps one.  Returns the affected replica
        name or None (signal 0 / at the fleet bounds)."""
        if sig > 0:
            return self.scale_up(now=now)
        if sig < 0:
            return self.scale_down(now=now)
        return None

    def scale_up(self, now=None):
        now = self._clock() if now is None else now
        with self._lock:
            active = [r for r in self._replicas.values()
                      if r.state not in ("stopping", "stopped")]
            if len(active) >= self.max_replicas:
                return None
            name = f"{self.name_prefix}-{self._next_idx}"
            self._next_idx += 1
            rep = SupervisedReplica(name, self._free_port())
            self._replicas[name] = rep
        self._spawn(rep, now)
        if not self._wait_ready(rep, self._clock() + self.ready_timeout_s):
            self._record_death(rep, self._clock(),
                               reason="scale-up failed readiness")
            return None
        if self._router is not None:
            self._router.add_replica((rep.name, rep.target()))
        _flight.record_event("fleet_proc_scale_up", replica=rep.name)
        return rep.name

    def scale_down(self, now=None):
        now = self._clock() if now is None else now
        with self._lock:
            candidates = [r for r in self._replicas.values()
                          if r.state == "ready"]
            if len(candidates) <= self.min_replicas:
                return None
            rep = candidates[-1]  # newest first out (LIFO keeps the
            rep.state = "stopping"  # long-lived warm replicas serving)
        if self._router is not None \
                and rep.name in self._router._replicas:
            self._router.remove_replica(rep.name)
        self._stop_one(rep)
        _flight.record_event("fleet_proc_scale_down", replica=rep.name)
        return rep.name

    # -------------------------------------------------------------- shutdown
    def _stop_one(self, rep, deadline=None):
        """Drain -> SIGTERM -> grace -> SIGKILL for one child; counts the
        escalation.  ``deadline`` (monotonic) bounds the whole sequence."""
        if deadline is None:
            deadline = self._clock() + self.drain_deadline_s \
                + self.term_grace_s
        escalated = False
        if rep.alive():
            try:
                rep.proc.send_signal(signal.SIGTERM)
            except ProcessLookupError:
                pass
        while rep.alive():
            remaining = deadline - self._clock()
            if remaining <= 0:
                # drain blew its deadline: escalate
                self._kill(rep)
                self.escalations += 1
                _M_SIGKILLS.inc()
                _flight.record_event("fleet_proc_sigkill",
                                     replica=rep.name)
                escalated = True
                break
            try:
                rep.proc.wait(timeout=min(0.1, remaining))
            except subprocess.TimeoutExpired:
                continue
        rep.state = "stopped"
        _M_BACKOFF.labels(replica=rep.name).set(0.0)
        return escalated

    def stop(self):
        """Graceful fleet shutdown: SIGTERM everyone (each child drains
        bounded by its --drain-deadline), shared monotonic grace
        deadline, SIGKILL only the stragglers.  Returns the escalation
        count for this stop."""
        before = self.escalations
        reps = [r for r in self.replicas() if r.state != "stopped"]
        for rep in reps:
            if rep.alive():
                try:
                    rep.proc.send_signal(signal.SIGTERM)
                except ProcessLookupError:
                    pass
        deadline = self._clock() + self.drain_deadline_s + self.term_grace_s
        for rep in reps:
            self._stop_one(rep, deadline=deadline)
        return self.escalations - before

    # -------------------------------------------------------------- operator
    def procz(self):
        """The `/procz` payload: per-process supervision state."""
        return {"replicas": [r.to_dict() for r in self.replicas()],
                "escalations": self.escalations,
                "model": self.model}

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()
        return False
