"""Multi-replica serving plane: prefix-affinity router + fleet controller.

One ``LLMEngine`` is a single process; fleet traffic needs a front door
over N of them.  This module is that door, stdlib-only (http.client /
json / threading — the same constraint as the telemetry stack):

- **ReplicaServer** puts one engine on the wire by registering three app
  endpoints on the engine's EXISTING ``TelemetryServer`` (one port serves
  data + `/metrics` + `/healthz`): ``POST /admitz`` (submit; immediate
  accepted/shed ack), ``GET /pollz`` (bounded wait for the result),
  ``POST /cancelz`` (the retry-safety probe — see below).
- **Router** places each request by PREFIX AFFINITY first: the prompt's
  chained page-block key (``prefix_cache.prefix_key`` — the SAME
  derivation the radix index uses, so router and cache can never diverge)
  looks up a bounded LRU affinity table mapping prefix -> replica, and
  same-prefix traffic lands where the kv pages are already warm.  Cold
  prefixes and dead affinities fall to LOAD-AWARE scoring fed by the
  fleet ``Scraper`` (queue depth, kv-page utilization, SLO burn rate),
  with round-robin breaking ties.  Per-replica deadlines bound each hop,
  failures retry on the next replica, and a saturated fleet sheds with
  ``ServerOverloadedError``.
- **FleetController** closes the loop: it feeds the router's scrape
  samples through the alerting plane (``AlertEngine`` + ``AlertPolicy``)
  and executes the decisions — restart unhealthy replicas (port pinned,
  so the address survives), QUARANTINE one that flaps (too many restarts
  inside a window), and emit scale-up/down signals from sustained
  burn-rate/backlog episodes.

Retry-safety rule (README §Serving): a request may be retried on another
replica ONLY when this one confirmably never accepted it — connect
refused (nothing sent), a 503 ``admitted: false`` ack, an unknown
``req_id``, or a ``/cancelz`` that reports the cancel WON (the replica
will never deliver tokens for it).  After a stall/reset mid-exchange the
router reconnects and asks ``/cancelz``: cancel won -> safe to retry
elsewhere; cancel lost -> the result already exists, fetch it via
``/pollz``.  Either way a request's tokens are delivered from exactly one
replica.

Trace propagation: the router starts one trace per request and ships its
``trace_id`` in the ``/admitz`` body; the replica's engine adopts it
(``submit(trace_id=)``), and a shared ``TraceStore`` grafts the two
segments into ONE ``/tracez`` document — router hop and replica
execution under a single id.

No jax / numpy-heavy imports at module top beyond what prefix_key needs;
the router itself never touches the device.
"""
from __future__ import annotations

import http.client
import itertools
import json
import socket
import threading
import time
import urllib.parse
import uuid
from collections import OrderedDict, deque
from concurrent.futures import Future, ThreadPoolExecutor

import numpy as np

from ..observability import flight_recorder as _flight
from ..observability import metrics as _obs
from ..observability import tracing as _tracing
from .llm_server import DeadlineExceededError, ServerOverloadedError
from .prefix_cache import prefix_key

__all__ = ["PrefixAffinityTable", "ReplicaServer", "Router",
           "FleetController"]

# Router/fleet telemetry (README §Observability catalogue).
_M_REQS = _obs.counter(
    "router_requests_total",
    "Requests routed, by terminal outcome", labelnames=("outcome",))
_M_AFF_HITS = _obs.counter(
    "router_affinity_hits_total",
    "Requests routed to their prefix-affine replica")
_M_AFF_MISSES = _obs.counter(
    "router_affinity_misses_total",
    "Requests with no usable prefix affinity (cold or replica unroutable)")
_M_RETRIES = _obs.counter(
    "router_retries_total",
    "Un-accepted requests re-routed to the next replica")
_M_REPLICA_LOST = _obs.counter(
    "router_replica_lost_total",
    "Accepted requests re-routed after the supervisor's death witness "
    "confirmed the admitted replica process died (retry-safe: the dead "
    "incarnation can never deliver)")
_M_SHED_R = _obs.counter(
    "router_requests_shed_total",
    "Requests shed by the router (no routable replica / fleet saturated)")
_M_DUR = _obs.histogram(
    "router_request_duration_seconds",
    "End-to-end routed request latency (router-side)")
_M_OVERHEAD = _obs.histogram(
    "router_overhead_seconds",
    "Router-added latency: routing decision + admission ack, excluding "
    "replica execution")
_M_REPLICA_UP = _obs.gauge(
    "router_replica_up",
    "Replica routability as the router sees it (1 routable, 0 not)",
    labelnames=("replica",))
_M_AFF_DEPTH = _obs.gauge(
    "router_affinity_table_depth",
    "Prefix->replica entries in the bounded affinity table")
_M_FLEET_RESTARTS = _obs.counter(
    "fleet_restarts_total",
    "Replica restarts executed by the fleet controller")
_M_FLEET_QUARANTINES = _obs.counter(
    "fleet_quarantines_total",
    "Replicas quarantined for flapping (restart storm inside the window)")
_M_SCALE_SIGNAL = _obs.gauge(
    "fleet_scale_signal_value",
    "Latest controller scale signal (+1 scale up, -1 scale down, 0 hold)")
_M_SCALE_UP = _obs.counter(
    "fleet_scale_up_signals_total",
    "Sustained burn-rate/backlog episodes that asked for more replicas")
_M_SCALE_DOWN = _obs.counter(
    "fleet_scale_down_signals_total",
    "Sustained idle episodes that allowed shrinking the fleet")


def _http_json(host, port, method, path, body=None, timeout=5.0):
    """One JSON request/response over a fresh connection.  Uses
    ``http.client`` (socket.create_connection underneath), so the
    fault-injection harness (testing.faults.SocketFaults) applies."""
    conn = http.client.HTTPConnection(host, int(port), timeout=timeout)
    try:
        payload = None
        headers = {}
        if body is not None:
            payload = json.dumps(body).encode()
            headers["Content-Type"] = "application/json"
        conn.request(method, path, body=payload, headers=headers)
        resp = conn.getresponse()
        raw = resp.read()
        doc = json.loads(raw) if raw else {}
        return resp.status, doc
    finally:
        conn.close()


# --------------------------------------------------------------- affinity
class PrefixAffinityTable:
    """Bounded LRU map of prefix key -> replica name.

    The key is ``prefix_cache.prefix_key`` of the prompt — the chained
    page-block hash the radix index itself uses, so "same prefix" means
    exactly "would share kv pages".  Bounded: recording past ``capacity``
    evicts the least-recently-used entry, so a long-tailed prefix
    population can never grow the router without bound.
    """

    def __init__(self, capacity=4096):
        self.capacity = max(1, int(capacity))
        self._table: "OrderedDict[bytes, str]" = OrderedDict()
        self._lock = threading.Lock()

    def __len__(self):
        with self._lock:  # len() during a concurrent record() can resize
            return len(self._table)

    def get(self, key):
        """Replica recorded for ``key`` (LRU-touched), or None."""
        with self._lock:
            name = self._table.get(key)
            if name is not None:
                self._table.move_to_end(key)
            return name

    def record(self, key, replica):
        with self._lock:
            self._table[key] = str(replica)
            self._table.move_to_end(key)
            while len(self._table) > self.capacity:
                self._table.popitem(last=False)
            _M_AFF_DEPTH.set(len(self._table))

    def drop_replica(self, replica):
        """Forget every entry pointing at ``replica`` (it restarted or
        left: its kv pages are gone, the affinity is stale)."""
        with self._lock:
            dead = [k for k, v in self._table.items() if v == replica]
            for k in dead:
                del self._table[k]
            _M_AFF_DEPTH.set(len(self._table))
        return len(dead)


# --------------------------------------------------------------- replica
class _PendingRequest:
    """Replica-side record of one wire request."""

    __slots__ = ("future", "admitted", "cancelled")

    def __init__(self, future):
        self.future = future
        self.admitted = threading.Event()  # set at first slot admission
        self.cancelled = False


class ReplicaServer:
    """One engine on the wire, riding its own telemetry server's port.

    Requires an engine built with ``metrics_port=`` (the data plane
    shares the telemetry socket — one address per replica for `/admitz`,
    `/pollz`, `/cancelz`, `/metrics`, `/healthz`, `/tracez`).  The port
    is PINNED at construction: a ``restart()`` rebinds the same address,
    so the router's target list stays valid across controller restarts.
    """

    #: Completed wire requests linger until this many are outstanding —
    #: a crashed router must not leak the result table without bound.
    MAX_PENDING = 1024

    def __init__(self, engine, name=None):
        if engine.telemetry is None:
            raise ValueError(
                "ReplicaServer needs an engine with metrics_port= (the "
                "wire endpoints ride the telemetry server's port)")
        self.engine = engine
        engine.telemetry.pin()  # restart() must rebind the same address
        self.name = str(name) if name else f"replica-{engine.telemetry.port}"
        self._pending: "OrderedDict[str, _PendingRequest]" = OrderedDict()
        self._lock = threading.Lock()
        tel = engine.telemetry
        tel.register_post_endpoint("/admitz", self._admitz)
        tel.register_post_endpoint("/cancelz", self._cancelz)
        tel.register_json_endpoint("/pollz", self._pollz)

    @property
    def port(self):
        return self.engine.telemetry.port

    @property
    def url(self):
        return self.engine.telemetry.url

    def target(self):
        """``host:port`` string for the router / scraper target list."""
        return f"{self.engine.telemetry.host}:{self.port}"

    # ------------------------------------------------------------ wire API
    def _admitz(self, query, body):
        """POST /admitz: submit one request.  Immediate ack: 200
        ``{"accepted": true}`` once the engine queued it (it WILL resolve
        — tokens or a terminal error — retrying elsewhere now risks double
        execution), 503 ``{"accepted": false}`` when shed (draining /
        queue full: confirmably never accepted, retry-safe)."""
        try:
            doc = json.loads(body or b"{}")
            req_id = str(doc["req_id"])
            prompt = np.asarray(doc["prompt_ids"], np.int32)
        except Exception as e:
            return 400, {"accepted": False, "error": f"bad request: {e!r}"}
        rec_holder = {}

        def on_admit():
            rec = rec_holder.get("rec")
            if rec is not None:
                rec.admitted.set()

        try:
            fut = self.engine.submit(
                prompt,
                max_new_tokens=int(doc.get("max_new_tokens", 32)),
                do_sample=bool(doc.get("do_sample", False)),
                temperature=float(doc.get("temperature", 1.0)),
                top_k=int(doc.get("top_k", 0)),
                top_p=float(doc.get("top_p", 1.0)),
                timeout=doc.get("timeout"),
                trace_id=doc.get("trace_id") or None,
                adapter_id=doc.get("adapter_id"),
                constraint=doc.get("constraint"),
                on_admit=on_admit)
        except ServerOverloadedError as e:
            return 503, {"accepted": False, "error": str(e),
                         "draining": bool(self.engine.stats().get(
                             "draining"))}
        except Exception as e:
            return 500, {"accepted": False, "error": repr(e)}
        rec = _PendingRequest(fut)
        rec_holder["rec"] = rec
        with self._lock:
            self._pending[req_id] = rec
            # evict the OLDEST finished records past the bound; live
            # futures are never dropped (their results must stay pollable)
            while len(self._pending) > self.MAX_PENDING:
                victim = next((k for k, r in self._pending.items()
                               if r.future.done()), None)
                if victim is None:
                    break
                del self._pending[victim]
        return 200, {"accepted": True, "req_id": req_id,
                     "replica": self.name}

    def _cancelz(self, query, body):
        """POST /cancelz?req_id=: the retry-safety probe.  ``cancelled:
        true`` => this replica will NEVER deliver tokens for the request
        (safe to retry it elsewhere); ``cancelled: false`` => the result
        already exists — fetch it with /pollz instead of retrying."""
        q = urllib.parse.parse_qs(query or "")
        req_id = (q.get("req_id") or [None])[0]
        with self._lock:
            rec = self._pending.get(req_id or "")
        if rec is None:
            return 404, {"error": f"unknown req_id {req_id!r}"}
        won = rec.future.cancel()
        if won:
            rec.cancelled = True
        return 200, {"cancelled": bool(won or rec.future.cancelled()),
                     "admitted": rec.admitted.is_set()}

    def _pollz(self, query):
        """GET /pollz?req_id=&wait_s=: bounded wait for the result.  The
        wait is on the request FUTURE, so a routed caller needs no
        long-lived connection into the engine thread."""
        q = urllib.parse.parse_qs(query or "")
        req_id = (q.get("req_id") or [None])[0]
        try:
            wait_s = float((q.get("wait_s") or [0.0])[0])
        except ValueError:
            wait_s = 0.0
        with self._lock:
            rec = self._pending.get(req_id or "")
        if rec is None:
            return 404, {"error": f"unknown req_id {req_id!r}"}
        fut = rec.future
        if wait_s > 0 and not fut.done():
            try:
                fut.result(timeout=wait_s)
            except Exception:
                pass  # classified below from the future's terminal state
        if not fut.done():
            return 200, {"done": False, "admitted": rec.admitted.is_set()}
        with self._lock:
            self._pending.pop(req_id, None)
        if fut.cancelled():
            return 200, {"done": True, "error": "cancelled",
                         "error_type": "cancelled"}
        exc = fut.exception()
        if exc is not None:
            return 200, {"done": True, "error": str(exc),
                         "error_type": type(exc).__name__}
        return 200, {"done": True, "tokens": list(fut.result())}

    # ----------------------------------------------------------- lifecycle
    def drain(self, timeout=None):
        return self.engine.drain(timeout=timeout)

    def restart(self):
        """Stop and restart the engine in place (the controller's restart
        actuation).  The pinned telemetry port rebinds the same address;
        draining state clears — a restarted replica serves."""
        self.engine.stop()
        self.engine.resume()
        self.engine.start()
        return self


# ----------------------------------------------------------------- router
class _ReplicaState:
    """Router-side view of one replica."""

    __slots__ = ("name", "host", "port", "up", "draining", "quarantined",
                 "restart_marks")

    def __init__(self, name, host, port):
        self.name = str(name)
        self.host = host
        self.port = int(port)
        self.up = True          # until a poll says otherwise
        self.draining = False
        self.quarantined = False
        self.restart_marks = deque()  # mono stamps of controller restarts

    @property
    def routable(self):
        return self.up and not self.draining and not self.quarantined

    def state(self):
        if self.quarantined:
            return "quarantined"
        if self.draining:
            return "draining"
        return "up" if self.up else "down"

    def to_dict(self):
        return {"name": self.name, "target": f"{self.host}:{self.port}",
                "state": self.state(), "up": self.up,
                "draining": self.draining, "quarantined": self.quarantined,
                "restarts": len(self.restart_marks)}


class _ReplicaLost(Exception):
    """Internal: the death witness CONFIRMED the process serving an
    accepted request died — retry-safe despite admission (the dead
    incarnation can never deliver), so request() re-routes it."""


class Router:
    """Prefix-affinity-first HTTP router over N engine replicas.

    ``replicas``: list of :class:`ReplicaServer` (in-process fleet) or
    ``(name, "host:port")`` pairs / bare ``"host:port"`` strings (remote
    fleet).  ``page_size`` and ``affinity_blocks`` define the affinity
    key: the chained hash of the first ``affinity_blocks`` full
    page-blocks of the prompt (``prefix_cache.prefix_key``) — deep enough
    to separate system prompts, shallow enough that "same system prompt,
    different question" still maps to one bucket.

    Placement: affinity hit on a routable replica wins; otherwise
    replicas are scored by the latest scrape samples (queue depth +
    weighted kv-page utilization + weighted worst SLO burn rate) and the
    round-robin cursor breaks ties — then the affinity is (re)recorded
    for the replica that actually ACCEPTED the request.

    ``poll()`` refreshes the fleet view: one scrape per replica (load
    samples + scrape staleness -> up/down) plus one direct ``/healthz``
    probe (per-replica draining detection — the healthcheck GAUGE is
    process-global and aliases in-process fleets, the JSON detail is
    not).  Call it from the controller's tick or any operator loop.
    """

    def __init__(self, replicas, page_size=128, affinity_blocks=4,
                 affinity_capacity=4096, request_timeout_s=30.0,
                 per_replica_timeout_s=None, max_retries=None,
                 scrape_timeout_s=2.0, staleness_s=30.0, poll_wait_s=0.05,
                 metrics_port=None, tracer=None, clock=time.monotonic,
                 max_workers=8):
        from ..observability.scrape import Scraper, ScrapeTarget

        self.ps = int(page_size)
        self.affinity_blocks = int(affinity_blocks)
        self.affinity = PrefixAffinityTable(affinity_capacity)
        self.request_timeout_s = float(request_timeout_s)
        self.per_replica_timeout_s = None if per_replica_timeout_s is None \
            else float(per_replica_timeout_s)
        self.poll_wait_s = float(poll_wait_s)
        self.staleness_s = float(staleness_s)
        self._clock = clock
        self._tracer = tracer if tracer is not None else _tracing.TRACER
        self._replicas: "OrderedDict[str, _ReplicaState]" = OrderedDict()
        self._witness = None  # supervisor death witness (set_process_witness)
        for rep in replicas:
            name, host, port = self._parse_replica(rep)
            if name in self._replicas:
                raise ValueError(f"duplicate replica name {name!r}")
            self._replicas[name] = _ReplicaState(name, host, port)
        if not self._replicas:
            raise ValueError("Router needs at least one replica")
        self.max_retries = len(self._replicas) - 1 if max_retries is None \
            else int(max_retries)
        self.scraper = Scraper(
            [ScrapeTarget(f"{r.host}:{r.port}", name=r.name)
             for r in self._replicas.values()],
            timeout_s=scrape_timeout_s, retries=0)
        self._samples = None  # latest fleet SampleSet (load scores)
        self._rr = itertools.count()  # round-robin tie-breaker cursor
        self._affinity_hits = 0
        self._affinity_misses = 0
        self._shed = 0
        self._retries = 0
        self._overhead_s = 0.0  # decision + admission ack, summed
        self._overhead_n = 0
        self._lock = threading.Lock()
        self._pool = ThreadPoolExecutor(
            max_workers=int(max_workers),
            thread_name_prefix="paddle-tpu-router")
        self.telemetry = None
        if metrics_port is not None:
            from ..observability.exporter import TelemetryServer

            self.telemetry = TelemetryServer(port=metrics_port,
                                             traces=self._tracer)
            self.telemetry.register_healthcheck("fleet", self._check_fleet)
            self.telemetry.register_json_endpoint(
                "/routerz", lambda query: self.routerz())
            self.telemetry.start()

    # ------------------------------------------------------------ fleet view
    @staticmethod
    def _parse_replica(rep):
        """ReplicaServer | (name, "host:port") | "host:port" ->
        (name, host, port)."""
        if isinstance(rep, ReplicaServer):
            name, target = rep.name, rep.target()
        elif isinstance(rep, tuple):
            name, target = rep
        else:
            name, target = None, str(rep)
        host, _, port = str(target).rpartition(":")
        return (str(name) if name else f"{host}:{port}"), host, port

    def _snapshot(self):
        with self._lock:
            return list(self._replicas.values())

    def _check_fleet(self):
        reps = self._snapshot()
        n = sum(r.routable for r in reps)
        if n == 0:
            return False, "no routable replica"
        return True, f"{n}/{len(reps)} replicas routable"

    def replicas(self):
        return self._snapshot()

    def quarantine(self, name, on=True):
        # lookup under the lock (add/remove mutate the dict concurrently);
        # the per-replica flag flip happens on the handle outside it
        with self._lock:
            rep = self._replicas[str(name)]
        rep.quarantined = bool(on)
        if on:
            self.affinity.drop_replica(rep.name)
        self._publish_up()
        return rep

    def set_process_witness(self, fn):
        """Install the supervisor's death witness: ``fn(name)`` returns
        the live incarnation number serving ``name`` or None when no
        live process exists.  With a witness installed, an ACCEPTED
        request whose replica's incarnation changed (or vanished) is
        re-routed instead of failed — process death is proof the admitted
        work can never be delivered, so the retry cannot double-deliver."""
        self._witness = fn
        return self

    def add_replica(self, replica):
        """Add one replica to the live rotation (same accepted forms as
        the constructor).  Router view and scrape-target list update
        atomically with respect to placement/poll — both swap under the
        router lock / by list snapshot."""
        from ..observability.scrape import ScrapeTarget

        name, host, port = self._parse_replica(replica)
        state = _ReplicaState(name, host, port)
        with self._lock:
            if name in self._replicas:
                raise ValueError(f"duplicate replica name {name!r}")
            self._replicas[name] = state
        self.scraper.add_target(ScrapeTarget(f"{host}:{port}", name=name))
        self._publish_up()
        _flight.record_event("router_replica_added", replica=name)
        return state

    def remove_replica(self, name):
        """Drop one replica from rotation: placement stops immediately,
        its scrape target and affinity entries go with it."""
        name = str(name)
        with self._lock:
            rep = self._replicas.pop(name, None)
        if rep is None:
            return None
        self.scraper.remove_target(name)
        self.affinity.drop_replica(name)
        _M_REPLICA_UP.labels(replica=name).set(0.0)
        _flight.record_event("router_replica_removed", replica=name)
        return rep

    def _publish_up(self):
        for r in self._snapshot():
            _M_REPLICA_UP.labels(replica=r.name).set(
                1.0 if r.routable else 0.0)

    def probe_health(self, rep, timeout=2.0):
        """Direct per-replica `/healthz` probe: returns the parsed JSON
        (or None when unreachable) and updates the draining flag from the
        ``admission`` check's detail — per-replica truth even when N
        in-process engines alias the process-global gauges."""
        try:
            status, doc = _http_json(rep.host, rep.port, "GET", "/healthz",
                                     timeout=timeout)
        except Exception:
            return None
        checks = doc.get("checks") or {}
        adm = checks.get("admission") or {}
        rep.draining = (not adm.get("ok", True)) \
            and adm.get("detail") == "draining"
        return doc

    def poll(self):
        """Refresh the fleet view: scrape every replica (load samples;
        scrape failure/staleness marks it down) and probe `/healthz` for
        draining.  Returns ``(SampleSet, [ScrapeResult])`` — the
        controller feeds both into the alerting plane."""
        samples, results = self.scraper.poll()
        # snapshot the membership once under the lock; the slow per-replica
        # probes then run against stable handles (a replica removed mid-poll
        # just gets one last harmless probe)
        with self._lock:
            replicas = dict(self._replicas)
        for res in results:
            rep = replicas.get(res.target.name)
            if rep is None:
                continue
            rep.up = res.ok and \
                self.scraper.staleness(rep.name) <= self.staleness_s
            if rep.up:
                self.probe_health(rep)
            else:
                rep.draining = False  # unreachable, not draining
        self._samples = samples
        self._publish_up()
        return samples, results

    # ------------------------------------------------------------- placement
    def _sample(self, name, family, selector=None, default=0.0):
        samples = self._samples
        if samples is None:
            return default
        sel = {"target": name}
        if selector:
            sel.update(selector)
        hits = samples.match(family, sel)
        return max(v for _, v in hits) if hits else default

    def load_score(self, name):
        """Lower = less loaded.  Queue depth is the primary signal; page
        utilization and the worst SLO burn rate weigh in so a replica
        with a short queue but a nearly-dry page pool (or burning error
        budget) stops attracting cold traffic.  Device HBM pressure
        (``hbm_utilization_ratio``, exported by the profiling plane) joins
        with the same weight as page utilization — absent-not-zero: a
        pre-profiling replica that doesn't export the family contributes
        nothing rather than looking artificially idle."""
        q = self._sample(name, "llm_queue_depth")
        util = self._sample(name, "llm_kv_page_utilization_ratio")
        burn = self._sample(name, "slo_burn_rate_ratio")
        score = q + 4.0 * util + 8.0 * burn
        hbm = self._sample(name, "hbm_utilization_ratio", default=None)
        if hbm is not None:
            score += 4.0 * hbm
        return score

    def pick_replicas(self, prompt_ids, adapter_id=None):
        """Ordered candidate list for one request: the prefix-affine
        replica first (if routable), then the rest by ascending load
        score with the round-robin cursor breaking ties.  Returns
        ``(key, [replica_state, ...], affinity_hit)``.  ``adapter_id``
        seeds the affinity key (prefix_cache._root_key), so requests for
        different adapters never share an affinity bucket — their kv is
        not reusable across adapters."""
        key = prefix_key(prompt_ids, self.ps, blocks=self.affinity_blocks,
                         adapter_id=adapter_id)
        routable = [r for r in self._snapshot() if r.routable]
        aff_name = self.affinity.get(key)
        first = None
        hit = False
        if aff_name is not None:
            for r in routable:
                if r.name == aff_name:
                    first, hit = r, True
                    break
        rest = [r for r in routable if r is not first]
        if rest:
            rr = next(self._rr)
            scored = sorted(
                enumerate(rest),
                key=lambda iv: (self.load_score(iv[1].name),
                                (iv[0] - rr) % len(rest)))
            rest = [r for _, r in scored]
        order = ([first] if first is not None else []) + rest
        return key, order, hit

    # ------------------------------------------------------------- data path
    def request(self, prompt_ids, max_new_tokens=32, do_sample=False,
                temperature=1.0, top_k=0, top_p=1.0, timeout=None,
                adapter_id=None, constraint=None):
        """Route one request and block for its tokens.

        ``adapter_id`` selects a LoRA adapter registered on the replicas
        (and partitions the affinity key — adapter kv is never shared);
        ``constraint`` is a regex string or JSON-schema dict compiled
        replica-side into a decoding mask (inference/constrain.py).

        Raises ``ServerOverloadedError`` when no replica accepts it
        (fleet saturated / all down), ``DeadlineExceededError`` past the
        request deadline, or the replica-side error otherwise."""
        t0 = self._clock()
        deadline = t0 + (self.request_timeout_s
                         if timeout is None else float(timeout))
        prompt = np.asarray(prompt_ids, np.int32).reshape(-1)
        trace = self._tracer.start_trace(
            "router_request", prompt_tokens=int(prompt.size),
            max_new_tokens=int(max_new_tokens))
        key, order, aff_hit = self.pick_replicas(prompt,
                                                 adapter_id=adapter_id)
        with self._lock:
            if aff_hit:
                self._affinity_hits += 1
            else:
                self._affinity_misses += 1
        (_M_AFF_HITS if aff_hit else _M_AFF_MISSES).inc()
        trace.set_attr("affinity_hit", bool(aff_hit))
        if not order:
            self._count_shed(trace, "no_routable_replica")
            raise ServerOverloadedError(
                "no routable replica (all down/draining/quarantined)")
        req_id = uuid.uuid4().hex
        body = {"req_id": req_id, "prompt_ids": [int(t) for t in prompt],
                "max_new_tokens": int(max_new_tokens),
                "do_sample": bool(do_sample),
                "temperature": float(temperature), "top_k": int(top_k),
                "top_p": float(top_p),
                "trace_id": trace.trace_id or None}
        if adapter_id is not None:
            body["adapter_id"] = adapter_id
        if constraint is not None:
            body["constraint"] = constraint
        last_err = None
        for attempt, rep in enumerate(order[:self.max_retries + 1]):
            remaining = deadline - self._clock()
            if remaining <= 0:
                self._finish(trace, "expired", t0, None)
                raise DeadlineExceededError(
                    "request deadline expired while routing")
            hop_budget = remaining if self.per_replica_timeout_s is None \
                else min(remaining, self.per_replica_timeout_s)
            if attempt:
                _M_RETRIES.inc()
                with self._lock:
                    self._retries += 1
                trace.inc_attr("retries")
            body["timeout"] = round(hop_budget, 3)
            inc0 = self._witness_of(rep)  # pre-admit incarnation capture
            with trace.span("admit", replica=rep.name,
                            attempt=attempt) as sp:
                verdict, doc = self._admit_on(rep, body, hop_budget)
                sp.set_attr("verdict", verdict)
            if verdict == "accepted":
                overhead = max(0.0, self._clock() - t0)
                _M_OVERHEAD.observe(overhead)
                with self._lock:
                    self._overhead_s += overhead
                    self._overhead_n += 1
                self.affinity.record(key, rep.name)
                try:
                    return self._await_result(rep, req_id, trace, t0,
                                              deadline, doc,
                                              self._witness_of(rep))
                except _ReplicaLost as e:
                    # the admitted process DIED (witnessed): it can never
                    # deliver, so re-routing cannot double-deliver
                    last_err = str(e)
                    _M_REPLICA_LOST.inc()
                    rep.up = False
                    self.affinity.drop_replica(rep.name)
                    self._publish_up()
                    _flight.record_event("router_replica_lost",
                                         replica=rep.name, req_id=req_id)
                    req_id = uuid.uuid4().hex
                    body["req_id"] = req_id
                    continue
            last_err = doc.get("error")
            if verdict == "dead" and self._confirm_lost(rep, inc0):
                # cancel probe unreachable, but the witness confirms the
                # admit-time process is gone — nothing alive holds the
                # request, so it is retry-safe after all
                verdict = "down"
                last_err = f"{last_err}; process death witnessed"
            if verdict == "down":
                rep.up = False
                self.affinity.drop_replica(rep.name)
                self._publish_up()
            elif verdict == "draining":
                rep.draining = True
                self._publish_up()
            elif verdict == "dead":
                # sent but unconfirmable AND /cancelz unreachable: the
                # replica may still execute it — retrying elsewhere could
                # deliver twice, so this request fails here
                self._finish(trace, "error", t0, None)
                raise ServerOverloadedError(
                    f"replica {rep.name} died mid-request and its cancel "
                    f"probe is unreachable; not retry-safe: {last_err}")
            # "shed"/"rejected": confirmably never accepted — retry next
        self._count_shed(trace, "retries_exhausted")
        raise ServerOverloadedError(
            f"no replica accepted the request after "
            f"{min(len(order), self.max_retries + 1)} attempt(s); "
            f"last error: {last_err}")

    def submit(self, prompt_ids, **kwargs):
        """Async variant: returns a Future of the token list."""
        return self._pool.submit(self.request, prompt_ids, **kwargs)

    def _admit_on(self, rep, body, hop_budget):
        """One admission attempt.  Returns ``(verdict, doc)`` with
        verdict in {"accepted", "shed", "rejected", "down", "draining",
        "dead"} — "down"/"shed"/"rejected"/"draining" are all
        CONFIRMABLY un-accepted (retry-safe); "dead" is not."""
        try:
            status, doc = _http_json(rep.host, rep.port, "POST", "/admitz",
                                     body=body, timeout=hop_budget)
        except (ConnectionRefusedError, ConnectionAbortedError) as e:
            return "down", {"error": repr(e)}  # nothing reached the peer
        except (socket.timeout, ConnectionResetError, OSError,
                http.client.HTTPException) as e:
            # ambiguous: the request may have been sent.  Reconnect and
            # ask /cancelz — the retry-safety probe.
            return self._recover(rep, body["req_id"], e)
        if status == 200 and doc.get("accepted"):
            return "accepted", doc
        if status == 503:
            return ("draining" if doc.get("draining") else "shed"), doc
        return "rejected", doc

    def _witness_of(self, rep):
        """Current live incarnation of ``rep`` per the death witness
        (None = witness absent OR no live process)."""
        if self._witness is None:
            return None
        try:
            return self._witness(rep.name)
        except Exception:
            return None

    def _process_lost(self, rep, inc0):
        """True when the death witness CONFIRMS the process observed at
        ``inc0`` is gone (died or was respawned since).  Without a
        witness this is always False — the conservative pre-supervisor
        behavior."""
        if self._witness is None:
            return False
        try:
            inc = self._witness(rep.name)
        except Exception:
            return False
        return inc is None or inc != inc0

    def _confirm_lost(self, rep, inc0, wait_s=1.0):
        """_process_lost with a short confirm window: a SIGKILLed child
        is not waitable by the supervisor for a few milliseconds, so the
        witness can lag the wire failure that got us here.  Only the
        already-terminal dead-verdict path pays the wait, and only when
        the witness keeps vouching for a process whose socket just
        vanished."""
        deadline = self._clock() + wait_s
        while True:
            if self._process_lost(rep, inc0):
                return True
            if self._clock() >= deadline:
                return False
            time.sleep(0.02)

    def _recover(self, rep, req_id, exc):
        """Post-stall/reset classification via /cancelz (fresh
        connection): cancel won -> retry-safe ("shed"); cancel lost ->
        result exists, poll it ("accepted"); unknown id -> never arrived
        ("down"); probe unreachable -> "dead" (not retry-safe)."""
        try:
            status, doc = _http_json(
                rep.host, rep.port, "POST",
                f"/cancelz?req_id={req_id}", timeout=2.0)
        except Exception:
            return "dead", {"error": f"{exc!r}; cancel probe unreachable"}
        if status == 404:
            return "down", {"error": f"{exc!r}; request never arrived"}
        if doc.get("cancelled"):
            return "shed", {"error": f"{exc!r}; cancelled un-admitted"}
        return "accepted", {"recovered": True}

    def _await_result(self, rep, req_id, trace, t0, deadline, admit_doc,
                      inc0=None):
        """Poll the accepted request to completion on ``rep``.  The
        request is past its admission ack, so errors here are terminal —
        EXCEPT witnessed process death (``inc0`` is the admit-time
        incarnation): a dead process can never deliver, so
        ``_ReplicaLost`` unwinds to request() for a safe re-route."""
        with trace.span("replica_execute", replica=rep.name) as sp:
            probe_failures = 0
            while True:
                remaining = deadline - self._clock()
                if remaining <= 0:
                    self._cancel_quiet(rep, req_id)
                    self._finish(trace, "expired", t0, rep)
                    raise DeadlineExceededError(
                        f"request deadline expired awaiting replica "
                        f"{rep.name}")
                wait = min(self.poll_wait_s, remaining)
                try:
                    status, doc = _http_json(
                        rep.host, rep.port, "GET",
                        f"/pollz?req_id={req_id}&wait_s={wait:.3f}",
                        timeout=max(1.0, wait * 4))
                except Exception as e:
                    # admitted work: keep polling on fresh connections
                    # until the deadline — transient socket faults must
                    # not lose a request that is still decoding.  A
                    # witnessed process death is NOT transient: bail out
                    # for a retry-safe re-route.
                    if self._process_lost(rep, inc0):
                        raise _ReplicaLost(
                            f"replica {rep.name} process died awaiting "
                            f"{req_id}: {e!r}")
                    probe_failures += 1
                    sp.set_attr("poll_failures", probe_failures)
                    continue
                if status == 404:
                    if self._process_lost(rep, inc0):
                        raise _ReplicaLost(
                            f"replica {rep.name} restarted and forgot "
                            f"accepted request {req_id}")
                    self._finish(trace, "error", t0, rep)
                    raise ServerOverloadedError(
                        f"replica {rep.name} forgot accepted request "
                        f"{req_id} (restarted?)")
                if not doc.get("done"):
                    continue
                err = doc.get("error")
                if err is not None:
                    et = doc.get("error_type", "")
                    self._finish(trace, "error", t0, rep)
                    if et == "DeadlineExceededError":
                        raise DeadlineExceededError(err)
                    if et == "ServerOverloadedError":
                        raise ServerOverloadedError(err)
                    raise RuntimeError(
                        f"replica {rep.name} failed the request: {err}")
                tokens = [int(t) for t in doc.get("tokens", [])]
                sp.set_attr("tokens", len(tokens))
                self._finish(trace, "ok", t0, rep)
                return tokens

    def _cancel_quiet(self, rep, req_id):
        try:
            _http_json(rep.host, rep.port, "POST",
                       f"/cancelz?req_id={req_id}", timeout=1.0)
        except Exception:
            pass

    def _count_shed(self, trace, reason):
        _M_SHED_R.inc()
        _M_REQS.labels(outcome="shed").inc()
        with self._lock:
            self._shed += 1
        _flight.record_event("router_shed", reason=reason)
        trace.end(status="shed", reason=reason)

    def _finish(self, trace, status, t0, rep):
        dur = max(0.0, self._clock() - t0)
        _M_DUR.observe(dur, exemplar=trace.trace_id or None)
        _M_REQS.labels(outcome=status).inc()
        trace.end(status=status,
                  replica=rep.name if rep is not None else None)

    # ------------------------------------------------------------- operator
    def routerz(self):
        """The `/routerz` payload: per-replica state + routing counters."""
        with self._lock:
            hits, misses = self._affinity_hits, self._affinity_misses
            shed, retries = self._shed, self._retries
            ov_s, ov_n = self._overhead_s, self._overhead_n
        total = hits + misses
        replicas = []
        for r in self._snapshot():
            d = r.to_dict()
            # Profiling-plane enrichment (PR 14): both keys stay absent
            # when the replica exports neither family, so /routerz
            # consumers can distinguish "old replica" from "0.0".
            hbm = self._sample(r.name, "hbm_utilization_ratio",
                               default=None)
            if hbm is not None:
                d["hbm_utilization_ratio"] = round(hbm, 4)
            stamp = self._sample(r.name, "jit_last_compile_unix_seconds",
                                 default=0.0)
            if stamp > 0:
                now = time.time()  # tpulint: disable=impure-trace
                d["last_compile_age_s"] = round(max(0.0, now - stamp), 1)
            # Hierarchical-kv enrichment (PR 19): absent when the replica
            # predates the tiers or runs with them off — a pre-tier
            # replica must read as "no tiers", not "empty tiers".
            host_bytes = self._sample(r.name, "llm_kv_host_pool_bytes",
                                      default=None)
            if host_bytes is not None:
                tiers = {"host_pool_bytes": int(host_bytes)}
                for tier in ("hbm", "host", "disk"):
                    tok = self._sample(r.name, "llm_prefix_tier_hits_total",
                                       selector={"tier": tier}, default=None)
                    if tok is not None:
                        tiers[f"{tier}_hit_tokens"] = int(tok)
                lower = sum(tiers.get(k, 0) for k in
                            ("host_hit_tokens", "disk_hit_tokens"))
                total_tok = lower + tiers.get("hbm_hit_tokens", 0)
                if total_tok:
                    tiers["lower_tier_hit_ratio"] = round(
                        lower / total_tok, 4)
                d["kv_tiers"] = tiers
            # Goodput-ledger enrichment (PR 20): absent when the replica
            # predates the ledger — fleetwatch renders a dash, never 0.0
            # (a 0.0 goodput ratio means "all waste", a real alarm).
            gp = self._sample(r.name, "goodput_ratio",
                              selector={"domain": "serve"}, default=None)
            if gp is not None:
                d["goodput_ratio"] = round(gp, 4)
            replicas.append(d)
        return {
            "replicas": replicas,
            "affinity": {
                "entries": len(self.affinity),
                "capacity": self.affinity.capacity,
                "hits": hits, "misses": misses,
                "hit_ratio": hits / total if total else 0.0,
                "blocks": self.affinity_blocks,
                "page_size": self.ps,
            },
            "shed": shed,
            "retries": retries,
            "overhead_us_mean": round(ov_s / ov_n * 1e6, 2) if ov_n
            else 0.0,
        }

    def stats(self):
        return self.routerz()

    def stop(self):
        self._pool.shutdown(wait=False)
        if self.telemetry is not None:
            self.telemetry.stop()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()
        return False


# ------------------------------------------------------------- controller
class FleetController:
    """Alert-driven replica lifecycle: restart, quarantine, scale signals.

    Consumes the router's fleet scrape through the PR-7 alerting plane:
    ``tick()`` polls the router (sense), evaluates the rule set (decide),
    and executes the policy's decisions (act) — restart a replica whose
    healthcheck fails or whose scrape target went down/stale, QUARANTINE
    one that restarts more than ``restart_limit`` times inside
    ``restart_window_s`` (flapping: restarting it again just burns
    traffic), and derive scale signals from sustained episodes:
    ``scale_patience`` consecutive hot ticks (SLO burn / queue backlog
    firing) emit +1, the same count of idle ticks (nothing firing, empty
    queues) emit -1.

    ``replicas`` maps name -> :class:`ReplicaServer` for in-process
    restart actuation; ``restart_hook(name)`` overrides it for external
    fleets (k8s delete-pod, systemd restart).  Deterministic under an
    injected ``clock`` and explicit ``tick(samples=, now=)``.
    """

    def __init__(self, router, replicas=None, rules=None,
                 restart_hook=None, clock=time.monotonic,
                 restart_limit=3, restart_window_s=600.0,
                 scale_patience=3):
        from ..observability.alerts import (AlertEngine, AlertPolicy,
                                            default_rules)

        self.router = router
        self.replicas = dict(replicas or {})
        self.restart_hook = restart_hook
        self._clock = clock
        self.restart_limit = int(restart_limit)
        self.restart_window_s = float(restart_window_s)
        self.scale_patience = max(1, int(scale_patience))
        self.engine = AlertEngine(
            rules=rules if rules is not None else default_rules(),
            clock=clock)
        actions = {r.name: act for r, act in
                   ((r, self._ACTIONS.get(r.name))
                    for r in self.engine.rules) if act}
        self.policy = AlertPolicy(actions=actions, engine=self.engine,
                                  clock=clock, min_interval_s=0)
        self._hot_ticks = 0
        self._cold_ticks = 0
        self.scale_signal = 0
        self.restarts: list[tuple] = []      # (now, replica, alert)
        self.quarantines: list[tuple] = []   # (now, replica)

    #: Which firing rules actuate which lifecycle action.  SLO burn and
    #: backlog deliberately do NOT restart anything — they are load, not
    #: sickness; they feed the scale signal instead.
    _ACTIONS = {
        "healthcheck_failing": "restart",
        "scrape_target_down": "restart",
        "scrape_target_stale": "restart",
        "slo_burn_rate_high": "widen_deadline",
        "llm_queue_backlog": "widen_deadline",
    }

    def tick(self, samples=None, now=None):
        """One sense-decide-act turn.  Returns a summary dict."""
        if samples is None:
            samples, _ = self.router.poll()
        now = self._clock() if now is None else now
        decisions = self.policy.poll(samples=samples, now=now)
        acted = {"restarts": [], "quarantines": [], "decisions":
                 [d.to_dict() for d in decisions]}
        for d in decisions:
            if d.action != "restart":
                continue
            if d.alert == "healthcheck_failing" \
                    and d.labels.get("check") == "admission":
                continue  # intentional drain, not sickness
            name = d.labels.get("target")
            if not name or name not in {r.name for r in
                                        self.router.replicas()}:
                continue
            rep = self.router._replicas[name]
            if rep.quarantined:
                continue  # already benched; restarting it again is noise
            if rep.draining:
                continue  # let the drain finish; restart would lose work
            if self._flapping(rep, now):
                rep.quarantined = True
                self.router.affinity.drop_replica(name)
                _M_FLEET_QUARANTINES.inc()
                self.quarantines.append((now, name))
                acted["quarantines"].append(name)
                _flight.record_event("fleet_quarantine", replica=name,
                                     alert=d.alert)
                continue
            self._restart(rep, d, now)
            acted["restarts"].append(name)
        self._scale(samples, now)
        acted["scale"] = self.scale_signal
        self.router._publish_up()
        return acted

    def _flapping(self, rep, now):
        """True when one MORE restart would exceed the per-window limit —
        the restart storm verdict that benches the replica instead."""
        while rep.restart_marks and \
                now - rep.restart_marks[0] > self.restart_window_s:
            rep.restart_marks.popleft()
        return len(rep.restart_marks) >= self.restart_limit

    def _restart(self, rep, decision, now):
        rep.restart_marks.append(now)
        self.restarts.append((now, rep.name, decision.alert))
        _M_FLEET_RESTARTS.inc()
        _flight.record_event("fleet_restart", replica=rep.name,
                             alert=decision.alert)
        # stale affinity: the restarted engine's kv pages are gone
        self.router.affinity.drop_replica(rep.name)
        if rep.name in self.replicas:
            self.replicas[rep.name].restart()
            rep.up = True
            rep.draining = False
        elif self.restart_hook is not None:
            self.restart_hook(rep.name)

    def _scale(self, samples, now):
        """Sustained-episode scale signal: ``scale_patience`` consecutive
        hot ticks (burn/backlog firing) => +1; the same count of idle
        ticks (nothing firing AND no queued work) => -1; otherwise 0."""
        firing = {f["alert"] for f in self.engine.firing()}
        hot = bool(firing & {"slo_burn_rate_high", "llm_queue_backlog"})
        depth = sum(v for _, v in samples.match("llm_queue_depth")) \
            if samples is not None else 0.0
        cold = not firing and depth <= 0
        signal = 0
        if hot:
            self._hot_ticks += 1
            self._cold_ticks = 0
            if self._hot_ticks == self.scale_patience:
                signal = 1
                _M_SCALE_UP.inc()
        elif cold:
            self._cold_ticks += 1
            self._hot_ticks = 0
            if self._cold_ticks == self.scale_patience:
                signal = -1
                _M_SCALE_DOWN.inc()
        else:
            self._hot_ticks = 0
            self._cold_ticks = 0
        self.scale_signal = signal
        _M_SCALE_SIGNAL.set(float(signal))
        if signal:
            _flight.record_event("fleet_scale_signal", signal=int(signal))
        return signal

    def stats(self):
        return {
            "restarts": len(self.restarts),
            "quarantines": len(self.quarantines),
            "scale_signal": self.scale_signal,
            "hot_ticks": self._hot_ticks,
            "cold_ticks": self._cold_ticks,
            "replicas": [r.to_dict() for r in self.router.replicas()],
        }
