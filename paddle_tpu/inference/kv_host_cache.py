"""Hierarchical kv tiers under the prefix cache: host RAM + disk.

HBM holds far fewer warm prefixes than a fleet has warm sessions — eviction
from the radix index (`prefix_cache.PrefixCache`) used to DESTROY a prefix,
so every capacity miss cost a full re-prefill at compute-bound rates.  This
module is the two lower tiers that turn that miss into a copy:

- **Host tier** (`HostKVPool`): pinned-numpy page blocks keyed by the SAME
  chained block hashes as the radix index (and therefore adapter-seeded —
  tiers can never cross adapters: the seed is baked into every key).  One
  entry == one kv page across every layer's pools (bf16 2-tuples or int8
  4-tuples with f32 scales — whatever `gather_pages_to_host` produced).
  Bounded in PAGES; LRU overflow demotes once more, to disk.
- **Disk tier**: one file per entry on the checkpoint volume, written with
  the PR-1 atomic protocol (tmp + ``os.replace``) and a sha256 over the
  blob, so a torn spill is INVISIBLE: a truncated or bit-flipped file fails
  verification on load, is quarantined (renamed ``*.quarantined``, never
  retried), and the engine falls back to re-prefill — corrupt kv is never
  served.  bf16 round-trips bit-exact through ml_dtypes' numpy dtype.

The pool owns NO device memory, NO locks and NO metric families: the
engine serializes access under its own lock and owns the counters — this
class stays a plain deterministic data structure that unit-tests stand
alone (same division of labor as the radix index itself).

A full in-memory catalog (key -> tier + tail tokens) spans both tiers, so
chain walks and partial-tail longest-common-prefix matching never touch
disk; only a confirmed promotion pays the read.
"""
from __future__ import annotations

import hashlib
import json
import os

import numpy as np

__all__ = ["HostKVPool"]

_SCHEMA = 1
_SUFFIX = ".kvblk"


def _np_dtype(name):
    """numpy dtype from its string name; ``bfloat16`` resolves through
    ml_dtypes (jax's numpy-compatible bf16), which plain np.dtype rejects."""
    try:
        return np.dtype(name)
    except TypeError:
        import jax.numpy as jnp

        return np.dtype(getattr(jnp, name))


class _Entry:
    """One staged kv page: per-layer tuples of host arrays + tail tokens."""

    __slots__ = ("key", "parent", "ntok", "tokens", "blocks", "tier")

    def __init__(self, key, parent, ntok, tokens, blocks, tier="host"):
        self.key = key
        self.parent = parent
        self.ntok = int(ntok)
        self.tokens = tokens  # None for full blocks; np.int32 for partials
        self.blocks = blocks  # [(np arrays per pool element)] per layer
        self.tier = tier      # which tier served it (set on get())


class HostKVPool:
    """Host-RAM + disk staging tiers for demoted prefix-cache pages.

    ``host_pages`` bounds the RAM tier (entries, i.e. kv pages); overflow
    spills LRU-first to ``disk_dir`` when configured (bounded by
    ``disk_pages``, oldest spill deleted first) and is dropped otherwise.
    All keys are the radix index's chained block hashes — content
    addressed, so an entry can never go stale while its key exists (same
    key == same tokens under the same adapter == same kv bytes).
    """

    def __init__(self, host_pages=64, disk_dir=None, disk_pages=0):
        self.host_pages = max(0, int(host_pages))
        self.disk_dir = disk_dir
        self.disk_pages = max(0, int(disk_pages)) if disk_dir else 0
        self._host: dict[bytes, _Entry] = {}   # insertion order == LRU
        self._disk: dict[bytes, dict] = {}     # key -> catalog record
        self._partials: dict[bytes, set[bytes]] = {}  # parent -> tail keys
        self.host_bytes = 0
        # plain counters the engine's stats()/metrics read
        self.demotions_to_disk = 0
        self.disk_loads = 0
        self.quarantined = 0
        self.dropped = 0
        if self.disk_dir:
            os.makedirs(self.disk_dir, exist_ok=True)

    # ------------------------------------------------------------- lookup

    def __contains__(self, key):
        return key in self._host or key in self._disk

    def __len__(self):
        return len(self._host) + len(self._disk)

    def tier_of(self, key):
        if key in self._host:
            return "host"
        if key in self._disk:
            return "disk"
        return None

    def partial_candidates(self, parent):
        """Catalog records of every partial tail staged under ``parent``
        (both tiers): ``(key, ntok, tokens)`` — LCP matching runs on the
        in-memory tokens, disk is only read for the winner."""
        out = []
        for k in sorted(self._partials.get(parent, ())):
            if k in self._host:
                e = self._host[k]
                out.append((k, e.ntok, e.tokens))
            elif k in self._disk:
                rec = self._disk[k]
                out.append((k, rec["ntok"], rec["tokens"]))
        return out

    def get(self, key):
        """The staged entry for ``key`` or None.  A disk hit verifies the
        blob checksum; any mismatch/parse failure quarantines the file
        (renamed, counted, never retried) and reads as a miss — the
        engine then re-prefills instead of serving corrupt kv."""
        e = self._host.get(key)
        if e is not None:
            self._host[key] = self._host.pop(key)  # LRU touch
            e.tier = "host"
            return e
        rec = self._disk.get(key)
        if rec is None:
            return None
        e = self._load_spill(key, rec)
        if e is None:
            return None
        self.disk_loads += 1
        e.tier = "disk"
        return e

    # ----------------------------------------------------------- mutation

    def put(self, key, parent, ntok, tokens, blocks):
        """Stage one demoted page.  Idempotent by key (content-addressed);
        RAM overflow demotes the pool's own LRU entry to disk."""
        if key in self._host or key in self._disk:
            return False
        if self.host_pages <= 0:
            return False
        tokens = None if tokens is None else np.asarray(tokens, np.int32)
        e = _Entry(key, parent, ntok, tokens, blocks)
        self._host[key] = e
        self.host_bytes += self._entry_bytes(e)
        if tokens is not None:
            self._partials.setdefault(parent, set()).add(key)
        while len(self._host) > self.host_pages:
            old_key, old = next(iter(self._host.items()))
            self._pop_host(old_key)
            if self.disk_pages > 0:
                self._spill(old)
                self.demotions_to_disk += 1
            else:
                self._drop_partial(old_key, old.parent)
                self.dropped += 1
        return True

    def discard(self, key):
        """Drop ``key`` from whichever tier holds it (quarantine's caller-
        side twin: the engine discards an entry it refused to promote)."""
        if key in self._host:
            e = self._pop_host(key)
            self._drop_partial(key, e.parent)
        elif key in self._disk:
            rec = self._disk.pop(key)
            self._drop_partial(key, rec["parent"])
            try:
                os.remove(rec["path"])
            except OSError:
                pass

    def _pop_host(self, key):
        e = self._host.pop(key)
        self.host_bytes -= self._entry_bytes(e)
        return e

    def _drop_partial(self, key, parent):
        sibs = self._partials.get(parent)
        if sibs is not None:
            sibs.discard(key)
            if not sibs:
                del self._partials[parent]

    @staticmethod
    def _entry_bytes(e):
        return sum(int(a.nbytes) for lt in e.blocks for a in lt)

    # ---------------------------------------------------------- disk tier

    def _spill_path(self, key):
        return os.path.join(self.disk_dir, key.hex() + _SUFFIX)

    def _spill(self, e):
        """Atomic spill: header JSON line + concatenated raw blobs, sha256
        over the blob region, tmp + ``os.replace`` (the PR-1 checkpoint
        protocol) — a writer killed mid-write leaves only a tmp file or a
        torn final file that checksum verification quarantines on load."""
        while len(self._disk) >= self.disk_pages:
            old_key = next(iter(self._disk))
            rec = self._disk.pop(old_key)
            self._drop_partial(old_key, rec["parent"])
            try:
                os.remove(rec["path"])
            except OSError:
                pass
        blob = b"".join(np.ascontiguousarray(a).tobytes()
                        for lt in e.blocks for a in lt)
        header = {
            "schema": _SCHEMA,
            "parent": e.parent.hex(),
            "ntok": e.ntok,
            "tokens": None if e.tokens is None else e.tokens.tolist(),
            "layout": [[(str(a.dtype), list(a.shape)) for a in lt]
                       for lt in e.blocks],
            "sha256": hashlib.sha256(blob).hexdigest(),
            "blob_bytes": len(blob),
        }
        path = self._spill_path(e.key)
        tmp = path + ".tmp"
        try:
            with open(tmp, "wb") as f:
                f.write(json.dumps(header).encode() + b"\n")
                f.write(blob)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
        except OSError:
            # failing media / torn injected write: the spill is lost (the
            # entry degrades to a tier miss), never half-visible
            try:
                os.remove(tmp)
            except OSError:
                pass
            self.dropped += 1
            self._drop_partial(e.key, e.parent)
            return
        self._disk[e.key] = {"path": path, "parent": e.parent,
                             "ntok": e.ntok, "tokens": e.tokens}

    def _load_spill(self, key, rec):
        """Read + verify one spill; corrupt files are quarantined and the
        catalog entry dropped, so the caller sees a plain miss."""
        try:
            with open(rec["path"], "rb") as f:
                header = json.loads(f.readline())
                blob = f.read()
            if (header.get("schema") != _SCHEMA
                    or len(blob) != header["blob_bytes"]
                    or hashlib.sha256(blob).hexdigest() != header["sha256"]):
                raise ValueError("kv spill failed verification")
            blocks, off = [], 0
            for lt in header["layout"]:
                arrs = []
                for dtype_name, shape in lt:
                    dt = _np_dtype(dtype_name)
                    n = int(np.prod(shape)) * dt.itemsize
                    arrs.append(np.frombuffer(
                        blob[off:off + n], dtype=dt).reshape(shape))
                    off += n
                blocks.append(tuple(arrs))
            tokens = rec["tokens"]
            return _Entry(key, rec["parent"], rec["ntok"], tokens, blocks)
        except (OSError, ValueError, KeyError, json.JSONDecodeError):
            self.quarantined += 1
            self._disk.pop(key, None)
            self._drop_partial(key, rec["parent"])
            try:
                os.replace(rec["path"], rec["path"] + ".quarantined")
            except OSError:
                pass
            return None

    # -------------------------------------------------------- diagnostics

    def stats(self):
        return {
            "host_entries": len(self._host),
            "host_pages": self.host_pages,
            "host_bytes": self.host_bytes,
            "disk_entries": len(self._disk),
            "disk_pages": self.disk_pages,
            "demotions_to_disk": self.demotions_to_disk,
            "disk_loads": self.disk_loads,
            "quarantined": self.quarantined,
            "dropped": self.dropped,
        }
