"""Radix/trie prefix index over paged kv-cache blocks (host side).

Fleet traffic is dominated by requests sharing system prompts and few-shot
prefixes; the paged kv cache (models/kv_cache.py) makes reusing their kv a
pure page-table problem — the ragged paged decode kernel already walks
arbitrary per-slot page tables, so a shared page needs ZERO kernel changes.
This module is the index that finds the shareable pages:

- **Chained block hashes.**  A prompt is split into page-aligned blocks of
  ``page_size`` tokens; block i's key is ``sha1(parent_key || tokens_i)``,
  so a key commits to the ENTIRE prefix up to and including its block (two
  prompts share a node only if every earlier token matches too).  Keys are
  deterministic across processes — a cache test reproduces exactly.
- **Full nodes** hold one completely-filled page.  They are only ever READ
  by later requests (writes happen past the prompt), so they can be mapped
  into any number of slots with no copy.
- **Partial tail nodes** hold the prompt's last, partially-filled page
  (``ntok < page_size`` valid rows) and record their raw tokens so a later
  prompt can match the LONGEST common prefix of the tail, not just the
  whole block.  A slot that maps a partial tail will eventually write into
  it (its own continuation rows) — the engine forks the page copy-on-write
  at that moment, leaving the cached rows frozen.
- **LRU eviction.**  When the page pool runs dry the engine asks for the
  least-recently-used LEAF whose page nobody but the cache holds; interior
  nodes are never evicted from under a live chain (a matched chain pins its
  pages via slot refcounts, so its nodes never satisfy the predicate).

The index owns NO device memory and NO refcounts: it returns/accepts page
ids and the engine's allocator does the incref/decref — which keeps this
class a plain deterministic data structure that unit-tests stand alone.
"""
from __future__ import annotations

import hashlib

import numpy as np

__all__ = ["PrefixCache", "chained_block_key", "prefix_key"]

_ROOT = b""  # parent key of a prompt's first block


def _root_key(adapter_id):
    """Chain seed for a prompt's first block.

    ``None`` (the base model) keeps the historical empty seed, so every
    pre-multi-tenant key — and the golden digests pinning them — is
    unchanged.  A LoRA adapter id seeds the chain with a domain-separated
    digest of the id: kv computed under adapter A never matches a request
    for adapter B (same tokens, different weights => different kv), and
    the router's affinity table inherits the same split because it hashes
    through :func:`prefix_key`.
    """
    if adapter_id is None:
        return _ROOT
    h = hashlib.sha1(b"\x00adapter\x00")
    h.update(str(adapter_id).encode("utf-8", "surrogatepass"))
    return h.digest()


def chained_block_key(parent, blk_bytes, partial=False):
    """Key of one page block given its ``parent`` chain key.

    ``sha1(parent || tokens)`` — the key commits to the entire prefix up to
    and including this block.  This is the ONE derivation shared by the
    radix index below and the router's affinity table
    (``inference.router``): factoring it here is what guarantees the two
    can never diverge on what counts as "the same prefix".
    """
    h = hashlib.sha1(parent)
    if partial:
        # domain-separate partial tails: a 7-token tail must never
        # collide with a full block whose first bytes match
        h.update(b"\x00partial\x00")
    h.update(blk_bytes)
    return h.digest()


def prefix_key(prompt, page_size, blocks=None, adapter_id=None):
    """Affinity key of ``prompt``: the chained key of its cacheable prefix.

    Chains the same page-aligned block keys ``PrefixCache`` indexes (over
    the ``len(prompt) - 1`` usable tokens — the last token is always
    recomputed), capped at ``blocks`` full blocks so a router can bucket on
    the shared head (system prompt + few-shot prefix) instead of the whole
    prompt.  Prompts shorter than one page fall back to the
    domain-separated partial-tail key, matching ``PrefixCache.insert``'s
    tail node — so two requests get the same key exactly when the cache
    would give them the same chain.  ``adapter_id`` seeds the chain
    (:func:`_root_key`): kv under different adapters never matches, and
    ``None`` keeps the historical keys bit for bit.
    """
    prompt = np.asarray(prompt, np.int32)
    ps = int(page_size)
    usable = max(0, prompt.size - 1)
    full = usable // ps
    if blocks is not None:
        full = min(full, int(blocks))
    key = _root_key(adapter_id)
    for i in range(full):
        key = chained_block_key(key, prompt[i * ps:(i + 1) * ps].tobytes())
    if full == 0 and usable > 0:
        key = chained_block_key(key, prompt[:usable].tobytes(), partial=True)
    return key


class _Node:
    __slots__ = ("key", "parent", "page", "ntok", "tokens", "nchildren",
                 "last_used")

    def __init__(self, key, parent, page, ntok, tokens):
        self.key = key
        self.parent = parent
        self.page = int(page)
        self.ntok = int(ntok)
        self.tokens = tokens  # None for full blocks; np.int32 for partials
        self.nchildren = 0
        self.last_used = 0


class PrefixCache:
    """Trie of cached prompt-prefix pages, keyed by chained block hashes."""

    def __init__(self, page_size):
        self.ps = int(page_size)
        self._nodes: dict[bytes, _Node] = {}
        self._partials: dict[bytes, set[bytes]] = {}  # parent -> partial keys
        self._tick = 0  # LRU clock: bumped on every touch, no wall time

    def __len__(self):
        return len(self._nodes)

    def pages(self):
        """Every page currently held by the index (diagnostics/invariants)."""
        return [n.page for n in self._nodes.values()]

    def _touch(self, node):
        self._tick += 1
        node.last_used = self._tick

    # kept as a method name so call sites read as "the cache's key scheme";
    # the derivation itself lives in chained_block_key (shared with the
    # router's affinity table)
    _child_key = staticmethod(chained_block_key)

    # ------------------------------------------------------------- lookup

    def match(self, prompt, adapter_id=None):
        """Longest cached prefix of ``prompt`` an admission can map.

        Capped at ``len(prompt) - 1`` tokens: the last prompt token's
        logits ARE the first output token, so at least one position must
        always be recomputed.  Returns ``(matched_tokens, pages)`` where
        ``pages`` covers page indices ``0 .. len(pages)-1`` of the slot's
        table (the last page is partially valid when ``matched_tokens`` is
        off the page grid).  Touches every matched node for LRU.
        """
        prompt = np.asarray(prompt, np.int32)
        usable = prompt.size - 1
        key, matched, pages = _root_key(adapter_id), 0, []
        while matched + self.ps <= usable:
            k = self._child_key(key, prompt[matched:matched + self.ps]
                                .tobytes())
            node = self._nodes.get(k)
            if node is None:
                break
            self._touch(node)
            pages.append(node.page)
            matched += self.ps
            key = k
        best, best_t = None, 0
        # sorted: set order varies with hash randomization, and an
        # equal-overlap tie must pick the same node in every process
        for pk in sorted(self._partials.get(key, ())):
            node = self._nodes[pk]
            t_max = min(node.ntok, usable - matched)
            if t_max <= 0:
                continue
            eq = node.tokens[:t_max] == prompt[matched:matched + t_max]
            t = t_max if eq.all() else int(np.argmin(eq))
            if t > best_t:
                best, best_t = node, t
        if best is not None:
            self._touch(best)
            pages.append(best.page)
            matched += best_t
        return matched, pages

    # ----------------------------------------------------------- mutation

    def insert(self, prompt, slot_pages, adapter_id=None):
        """Register a freshly prefilled prompt's pages.

        ``slot_pages[i]`` must hold tokens ``i*ps .. (i+1)*ps - 1`` — the
        engine's slot layout.  Blocks already cached are only touched (the
        slot keeps its private duplicate; it frees on finish).  Returns the
        pages NEWLY held by the index — the caller increfs each, which is
        what keeps them alive after the slot releases.
        """
        prompt = np.asarray(prompt, np.int32)
        n = prompt.size
        key, new_holds = _root_key(adapter_id), []
        full = n // self.ps
        for i in range(full):
            blk = prompt[i * self.ps:(i + 1) * self.ps]
            k = self._child_key(key, blk.tobytes())
            node = self._nodes.get(k)
            if node is None:
                node = _Node(k, key, slot_pages[i], self.ps, None)
                self._nodes[k] = node
                parent = self._nodes.get(key)
                if parent is not None:
                    parent.nchildren += 1
                new_holds.append(node.page)
            self._touch(node)
            key = k
        t = n - full * self.ps
        if t > 0:
            tail = prompt[full * self.ps:]
            k = self._child_key(key, tail.tobytes(), partial=True)
            node = self._nodes.get(k)
            if node is None:
                node = _Node(k, key, slot_pages[full], t, tail.copy())
                self._nodes[k] = node
                self._partials.setdefault(key, set()).add(k)
                parent = self._nodes.get(key)
                if parent is not None:
                    parent.nchildren += 1
                new_holds.append(node.page)
            self._touch(node)
        return new_holds

    def freeable_count(self, pinned_page):
        """How many pages leaf-first eviction could EVER free right now:
        every node except those on the path to a node whose page
        ``pinned_page(page)`` says is held beyond the cache (a pinned node
        can't be evicted, so neither can its ancestors — evicting an
        interior node would strand the pinned chain).  Lets the engine
        refuse an eviction run that would destroy warm entries without
        ultimately covering the allocation."""
        pinned = set()
        for node in self._nodes.values():
            if pinned_page(node.page):
                k = node.key
                while k != _ROOT and k not in pinned:
                    n = self._nodes.get(k)
                    if n is None:
                        break  # orphaned boundary (evicted interior parent)
                    pinned.add(k)
                    k = n.parent
        return len(self._nodes) - len(pinned)

    def evict_one(self, evictable):
        """Remove the least-recently-used LEAF whose page satisfies
        ``evictable(page)`` (the engine passes "held by nobody but the
        cache").  Returns ``(key, tokens, page, ntok)`` of the evicted
        node (caller decrefs the page) or None.  Returning the node's
        identity — not just its page — is what lets the hierarchical-kv
        demotion path look up / commit a host-tier copy WITHOUT
        re-deriving the hash chain.  The LRU scan is O(nodes) — the index
        is host-side and small next to a page pool worth of HBM."""
        best = None
        for node in self._nodes.values():
            if node.nchildren == 0 and evictable(node.page):
                if best is None or node.last_used < best.last_used:
                    best = node
        if best is None:
            return None
        self._remove(best)
        return best.key, best.tokens, best.page, best.ntok

    def evict_page(self, page):
        """Remove the leaf node holding ``page`` (the steal-back path: a
        slot about to write a tail page whose ONLY other holder is the
        cache reclaims it in place instead of paying a copy).  Returns the
        removed node's ``(key, tokens, page, ntok)`` — same shape as
        :meth:`evict_one`, so the demotion path treats both eviction
        flavors identically — or None when no leaf holds the page."""
        for node in self._nodes.values():
            if node.page == page and node.nchildren == 0:
                self._remove(node)
                return node.key, node.tokens, node.page, node.ntok
        return None

    # ------------------------------------------------- hierarchical tiers

    def node_info(self, key):
        """(page, ntok) of the node at ``key`` or None — the demotion
        worker's commit check: a staged host copy is only valid while the
        node still exists on the SAME page (cached pages are frozen by the
        COW rule, so same node + same page == same content)."""
        node = self._nodes.get(key)
        return (node.page, node.ntok) if node is not None else None

    def lru_entries(self):
        """Every node as ``(key, parent, page, ntok, tokens)``, least
        recently used first — the demotion worker's candidate scan (it
        stages cold entries host-side BEFORE eviction destroys them)."""
        return [(n.key, n.parent, n.page, n.ntok, n.tokens)
                for n in sorted(self._nodes.values(),
                                key=lambda n: n.last_used)]

    def readmit(self, key, parent, page, ntok, tokens=None):
        """Re-insert a PROMOTED node (host/disk tier -> a freshly uploaded
        device page) under its original chain key.  The caller walks the
        chain in order, so ``parent`` is already present (or is the chain
        seed); after readmission a re-run of :meth:`match` sees the page
        exactly as if it had never been evicted.  Returns False (no-op)
        when the key is already indexed — a concurrent prefill won the
        race and the caller must roll its page back."""
        if key in self._nodes:
            return False
        node = _Node(key, parent, page, ntok,
                     None if tokens is None
                     else np.asarray(tokens, np.int32))
        self._nodes[key] = node
        if node.tokens is not None:
            self._partials.setdefault(parent, set()).add(key)
        par = self._nodes.get(parent)
        if par is not None:
            par.nchildren += 1
        self._touch(node)
        return True

    def _remove(self, node):
        del self._nodes[node.key]
        if node.tokens is not None:
            siblings = self._partials.get(node.parent)
            if siblings is not None:
                siblings.discard(node.key)
                if not siblings:
                    del self._partials[node.parent]
        parent = self._nodes.get(node.parent)
        if parent is not None:
            parent.nchildren -= 1
