"""One serving replica as a REAL process: ``python -m
paddle_tpu.inference.replica_main --name replica-0 --port 8471``.

This is the subprocess body the :class:`~paddle_tpu.inference.
fleet_supervisor.ReplicaSupervisor` spawns — the piece that turns the
in-process fleet of PRs 10–15 into a fleet that can actually die.  It
builds one engine (``--model tiny``: the seeded tiny-Llama ``LLMEngine``,
token-identical across replicas; ``--model stub``: a compile-free stub
engine for supervisor-level tests), wraps it in a ``ReplicaServer`` on
the ASSIGNED ``--port`` (the supervisor pins the address so restarts
rebind it), and serves until SIGTERM.

Signal/deadline contract (README §Serving, "Multi-process fleet"):

- SIGTERM => drain bounded by ``--drain-deadline`` (requests still in
  flight past it fail with ``DeadlineExceededError`` — never silently
  dropped), then clean exit 0.  The supervisor escalates to SIGKILL only
  after its own grace deadline expires.
- Readiness is ``/healthz`` 200 on the assigned port — the supervisor
  gates rotation entry on it.

Fault seams (testing/faults.py ``ProcFaults``): the spec arrives via the
``PADDLE_TPU_PROC_FAULTS`` env var (armed per-incarnation by the
supervisor) or at runtime through ``POST /faultz`` (only when spawned
with ``--allow-faultz``); ``/admitz`` and ``/pollz`` are wrapped with
the call-counted kill seams, and ``wedge_drain`` turns the SIGTERM drain
into a wedge so escalation paths are testable.  All of it is inert in
production spawns: no env var, no ``--allow-faultz``, no overhead beyond
two counter increments.
"""
from __future__ import annotations

import argparse
import json
import signal
import sys
import threading
import time

import numpy as np

from ..testing import faults as _faults


class _StubEngine:
    """Compile-free engine stand-in for supervisor-level chaos tests.

    Implements exactly the surface ``ReplicaServer`` and the drain
    contract need — telemetry (with the ``admission`` healthcheck the
    router's drain detection reads), ``submit`` resolving a
    deterministic token list immediately, ``drain``/``resume``/
    ``start``/``stop``.  Tokens are a pure function of the prompt, so
    zero-double-delivery and exactly-once assertions hold across
    replicas and restarts without ever compiling a model.
    """

    def __init__(self, port):
        from ..observability.exporter import TelemetryServer

        self._draining = False
        self.telemetry = TelemetryServer(port=port)
        self.telemetry.register_healthcheck("pump", lambda: (True, "stub"))
        self.telemetry.register_healthcheck("admission",
                                            self._check_admission)
        self.telemetry.start()

    def _check_admission(self):
        if self._draining:
            return False, "draining"
        return True, "accepting"

    @staticmethod
    def tokens_for(prompt_ids, n):
        """The deterministic oracle tests compare deliveries against."""
        base = int(np.asarray(prompt_ids, np.int64).sum())
        return [(base + 31 * i) % 50257 for i in range(int(n))]

    def submit(self, prompt_ids, max_new_tokens=32, on_admit=None,
               **kwargs):
        from .llm_server import ServerOverloadedError
        from concurrent.futures import Future

        if self._draining:
            raise ServerOverloadedError("draining: shedding new requests")
        fut = Future()
        if on_admit is not None:
            on_admit()
        fut.set_result(self.tokens_for(prompt_ids, max_new_tokens))
        return fut

    def stats(self):
        return {"draining": self._draining, "queue_depth": 0}

    def drain(self, timeout=None, deadline_s=None):
        self._draining = True
        return True

    def resume(self):
        self._draining = False
        return self

    def start(self):
        return self

    def stop(self):
        self.telemetry.stop()


def _build_engine(args):
    """``--model tiny``: the fleetserve tiny-Llama engine (identical
    seeded weights on every replica => token parity across the fleet);
    ``--model stub``: no model at all."""
    if args.model == "stub":
        return _StubEngine(args.port)
    import paddle_tpu as paddle
    from .llm_server import LLMEngine
    from ..models import LlamaConfig, LlamaForCausalLM

    paddle.seed(args.seed)
    cfg = LlamaConfig.tiny(tensor_parallel=False, use_flash_attention=False,
                           max_position_embeddings=max(256,
                                                       args.max_seq_len))
    model = LlamaForCausalLM(cfg)
    model.eval()
    eng = LLMEngine(model, max_batch_slots=args.slots,
                    max_seq_len=args.max_seq_len, kv_layout="paged",
                    page_size=args.page_size, prefill_chunk=args.page_size,
                    metrics_port=args.port)
    eng.start()
    return eng


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--name", default="replica-0")
    ap.add_argument("--port", type=int, required=True,
                    help="assigned telemetry+data port (pinned by the "
                         "supervisor across restarts)")
    ap.add_argument("--model", choices=("tiny", "stub"), default="tiny")
    ap.add_argument("--page-size", type=int, default=16)
    ap.add_argument("--slots", type=int, default=2)
    ap.add_argument("--max-seq-len", type=int, default=128)
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--drain-deadline", type=float, default=10.0,
                    help="SIGTERM drain bound (seconds); in-flight work "
                         "past it fails with DeadlineExceededError")
    ap.add_argument("--allow-faultz", action="store_true",
                    help="expose POST /faultz (runtime fault arming — "
                         "test harness only)")
    args = ap.parse_args(argv)

    faults = _faults.load_proc_faults()
    if faults.exit_at_start:
        return 3  # injected crash-at-start (restart-storm fodder)
    if faults.slow_start_s > 0:
        time.sleep(faults.slow_start_s)  # readiness delayed past the gate

    from .router import ReplicaServer

    engine = _build_engine(args)
    server = ReplicaServer(engine, name=args.name)
    tel = engine.telemetry

    # fault seams: wrap the wire endpoints ReplicaServer just registered
    # (re-registration replaces; the originals are its bound methods)
    def admitz(query, body):
        faults.on_admit()  # may SIGKILL this process before the reply
        return server._admitz(query, body)

    def pollz(query):
        faults.on_poll()
        return server._pollz(query)

    tel.register_post_endpoint("/admitz", admitz)
    tel.register_json_endpoint("/pollz", pollz)

    if args.allow_faultz:
        def faultz(query, body):
            try:
                spec = json.loads(body or b"{}")
            except ValueError as e:
                return 400, {"error": f"bad fault spec: {e!r}"}
            # counters let a harness arm "the Nth call from NOW"
            # deterministically: read, add, re-POST the absolute index
            return 200, {"armed": faults.arm(spec),
                         "admits": faults.admits, "polls": faults.polls}

        tel.register_post_endpoint("/faultz", faultz)

    # /drainz: supervisor-driven bounded drain (scale-down reaps call it
    # before SIGTERM so in-flight work completes while the process is
    # still in the rotation's past)
    def drainz(query, body):
        try:
            doc = json.loads(body or b"{}")
            deadline_s = float(doc.get("deadline_s", args.drain_deadline))
        except (ValueError, TypeError) as e:
            return 400, {"error": f"bad drain request: {e!r}"}
        ok = engine.drain(deadline_s=deadline_s)
        return 200, {"drained": bool(ok)}

    tel.register_post_endpoint("/drainz", drainz)

    stop_ev = threading.Event()
    signal.signal(signal.SIGTERM, lambda *a: stop_ev.set())
    signal.signal(signal.SIGINT, lambda *a: stop_ev.set())
    print(f"replica {args.name} serving on {tel.host}:{tel.port} "
          f"(model={args.model})", flush=True)
    stop_ev.wait()

    if faults.wedge_drain:
        # injected crash-during-drain: never finish shutting down — the
        # supervisor must SIGKILL us on its escalation deadline
        while True:
            time.sleep(60)
    engine.drain(deadline_s=args.drain_deadline)
    engine.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
