"""paddle.inference — AnalysisPredictor-shaped serving API.

Reference analog: `paddle/fluid/inference/api/analysis_predictor.h` +
`paddle_inference_api.h` (Config -> create_predictor -> input/output handles
-> run).  TPU-native: the "optimized program" is the AOT StableHLO artifact
written by `paddle.jit.save` (jax.export), loaded once and executed via PJRT;
the pass pipeline the reference runs at load time (IR fusions etc.) is XLA's
job at compile time.  Variable batch sizes go through pad-to-bucket, the same
§7.3.4 policy the OCR pipeline uses, so serving traffic compiles a bounded set
of programs.
"""
from __future__ import annotations

import numpy as np

from ..tensor.tensor import Tensor

__all__ = ["Config", "Predictor", "create_predictor", "PrecisionType",
           "DynamicBatcher", "LLMEngine", "ServerOverloadedError",
           "DeadlineExceededError", "Router", "ReplicaServer",
           "FleetController", "PrefixAffinityTable",
           "compile_constraint", "TokenConstraint"]


def __getattr__(name):
    if name in ("LLMEngine", "ServerOverloadedError",
                "DeadlineExceededError"):  # lazy: avoid importing the LLM
        from . import llm_server          # stack for classic predictor users

        return getattr(llm_server, name)
    if name in ("Router", "ReplicaServer", "FleetController",
                "PrefixAffinityTable"):   # lazy: the serving plane pulls in
        from . import router              # the LLM stack transitively

        return getattr(router, name)
    if name in ("compile_constraint", "TokenConstraint"):
        from . import constrain           # lazy: keeps the classic

        return getattr(constrain, name)   # predictor import path lean
    raise AttributeError(name)


class PrecisionType:
    Float32 = "float32"
    Half = "float16"
    Bfloat16 = "bfloat16"
    Int8 = "int8"  # accepted for API parity; XLA decides quantization


class Config:
    """Ref paddle_analysis_config.h AnalysisConfig: model paths + knobs.
    Accepts Config(prog_file_prefix) or Config(model_dir) like the reference's
    two constructors; GPU/MKLDNN/TensorRT toggles are accepted and recorded
    (XLA/PJRT owns those decisions on TPU)."""

    def __init__(self, model_path=None, params_path=None):
        self._prefix = None
        if model_path is not None:
            p = str(model_path)
            for suffix in (".pdmodel", ".pdiparams"):
                if p.endswith(suffix):
                    p = p[: -len(suffix)]
            self._prefix = p
        self._dynamic_batch = True
        self._memory_pool_mb = 0
        self._enabled = {}
        self._switches = {"ir_optim": True, "glog_info": True}

    # --- reference-shaped knob surface (recorded; XLA owns the behavior)
    def enable_use_gpu(self, memory_pool_init_size_mb=0, device_id=0):
        self._memory_pool_mb = memory_pool_init_size_mb
        self._enabled["gpu"] = device_id

    def disable_gpu(self):
        self._enabled.pop("gpu", None)

    def enable_mkldnn(self):
        self._enabled["mkldnn"] = True

    def enable_memory_optim(self):
        self._enabled["memory_optim"] = True

    def switch_ir_optim(self, flag=True):
        self._switches["ir_optim"] = bool(flag)

    def disable_glog_info(self):
        self._switches["glog_info"] = False

    def set_cpu_math_library_num_threads(self, n):
        self._enabled["cpu_threads"] = int(n)

    # --- TPU-specific: dynamic-batch policy against the fixed-shape program
    def switch_dynamic_batch(self, flag=True):
        """On (default): smaller batches are zero-padded up to the exported
        batch size and larger ones are executed in chunks — ONE compiled
        program serves any request size (§7.3.4 bounded-shapes policy)."""
        self._dynamic_batch = bool(flag)

    def model_path(self):
        return self._prefix


class _IOHandle:
    """Ref ZeroCopyTensor: copy_from_cpu / reshape / copy_to_cpu."""

    def __init__(self, name):
        self.name = name
        self._array = None

    def reshape(self, shape):
        if self._array is not None:
            self._array = np.reshape(self._array, shape)

    def copy_from_cpu(self, data):
        self._array = np.asarray(data)

    def copy_to_cpu(self):
        return np.asarray(self._array)

    def shape(self):
        return list(self._array.shape) if self._array is not None else []


class Predictor:
    """Ref analysis_predictor.h: named I/O handles around the loaded program."""

    def __init__(self, config: Config, _shared_layer=None):
        from .. import jit as _jit

        if _shared_layer is None and config.model_path() is None:
            raise ValueError("inference.Config needs a model path prefix "
                             "(artifacts written by paddle.jit.save)")
        self._config = config
        self._layer = _shared_layer if _shared_layer is not None \
            else _jit.load(config.model_path())
        specs = self._layer._info.get("inputs") or []
        if specs:
            self._input_names = [s["name"] for s in specs]
            self._input_specs = specs
        else:  # legacy artifact without recorded specs: single input assumed
            self._input_names = ["x0"]
            self._input_specs = None
        self._inputs = {n: _IOHandle(n) for n in self._input_names}
        self._outputs: dict[str, _IOHandle] = {}
        self._output_names: list[str] = []

    def clone(self):
        """A predictor sharing THIS predictor's loaded program and weights
        (zero-copy — the exported program and its parameter arrays are
        immutable) with independent I/O handles, safe to drive from another
        thread (ref analysis_predictor.h Clone: one engine, N streams)."""
        return Predictor(self._config, _shared_layer=self._layer)

    def get_input_names(self):
        return list(self._input_names)

    def get_input_handle(self, name):
        return self._inputs[name]

    def get_output_names(self):
        return list(self._output_names)

    def get_output_handle(self, name):
        return self._outputs[name]

    @property
    def _program_batch(self):
        """Batch size the program was exported with (leading dim of input 0,
        from the input specs recorded at jit.save time)."""
        if self._input_specs and self._input_specs[0]["shape"]:
            dim0 = self._input_specs[0]["shape"][0]
            return int(dim0) if dim0 and int(dim0) > 0 else None
        return None

    def _exec(self, arrays):
        outs = self._layer(*[Tensor(a) for a in arrays])
        outs = outs if isinstance(outs, (tuple, list)) else (outs,)
        return [np.asarray(o._value) for o in outs]

    def run(self, inputs=None):
        """Execute the program.  `run([arrays...])` is also accepted and
        returns the outputs directly (convenience beyond the reference API).

        With dynamic batch on (default), any request batch size is served by
        the ONE exported program: pad-to-program-batch for small requests,
        chunked execution for large ones."""
        if inputs is not None:
            for name, arr in zip(self._input_names, inputs):
                self._inputs[name].copy_from_cpu(arr)
        arrays = [self._inputs[n]._array for n in self._input_names]
        if any(a is None for a in arrays):
            missing = [n for n in self._input_names
                       if self._inputs[n]._array is None]
            raise RuntimeError(f"inputs not set: {missing}")
        arrays = [np.asarray(a) for a in arrays]

        pb = self._program_batch
        # only inputs whose exported leading dim == the program batch are
        # batched; constants/side inputs pass through whole
        if self._input_specs:
            is_batched = [bool(s["shape"]) and s["shape"][0] == pb
                          for s in self._input_specs]
        else:
            is_batched = [a.ndim >= 1 for a in arrays]
        n = next((a.shape[0] for a, b in zip(arrays, is_batched) if b), None)
        if (not self._config._dynamic_batch) or pb is None or n is None or n == pb:
            out_arrays = self._exec(arrays)
        else:
            # chunked + padded serving against the fixed-batch program
            out_chunks = []
            reals = []
            for start in range(0, n, pb):
                chunk = []
                real = min(pb, n - start)
                for a, b in zip(arrays, is_batched):
                    if not b:
                        chunk.append(a)
                        continue
                    c = a[start:start + pb]
                    if c.shape[0] < pb:
                        c = np.pad(c, [(0, pb - c.shape[0])] + [(0, 0)] * (a.ndim - 1))
                    chunk.append(c)
                out_chunks.append(self._exec(chunk))
                reals.append(real)
            # concatenate only outputs carrying the program batch dim; others
            # (per-model scalars/constants) come from the first chunk
            out_arrays = []
            for i in range(len(out_chunks[0])):
                o0 = out_chunks[0][i]
                if o0.ndim >= 1 and o0.shape[0] == pb:
                    out_arrays.append(np.concatenate(
                        [c[i][:r] for c, r in zip(out_chunks, reals)]))
                else:
                    out_arrays.append(o0)

        self._output_names = [f"out{i}" for i in range(len(out_arrays))]
        self._outputs = {}
        for name, arr in zip(self._output_names, out_arrays):
            h = _IOHandle(name)
            h.copy_from_cpu(arr)
            self._outputs[name] = h
        return out_arrays


class DynamicBatcher:
    """Concurrent-request micro-batching over one Predictor (the TPU analog
    of the reference's multi-stream AnalysisPredictor serving: one compiled
    fixed-batch program, many callers).

    Callers `submit()` single-sample (or small-batch) requests from any
    thread; a background worker coalesces up to `max_batch_size` samples or
    `timeout_ms` of queue age into ONE padded program execution and fans the
    rows back to each caller's Future.  `infer()` is the blocking wrapper.
    """

    def __init__(self, predictor: Predictor, max_batch_size=32, timeout_ms=5.0):
        import queue
        import threading

        self._pred = predictor
        self._max = int(max_batch_size)
        self._timeout = float(timeout_ms) / 1000.0
        self._q: "queue.Queue" = queue.Queue()
        self._closed = False
        self._worker = threading.Thread(target=self._loop, daemon=True)
        self._worker.start()

    def submit(self, *arrays):
        """Enqueue one request ([1_or_k, ...] per input); returns a Future of
        the output list (rows matching the request's batch).  Shape/arity
        are validated HERE so one malformed request cannot poison the
        co-batched requests of other callers."""
        from concurrent.futures import Future

        if self._closed:
            raise RuntimeError("DynamicBatcher is closed")
        arrays = [np.asarray(a) for a in arrays]
        if len(arrays) != len(self._pred.get_input_names()):
            raise ValueError(
                f"expected {len(self._pred.get_input_names())} inputs, "
                f"got {len(arrays)}")
        if any(a.ndim == 0 for a in arrays):
            raise ValueError("batcher inputs need a leading batch dim")
        n = arrays[0].shape[0]
        if any(a.shape[0] != n for a in arrays):
            raise ValueError("all inputs must share the leading batch dim")
        specs = self._pred._input_specs or []
        for a, s in zip(arrays, specs):
            shp = s.get("shape")
            if not shp:
                continue
            want = tuple(shp)[1:]
            # validate rank and every STATIC trailing dim positionally —
            # dynamic dims (None/-1) are wildcards, but their presence must
            # not disable the check for the static dims around them
            if len(a.shape) - 1 != len(want) or any(
                    w is not None and int(w) >= 0 and int(d) != int(w)
                    for d, w in zip(a.shape[1:], want)):
                raise ValueError(
                    f"input {s.get('name')}: trailing shape {a.shape[1:]} "
                    f"does not match the exported {tuple(want)}")
        fut = Future()
        self._q.put((arrays, n, fut))
        return fut

    def infer(self, *arrays):
        return self.submit(*arrays).result()

    def close(self):
        self._closed = True
        self._q.put(None)
        self._worker.join(timeout=10)

    def _loop(self):
        import queue
        import time as _time

        pending = None  # a dequeued request deferred to the next batch
        while True:
            item = pending or self._q.get()
            pending = None
            if item is None:
                return
            batch = [item]
            total = item[1]
            deadline = _time.monotonic() + self._timeout
            while total < self._max:
                remaining = deadline - _time.monotonic()
                if remaining <= 0:
                    break
                try:
                    nxt = self._q.get(timeout=remaining)
                except queue.Empty:
                    break
                if nxt is None:
                    self._q.put(None)  # propagate shutdown after this batch
                    break
                if total + nxt[1] > self._max or any(
                        a.shape[1:] != b.shape[1:]
                        for a, b in zip(batch[0][0], nxt[0])):
                    # would overshoot the cap, or (dynamic-dim exports)
                    # trailing shapes differ and cannot concatenate: defer
                    # to its own batch instead of poisoning this one
                    pending = nxt
                    break
                batch.append(nxt)
                total += nxt[1]
            try:
                ins = [np.concatenate([req[0][i] for req in batch])
                       for i in range(len(batch[0][0]))]
                outs = self._pred.run(ins)
                sliced = [bool(o.ndim) and o.shape[0] == total for o in outs]
                off = 0
                for arrays, n, fut in batch:
                    if not fut.done():  # a caller may have cancelled
                        fut.set_result([o[off:off + n] if s else o
                                        for o, s in zip(outs, sliced)])
                    off += n
            except Exception as e:
                if len(batch) > 1:
                    # one request may be poisoning the co-batch: retry each
                    # request individually so healthy callers still get
                    # results and only the bad one sees the exception
                    for arrays, n, fut in batch:
                        if fut.done():
                            continue
                        try:
                            fut.set_result(list(self._pred.run(list(arrays))))
                        except Exception as ee:
                            if not fut.done():
                                fut.set_exception(ee)
                else:
                    for _, _, fut in batch:
                        if not fut.done():
                            fut.set_exception(e)


def create_predictor(config: Config) -> Predictor:
    """Ref api/analysis_predictor.cc CreatePaddlePredictor."""
    return Predictor(config)
